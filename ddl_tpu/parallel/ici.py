"""ICI ingest tier: device-side fan-out + loader→trainer redistribution.

The layer between the loader and every parallelism axis (ROADMAP item
1).  A committed window crosses H2D exactly once — onto one *anchor*
device — and every further hop rides ICI under this module's control:

1. **Fan-out** (:mod:`ddl_tpu.ops.ici_fanout`): a Pallas
   ``make_async_remote_copy`` ring replicates or shards the anchor's
   window across a flat device ring (double-buffered DMA pipeline).
2. **Redistribution**: the ring layout ("split n ways along one dim",
   ring-ordered) is moved to the trainer's ``dp×fsdp×tp``
   ``NamedSharding`` as a short sequence of portable, memory-bounded
   collectives — the ring order is chosen target-major so the only leg
   ever needed is a tiled ``all_gather`` over the replication axes
   (following *Memory-efficient array redistribution through portable
   collective communication*, arXiv:2112.01075: per-axis legs, never an
   unsharded intermediate).  Peak per-device live bytes — including the
   ring's window-sized SPMD landing block that every device must hold —
   are computed in the plan and asserted against ``max_memory_factor``
   × the window size.

Planning is geometry-cached; steady-state windows dispatch two compiled
programs (fan-out kernel + finish collective) and allocate nothing on
the host.  Two fallback rungs to the ``xla`` path — the pre-existing
``device_put`` scatter: an UNPLANNABLE geometry (ragged batch,
indivisible split) degrades that geometry only, while a DMA-leg failure
(or the ``ici.fanout`` chaos site) latches the whole tier off — so the
degradation ladder covers the new tier (``ici.fallbacks`` counts both
rungs).

Observability (all flowing into ``north_star_report`` / the bench
``ici`` block): ``ici.bytes`` (wire bytes the fan-out moved),
``ici.windows``, ``ici.fallbacks``, ``ici.fanout`` / ``ici.redistribute``
dispatch timers, and the ``ici.peak_bytes`` gauge (the plan's asserted
per-device peak).
"""

from __future__ import annotations

import dataclasses
import functools
import logging
import os
from typing import Any, List, Optional, Sequence, Tuple

import numpy as np

from ddl_tpu import envspec
from ddl_tpu.exceptions import ShutdownRequested
from ddl_tpu.faults import fault_point
from ddl_tpu.observability import Metrics, metrics as default_metrics

logger = logging.getLogger("ddl_tpu")

#: Redistribution legs may not exceed this multiple of the WINDOW size
#: in per-device live bytes (the arXiv:2112.01075 discipline: a
#: bounded-memory plan or no plan).  The accounting includes the SPMD
#: ring's per-device landing block — shard_map needs an equal-shaped
#: input block on EVERY ring device, so each non-source device carries
#: one window-sized (cached, pinned) landing buffer through every leg —
#: plus the kernel's output and the scatter's double-buffered VMEM
#: transit.  3.0 is the worst case the shipped legs can construct: a
#: single-chunk replicate (landing + payload output + sink chunk = 3
#: windows); every multi-chunk or shard plan sits under it.
DEFAULT_MEMORY_FACTOR = 3.0


def fused_enabled(default: bool = True) -> bool:
    """The ``DDL_TPU_FUSED`` escape hatch (default ON).

    Gates both halves of the fused compute/ingest step: the
    distributor's two-slot (double-buffered landing) dispatch here and
    the trainer's fused stream loop (``Trainer._fused_stream_loop``).
    ``DDL_TPU_FUSED=0`` restores the synchronous discipline everywhere
    — the same path a latched DMA failure degrades to.
    """
    val = envspec.raw("DDL_TPU_FUSED")
    if val is None:
        return default
    return val != "0"


class PlanError(ValueError):
    """The target sharding has no bounded-memory ICI plan (caller falls
    back to the XLA path)."""


@dataclasses.dataclass(frozen=True)
class RedistLeg:
    """One plan step: what moves, over which axes, at what cost.

    ``asynchronous`` marks a leg emitted as a start/wait PAIR (the
    fused two-slot protocol): its start is the async dispatch of the
    slot's ring program and its wait is the consuming step's first use
    of the data.  Async legs are REMAT-COMPATIBLE by construction —
    they run outside the consuming step's trace, so a consumer wrapped
    in ``jax.checkpoint`` recomputes its own activations from the
    landed window (an input) without ever re-executing the DMA ring
    (asserted by tests/test_ici.py's remat row).
    """

    kind: str  #: "fanout.replicate" | "fanout.shard" | "all_gather" | "reshape"
    axes: Tuple[str, ...]  #: named mesh axes the leg communicates over
    ici_bytes: int  #: bytes this leg moves over ICI (wire, per window)
    peak_bytes: int  #: max per-device live bytes during the leg
    asynchronous: bool = False  #: emitted as a start/wait pair (fused)
    #: Wire dtype of the bytes THIS leg moves (``ddl_tpu.wire``): a
    #: quantized replicate leg reports the int8+scales bytes it
    #: actually moves, never the raw window size — ``ici_bytes`` above
    #: is already the encoded figure, this names the encoding so
    #: ``bandwidth_utilization``'s numerator cannot flatter itself.
    wire_dtype: str = "raw"


@dataclasses.dataclass(frozen=True)
class DistributionPlan:
    """A geometry's full route from anchor device to target sharding."""

    mode: str  #: "replicate" | "shard"
    shape: Tuple[int, ...]
    dtype: Any
    split_dim: Optional[int]  #: window dim the target shards (None = replicated)
    split_axes: Tuple[str, ...]  #: mesh axes sharding split_dim (target-major)
    rest_axes: Tuple[str, ...]  #: replication axes the finish leg gathers
    ring_devices: Tuple[Any, ...]  #: fan-out ring, target-major order
    legs: Tuple[RedistLeg, ...]
    wire_bytes: int  #: total ICI bytes per window
    payload_bytes: int  #: bytes usefully delivered per window
    peak_bytes: int  #: max per-device live bytes across legs (incl. landing)
    dst_shard_bytes: int  #: destination per-device shard size
    peak_factor: float  #: peak_bytes / window bytes (asserted bound)
    n_slots: int = 1  #: landing slots priced in flight (2 = fused)
    #: Wire format the fan-out ring carries (``ddl_tpu.wire``): "raw"
    #: moves the window's storage dtype; "bf16"/"int8" encode on the
    #: anchor (device-side, jitted — never a host round trip), the ring
    #: kernels move the uint8 payload (+ per-row scales), and the
    #: finish legs decode at the landing edge.  ``wire_bytes``/leg
    #: ``ici_bytes`` price the ENCODED bytes.
    wire_dtype: str = "raw"
    encoded_bytes: int = 0  #: 2D encoded bytes per window (== nbytes for raw)

    @property
    def anchor(self):
        """The device H2D lands on (ring source)."""
        return self.ring_devices[0]


def _split_layout(spec: Any, ndim: int) -> Tuple[Optional[int], Tuple[str, ...]]:
    """The single (dim, mesh-axes) pair a supported target spec shards,
    or (None, ()) for full replication.  Raises PlanError on specs the
    fan-out ring cannot source (more than one sharded dim)."""
    sharded = []
    entries = tuple(spec) + (None,) * (ndim - len(tuple(spec)))
    for dim, entry in enumerate(entries):
        if entry is None:
            continue
        axes = (entry,) if isinstance(entry, str) else tuple(entry)
        if axes:
            sharded.append((dim, axes))
    if not sharded:
        return None, ()
    if len(sharded) > 1:
        raise PlanError(
            f"target spec {spec} shards {len(sharded)} dims; the ICI "
            "fan-out sources a single split dim"
        )
    return sharded[0]


def _ring_order(mesh: Any, split_axes: Tuple[str, ...],
                rest_axes: Tuple[str, ...]) -> Tuple[Any, ...]:
    """Mesh devices flattened target-major (split axes outermost, in
    spec order): ring block ``i`` then lands exactly where the target
    layout wants row-block ``i``, so the finish leg is a pure gather
    over ``rest_axes`` — never a permute."""
    names = list(mesh.axis_names)
    order = [names.index(a) for a in split_axes] + [
        names.index(a) for a in rest_axes
    ]
    return tuple(np.transpose(mesh.devices, order).reshape(-1))


def wire_cols(cols: int, dtype: Any, wire_dtype: str) -> int:
    """uint8 columns of one encoded 2D row: the payload bytes plus (for
    int8) the per-row fp32 block scales — scales travel WITH their rows
    so any row split carries its own decode state.  Delegates to THE
    size formulas in ``ddl_tpu.wire`` (one row = a (1, cols) window),
    so the plan's pricing can never drift from what the encode
    actually produces.  Public: the device-shuffle planner
    (``ops/device_shuffle.plan_exchange``) prices the host path's
    wire-encoded DCN legs with the same formula the distribution plan
    uses, so the two tiers' accounting cannot diverge."""
    from ddl_tpu import wire

    return wire.encoded_nbytes(
        (1, cols), dtype, wire_dtype
    ) + wire.scale_bytes_for((1, cols), wire_dtype)


#: Backwards-compatible private alias (pre-device-shuffle call sites).
_wire_cols = wire_cols


def plan_distribution(
    shape: Sequence[int],
    dtype: Any,
    sharding: Any,
    max_memory_factor: Optional[float] = None,
    n_chunks: Optional[int] = None,
    n_slots: int = 1,
    wire_dtype: str = "raw",
) -> DistributionPlan:
    """Plan the anchor→``sharding`` route for one window geometry.

    ``n_slots`` prices the fused two-slot protocol: with 2 landing
    slots, window N+1's fan-out is live (its landing buffers, output
    and transit) while window N's finish legs run, so every leg's peak
    carries one extra in-flight fan-out's worth of bytes and the
    fan-out legs themselves are emitted ``asynchronous`` — start/wait
    pairs whose wait is the consuming step's first use (and which
    therefore survive a ``jax.checkpoint`` around that step).
    ``max_memory_factor`` defaults to ``DEFAULT_MEMORY_FACTOR *
    n_slots`` — the single-slot worst case per in-flight slot.

    Raises :class:`PlanError` when no bounded plan exists (unsupported
    spec shape, split dim not divisible by the device count, or the
    computed peak exceeding ``max_memory_factor`` × the window) —
    callers fall back to the XLA path and count it.
    """
    from ddl_tpu import wire as wire_mod
    from ddl_tpu.ops import ici_fanout

    shape = tuple(int(s) for s in shape)
    dtype = np.dtype(dtype)
    nbytes = int(np.prod(shape)) * dtype.itemsize
    mesh = sharding.mesh
    spec = sharding.spec
    n_dev = int(np.prod(list(mesh.shape.values())))
    split_dim, split_axes = _split_layout(spec, len(shape))
    rest_axes = tuple(
        a for a in mesh.axis_names if a not in split_axes
    )
    n_chunks = n_chunks or ici_fanout.DEFAULT_CHUNKS
    n_slots = max(1, min(int(n_slots), ici_fanout.N_SLOTS))
    if max_memory_factor is None:
        max_memory_factor = DEFAULT_MEMORY_FACTOR * n_slots
    fused = n_slots > 1
    # Lossy wire only applies to float windows: an int/token geometry
    # silently plans raw (values would corrupt for zero win) — the
    # distributor's per-geometry plan cache makes this a per-geometry
    # decision, exactly like the xla fallback.
    wire_dtype = wire_mod.check_wire_dtype(wire_dtype)
    if wire_dtype != "raw" and not wire_mod.lossy_supported(dtype):
        wire_dtype = "raw"

    if split_dim is None:
        ring = _ring_order(mesh, (), rest_axes)
        # The kernel clamps the chunk count to the split-dim extent;
        # mirror it so the plan prices what actually runs.
        rows = shape[0]
        n_chunks = max(1, min(n_chunks, rows))
        enc = rows * _wire_cols(
            int(np.prod(shape)) // rows, dtype, wire_dtype
        )
        wire = ici_fanout.wire_bytes(
            "replicate", enc, n_dev, n_chunks, rows=rows
        )
        payload = ici_fanout.payload_bytes("replicate", nbytes, n_dev)
        # Per-device live: the window-sized SPMD landing block (cached —
        # every ring device needs an equal-shaped input block) + the
        # kernel output (the full window, which IS the target, plus the
        # sink chunk riding along during the kernel).  Chunk = whole
        # padded rows, matching the kernel's row padding.  Every
        # ADDITIONAL in-flight landing slot pins one more landing +
        # output set for its whole dispatch span.  Wire plans size the
        # ring pieces at the ENCODED bytes and add the decoded output
        # (raw size) the landing-edge decode materialises.
        chunk = -(-rows // n_chunks) * (enc // rows)
        slot_live = 2 * enc + chunk
        peak = n_slots * slot_live + (nbytes if wire_dtype != "raw" else 0)
        legs = (
            RedistLeg("fanout.replicate", ("x",), wire, peak,
                      asynchronous=fused, wire_dtype=wire_dtype),
        )
        dst = nbytes
        plan = DistributionPlan(
            mode="replicate", shape=shape, dtype=dtype, split_dim=None,
            split_axes=(), rest_axes=rest_axes, ring_devices=ring,
            legs=legs, wire_bytes=wire, payload_bytes=payload,
            peak_bytes=peak, dst_shard_bytes=dst,
            peak_factor=peak / nbytes, n_slots=n_slots,
            wire_dtype=wire_dtype, encoded_bytes=enc,
        )
    else:
        split = shape[split_dim]
        if split % n_dev:
            raise PlanError(
                f"split dim {split_dim} ({split} rows) not divisible by "
                f"the {n_dev}-device ring"
            )
        g = int(np.prod([mesh.shape[a] for a in split_axes]))
        ring = _ring_order(mesh, split_axes, rest_axes)
        enc = split * _wire_cols(
            int(np.prod(shape)) // split, dtype, wire_dtype
        )
        wire = ici_fanout.wire_bytes("shard", enc, n_dev)
        payload = ici_fanout.payload_bytes("shard", nbytes, n_dev)
        block = enc // n_dev
        dst = nbytes // g
        # Scatter slot-live: the window-sized SPMD landing block (cached
        # on every ring device) + the output block + the kernel's
        # double-buffered VMEM transit (2 blocks) — all at the ENCODED
        # size for wire plans.  With the fused two-slot protocol the
        # NEXT window's fan-out is live through every leg of this
        # window's plan, so each leg carries one extra slot-live span.
        slot_live = enc + 3 * block
        extra = (n_slots - 1) * slot_live
        legs: List[RedistLeg] = [
            RedistLeg("fanout.shard", ("x",), wire, slot_live + extra,
                      asynchronous=fused, wire_dtype=wire_dtype),
        ]
        dec_extra = nbytes // g if wire_dtype != "raw" else 0
        if rest_axes:
            m = n_dev // g
            # Tiled all_gather over the replication axes: each device
            # receives the m-1 sibling ENCODED blocks of its target
            # shard (decode runs after the gather, so this leg moves
            # wire bytes too); the pinned landing block + kernel output
            # stay live under it, and the decoded shard (raw dst size)
            # materialises at the landing edge.
            legs.append(
                RedistLeg(
                    "all_gather", rest_axes, n_dev * (m - 1) * block,
                    enc + block + enc // g + dec_extra + extra,
                    wire_dtype=wire_dtype,
                )
            )
        legs.append(
            RedistLeg("reshape", (), 0, enc + dst + dec_extra + extra)
        )
        peak = max(leg.peak_bytes for leg in legs)
        plan = DistributionPlan(
            mode="shard", shape=shape, dtype=dtype, split_dim=split_dim,
            split_axes=split_axes, rest_axes=rest_axes, ring_devices=ring,
            legs=tuple(legs), wire_bytes=wire + (
                legs[1].ici_bytes if rest_axes else 0
            ),
            payload_bytes=payload, peak_bytes=peak, dst_shard_bytes=dst,
            peak_factor=peak / nbytes, n_slots=n_slots,
            wire_dtype=wire_dtype, encoded_bytes=enc,
        )
    if plan.peak_factor > max_memory_factor:
        raise PlanError(
            f"plan peak {plan.peak_bytes}B is {plan.peak_factor:.2f}x the "
            f"window ({nbytes}B) — over the "
            f"{max_memory_factor}x memory bound"
        )
    return plan


# -- compiled execution pieces (geometry-cached) ------------------------------


# Hashable Mesh wrapper for lru_cache keys — the one definition lives
# with the other mesh-keyed compiled-call caches (importing it here is
# free: ddl_tpu.parallel.__init__ already loads collectives eagerly).
from ddl_tpu.parallel.collectives import _MeshKey  # noqa: E402


def _value_ready(value: Any) -> bool:
    """Non-blocking completion probe for the fused-step OBSERVABILITY
    paths (slots-in-flight gauge, the trainer's overlap accounting):
    one shared implementation (:func:`ddl_tpu.utils.value_ready`), with
    the ready-by-default fallback — gauges degrade to zero rather than
    the probe becoming a sync."""
    from ddl_tpu.utils import value_ready

    return value_ready(value, default=True)


@functools.lru_cache(maxsize=64)
def _to2d_call(device: Any, shape: Tuple[int, ...], dtype_name: str,
               split_dim: int):
    """Anchor-local (split, -1) view builder: moveaxis + reshape, one
    compiled program per geometry, stays on the anchor device."""
    import jax
    import jax.numpy as jnp

    sds = jax.sharding.SingleDeviceSharding(device)

    def body(x):
        return jnp.moveaxis(x, split_dim, 0).reshape(shape[split_dim], -1)

    return jax.jit(body, out_shardings=sds)


def _jx_encode2d(x: Any, wire_dtype: str) -> Any:
    """Device-side 2D wire encode (traced): float rows → uint8 rows.

    bf16 bitcasts to 2 bytes/value; int8 rides the SAME blockwise
    quantizer the optimizer wire uses
    (``parallel.collectives.quantize_blockwise``) with the per-row fp32
    scales bitcast and concatenated after the payload columns — scales
    travel WITH their rows, so any row split carries its decode state.
    Runs on the anchor inside a jitted call: the window is never
    materialised at fp32 between the encode and the ring send.
    """
    import jax.numpy as jnp
    from jax import lax

    rows = x.shape[0]
    if wire_dtype == "bf16":
        b = lax.bitcast_convert_type(x.astype(jnp.bfloat16), jnp.uint8)
        return b.reshape(rows, -1)
    from ddl_tpu import wire
    from ddl_tpu.parallel.collectives import quantize_blockwise

    q, s = quantize_blockwise(x.astype(jnp.float32), wire.QUANT_BLOCK)
    qb = lax.bitcast_convert_type(q, jnp.uint8)
    sb = lax.bitcast_convert_type(s, jnp.uint8).reshape(rows, -1)
    return jnp.concatenate([qb, sb], axis=1)


def _jx_decode2d(w: Any, cols: int, dtype: Any, wire_dtype: str) -> Any:
    """Inverse of :func:`_jx_encode2d` (traced, landing-edge local)."""
    import jax.numpy as jnp
    from jax import lax

    rows = w.shape[0]
    if wire_dtype == "bf16":
        v = lax.bitcast_convert_type(
            w.reshape(rows, cols, 2), jnp.bfloat16
        )
        return v.astype(dtype)
    from ddl_tpu import wire
    from ddl_tpu.parallel.collectives import dequantize_blockwise

    nblk = -(-cols // wire.QUANT_BLOCK)
    q = lax.bitcast_convert_type(w[:, :cols], jnp.int8)
    s = lax.bitcast_convert_type(
        w[:, cols:].reshape(rows, nblk, 4), jnp.float32
    )
    return dequantize_blockwise(q, s, dtype, wire.QUANT_BLOCK)


@functools.lru_cache(maxsize=64)
def _encode2d_call(device: Any, rows: int, cols: int, dtype_name: str,
                   wire_dtype: str):
    """Anchor-local jitted wire encode: (rows, cols) dtype → (rows,
    wire_cols) uint8, pinned to the anchor device (one compiled program
    per geometry, like :func:`_to2d_call`)."""
    import jax

    sds = jax.sharding.SingleDeviceSharding(device)
    return jax.jit(
        lambda x: _jx_encode2d(x, wire_dtype), out_shardings=sds
    )


@functools.lru_cache(maxsize=64)
def _finish_replicate_wire_call(mesh_key: _MeshKey, shape: Tuple[int, ...],
                                dtype_name: str, wire_dtype: str):
    """Replicated encoded 2D view → decoded window at the target mesh's
    fully-replicated sharding (decode is per-device local compute — the
    landing-edge dequantize)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = mesh_key.mesh
    cols = int(np.prod(shape)) // shape[0]
    sharding = NamedSharding(mesh, P(*([None] * len(shape))))
    dtype = np.dtype(dtype_name)
    return jax.jit(
        lambda w: _jx_decode2d(w, cols, dtype, wire_dtype).reshape(shape),
        out_shardings=sharding,
    )


@functools.lru_cache(maxsize=64)
def _finish_shard_call(mesh_key: _MeshKey, shape: Tuple[int, ...],
                       dtype_name: str, split_dim: int,
                       split_axes: Tuple[str, ...],
                       rest_axes: Tuple[str, ...],
                       wire_dtype: str = "raw"):
    """The single finish collective for shard mode: gather the
    replication axes (tiled on the split dim), restore the window's dim
    order locally, land on the exact target spec.  Wire plans gather
    the ENCODED rows (the gather leg moves wire bytes too) and decode
    at the landing edge, after the collective."""
    import jax
    from jax import lax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ddl_tpu._compat import shard_map

    mesh = mesh_key.mesh
    other_dims = tuple(
        d for d in range(len(shape)) if d != split_dim
    )
    cols = int(np.prod(shape)) // shape[split_dim]
    dtype = np.dtype(dtype_name)

    def body(x):  # x: (split_local, flat_features | wire_cols)
        if rest_axes:
            x = lax.all_gather(
                x, rest_axes if len(rest_axes) > 1 else rest_axes[0],
                axis=0, tiled=True,
            )
        import jax.numpy as jnp

        if wire_dtype != "raw":
            x = _jx_decode2d(x, cols, dtype, wire_dtype)
        x = x.reshape((x.shape[0],) + tuple(shape[d] for d in other_dims))
        return jnp.moveaxis(x, 0, split_dim)

    in_spec = P(tuple(split_axes) + tuple(rest_axes), None)
    out_entries: List[Any] = [None] * len(shape)
    out_entries[split_dim] = tuple(split_axes)
    out_spec = P(*out_entries)
    fn = shard_map(
        body, mesh=mesh, in_specs=in_spec, out_specs=out_spec,
        check_vma=False,
    )
    return jax.jit(
        fn,
        in_shardings=NamedSharding(mesh, in_spec),
        out_shardings=NamedSharding(mesh, out_spec),
    )


@functools.lru_cache(maxsize=64)
def _finish_replicate_call(mesh_key: _MeshKey, shape: Tuple[int, ...],
                           dtype_name: str):
    """Replicated 2D view → the window's original shape, landed on the
    target mesh's fully-replicated sharding (local reshape per device)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = mesh_key.mesh
    sharding = NamedSharding(mesh, P(*([None] * len(shape))))
    return jax.jit(
        lambda x: x.reshape(shape), out_shardings=sharding
    )


class IciDistributor:
    """Executes :func:`plan_distribution` routes for one target sharding.

    Geometry plans (and their compiled programs) are cached.  Two
    fallback rungs, scoped to match their causes:

    - **Per-geometry** — a shape with no bounded plan (ragged final
      batch, indivisible split) takes the XLA scatter for THAT geometry
      only, counted once at plan time; plannable geometries keep riding
      ICI.
    - **Tier-wide latch** — a failed DMA leg (or the ``ici.fanout``
      chaos site) sets ``faulted`` and every later window takes the XLA
      fallback — the chip keeps training while the bench/report shows
      ``ici.fallbacks`` ticking.  The first window of each geometry is
      synchronized (``block_until_ready``) inside the ladder's
      try/except, because on real TPUs dispatch is async and a bring-up
      DMA failure would otherwise surface at the CONSUMER's sync point,
      outside the ladder; steady-state windows stay async.  A mid-stream
      link failure on already-validated geometry still surfaces
      downstream — that rung is the trainer's existing failure path, not
      this latch.

    **Fused two-slot dispatch** (default, ``DDL_TPU_FUSED=0`` off):
    consecutive windows alternate between :data:`~ddl_tpu.ops.
    ici_fanout.N_SLOTS` device-side landing slots — per-slot collective
    ids and landing buffers — so window N+1's ring program is dispatched
    (``fanout_start``) while window N's output is still being consumed,
    and the DMA semaphores are waited on only at the consuming step's
    first use of the data (``fanout_wait``'s data dependence).  The
    ``ici.slots_in_flight`` gauge tracks how many slots actually carry
    an unresolved window (high-water rides ``.max``); every fused
    window also ticks ``ici.fused_windows``.  A latch clears the
    in-flight tracking but never strands a started slot: already-
    dispatched ring programs resolve on their own device-side
    semaphores, independent of later windows taking the xla path.
    """

    def __init__(
        self,
        sharding: Any,
        metrics: Optional[Metrics] = None,
        interpret: Optional[bool] = None,
        max_memory_factor: Optional[float] = None,
        n_chunks: Optional[int] = None,
        n_slots: Optional[int] = None,
        wire_dtype: Optional[str] = None,
    ):
        from ddl_tpu import wire
        from ddl_tpu.ops import ici_fanout

        # Wire format the fan-out carries (ddl_tpu.wire): encode on the
        # anchor, move uint8 over the ring, decode at the landing edge.
        # None defers to DDL_TPU_WIRE_DTYPE (the one data-plane knob);
        # pass "raw" explicitly when the slot wire already encoded
        # upstream — re-quantizing a decoded window erases the win
        # (ddl-lint DDL021's decode-then-requantize finding).
        self.wire_dtype = wire.resolve_wire_dtype(wire_dtype)
        self.sharding = sharding
        self.metrics = metrics or default_metrics()
        self.interpret = interpret
        if n_slots is None:
            n_slots = ici_fanout.N_SLOTS if fused_enabled() else 1
        self.n_slots = max(1, min(int(n_slots), ici_fanout.N_SLOTS))
        # The plan's memory bound scales with the in-flight slot count
        # (each slot pins one landing + output set); an explicit factor
        # wins.
        if max_memory_factor is None:
            max_memory_factor = DEFAULT_MEMORY_FACTOR * self.n_slots
        self.max_memory_factor = max_memory_factor
        self.n_chunks = n_chunks
        self.faulted = False
        self._slot = 0  # next landing slot (cycled per fused window)
        # Recent async outputs, tracked ONLY for the slots_in_flight
        # gauge (bounded by n_slots; resolved entries are swept on the
        # next dispatch).  Dropping an entry never cancels its window.
        self._in_flight: "list" = []
        self._mesh_key = _MeshKey(sharding.mesh)
        # geometry -> DistributionPlan | PlanError; windows recur over a
        # handful of geometries, and a failed plan must not be re-derived
        # (nor re-logged, nor re-counted) per window.  Bounded: 8
        # geometries LRU.
        self._plans: "dict" = {}
        # Geometries whose FIRST window completed a synchronized
        # dispatch — later windows skip the block_until_ready.
        self._validated: set = set()
        # Unplannable geometries already logged + counted: the LRU can
        # evict and re-derive their PlanError, but ``ici.fallbacks``
        # must tick once per geometry, not once per re-derivation.
        self._counted_failures: set = set()

    def plan(self, shape: Sequence[int], dtype: Any) -> DistributionPlan:
        key = (tuple(int(s) for s in shape), np.dtype(dtype).name)
        # pop + re-insert marks recency (dict preserves insertion
        # order), so the hot per-window geometry is never the one
        # evicted by a burst of rare put_batch shapes.
        hit = self._plans.pop(key, None)
        if hit is None:
            try:
                hit = plan_distribution(
                    key[0], key[1], self.sharding,
                    max_memory_factor=self.max_memory_factor,
                    n_chunks=self.n_chunks, n_slots=self.n_slots,
                    wire_dtype=self.wire_dtype,
                )
            except PlanError as e:
                hit = e
                # Counted + logged ONCE per geometry for the
                # distributor's life (NOT per cache insert — the LRU
                # may evict and re-derive a PlanError): this geometry
                # rides the xla scatter, the tier stays up for
                # plannable ones.
                if key not in self._counted_failures:
                    self._counted_failures.add(key)
                    logger.warning(
                        "ddl_tpu: no bounded ICI plan for %s/%s (%s) — "
                        "this geometry takes the xla path",
                        key[0], key[1], e,
                    )
                    self.metrics.incr("ici.fallbacks")
            if len(self._plans) >= 8:
                self._plans.pop(next(iter(self._plans)))
        self._plans[key] = hit
        if isinstance(hit, PlanError):
            raise hit
        return hit

    def anchor(self, shape: Sequence[int], dtype: Any) -> Any:
        """The device H2D must land on for this geometry."""
        return self.plan(shape, dtype).anchor

    def put(self, arr: Any, device_put: Any) -> Any:
        """The ingest seam's one-call entry: H2D ``arr`` onto the plan's
        anchor device with ``device_put``, then distribute over ICI.  A
        geometry with no bounded plan takes one XLA-scattered put for
        that geometry instead — the seam sees exactly the exceptions the
        plain xla path would raise, never an ICI-specific one."""
        if not self.faulted:
            try:
                anchor = self.plan(arr.shape, arr.dtype).anchor
            except PlanError:
                pass  # counted+logged once in plan(); per-geometry xla
            else:
                # Fan-out DISPATCH span, keyed on the thread's current
                # window (ddl_tpu.obs; the ring kernels are async — the
                # span is the host-side cost the fused step must hide).
                from ddl_tpu.obs import spans as obs_spans

                _span_t0 = obs_spans.t0()
                out = self.distribute(device_put(arr, anchor))
                obs_spans.record(
                    "ici.fanout", *obs_spans.current_window(), _span_t0
                )
                return out
        return device_put(arr, self.sharding)

    def distribute(self, block: Any) -> Any:
        """Move an anchor-resident window to the target sharding over
        ICI.  An unplannable geometry re-routes through the XLA path
        (that geometry only); any fan-out execution failure (including
        the ``ici.fanout`` chaos site) re-routes AND latches the
        fallback for the rest of the distributor's life."""
        if self.faulted:
            return self._xla_fallback(block)
        try:
            plan = self.plan(block.shape, block.dtype)
        except PlanError:
            return self._xla_fallback(block)
        try:
            return self._distribute_planned(block, plan)
        except (ShutdownRequested, KeyboardInterrupt):
            raise  # a shutdown is not a DMA failure — never latch on it
        except Exception as e:  # noqa: BLE001 - ladder rung, re-routed
            self._latch(f"{type(e).__name__}: {e}")
            return self._xla_fallback(block)

    def _distribute_planned(self, block: Any, plan: DistributionPlan) -> Any:
        import time

        from ddl_tpu.ops import ici_fanout

        fault_point("ici.fanout")
        m = self.metrics
        dtype_name = np.dtype(block.dtype).name
        slot = self._slot
        t0 = time.perf_counter()
        if plan.mode == "replicate":
            flat = _to2d_call(
                plan.anchor, plan.shape, dtype_name, 0
            )(block)
            if plan.wire_dtype != "raw":
                # Anchor-side device encode: the ring moves uint8 wire
                # rows; the window is never a host fp32 temp between
                # the encode and the send (DDL021 discipline).
                flat = _encode2d_call(
                    plan.anchor, plan.shape[0],
                    int(np.prod(plan.shape)) // plan.shape[0],
                    dtype_name, plan.wire_dtype,
                )(flat)
            ticket = ici_fanout.fanout_start(
                "replicate", flat, plan.ring_devices, src=0, slot=slot,
                n_chunks=self.n_chunks or ici_fanout.DEFAULT_CHUNKS,
                interpret=self.interpret,
            )
            m.add_time("ici.fanout", time.perf_counter() - t0)
            t1 = time.perf_counter()
            rep = ici_fanout.replicated_view(
                ici_fanout.fanout_wait(ticket), plan.ring_devices
            )
            if plan.wire_dtype != "raw":
                result = _finish_replicate_wire_call(
                    self._mesh_key, plan.shape, dtype_name,
                    plan.wire_dtype,
                )(rep)
            else:
                result = _finish_replicate_call(
                    self._mesh_key, plan.shape, dtype_name
                )(rep)
            m.add_time("ici.redistribute", time.perf_counter() - t1)
        else:
            flat = _to2d_call(
                plan.anchor, plan.shape, dtype_name, plan.split_dim
            )(block)
            if plan.wire_dtype != "raw":
                flat = _encode2d_call(
                    plan.anchor, plan.shape[plan.split_dim],
                    int(np.prod(plan.shape)) // plan.shape[plan.split_dim],
                    dtype_name, plan.wire_dtype,
                )(flat)
            ticket = ici_fanout.fanout_start(
                "shard", flat, plan.ring_devices, src=0, slot=slot,
                interpret=self.interpret,
            )
            m.add_time("ici.fanout", time.perf_counter() - t0)
            t1 = time.perf_counter()
            result = _finish_shard_call(
                self._mesh_key, plan.shape, dtype_name, plan.split_dim,
                plan.split_axes, plan.rest_axes, plan.wire_dtype,
            )(self._onto_mesh(ici_fanout.fanout_wait(ticket), plan))
            m.add_time("ici.redistribute", time.perf_counter() - t1)
        key = (plan.shape, np.dtype(plan.dtype).name)
        if key not in self._validated:
            # First window of a geometry: synchronize so that a
            # bring-up DMA failure — asynchronous on real TPUs, where
            # dispatch returns before the ring kernel runs — surfaces
            # HERE, inside distribute()'s try/except, and latches the
            # xla fallback instead of stranding the consumer's
            # block_until_ready.  Steady-state windows stay async (the
            # fused wait is the consuming step's first use of the data).
            import jax

            ici_fanout.fanout_wait(ticket, sync=True)  # ddl-lint: disable=DDL020 - bring-up validation, once per geometry
            jax.block_until_ready(result)  # ddl-lint: disable=DDL020 - bring-up validation, once per geometry
            self._validated.add(key)
        # Landing-slot bookkeeping: cycle the slot AFTER a successful
        # dispatch (an exception re-routes through the ladder without
        # burning the slot), count the fused window, and refresh the
        # slots-in-flight gauge from a non-blocking readiness probe.
        if plan.n_slots > 1:
            self._slot = (slot + 1) % plan.n_slots
            m.incr("ici.fused_windows")
        self._track_in_flight(result)
        m.incr("ici.bytes", float(plan.wire_bytes))
        if plan.wire_dtype != "raw":
            # Wire accounting (ddl_tpu.wire): what the ring actually
            # moved per window vs the logical raw bytes it delivered.
            m.incr("wire.encoded_bytes", float(plan.encoded_bytes))
            m.incr(
                "wire.payload_bytes",
                float(int(np.prod(plan.shape)) * plan.dtype.itemsize),
            )
        m.incr("ici.windows")
        m.set_gauge("ici.peak_bytes", float(plan.peak_bytes))
        return result

    def _track_in_flight(self, result: Any) -> None:
        """Sweep resolved windows, record ``result``, refresh the
        ``ici.slots_in_flight`` gauge (high-water on ``.max``) — all
        non-blocking; tracking is observability, never a wait.

        Entries are WEAK references: after the stream's last window
        there is no next dispatch to sweep on, and a strong reference
        would pin up to ``n_slots`` window-sized device buffers for the
        distributor's remaining life.  The consumer dropping the window
        releases the tracking with it."""
        import weakref

        self._in_flight = [
            r for r in self._in_flight
            if r() is not None and not _value_ready(r())
        ]
        # Every survivor of the sweep is by construction alive and
        # unresolved, so occupancy is the survivor count plus one probe
        # of the new result — no second pass over the tracked set.
        occupied = len(self._in_flight) + (
            0 if _value_ready(result) else 1
        )
        try:
            self._in_flight.append(weakref.ref(result))
        except TypeError:
            pass  # non-weakrefable value: skip tracking, never pin
        del self._in_flight[: -max(1, self.n_slots)]  # bounded
        occupied = min(occupied, self.n_slots)
        self.metrics.set_gauge("ici.slots_in_flight", float(occupied))

    def _onto_mesh(self, ring_out: Any, plan: DistributionPlan) -> Any:
        """Zero-copy reinterpretation of the ring's block-per-device
        output as a trainer-mesh global array (split dim sharded over
        every axis, target-major) — the finish collective's input."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        spec = P(tuple(plan.split_axes) + tuple(plan.rest_axes), None)
        sharding = NamedSharding(self.sharding.mesh, spec)
        by_device = {s.device: s.data for s in ring_out.addressable_shards}
        order = sharding.addressable_devices_indices_map(ring_out.shape)
        return jax.make_array_from_single_device_arrays(
            ring_out.shape, sharding,
            [by_device[d] for d in order],
        )

    def _latch(self, why: str) -> None:
        if not self.faulted:
            logger.error(
                "ddl_tpu: ICI distribution failed (%s) — latched "
                "fallback to the xla path", why,
            )
        self.faulted = True
        # Drop the in-flight tracking but never the windows themselves:
        # an already-dispatched slot resolves on its own device-side
        # semaphores — the latch only re-routes FUTURE windows, so a
        # mid-fused-step failure cannot strand a started slot.
        self._in_flight = []
        self.metrics.set_gauge("ici.slots_in_flight", 0.0)
        self.metrics.incr("ici.fallbacks")

    def _xla_fallback(self, block: Any) -> Any:
        """The pre-ICI behavior: let XLA scatter from the anchor."""
        import jax

        return jax.device_put(block, self.sharding)


#: The loader→trainer sharding pairs the dryrun/property tests cover on
#: the 8-device virtual mesh: every trainer layout the repo's examples
#: use, from pure dp to dp×fsdp×tp, batch-dim and leading-dim splits,
#: plus full replication.  (mesh axes, target spec entries) — specs are
#: built per-test so the module stays importable without jax devices.
DRYRUN_MATRIX: Tuple[Tuple[Tuple[Tuple[str, int], ...], Tuple[Any, ...]], ...] = (
    ((("dp", 8),), ("dp", None)),
    ((("dp", 8),), (None, "dp")),
    ((("dp", 4), ("fsdp", 2)), (None, "dp")),
    ((("dp", 4), ("fsdp", 2)), (("dp", "fsdp"), None)),
    ((("dp", 2), ("fsdp", 2), ("tp", 2)), (None, "dp")),
    ((("dp", 2), ("fsdp", 2), ("tp", 2)), (("dp", "fsdp"), None)),
    ((("dp", 2), ("fsdp", 4)), (None, None)),
    ((("dp", 8),), (None, "dp", None)),
)
