"""Distributed optimizer: ZeRO-1 cross-replica weight-update sharding.

The train step's optimizer state was fully replicated across the ``dp``
axis — at adamw that is 2× the params in moments PER REPLICA, the single
biggest HBM waste left in the training hot path (train_big at 1.39B:
params+moments ≈ 8.4 GiB replicated per chip, BENCH_TPU_r05).  This
module is the cross-replica sharding of the weight update from
*Automatic Cross-Replica Sharding of Weight Update in Data-Parallel
Training* (arXiv:2004.13336), realised the GSPMD-native way:

- **state sharding**: :meth:`ShardedOptimizer.init` pins a ``dp``-sharded
  view of the params inside the init program, so every param-derived
  state leaf (adam moments) comes out sharded ``dp`` × whatever the
  param spec already shards (fsdp/tp/pp compose for free — the zero1
  spec only ADDS the dp axis to a dividing dimension).
- **reduce-scatter**: :meth:`update` constrains the (GSPMD-reduced)
  grads to the same dp-sharded layout; XLA's SPMD partitioner
  canonicalises all-reduce + slice into a reduce-scatter, which is
  exactly the compiler transformation the paper describes.
- **shard-local update**: the inner optax transformation runs on 1/dp of
  every leaf.
- **all-gather**: the updates are constrained back to the param layout
  (gathering the UPDATE rather than the updated params is the
  optax-shaped equivalent — ``apply_updates`` adds the gathered update
  to the dp-replicated params).  With ``grad_comm="int8"`` the gather
  moves the EQuARX wire format for real: the update shard quantizes to
  int8 + fp32 block scales (``parallel.collectives``), the sharding
  constraint gathers the INT8 payload (visible as an s8 all-gather in
  the compiled HLO), and the dequantize runs replica-local — a ~3.9×
  cut of the gather leg's bytes.  The reduce leg's quantization applies
  the same wire numerics to the sharded grads (the explicit-collective
  form is :func:`~ddl_tpu.parallel.collectives.quantized_all_reduce`,
  for shard_map contexts); the loss-curve-parity gate
  (:func:`loss_parity`) is what licenses the int8 path.

Observability (``opt.*`` family → ``north_star_report`` → the bench
``opt`` block): ``opt.state_bytes_per_replica`` /
``opt.state_bytes_total`` gauges (set at init from the REAL placed
state), ``opt.grad_comm_bytes_raw`` / ``opt.grad_comm_bytes_quantized``
per-step gauges (set at trace time, the pp.bubble pattern), and the
``opt.gather`` / ``opt.scatter`` timers (:meth:`measure_legs`).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

import numpy as np

#: Relative loss-drift tolerance of the int8 grad-comm parity gate: the
#: quantized run's loss curve must stay within this of the fp32 curve
#: at every compared step.  2e-2 is ~4× the drift measured on the bench
#: geometry (tests pin the measured margin), so a real numerics
#: regression trips it while rounding noise does not.
PARITY_REL_TOL = 2e-2

_VALID_GRAD_COMM = ("fp32", "int8")


def _axes_of(entry: Any) -> Tuple[str, ...]:
    if entry is None:
        return ()
    if isinstance(entry, str):
        return (entry,)
    return tuple(entry)


def zero1_sharding(named_sh: Any, shape: Any, axis: str = "dp") -> Any:
    """The dp-extended NamedSharding of one param leaf.

    Adds ``axis`` to the first dimension it divides (on top of whatever
    the spec already shards there); leaves already sharded over ``axis``
    pass through, and a leaf no dimension of which divides stays
    replicated over ``axis`` (scalars, odd-shaped norms on huge meshes)
    — correctness never depends on the extension, only the memory win
    does.
    """
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = named_sh.mesh
    if axis not in mesh.axis_names or mesh.shape[axis] <= 1:
        return named_sh
    n_axis = mesh.shape[axis]
    spec = tuple(named_sh.spec)
    parts = list(spec) + [None] * (len(shape) - len(spec))
    if any(axis in _axes_of(e) for e in parts):
        return named_sh
    for i, dim in enumerate(shape):
        axes = _axes_of(parts[i])
        n = int(np.prod([mesh.shape[a] for a in axes])) if axes else 1
        if dim % (n * n_axis) == 0:
            parts[i] = axes + (axis,)
            return NamedSharding(mesh, P(*parts))
    return named_sh


def _tree_bytes(tree: Any) -> int:
    import jax

    return sum(
        int(np.prod(np.shape(x)) or 1) * np.dtype(x.dtype).itemsize
        for x in jax.tree.leaves(tree)
    )


def _spec_extent(sh: Any, shape: Any, axis: Optional[str] = None) -> int:
    """Devices a leaf is split over (all spec axes, or just ``axis``)."""
    mesh = sh.mesh
    ext = 1
    for i, entry in enumerate(tuple(sh.spec)[: len(shape)]):
        for a in _axes_of(entry):
            if axis is None or a == axis:
                ext *= mesh.shape[a]
    return ext


def state_bytes_per_replica(state: Any, axis: str = "dp") -> int:
    """Optimizer-state bytes STORED per data-parallel replica: each
    leaf's bytes divided by the extent of ``axis`` in its placed
    sharding (1 where the leaf is dp-replicated).  Under zero1 the
    param-derived leaves carry ``axis``, so this shrinks ~dp×."""
    import jax
    from jax.sharding import NamedSharding

    total = 0
    for leaf in jax.tree.leaves(state):
        nbytes = int(np.prod(np.shape(leaf)) or 1) * np.dtype(
            leaf.dtype
        ).itemsize
        sh = getattr(leaf, "sharding", None)
        ext = (
            _spec_extent(sh, np.shape(leaf), axis)
            if isinstance(sh, NamedSharding)
            else 1
        )
        total += nbytes // ext
    return total


class ShardedOptimizer:
    """optax-compatible wrapper: ZeRO-1 state/update sharding over dp.

    ``ShardedOptimizer(inner, mesh, param_spec_tree)`` exposes the optax
    ``init``/``update`` interface, so it drops into
    :func:`ddl_tpu.parallel.train.make_train_step` /
    :func:`~ddl_tpu.parallel.train.make_multistep` (which wrap
    automatically from ``optimizer_sharding="zero1"``) and anything else
    that speaks GradientTransformation.  ``update`` MUST run inside the
    caller's jit (the constraints are trace-time annotations).

    - ``axis``: the replica axis to shard over (default ``"dp"``); a
      mesh without it (or extent 1) makes the wrapper an exact pass-
      through (modulo ``grad_comm``).
    - ``grad_comm``: ``"fp32"`` (exact) or ``"int8"`` (EQuARX wire
      format on the grad reduce + the update gather; gate with
      :func:`loss_parity`).
    - ``stochastic_rounding``: the int8 path rounds stochastically —
      unbiased in expectation, deterministic per step (each leaf's key
      folds ``seed`` ⊕ phase ⊕ leaf index ⊕ the bits of the leaf's
      first element, so successive steps draw fresh randomness without
      an extra key leaf changing the checkpoint tree).
    """

    def __init__(
        self,
        inner: Any,
        mesh: Any,
        param_spec_tree: Any,
        axis: Optional[str] = "dp",
        grad_comm: str = "fp32",
        stochastic_rounding: bool = False,
        block: Optional[int] = None,
        seed: int = 0,
    ):
        from ddl_tpu.parallel.collectives import QUANT_BLOCK

        if grad_comm not in _VALID_GRAD_COMM:
            raise ValueError(
                f"grad_comm must be one of {_VALID_GRAD_COMM}, "
                f"got {grad_comm!r}"
            )
        self._inner = inner
        self.mesh = mesh
        self.spec_tree = param_spec_tree
        self.axis = axis
        self.grad_comm = grad_comm
        self.stochastic_rounding = bool(stochastic_rounding)
        self.block = int(block or QUANT_BLOCK)
        self.seed = int(seed)
        # axis=None: the wrapper applies ONLY the grad_comm wire format
        # (the optimizer_sharding="none", grad_comm="int8" combination).
        self.active = (
            axis is not None
            and axis in mesh.axis_names
            and mesh.shape[axis] > 1
        )
        self.n_replicas = mesh.shape[axis] if self.active else 1

    # -- sharding resolution ------------------------------------------------

    def _shardings(self, tree: Any) -> Tuple[Any, Any]:
        """(param shardings, zero1 shardings) for a params-shaped tree —
        resolved from the spec tree + the tree's (possibly traced)
        shapes, so concrete init and traced update agree exactly."""
        import jax

        from ddl_tpu.parallel.train import _named, _prune_indivisible

        param_sh = jax.tree.map(
            _prune_indivisible, _named(self.mesh, self.spec_tree), tree
        )
        z1_sh = jax.tree.map(
            lambda sh, x: zero1_sharding(sh, np.shape(x), self.axis),
            param_sh,
            tree,
        )
        return param_sh, z1_sh

    @staticmethod
    def _constrain(tree: Any, sh_tree: Any) -> Any:
        import jax

        return jax.tree.map(
            jax.lax.with_sharding_constraint, tree, sh_tree
        )

    # -- optax interface ----------------------------------------------------

    def _state_out_shardings(self, params: Any, z1_sh: Any) -> Any:
        """zero1 shardings for the whole optimizer-state tree, matched
        by KEY PATH: optax states embed param-shaped subtrees (adam's
        ``mu``/``nu`` are ``tree.map``s over params), so a state leaf
        whose path ends with a param's path (longest suffix wins, shape
        must agree) IS that param's moment and takes its zero1 sharding;
        everything else (adam's scalar count) pins mesh-replicated.

        Explicit out_shardings rather than GSPMD propagation from a
        constrained input: the moments are ``zeros_like`` CONSTANTS with
        no data dependence on the params, so propagation into them is
        shape-dependent luck (observed: one geometry sharded, another
        fully replicated).
        """
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P
        from jax.tree_util import (
            tree_flatten_with_path,
            tree_unflatten,
        )

        p_flat, _ = tree_flatten_with_path(params)
        sh_leaves = jax.tree.leaves(
            z1_sh, is_leaf=lambda x: isinstance(x, NamedSharding)
        )
        by_path = {
            tuple(path): (np.shape(leaf), sh)
            for (path, leaf), sh in zip(p_flat, sh_leaves)
        }
        replicated = NamedSharding(self.mesh, P())
        state_shapes = jax.eval_shape(self._inner.init, params)
        s_flat, treedef = tree_flatten_with_path(state_shapes)
        out = []
        for path, leaf in s_flat:
            path = tuple(path)
            sh = replicated
            for start in range(len(path)):  # longest suffix first
                hit = by_path.get(path[start:])
                if hit is not None and hit[0] == tuple(leaf.shape):
                    sh = hit[1]
                    break
            out.append(sh)
        return tree_unflatten(treedef, out)

    def init(self, params: Any) -> Any:
        """Inner init compiled with explicit zero1 out_shardings
        (:meth:`_state_out_shardings`) — every param-derived state leaf
        (adam moments) lands dp-sharded on the mesh, scalars land
        mesh-replicated, so the whole state lives on one device set
        (mixed sets break donation/restore)."""
        import jax

        if not self.active:
            return self._inner.init(params)
        _, z1_sh = self._shardings(params)
        state = jax.jit(
            self._inner.init,
            out_shardings=self._state_out_shardings(params, z1_sh),
        )(params)
        self._record_state_bytes(state)
        return state

    def update(
        self, grads: Any, state: Any, params: Optional[Any] = None
    ) -> Tuple[Any, Any]:
        """reduce-scatter → shard-local inner update → all-gather.

        Runs under the caller's jit: the constraints are annotations
        GSPMD lowers to the collectives (all-reduce+slice fuses to
        reduce-scatter; the update constraint is the gather).  Traced
        once per compile, which is when the comm-bytes gauges record.
        """
        if not self.active:
            if self.grad_comm == "int8":
                grads = self._quantize_tree(grads, phase=0)
            return self._inner.update(grads, state, params)
        like = params if params is not None else grads
        param_sh, z1_sh = self._shardings(like)
        self._record_comm_bytes(grads)
        grads = self._constrain(grads, z1_sh)  # all-reduce -> reduce-scatter
        if self.grad_comm == "int8":
            # The reduce leg's wire numerics, applied to the shard each
            # replica owns (explicit-collective form: quantized_all_reduce).
            grads = self._quantize_tree(grads, phase=0)
        if params is not None:
            # Weight decay etc. read params: the dp-shard view is a
            # free slice of the replicated leaves.
            params = self._constrain(params, z1_sh)
        updates, state = self._inner.update(grads, state, params)
        if self.grad_comm == "int8":
            updates = self._gather_quantized(updates, param_sh)
        else:
            updates = self._constrain(updates, param_sh)  # all-gather
        return updates, state

    # -- int8 wire format ---------------------------------------------------

    def _leaf_keys(self, tree: Any, phase: int) -> Any:
        """Per-leaf stochastic-rounding keys: seed ⊕ phase ⊕ leaf index
        ⊕ a data-derived fold (the first element's bits) so successive
        steps draw fresh randomness without carrying key state."""
        import jax
        import jax.numpy as jnp

        from jax.tree_util import tree_flatten, tree_unflatten

        leaves, treedef = tree_flatten(tree)
        base = jax.random.PRNGKey(self.seed + 7919 * phase)
        keys = []
        for i, leaf in enumerate(leaves):
            first = jax.lax.bitcast_convert_type(
                jnp.ravel(leaf.astype(jnp.float32))[0], jnp.int32
            ).astype(jnp.uint32)
            keys.append(jax.random.fold_in(jax.random.fold_in(base, i), first))
        return tree_unflatten(treedef, keys)

    def _quantize_tree(self, tree: Any, phase: int) -> Any:
        import jax

        from ddl_tpu.parallel.collectives import quantize_dequantize

        keys = (
            self._leaf_keys(tree, phase)
            if self.stochastic_rounding
            else jax.tree.map(lambda _: None, tree)
        )
        return jax.tree.map(
            lambda x, k: x
            if np.ndim(x) == 0
            else quantize_dequantize(
                x, self.block, stochastic=self.stochastic_rounding, key=k
            ),
            tree,
            keys,
        )

    def _gather_quantized(self, updates: Any, param_sh: Any) -> Any:
        """All-gather the update in the int8 wire format: quantize the
        dp-shard, constrain the INT8 payload (and the tiny fp32 scales)
        to the gathered layout, dequantize replica-local."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from ddl_tpu.parallel.collectives import (
            dequantize_blockwise,
            quantize_blockwise,
        )

        replicated = NamedSharding(self.mesh, P())
        keys = (
            self._leaf_keys(updates, phase=1)
            if self.stochastic_rounding
            else jax.tree.map(lambda _: None, updates)
        )

        def one(u: Any, sh: Any, k: Any) -> Any:
            if np.ndim(u) == 0:
                return jax.lax.with_sharding_constraint(u, replicated)
            q, s = quantize_blockwise(
                u, self.block, stochastic=self.stochastic_rounding, key=k
            )
            # q keeps u's shape: the param sharding applies verbatim and
            # the gather moves s8 elements.  The barrier pins the int8
            # materialization — the values are round+clip exact, so the
            # algebraic simplifier would otherwise cancel the
            # f32->s8->f32 convert pair and the all-gather would silently
            # ride fp32 again (observed on the CPU backend).  Scales are
            # 1/block of the payload; gather them replicated.
            q = jax.lax.optimization_barrier(q)
            q = jax.lax.with_sharding_constraint(q, sh)
            s = jax.lax.with_sharding_constraint(s, replicated)
            return dequantize_blockwise(q, s, u.dtype, self.block)

        return jax.tree.map(one, updates, param_sh, keys)

    # -- observability ------------------------------------------------------

    def _record_state_bytes(self, state: Any) -> None:
        from ddl_tpu.observability import metrics as default_metrics

        m = default_metrics()
        m.set_gauge("opt.state_bytes_total", float(_tree_bytes(state)))
        m.set_gauge(
            "opt.state_bytes_per_replica",
            float(state_bytes_per_replica(state, self.axis)),
        )

    def _record_comm_bytes(self, grads: Any) -> None:
        # Trace-time (once per compile), like pipeline_apply's pp.*
        # gauges: per-step LOGICAL payload of the two collective legs
        # (reduce-scatter of grads + all-gather of updates).  Shapes are
        # static under trace, so these are plain Python ints.
        import jax

        from ddl_tpu.observability import metrics as default_metrics
        from ddl_tpu.parallel.collectives import quantized_bytes

        raw = 2 * _tree_bytes(grads)
        quant = 2 * sum(
            quantized_bytes(np.shape(g), self.block)
            if np.ndim(g) > 0
            else int(np.dtype(g.dtype).itemsize)
            for g in jax.tree.leaves(grads)
        )
        m = default_metrics()
        m.set_gauge("opt.grad_comm_bytes_raw", float(raw))
        m.set_gauge(
            "opt.grad_comm_bytes_quantized",
            float(quant if self.grad_comm == "int8" else raw),
        )

    def measure_legs(
        self, params: Any, metrics: Optional[Any] = None, trials: int = 3
    ) -> Dict[str, float]:
        """Measured wall time of the two collective legs on a params-
        sized tree: ``gather`` (dp-shard → param layout — the all-gather
        the update pays every step) and ``scatter`` (param layout →
        dp-shard — the slice half of the fused reduce-scatter).  Runs
        its own tiny jitted programs outside the train step (per-leg
        timers cannot be read out of one fused jit); records into the
        ``opt.gather`` / ``opt.scatter`` timers.
        """
        import time

        import jax

        from ddl_tpu.observability import metrics as default_metrics

        m = metrics or default_metrics()
        if not self.active:
            return {"gather_s": 0.0, "scatter_s": 0.0}
        param_sh, z1_sh = self._shardings(params)
        shard = jax.jit(lambda t: t, out_shardings=z1_sh)(params)
        gather = jax.jit(lambda t: t, out_shardings=param_sh)
        scatter = jax.jit(lambda t: t, out_shardings=z1_sh)
        full = jax.block_until_ready(gather(shard))  # compile
        jax.block_until_ready(scatter(full))
        out = {}
        for name, fn, arg in (
            ("gather", gather, shard),
            ("scatter", scatter, full),
        ):
            best = float("inf")
            for _ in range(trials):
                t0 = time.perf_counter()
                jax.block_until_ready(fn(arg))
                best = min(best, time.perf_counter() - t0)
            m.add_time(f"opt.{name}", best)
            out[f"{name}_s"] = best
        return out


# -- HBM accounting ----------------------------------------------------------


@dataclasses.dataclass
class HbmAccount:
    """Per-device HBM bytes of the persistent training residents."""

    param_bytes: int
    grad_bytes: int
    opt_state_bytes: int

    @property
    def total_bytes(self) -> int:
        return self.param_bytes + self.grad_bytes + self.opt_state_bytes


def hbm_accounting(
    shape_tree: Any,
    spec_tree: Any,
    mesh_axes: Dict[str, int],
    optimizer_sharding: str = "none",
    axis: str = "dp",
    moments_per_param: int = 2,
) -> HbmAccount:
    """Analytic per-device bytes for params + grads + optimizer state.

    Pure shape/spec arithmetic over an ``eval_shape`` tree (e.g. a
    model's ``param_shapes(cfg)``) and a mesh-shape dict — NO devices
    needed, so a v5e-32 layout prices on a laptop (the
    fits-only-with-zero1 test).  Mirrors ``_prune_indivisible``: a spec
    axis only shards a dimension it divides.  ``moments_per_param``:
    adam keeps 2 param-shaped fp-moment leaves (adamw too); SGD+momentum
    is 1.  Moments price at each leaf's own dtype (optax zeros_like).

    Transient peaks (activations, collective scratch) are deliberately
    out of scope — this accounts the residents whose footprint the
    optimizer-sharding decision controls.
    """

    def shard_extent(spec: Any, shape: Any, extra_axis: bool) -> int:
        parts = list(tuple(spec)) + [None] * (len(shape) - len(tuple(spec)))
        ext = 1
        extra_placed = not extra_axis
        for i, dim in enumerate(shape):
            axes = tuple(
                a for a in _axes_of(parts[i]) if mesh_axes.get(a, 1) > 1
            )
            n = int(np.prod([mesh_axes[a] for a in axes])) if axes else 1
            if n > 1 and dim % n == 0:
                ext *= n
            else:
                n = 1  # degrades replicated, as _prune_indivisible would
            if not extra_placed and dim % (n * mesh_axes.get(axis, 1)) == 0:
                ext *= mesh_axes.get(axis, 1)
                extra_placed = True
        return ext

    import jax
    from jax.sharding import PartitionSpec as P

    leaves = jax.tree.leaves(shape_tree)
    specs = [
        s if isinstance(s, P) else P()
        for s in jax.tree.leaves(
            spec_tree, is_leaf=lambda x: x is None or isinstance(x, P)
        )
    ]
    if len(leaves) != len(specs):
        raise ValueError(
            f"shape tree has {len(leaves)} leaves but spec tree {len(specs)}"
        )
    zero1 = optimizer_sharding == "zero1"
    if optimizer_sharding not in ("none", "zero1"):
        raise ValueError(
            f"optimizer_sharding must be 'none' or 'zero1', "
            f"got {optimizer_sharding!r}"
        )
    p_bytes = g_bytes = o_bytes = 0
    for leaf, spec in zip(leaves, specs):
        shape = tuple(leaf.shape)
        nbytes = int(np.prod(shape) or 1) * np.dtype(leaf.dtype).itemsize
        base = shard_extent(spec, shape, extra_axis=False)
        p_bytes += nbytes // base
        g_bytes += nbytes // base
        z1 = shard_extent(spec, shape, extra_axis=True) if zero1 else base
        o_bytes += moments_per_param * (nbytes // z1)
    return HbmAccount(p_bytes, g_bytes, o_bytes)


# -- the parity gate ---------------------------------------------------------


def loss_parity(
    ref_losses: Any, test_losses: Any, rel_tol: float = PARITY_REL_TOL
) -> Dict[str, Any]:
    """THE loss-curve-parity gate the int8 path is licensed by.

    Compares two per-step loss sequences (same init, same batches) and
    returns ``{"parity": bool, "max_rel_drift": float, "rel_tol": ...}``
    — parity holds when every step's relative drift stays under
    ``rel_tol``.  The bench ``opt`` block embeds this verbatim and
    bench_smoke asserts ``parity`` is true; tests pin the fp32 zero1
    path to max_rel_drift == 0.0 (bit-exact).
    """
    ref = np.asarray(ref_losses, dtype=np.float64)
    test = np.asarray(test_losses, dtype=np.float64)
    if ref.shape != test.shape:
        raise ValueError(
            f"loss curves differ in length: {ref.shape} vs {test.shape}"
        )
    denom = np.maximum(np.abs(ref), 1e-12)
    drift = float(np.max(np.abs(test - ref) / denom)) if ref.size else 0.0
    return {
        "parity": bool(drift <= rel_tol),
        "max_rel_drift": drift,
        "rel_tol": float(rel_tol),
    }
