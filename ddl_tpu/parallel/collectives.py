"""Device-side collectives: the TPU-native global shuffle.

This is the re-imagining of reference ``ddl/shuffle.py``'s MPI exchange
(``Sendrecv_replace`` between same-index producers across instances,
``shuffle.py:92-108``): the exchange block of every instance's window lives
dp-sharded in HBM, and one jitted ``shard_map`` moves the lanes along the
shared permutation with ``lax.ppermute`` — riding ICI/DCN, overlapping with
compute, with zero host involvement.  The ``all_to_all`` strategy (the
reference's never-finished second method, SURVEY Q8) redistributes the
exchange block uniformly across *all* instances in one collective.
"""

from __future__ import annotations

import functools
from typing import Any, Tuple

import numpy as np

from ddl_tpu.shuffle import (
    exchange_permutation,
    exchange_slices,
    inverse_permutation,
)


def _ppermute_pairs(p: np.ndarray) -> Tuple[Tuple[int, int], ...]:
    return tuple((int(i), int(pi)) for i, pi in enumerate(p))


@functools.lru_cache(maxsize=64)
def _build_sendrecv_step(
    mesh_key: Any, axis: str, num_exchange: int, perm: Tuple[int, ...]
):
    """Jitted window-shuffle step for one permutation (cached per perm)."""
    import jax
    from ddl_tpu._compat import shard_map
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = mesh_key.mesh
    p = np.array(perm)
    pinv = inverse_permutation(p)
    lane_a, lane_b = exchange_slices(num_exchange)

    def shard_fn(window: jax.Array) -> jax.Array:
        # window: (nData_per_instance, n_values) — this instance's shard.
        a = jax.lax.ppermute(window[lane_a], axis, _ppermute_pairs(p))
        b = jax.lax.ppermute(window[lane_b], axis, _ppermute_pairs(pinv))
        return jax.lax.concatenate(
            [a, b, window[lane_b.stop :]], dimension=0
        )

    fn = shard_map(
        shard_fn,
        mesh=mesh,
        in_specs=P(axis),
        out_specs=P(axis),
        check_vma=False,
    )
    spec = NamedSharding(mesh, P(axis))
    return jax.jit(fn, in_shardings=spec, out_shardings=spec)


@functools.lru_cache(maxsize=8)
def _build_all_to_all_step(mesh_key: Any, axis: str, num_exchange: int):
    """All-to-all strategy: every instance scatters its exchange block
    uniformly to all instances and gathers one sub-block from each."""
    import jax
    from ddl_tpu._compat import shard_map
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = mesh_key.mesh
    n = mesh.shape[axis]
    k = num_exchange - (num_exchange % n)  # rows divisible by n

    def shard_fn(window: jax.Array) -> jax.Array:
        block = window[:k].reshape(n, k // n, window.shape[1])
        mixed = jax.lax.all_to_all(
            block, axis, split_axis=0, concat_axis=0, tiled=False
        )
        return jax.lax.concatenate(
            [mixed.reshape(k, window.shape[1]), window[k:]], dimension=0
        )

    fn = shard_map(
        shard_fn, mesh=mesh, in_specs=P(axis), out_specs=P(axis),
        check_vma=False,
    )
    spec = NamedSharding(mesh, P(axis))
    return jax.jit(fn, in_shardings=spec, out_shardings=spec)


class _MeshKey:
    """Hashable wrapper so lru_cache can key on a Mesh."""

    def __init__(self, mesh: Any):
        self.mesh = mesh

    def __hash__(self) -> int:
        return hash((tuple(self.mesh.axis_names), self.mesh.devices.tobytes()))

    def __eq__(self, other: object) -> bool:
        return (
            isinstance(other, _MeshKey)
            and self.mesh.axis_names == other.mesh.axis_names
            and bool(np.all(self.mesh.devices == other.mesh.devices))
        )


class DeviceGlobalShuffler:
    """Per-round device-side global shuffle over a dp-sharded window.

    Usage: the trainer holds the global window as one dp-sharded array
    (instances × window rows).  Each round, ``shuffle(window)`` exchanges
    the lanes along a fresh shared permutation — the device analog of the
    producer-side loop in reference ``datapusher.py:152`` +
    ``shuffle.py:92-108``.
    """

    #: Fabric reach (see ddl_tpu.shuffle): XLA collectives ride ICI/DCN,
    #: the only host-spanning exchange — MULTIHOST handshakes key on this.
    span = "global"

    def __init__(
        self,
        mesh: Any,
        axis: str = "dp",
        num_exchange: int = 0,
        method: str = "sendrecv_replace",
        seed: int = 0,
    ):
        from ddl_tpu.shuffle import EXCHANGE_METHODS

        if method not in EXCHANGE_METHODS:
            raise NotImplementedError(
                f"method {method!r}; valid: {EXCHANGE_METHODS}"
            )
        self.mesh = mesh
        self.axis = axis
        self.num_exchange = num_exchange
        self.method = method
        self.seed = seed
        self._round = 0
        self._key = _MeshKey(mesh)

    @property
    def n_instances(self) -> int:
        return self.mesh.shape[self.axis]

    @property
    def exchange_round(self) -> int:
        """Completed exchange rounds (checkpoints read this)."""
        return self._round

    def rejoin(self, round_: int) -> None:
        """Re-enter the schedule at ``round_`` (checkpoint resume) — the
        same public re-entry hook the host-side shuffler exposes."""
        self._round = int(round_)

    def shuffle(self, window: Any) -> Any:
        """One exchange round; returns the window with lanes exchanged."""
        n = self.n_instances
        if n <= 1 or self.num_exchange < 2:
            return window
        if self.method == "all_to_all":
            step = _build_all_to_all_step(self._key, self.axis, self.num_exchange)
        else:
            perm = exchange_permutation(n, self.seed, self._round)
            step = _build_sendrecv_step(
                self._key, self.axis, self.num_exchange, tuple(int(x) for x in perm)
            )
        self._round += 1
        return step(window)

    def window_hook(self):
        """Adapter for ``Trainer.fit(window_stream=True, window_hook=)``.

        The trainer streams windows shaped ``(batches_per_window, batch,
        *features)`` sharded ``P(None, dp, ...)``; :meth:`shuffle` wants
        rows-leading ``P(dp)``.  The returned hook flattens to sample
        rows (batch-major, so contiguous dp blocks stay contiguous),
        reshardes, exchanges, and restores the window layout/sharding —
        making the device exchange a drop-in per-window transform for
        streamed training.  Runs OUTSIDE jit on concrete arrays; every
        op inside is jitted/XLA.

        NOTE for checkpoint/resume: the shuffler's round counter is
        state.  A resumed run must restore it (``LoaderCheckpoint.
        capture(loader, shuffler=...)`` / ``.apply``) or post-resume
        rounds replay the round-0 permutations.
        """
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        row_sh = NamedSharding(self.mesh, P(self.axis))

        def hook(win: Any) -> Any:
            bpw, batch = win.shape[0], win.shape[1]
            feat = win.shape[2:]
            win_sh = getattr(win, "sharding", None)
            rows = jnp.swapaxes(win, 0, 1).reshape(batch * bpw, -1)
            mixed = self.shuffle(jax.device_put(rows, row_sh))
            back = jnp.swapaxes(
                mixed.reshape((batch, bpw) + feat), 0, 1
            )
            return jax.device_put(back, win_sh) if win_sh else back

        # The hook carries its owner so Trainer.fit can checkpoint the
        # round counter whichever form the caller passes — the shuffler
        # itself or this adapter (previously the adapter shape silently
        # lost round state across resume, replaying round-0 permutations).
        hook.owner = self
        return hook
