"""Device-side collectives: the TPU-native global shuffle + quantized
gradient reduction.

This is the re-imagining of reference ``ddl/shuffle.py``'s MPI exchange
(``Sendrecv_replace`` between same-index producers across instances,
``shuffle.py:92-108``): the exchange block of every instance's window lives
dp-sharded in HBM, and one jitted ``shard_map`` moves the lanes along the
shared permutation with ``lax.ppermute`` — riding ICI/DCN, overlapping with
compute, with zero host involvement.  The ``all_to_all`` strategy (the
reference's never-finished second method, SURVEY Q8) redistributes the
exchange block uniformly across *all* instances in one collective.

The quantized-reduction half (:func:`quantize_blockwise` /
:func:`quantized_all_reduce`) is the wire format of the distributed
optimizer's gradient communication (EQuARX, arXiv:2506.17615): int8
payloads with one fp32 scale per ``block`` values, an optional
stochastic-rounding mode, and a two-phase all-reduce (int8
reduce-scatter → local fp32 accumulation → re-quantized int8
all-gather) for explicit-collective contexts
(``ddl_tpu.parallel.optimizer`` consumes the same quantizer for the
SPMD update gather).
"""

from __future__ import annotations

import functools
from typing import Any, Optional, Tuple

import numpy as np

from ddl_tpu.shuffle import (
    exchange_permutation,
    exchange_slices,
    inverse_permutation,
)


def _ppermute_pairs(p: np.ndarray) -> Tuple[Tuple[int, int], ...]:
    return tuple((int(i), int(pi)) for i, pi in enumerate(p))


@functools.lru_cache(maxsize=64)
def _build_sendrecv_step(
    mesh_key: Any, axis: str, num_exchange: int, perm: Tuple[int, ...]
):
    """Jitted window-shuffle step for one permutation (cached per perm)."""
    import jax
    from ddl_tpu._compat import shard_map
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = mesh_key.mesh
    p = np.array(perm)
    pinv = inverse_permutation(p)
    lane_a, lane_b = exchange_slices(num_exchange)

    def shard_fn(window: jax.Array) -> jax.Array:
        # window: (nData_per_instance, n_values) — this instance's shard.
        a = jax.lax.ppermute(window[lane_a], axis, _ppermute_pairs(p))
        b = jax.lax.ppermute(window[lane_b], axis, _ppermute_pairs(pinv))
        return jax.lax.concatenate(
            [a, b, window[lane_b.stop :]], dimension=0
        )

    fn = shard_map(
        shard_fn,
        mesh=mesh,
        in_specs=P(axis),
        out_specs=P(axis),
        check_vma=False,
    )
    spec = NamedSharding(mesh, P(axis))
    return jax.jit(fn, in_shardings=spec, out_shardings=spec)


@functools.lru_cache(maxsize=8)
def _build_all_to_all_step(mesh_key: Any, axis: str, num_exchange: int):
    """All-to-all strategy: every instance scatters its exchange block
    uniformly to all instances and gathers one sub-block from each."""
    import jax
    from ddl_tpu._compat import shard_map
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = mesh_key.mesh
    n = mesh.shape[axis]
    k = num_exchange - (num_exchange % n)  # rows divisible by n

    def shard_fn(window: jax.Array) -> jax.Array:
        block = window[:k].reshape(n, k // n, window.shape[1])
        mixed = jax.lax.all_to_all(
            block, axis, split_axis=0, concat_axis=0, tiled=False
        )
        return jax.lax.concatenate(
            [mixed.reshape(k, window.shape[1]), window[k:]], dimension=0
        )

    fn = shard_map(
        shard_fn, mesh=mesh, in_specs=P(axis), out_specs=P(axis),
        check_vma=False,
    )
    spec = NamedSharding(mesh, P(axis))
    return jax.jit(fn, in_shardings=spec, out_shardings=spec)


class _MeshKey:
    """Hashable wrapper so lru_cache can key on a Mesh."""

    def __init__(self, mesh: Any):
        self.mesh = mesh

    def __hash__(self) -> int:
        return hash((tuple(self.mesh.axis_names), self.mesh.devices.tobytes()))

    def __eq__(self, other: object) -> bool:
        return (
            isinstance(other, _MeshKey)
            and self.mesh.axis_names == other.mesh.axis_names
            and bool(np.all(self.mesh.devices == other.mesh.devices))
        )


class DeviceGlobalShuffler:
    """Per-round device-side global shuffle over a dp-sharded window.

    Usage: the trainer holds the global window as one dp-sharded array
    (instances × window rows).  Each round, ``shuffle(window)`` exchanges
    the lanes along a fresh shared permutation — the device analog of the
    producer-side loop in reference ``datapusher.py:152`` +
    ``shuffle.py:92-108``.
    """

    #: Fabric reach (see ddl_tpu.shuffle): XLA collectives ride ICI/DCN,
    #: the only host-spanning exchange — MULTIHOST handshakes key on this.
    span = "global"

    def __init__(
        self,
        mesh: Any,
        axis: str = "dp",
        num_exchange: int = 0,
        method: str = "sendrecv_replace",
        seed: int = 0,
    ):
        from ddl_tpu.shuffle import EXCHANGE_METHODS

        if method not in EXCHANGE_METHODS:
            raise NotImplementedError(
                f"method {method!r}; valid: {EXCHANGE_METHODS}"
            )
        self.mesh = mesh
        self.axis = axis
        self.num_exchange = num_exchange
        self.method = method
        self.seed = seed
        self._round = 0
        self._key = _MeshKey(mesh)

    @property
    def n_instances(self) -> int:
        return self.mesh.shape[self.axis]

    @property
    def exchange_round(self) -> int:
        """Completed exchange rounds (checkpoints read this)."""
        return self._round

    def rejoin(self, round_: int) -> None:
        """Re-enter the schedule at ``round_`` (checkpoint resume) — the
        same public re-entry hook the host-side shuffler exposes."""
        self._round = int(round_)

    def shuffle(self, window: Any) -> Any:
        """One exchange round; returns the window with lanes exchanged."""
        n = self.n_instances
        if n <= 1 or self.num_exchange < 2:
            return window
        if self.method == "all_to_all":
            step = _build_all_to_all_step(self._key, self.axis, self.num_exchange)
        else:
            perm = exchange_permutation(n, self.seed, self._round)
            step = _build_sendrecv_step(
                self._key, self.axis, self.num_exchange, tuple(int(x) for x in perm)
            )
        self._round += 1
        return step(window)

    def window_hook(self):
        """Adapter for ``Trainer.fit(window_stream=True, window_hook=)``.

        The trainer streams windows shaped ``(batches_per_window, batch,
        *features)`` sharded ``P(None, dp, ...)``; :meth:`shuffle` wants
        rows-leading ``P(dp)``.  The returned hook flattens to sample
        rows (batch-major, so contiguous dp blocks stay contiguous),
        reshardes, exchanges, and restores the window layout/sharding —
        making the device exchange a drop-in per-window transform for
        streamed training.  Runs OUTSIDE jit on concrete arrays; every
        op inside is jitted/XLA.

        NOTE for checkpoint/resume: the shuffler's round counter is
        state.  A resumed run must restore it (``LoaderCheckpoint.
        capture(loader, shuffler=...)`` / ``.apply``) or post-resume
        rounds replay the round-0 permutations.
        """
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        row_sh = NamedSharding(self.mesh, P(self.axis))

        def hook(win: Any) -> Any:
            bpw, batch = win.shape[0], win.shape[1]
            feat = win.shape[2:]
            win_sh = getattr(win, "sharding", None)
            rows = jnp.swapaxes(win, 0, 1).reshape(batch * bpw, -1)
            mixed = self.shuffle(jax.device_put(rows, row_sh))
            back = jnp.swapaxes(
                mixed.reshape((batch, bpw) + feat), 0, 1
            )
            return jax.device_put(back, win_sh) if win_sh else back

        # The hook carries its owner so Trainer.fit can checkpoint the
        # round counter whichever form the caller passes — the shuffler
        # itself or this adapter (previously the adapter shape silently
        # lost round state across resume, replaying round-0 permutations).
        hook.owner = self
        return hook


# -- quantized gradient communication (EQuARX wire format) -------------------
#
# Blockwise int8: one fp32 scale per ``block`` consecutive values along
# the LAST axis (leading axes untouched, so an array's dp/fsdp sharding
# survives quantization — with_sharding_constraint on the int8 payload
# is what makes the optimizer's update all-gather move 1/4 the bytes).
# ``q`` keeps the input's shape (int8), ``scales`` is
# ``x.shape[:-1] + (ceil(last/block),)`` fp32.

#: Default quantization granularity (values per fp32 scale).  256 keeps
#: the scale overhead at ~1.6% of the int8 payload while bounding the
#: per-block dynamic range loss (EQuARX uses the same order).
QUANT_BLOCK = 256


def block_scales(x: Any, block: int = QUANT_BLOCK) -> Any:
    """Per-block fp32 scales: ``max(|x|)/127`` over each ``block``-wide
    slice of the last axis (zero blocks get scale 1 so dequantize is
    exact there)."""
    import jax.numpy as jnp

    lead, last = x.shape[:-1], x.shape[-1]
    pad = (-last) % block
    xf = jnp.abs(x.astype(jnp.float32))
    if pad:
        xf = jnp.pad(xf, [(0, 0)] * len(lead) + [(0, pad)])
    s = jnp.max(xf.reshape(*lead, -1, block), axis=-1) / 127.0
    return jnp.where(s == 0.0, 1.0, s)


def _expand_scales(s: Any, last: int, block: int) -> Any:
    import jax.numpy as jnp

    return jnp.repeat(s, block, axis=-1)[..., :last]


def quantize_blockwise(
    x: Any,
    block: int = QUANT_BLOCK,
    stochastic: bool = False,
    key: Optional[Any] = None,
) -> Tuple[Any, Any]:
    """``x -> (q int8, scales fp32)`` with per-block scales.

    ``stochastic=True`` rounds ``floor(v + u)`` with ``u ~ U[0, 1)``
    drawn from ``key`` — unbiased in expectation (``E[q·s] = x``), the
    rounding mode that keeps long accumulation chains drift-free where
    round-to-nearest introduces a systematic bias.  Deterministic
    round-to-nearest otherwise.  Rank-0 inputs are the caller's problem
    (the optimizer tree walk passes scalars through unquantized).
    """
    import jax
    import jax.numpy as jnp

    if stochastic and key is None:
        raise ValueError("stochastic rounding requires an explicit key")
    s = block_scales(x, block)
    v = x.astype(jnp.float32) / _expand_scales(s, x.shape[-1], block)
    if stochastic:
        v = jnp.floor(v + jax.random.uniform(key, x.shape))
    else:
        v = jnp.round(v)
    q = jnp.clip(v, -127.0, 127.0).astype(jnp.int8)
    return q, s


def dequantize_blockwise(
    q: Any, scales: Any, dtype: Any, block: int = QUANT_BLOCK
) -> Any:
    """Inverse of :func:`quantize_blockwise` (up to rounding error)."""
    import jax.numpy as jnp

    out = q.astype(jnp.float32) * _expand_scales(
        scales, q.shape[-1], block
    )
    return out.astype(dtype)


def quantize_dequantize(
    x: Any,
    block: int = QUANT_BLOCK,
    stochastic: bool = False,
    key: Optional[Any] = None,
) -> Any:
    """Round-trip through the int8 wire format — the numerical effect a
    quantized collective applies to the values it moves."""
    q, s = quantize_blockwise(x, block, stochastic=stochastic, key=key)
    return dequantize_blockwise(q, s, x.dtype, block)


def quantized_bytes(shape: Any, block: int = QUANT_BLOCK) -> int:
    """Wire bytes of one quantized array: int8 payload + fp32 scales."""
    size = int(np.prod(shape)) if shape else 1
    last = int(shape[-1]) if shape else 1
    nblocks = -(-last // block)
    lead = size // max(last, 1)
    return size + 4 * lead * nblocks


def quantized_all_reduce(
    x: Any,
    axis_name: str,
    axis_size: int,
    block: int = QUANT_BLOCK,
    mean: bool = True,
    stochastic: bool = False,
    key: Optional[Any] = None,
) -> Any:
    """Two-phase quantized all-reduce for ``shard_map`` contexts.

    Each device quantizes its contribution and the collective moves ONLY
    int8 payloads + fp32 block scales: the flattened value splits into
    ``axis_size`` chunks, an int8 ``all_to_all`` reduce-scatters them
    (device *i* receives every peer's quantized chunk *i*), the chunk
    accumulates locally in fp32, re-quantizes, and an int8 ``all_gather``
    completes the reduction — the EQuARX two-phase structure, so the
    error model (quantize → sum → re-quantize) matches the paper's.
    Wire bytes per device ≈ ``2·(n-1)/n`` × the quantized payload vs the
    same factor × fp32 for ``lax.psum``: a ~3.9× cut at block=256.

    ``axis_size`` is explicit (static) because the chunk split must be
    shape-static under trace; pass ``mesh.shape[axis]``.  ``mean=True``
    divides by ``axis_size`` (the gradient-averaging convention).
    ``stochastic=True`` + ``key``: stochastic rounding on BOTH quantize
    phases (fold distinct data per phase yourself if you need
    independent draws; the second phase folds in a constant).
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    if axis_size < 1:
        raise ValueError(f"axis_size must be >= 1, got {axis_size}")
    shape, dtype = x.shape, x.dtype
    size = int(np.prod(shape)) if shape else 1
    flat = x.reshape((size,))
    pad = (-size) % (axis_size * block)
    if pad:
        flat = jnp.pad(flat, (0, pad))
    chunks = flat.reshape(axis_size, -1)  # (n, c): chunk i -> device i
    k1 = k2 = None
    if stochastic:
        k1, k2 = jax.random.split(key)
    q, s = quantize_blockwise(chunks, block, stochastic=stochastic, key=k1)
    if axis_size > 1:
        q = lax.all_to_all(q, axis_name, split_axis=0, concat_axis=0)
        s = lax.all_to_all(s, axis_name, split_axis=0, concat_axis=0)
    red = jnp.sum(
        q.astype(jnp.float32) * _expand_scales(s, q.shape[-1], block),
        axis=0,
    )
    if mean:
        red = red / axis_size
    q2, s2 = quantize_blockwise(
        red[None], block, stochastic=stochastic, key=k2
    )
    if axis_size > 1:
        q2 = lax.all_gather(q2[0], axis_name)  # (n, c): full vector back
        s2 = lax.all_gather(s2[0], axis_name)
    out = q2.astype(jnp.float32) * _expand_scales(s2, q2.shape[-1], block)
    return out.reshape((-1,))[:size].reshape(shape).astype(dtype)
