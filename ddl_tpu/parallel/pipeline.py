"""Pipeline (model) parallelism over a ``pp`` mesh axis.

Absent from the reference (SURVEY §2.3 lists PP as "—"); ddl_tpu implements
the TPU-idiomatic form: a GPipe microbatch schedule written as a single
``lax.scan`` under ``shard_map``, with activations hopping one ICI step per
tick via ``lax.ppermute``.  No host round trips, no per-stage programs —
one SPMD program where every device runs the same loop and the stage index
selects behaviour with ``where`` masks (compiler-friendly control flow, no
data-dependent branching).

Schedule (S stages, M microbatches, steps t = 0 .. S+M-2):

- stage 0 feeds microbatch t into the pipe while t < M,
- every stage applies its layer to the buffer it received,
- results hop to the next stage between ticks,
- stage S-1 emits microbatch t-S+1 for t >= S-1; outputs are returned to
  every device by a masked ``psum`` (valid only on the last stage before
  it).

The whole schedule is differentiable, so ``jax.grad`` through
``pipeline_apply`` yields the reverse schedule automatically — 1F1B-style
interleaving is left to XLA's scheduler rather than hand-written.

Stage parameters are user-stacked with a leading S axis sharded
``P("pp", ...)`` — at-rest storage holds only each device's own stage
(plus any fsdp/tp sharding of the trailing axes).  Inside the pipeline's
``shard_map`` each device needs its stage's weights IN FULL (``stage_fn``
is a plain local function), so trailing-axis shards are gathered at the
shard_map boundary each step.  The working-memory model, explicitly:
peak per-device weight bytes = params/S (own stage, full) + one
microbatch's activations — pp divides weight WORKING memory by S;
fsdp/tp on the trailing axes divide at-rest STORAGE only.  The gather
moves each device's own stage once per step over ICI (params/S bytes),
amortised across all S+M-1 ticks; it is not a per-tick cost.
:func:`ddl_tpu.models.llama.forward_pp` documents the 8B-scale numbers.
"""

from __future__ import annotations

import functools
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P


def bubble_fraction(n_stages: int, n_microbatches: int) -> float:
    """GPipe idle fraction: of the ``S + M - 1`` schedule ticks each
    stage sees, ``S - 1`` are fill/drain bubble — the ideal against
    which measured pipeline efficiency is judged (``tools/probe_pp.py``
    measures the actual ratio; the ``lax.cond`` in the tick body makes
    bubble ticks cost a branch instead of a layer, so measured should
    approach this analytic floor from above)."""
    if n_stages < 1 or n_microbatches < 1:
        raise ValueError((n_stages, n_microbatches))
    return (n_stages - 1) / (n_stages + n_microbatches - 1)


def stack_stage_params(per_stage: list) -> Any:
    """Stack a list of per-stage param pytrees along a new leading axis."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *per_stage)


def stack_layer_stages(layers: list, n_stages: int) -> Any:
    """Regroup a model's per-layer param list into ``n_stages`` equal
    stages stacked as ``(S, L/S, ...)`` leaves — the layout
    :func:`pipeline_apply` schedules, with each stage's ``stage_fn``
    scanning its own ``L/S`` layers.  Shared by every uniform-block
    family (llama, vit): one regrouping implementation, not one per
    model."""
    L = len(layers)
    if n_stages < 1 or L % n_stages:
        raise ValueError(
            f"n_layers={L} must divide into n_stages={n_stages}"
        )
    per = L // n_stages
    # The (S, L/S) layout IS two applications of stack_stage_params:
    # layers stack within each stage, then stages stack on top.
    return stack_stage_params(
        [
            stack_stage_params(layers[s * per : (s + 1) * per])
            for s in range(n_stages)
        ]
    )


def stage_spec_tree(layer_spec: Any, axis: str = "pp") -> Any:
    """PartitionSpecs for a :func:`stack_layer_stages` stage tree: the
    ``pp`` axis shards stages, the per-stage layer axis is unsharded,
    trailing axes keep the model's per-layer layout.  The spec-side
    twin of :func:`stack_layer_stages` — one transform, not one per
    model family."""
    return jax.tree.map(
        lambda s: P(axis, None, *tuple(s)),
        layer_spec,
        is_leaf=lambda v: isinstance(v, P),
    )


def pipeline_spec(inner_spec_tree: Any, axis: str = "pp") -> Any:
    """Prepend the pipeline axis to every leaf spec of a stage param tree.

    Pass the same ``axis`` used in :func:`pipeline_apply`.
    """
    return jax.tree.map(
        lambda s: P(axis, *tuple(s)),
        inner_spec_tree,
        is_leaf=lambda x: isinstance(x, P),
    )


def _pipeline_shard(params_local: Any, x: Any, *, stage_fn, axis: str,
                    n_micro: int):
    """Per-device body (under shard_map over ``axis``).

    params_local leaves have leading dim 1 (this device's stage) and —
    with ``stage_param_specs`` — trailing dims still sharded (the
    stage_fn then owns the collectives over those axes); x is the
    full (M, mb, ...) microbatched activation PYTREE (a bare array in
    the common case), replicated over ``axis``.
    """
    S = lax.psum(1, axis)
    my_stage = lax.axis_index(axis)
    params_my = jax.tree.map(lambda p: p[0], params_local)
    fwd_perm = [(i, (i + 1) % S) for i in range(S)]

    def tick(carry, t):
        buf, outputs = carry
        # Stage 0 ingests microbatch t (clamped once the pipe is draining).
        feed = jax.tree.map(lambda a: a[jnp.minimum(t, n_micro - 1)], x)
        inp = jax.tree.map(
            lambda f, b: jnp.where(my_stage == 0, f, b), feed, buf
        )
        # Stage s holds real data only for ticks s <= t < s + M — outside
        # that window (pipe filling/draining) the buffer is garbage, and
        # running stage_fn on it was pure bubble FLOPs (VERDICT r2 Weak
        # #5).  A runtime cond skips the compute: each device evaluates its
        # own scalar predicate, so fill/drain ticks cost a branch, not a
        # layer.
        live = (t >= my_stage) & (t < my_stage + n_micro)
        y = lax.cond(
            live,
            lambda a: stage_fn(params_my, a),
            lambda a: jax.tree.map(jnp.zeros_like, a),
            inp,
        )
        # Last stage emits microbatch t-S+1 once the pipe is full.
        out_idx = t - (S - 1)
        valid = (my_stage == S - 1) & (out_idx >= 0)
        outputs = lax.cond(
            valid,
            lambda o: jax.tree.map(
                lambda acc, v: lax.dynamic_update_index_in_dim(
                    acc, v, jnp.maximum(out_idx, 0), 0
                ),
                o, y,
            ),
            lambda o: o,
            outputs,
        )
        buf = jax.tree.map(
            lambda v: lax.ppermute(v, axis, fwd_perm), y
        )
        return (buf, outputs), None

    buf0 = jax.tree.map(lambda a: jnp.zeros_like(a[0]), x)
    out0 = jax.tree.map(
        lambda a: jnp.zeros((n_micro,) + a.shape[1:], a.dtype), x
    )
    (_, outputs), _ = lax.scan(
        tick, (buf0, out0), jnp.arange(n_micro + S - 1)
    )
    # Outputs are populated only on the last stage; psum broadcasts them.
    return jax.tree.map(
        lambda o: lax.psum(
            jnp.where(my_stage == S - 1, o, jnp.zeros_like(o)), axis
        ),
        outputs,
    )


def pipeline_apply(
    stacked_params: Any,
    x: Any,
    stage_fn: Callable[[Any, Any], Any],
    mesh: Any,
    n_microbatches: int,
    axis: str = "pp",
    batch_spec: "P | None" = None,
    stage_param_specs: Any = None,
) -> Any:
    """Apply S pipelined stages to a batch x (B, ...).

    - ``stacked_params``: stage params stacked on a leading S axis (see
      :func:`stack_stage_params`), sharded ``P(axis, ...)``.
    - ``stage_fn(stage_params, x) -> y`` with y structurally identical
      to x (uniform inter-stage activations, the usual transformer-block
      case).  ``x`` may be a PYTREE whose leaves share the leading batch
      axis — stages can then carry side state with the activation (e.g.
      a per-row router-aux accumulator riding the MoE residual stream);
      every leaf hops the ``ppermute`` together.
    - Falls back to a sequential scan over stages when the mesh has no
      ``axis`` (or size 1) — same math, no pipelining.

    B must divide into ``n_microbatches``; ``batch_spec`` shards the
    (M, mb, ...) microbatched input.  Default (None): auto — microbatches
    are dp-sharded on their batch dimension when the mesh has a ``dp``
    axis that divides it (each pp group works on its own dp shard instead
    of replicating the whole batch, VERDICT r2 Weak #5); otherwise
    replicated.

    ``stage_param_specs`` (a PartitionSpec pytree matching ONE stage's
    params, without the leading S axis): keep those trailing axes
    SHARDED inside the shard_map instead of gathering them at the
    boundary — ``stage_fn`` then receives local shards and owns the
    collectives over the named axes (e.g. Megatron tensor parallelism
    with explicit ``lax.psum(.., "tp")`` at the block reduction points).
    Per-device weight working memory drops from params/S to
    params/(S·tp).  Default (None): trailing axes gather at the
    boundary, ``stage_fn`` is a plain local function.
    """
    S = jax.tree.leaves(stacked_params)[0].shape[0]
    B = jax.tree.leaves(x)[0].shape[0]
    assert B % n_microbatches == 0, (B, n_microbatches)
    mb = B // n_microbatches
    if batch_spec is None:
        batch_spec = (
            P(None, "dp")
            if "dp" in mesh.axis_names
            and mesh.shape["dp"] > 1
            and mb % mesh.shape["dp"] == 0
            else P()
        )
    xm = jax.tree.map(
        lambda a: a.reshape((n_microbatches, mb) + a.shape[1:]), x
    )

    if axis not in mesh.axis_names or mesh.shape[axis] == 1:
        if stage_param_specs is not None:
            raise ValueError(
                "stage_param_specs (tensor-parallel-resident stages) "
                f"requires a {axis!r} mesh axis: the sequential fallback "
                "runs stage_fn outside shard_map, where its named-axis "
                "collectives cannot resolve"
            )

        # Per-MICROBATCH like the pipelined path — for per-row stage
        # functions this is identical to one full-batch pass, but
        # batch-coupled stages (MoE routing capacity/slot competition)
        # must see the same token groups on every mesh shape, or runs
        # would not be comparable between a pp mesh and the fallback.
        def run_stages(state):
            out, _ = lax.scan(
                lambda h, p: (stage_fn(p, h), None), state, stacked_params
            )
            return out

        out = lax.map(run_stages, xm)
        return jax.tree.map(
            lambda o, orig: o.reshape(orig.shape), out, x
        )
    assert mesh.shape[axis] == S, (
        f"stacked params have {S} stages but mesh {axis}={mesh.shape[axis]}"
    )

    from ddl_tpu._compat import shard_map

    if stage_param_specs is None:
        param_specs = jax.tree.map(lambda _: P(axis), stacked_params)
    else:
        param_specs = jax.tree.map(
            lambda s: P(axis, *tuple(s)),
            stage_param_specs,
            is_leaf=lambda v: isinstance(v, P),
        )
    # One batch spec serves every activation leaf (they share the
    # (M, mb) leading axes; a P names only leading dims).
    batch_specs = jax.tree.map(lambda _: batch_spec, x)
    fn = shard_map(
        functools.partial(
            _pipeline_shard, stage_fn=stage_fn, axis=axis,
            n_micro=n_microbatches,
        ),
        mesh=mesh,
        in_specs=(param_specs, batch_specs),
        out_specs=batch_specs,
        check_vma=False,
    )
    out = fn(stacked_params, xm)
    return jax.tree.map(
        lambda o, orig: o.reshape(orig.shape), out, x
    )
