"""Pipeline (model) parallelism over a ``pp`` mesh axis.

Absent from the reference (SURVEY §2.3 lists PP as "—"); ddl_tpu implements
the TPU-idiomatic form: a GPipe microbatch schedule written as a single
``lax.scan`` under ``shard_map``, with activations hopping one ICI step per
tick via ``lax.ppermute``.  No host round trips, no per-stage programs —
one SPMD program where every device runs the same loop and the stage index
selects behaviour with ``where`` masks (compiler-friendly control flow, no
data-dependent branching).

Two schedules behind one ``schedule=`` knob:

- ``"gpipe"`` (default) — S stages, M microbatches, ticks
  t = 0 .. S+M-2: stage 0 feeds microbatch t into the pipe while t < M,
  every stage applies its layer to the buffer it received, results hop
  to the next stage between ticks, stage S-1 emits microbatch t-S+1 for
  t >= S-1.  Fill/drain idles ``S-1`` of the ``S+M-1`` ticks:
  bubble = (S-1)/(M+S-1).
- ``"1f1b"`` — the interleaved-stage (Megatron "virtual pipeline")
  schedule: each device hosts ``n_chunks`` NON-ADJACENT stage chunks
  (device d owns global stages c·S+d), and activations circle the same
  ``ppermute`` ring ``n_chunks`` times.  Devices reach full occupancy
  after only ``S-1`` chunk-ticks (each 1/n_chunks the work of a gpipe
  tick), so bubble = (S-1)/(n_chunks·M + S-1) — 0.273 vs gpipe's 0.429
  at S=4/M=4/n_chunks=2.  Requires ``M % S == 0`` (microbatch groups
  must pack the ring seamlessly) and params stacked with
  ``stack_layer_stages(..., n_chunks=)``.

Outputs are returned to every device by a masked ``psum`` (valid only on
the last stage before it).  Both schedules are differentiable, so
``jax.grad`` through ``pipeline_apply`` yields the reverse schedule
automatically — the forward/backward 1F1B interleave itself is left to
XLA's scheduler over the reversed scan; the chunked circular placement
is what buys the smaller fill/drain bubble.

Stage parameters are user-stacked with a leading S axis sharded
``P("pp", ...)`` — at-rest storage holds only each device's own stage
(plus any fsdp/tp sharding of the trailing axes).  Inside the pipeline's
``shard_map`` each device needs its stage's weights IN FULL (``stage_fn``
is a plain local function), so trailing-axis shards are gathered at the
shard_map boundary each step.  The working-memory model, explicitly:
peak per-device weight bytes = params/S (own stage, full) + one
microbatch's activations — pp divides weight WORKING memory by S;
fsdp/tp on the trailing axes divide at-rest STORAGE only.  The gather
moves each device's own stage once per step over ICI (params/S bytes),
amortised across all S+M-1 ticks; it is not a per-tick cost.
:func:`ddl_tpu.models.llama.forward_pp` documents the 8B-scale numbers.
"""

from __future__ import annotations

import functools
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P


#: Schedules :func:`pipeline_apply` implements (``bubble_fraction``
#: prices both analytically).
SCHEDULES = ("gpipe", "1f1b")


def _resolve_chunks(schedule: str, n_chunks: "int | None") -> int:
    """Stage chunks per device for a schedule (gpipe: always 1; 1f1b:
    caller's ``n_chunks``, default 2 — 1 would be a gpipe relabel)."""
    if schedule not in SCHEDULES:
        raise ValueError(
            f"unknown schedule {schedule!r} (want one of {SCHEDULES})"
        )
    if schedule == "gpipe":
        if n_chunks not in (None, 1):
            raise ValueError(
                f"schedule='gpipe' is single-chunk; got n_chunks={n_chunks}"
                " (use schedule='1f1b' for interleaved stage chunks)"
            )
        return 1
    v = 2 if n_chunks is None else int(n_chunks)
    if v < 1:
        raise ValueError(f"n_chunks must be >= 1, got {n_chunks}")
    return v


def bubble_fraction(
    n_stages: int,
    n_microbatches: int,
    schedule: str = "gpipe",
    n_chunks: "int | None" = None,
) -> float:
    """Analytic fill/drain idle fraction of a schedule — the ideal
    against which measured pipeline efficiency is judged
    (``tools/probe_pp.py`` measures the actual ratio; the ``lax.cond``
    in the tick body makes bubble ticks cost a branch instead of a
    layer, so measured should approach this floor from above).

    gpipe: ``(S-1)/(M+S-1)`` — of the ``S+M-1`` ticks each device
    sees, ``S-1`` are ramp.  1f1b (interleaved, ``v = n_chunks``): the
    ramp is still ``S-1`` chunk-ticks but each device now works
    ``v·M`` chunk-ticks, so ``(S-1)/(v·M+S-1)`` — at S=4, M=4, v=2
    that is 3/11 = 0.273 against gpipe's 3/7 = 0.429."""
    if n_stages < 1 or n_microbatches < 1:
        raise ValueError((n_stages, n_microbatches))
    v = _resolve_chunks(schedule, n_chunks)
    return (n_stages - 1) / (v * n_microbatches + n_stages - 1)


def stack_stage_params(per_stage: list) -> Any:
    """Stack a list of per-stage param pytrees along a new leading axis."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *per_stage)


def stack_layer_stages(
    layers: list, n_stages: int, n_chunks: int = 1
) -> Any:
    """Regroup a model's per-layer param list into the stacked layout
    :func:`pipeline_apply` schedules.  Shared by every uniform-block
    family (llama, moe, vit): one regrouping implementation, not one
    per model.

    ``n_chunks == 1`` (gpipe): ``n_stages`` equal CONSECUTIVE stages
    stacked as ``(S, L/S, ...)`` leaves, each stage's ``stage_fn``
    scanning its own ``L/S`` layers.

    ``n_chunks > 1`` (the 1f1b interleaved schedule): ``(S, V, L/(S·V),
    ...)`` leaves with the Megatron virtual-pipeline assignment —
    device ``d`` chunk ``c`` holds global stage ``c·S + d``, i.e.
    NON-ADJACENT layer groups, so activations visit every device once
    per ring lap."""
    L = len(layers)
    total = n_stages * n_chunks
    if n_stages < 1 or n_chunks < 1 or L % total:
        raise ValueError(
            f"n_layers={L} must divide into n_stages={n_stages} x "
            f"n_chunks={n_chunks}"
        )
    per = L // total

    def group(s: int) -> Any:
        return stack_stage_params(layers[s * per : (s + 1) * per])

    if n_chunks == 1:
        # The (S, L/S) layout IS two applications of stack_stage_params:
        # layers stack within each stage, then stages stack on top.
        return stack_stage_params([group(s) for s in range(n_stages)])
    return stack_stage_params(
        [
            stack_stage_params(
                [group(c * n_stages + d) for c in range(n_chunks)]
            )
            for d in range(n_stages)
        ]
    )


def stage_spec_tree(
    layer_spec: Any, axis: str = "pp", n_chunks: int = 1
) -> Any:
    """PartitionSpecs for a :func:`stack_layer_stages` stage tree: the
    ``pp`` axis shards stages, the chunk (1f1b only) and per-stage
    layer axes are unsharded, trailing axes keep the model's per-layer
    layout.  The spec-side twin of :func:`stack_layer_stages` — one
    transform, not one per model family."""
    lead = (None,) * (2 if n_chunks > 1 else 1)
    return jax.tree.map(
        lambda s: P(axis, *lead, *tuple(s)),
        layer_spec,
        is_leaf=lambda v: isinstance(v, P),
    )


def pipeline_spec(inner_spec_tree: Any, axis: str = "pp") -> Any:
    """Prepend the pipeline axis to every leaf spec of a stage param tree.

    Pass the same ``axis`` used in :func:`pipeline_apply`.
    """
    return jax.tree.map(
        lambda s: P(axis, *tuple(s)),
        inner_spec_tree,
        is_leaf=lambda x: isinstance(x, P),
    )


def _pipeline_shard(params_local: Any, x: Any, *, stage_fn, axis: str,
                    n_micro: int, n_chunks: int = 1):
    """Per-device body (under shard_map over ``axis``), both schedules.

    params_local leaves have leading dim 1 (this device's stage; a
    second ``n_chunks`` dim follows for 1f1b) and — with
    ``stage_param_specs`` — trailing dims still sharded (the stage_fn
    then owns the collectives over those axes); x is the full
    (M, mb, ...) microbatched activation PYTREE (a bare array in the
    common case), replicated over ``axis``.

    One unified tick body: a microbatch's JOURNEY is ``V·S`` stage
    hops (device = stage mod S, so every hop is the same +1 ring
    ``ppermute``, wrapping S-1 → 0 between chunk laps).  At tick ``t``
    this device's journey offset is ``q = t - d``; the unique live
    (chunk, microbatch) it hosts is ``c = (q mod V·S) // S`` and
    ``m = (q // V·S)·S + (q mod S)`` — with V=1 this degenerates to
    exactly the classic GPipe indexing (c == 0, m == q).
    """
    S = lax.psum(1, axis)
    my_stage = lax.axis_index(axis)
    params_my = jax.tree.map(lambda p: p[0], params_local)
    fwd_perm = [(i, (i + 1) % S) for i in range(S)]
    V = n_chunks
    span = V * S  # journey length in ticks (one full set of chunk laps)

    def tick(carry, t):
        buf, outputs = carry
        q = t - my_stage
        # This device has real work only for the Mv consecutive ticks
        # q in [0, M·V) — outside that window (pipe filling/draining)
        # the buffer is garbage, and running stage_fn on it was pure
        # bubble FLOPs (VERDICT r2 Weak #5).  A runtime cond skips the
        # compute: each device evaluates its own scalar predicate, so
        # fill/drain ticks cost a branch, not a layer.
        live = (q >= 0) & (q < n_micro * V)
        qc = jnp.clip(q, 0, n_micro * V - 1)
        chunk = (qc % span) // S
        m = (qc // span) * S + (qc % S)
        # Device 0 ingests microbatch m whenever the arriving journey
        # position is a chunk-0 stage (global stage 0) — which is also
        # what discards a finished microbatch wrapping past the last
        # stage on the 1f1b ring.
        feed = jax.tree.map(lambda a: a[m], x)
        ingest = (my_stage == 0) & (chunk == 0)
        inp = jax.tree.map(
            lambda f, b: jnp.where(ingest, f, b), feed, buf
        )
        if V == 1:
            params_tick = params_my
        else:
            # The live chunk's weights: a dynamic slice of the local
            # (V, L/(S·V), ...) stack — differentiable (gather fwd,
            # scatter-add in the reverse schedule).
            params_tick = jax.tree.map(
                lambda p: lax.dynamic_index_in_dim(
                    p, chunk, 0, keepdims=False
                ),
                params_my,
            )
        y = lax.cond(
            live,
            lambda a: stage_fn(params_tick, a),
            lambda a: jax.tree.map(jnp.zeros_like, a),
            inp,
        )
        # The last device emits microbatch m after its final chunk
        # (global stage V·S - 1).
        valid = live & (my_stage == S - 1) & (chunk == V - 1)
        outputs = lax.cond(
            valid,
            lambda o: jax.tree.map(
                lambda acc, v: lax.dynamic_update_index_in_dim(
                    acc, v, m, 0
                ),
                o, y,
            ),
            lambda o: o,
            outputs,
        )
        buf = jax.tree.map(
            lambda v: lax.ppermute(v, axis, fwd_perm), y
        )
        return (buf, outputs), None

    buf0 = jax.tree.map(lambda a: jnp.zeros_like(a[0]), x)
    out0 = jax.tree.map(
        lambda a: jnp.zeros((n_micro,) + a.shape[1:], a.dtype), x
    )
    (_, outputs), _ = lax.scan(
        tick, (buf0, out0), jnp.arange(n_micro * V + S - 1)
    )
    # Outputs are populated only on the last stage; psum broadcasts them.
    return jax.tree.map(
        lambda o: lax.psum(
            jnp.where(my_stage == S - 1, o, jnp.zeros_like(o)), axis
        ),
        outputs,
    )


def pipeline_apply(
    stacked_params: Any,
    x: Any,
    stage_fn: Callable[[Any, Any], Any],
    mesh: Any,
    n_microbatches: int,
    axis: str = "pp",
    batch_spec: "P | None" = None,
    stage_param_specs: Any = None,
    schedule: str = "gpipe",
    n_chunks: "int | None" = None,
) -> Any:
    """Apply S pipelined stages to a batch x (B, ...).

    - ``schedule``: ``"gpipe"`` (default) or ``"1f1b"`` — the
      interleaved-stage schedule with ``n_chunks`` (default 2) stage
      chunks per device, cutting the fill/drain bubble from
      ``(S-1)/(M+S-1)`` to ``(S-1)/(n_chunks·M+S-1)`` (see
      :func:`bubble_fraction`).  1f1b requires ``n_microbatches % S ==
      0`` and params stacked via ``stack_layer_stages(...,
      n_chunks=)`` (leaves ``(S, n_chunks, L/(S·n_chunks), ...)``);
      outputs and gradients are bit-for-bit the same function as gpipe
      at identical (S·n_chunks total stages, M) — only the device
      placement and tick order change.
    - ``stacked_params``: stage params stacked on a leading S axis (see
      :func:`stack_stage_params`), sharded ``P(axis, ...)``.
    - ``stage_fn(stage_params, x) -> y`` with y structurally identical
      to x (uniform inter-stage activations, the usual transformer-block
      case).  ``x`` may be a PYTREE whose leaves share the leading batch
      axis — stages can then carry side state with the activation (e.g.
      a per-row router-aux accumulator riding the MoE residual stream);
      every leaf hops the ``ppermute`` together.
    - Falls back to a sequential scan over stages when the mesh has no
      ``axis`` (or size 1) — same math, no pipelining.

    B must divide into ``n_microbatches``; ``batch_spec`` shards the
    (M, mb, ...) microbatched input.  Default (None): auto — microbatches
    are dp-sharded on their batch dimension when the mesh has a ``dp``
    axis that divides it (each pp group works on its own dp shard instead
    of replicating the whole batch, VERDICT r2 Weak #5); otherwise
    replicated.

    ``stage_param_specs`` (a PartitionSpec pytree matching ONE stage's
    params, without the leading S axis): keep those trailing axes
    SHARDED inside the shard_map instead of gathering them at the
    boundary — ``stage_fn`` then receives local shards and owns the
    collectives over the named axes (e.g. Megatron tensor parallelism
    with explicit ``lax.psum(.., "tp")`` at the block reduction points).
    Per-device weight working memory drops from params/S to
    params/(S·tp).  Default (None): trailing axes gather at the
    boundary, ``stage_fn`` is a plain local function.
    """
    V = _resolve_chunks(schedule, n_chunks)
    S = jax.tree.leaves(stacked_params)[0].shape[0]
    B = jax.tree.leaves(x)[0].shape[0]
    assert B % n_microbatches == 0, (B, n_microbatches)
    if V > 1:
        if n_microbatches % S:
            raise ValueError(
                f"schedule='1f1b' needs n_microbatches ({n_microbatches}) "
                f"divisible by n_stages ({S}): microbatch groups of S "
                "pack the chunk laps seamlessly"
            )
        bad = [
            leaf.shape
            for leaf in jax.tree.leaves(stacked_params)
            if leaf.ndim < 2 or leaf.shape[1] != V
        ]
        if bad:
            raise ValueError(
                f"schedule='1f1b' with n_chunks={V} expects stage leaves "
                f"shaped (S, {V}, ...) — stack with "
                f"stack_layer_stages(layers, n_stages, n_chunks={V}); got "
                f"leading shapes {bad[:3]}"
            )
        # NB this check is necessary, not sufficient: a gpipe stack with
        # L/S == n_chunks is shape-INDISTINGUISHABLE from a chunked one
        # (its layer axis would be misread as the chunk axis and layers
        # would apply in the wrong global order).  The layout contract —
        # stack with the same n_chunks you schedule with — is the
        # caller's; the model-side stage_params(n_chunks=) helpers keep
        # the two knobs adjacent for exactly this reason.
    mb = B // n_microbatches
    if batch_spec is None:
        batch_spec = (
            P(None, "dp")
            if "dp" in mesh.axis_names
            and mesh.shape["dp"] > 1
            and mb % mesh.shape["dp"] == 0
            else P()
        )
    xm = jax.tree.map(
        lambda a: a.reshape((n_microbatches, mb) + a.shape[1:]), x
    )

    if axis not in mesh.axis_names or mesh.shape[axis] == 1:
        if stage_param_specs is not None:
            raise ValueError(
                "stage_param_specs (tensor-parallel-resident stages) "
                f"requires a {axis!r} mesh axis: the sequential fallback "
                "runs stage_fn outside shard_map, where its named-axis "
                "collectives cannot resolve"
            )

        # Per-MICROBATCH like the pipelined path — for per-row stage
        # functions this is identical to one full-batch pass, but
        # batch-coupled stages (MoE routing capacity/slot competition)
        # must see the same token groups on every mesh shape, or runs
        # would not be comparable between a pp mesh and the fallback.
        # The 1f1b chunk layout flattens back to global stage order
        # (stage c·S+d lives at [d, c], so (S, V) transposes to (V, S)
        # before the merge).
        seq_params = (
            stacked_params
            if V == 1
            else jax.tree.map(
                lambda a: jnp.swapaxes(a, 0, 1).reshape(
                    (a.shape[0] * a.shape[1],) + a.shape[2:]
                ),
                stacked_params,
            )
        )

        def run_stages(state):
            out, _ = lax.scan(
                lambda h, p: (stage_fn(p, h), None), state, seq_params
            )
            return out

        out = lax.map(run_stages, xm)
        return jax.tree.map(
            lambda o, orig: o.reshape(orig.shape), out, x
        )
    assert mesh.shape[axis] == S, (
        f"stacked params have {S} stages but mesh {axis}={mesh.shape[axis]}"
    )

    from ddl_tpu._compat import shard_map
    from ddl_tpu.observability import metrics as _default_metrics

    # Schedule observability (trace-time, once per compile): the
    # analytic bubble of the schedule that actually lowered, surfaced
    # through north_star_report / the bench JSON as pp.* gauges.
    _default_metrics().set_gauge(
        "pp.bubble",
        bubble_fraction(S, n_microbatches, schedule=schedule, n_chunks=V),
    )
    _default_metrics().set_gauge("pp.chunks", float(V))

    if stage_param_specs is None:
        param_specs = jax.tree.map(lambda _: P(axis), stacked_params)
    else:
        chunk_lead = (None,) if V > 1 else ()
        param_specs = jax.tree.map(
            lambda s: P(axis, *chunk_lead, *tuple(s)),
            stage_param_specs,
            is_leaf=lambda v: isinstance(v, P),
        )
    # One batch spec serves every activation leaf (they share the
    # (M, mb) leading axes; a P names only leading dims).
    batch_specs = jax.tree.map(lambda _: batch_spec, x)
    fn = shard_map(
        functools.partial(
            _pipeline_shard, stage_fn=stage_fn, axis=axis,
            n_micro=n_microbatches, n_chunks=V,
        ),
        mesh=mesh,
        in_specs=(param_specs, batch_specs),
        out_specs=batch_specs,
        check_vma=False,
    )
    out = fn(stacked_params, xm)
    return jax.tree.map(
        lambda o, orig: o.reshape(orig.shape), out, x
    )
