"""Parallelism layer: meshes, collectives, sharded training utilities.

TPU-native re-design of the reference's MPI topology + exchange machinery
(SURVEY §2.3): data parallelism and the global-shuffle peer group become
mesh axes; collectives are XLA ops inserted by ``shard_map``/``pjit``.
"""

from ddl_tpu.parallel.collectives import (
    DeviceGlobalShuffler,
    quantized_all_reduce,
)
from ddl_tpu.parallel.mesh import data_parallel_mesh, make_mesh
from ddl_tpu.parallel.optimizer import ShardedOptimizer, hbm_accounting
from ddl_tpu.parallel.pipeline import (
    bubble_fraction,
    pipeline_apply,
    pipeline_spec,
    stack_stage_params,
)

__all__ = [
    "DeviceGlobalShuffler",
    "ShardedOptimizer",
    "bubble_fraction",
    "data_parallel_mesh",
    "hbm_accounting",
    "make_mesh",
    "pipeline_apply",
    "pipeline_spec",
    "quantized_all_reduce",
    "stack_stage_params",
]
