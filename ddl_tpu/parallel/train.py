"""Sharded training steps: the consumer-side compute fed by the loader.

The reference delegated gradient data-parallelism to user-initialised
``torch.distributed`` DDP outside the library (reference
``tests/run_ddl.py:199-200``, SURVEY §2.3); the TPU-native equivalent is a
jitted train step with NamedSharding annotations — GSPMD inserts the psum
for dp-replicated gradients, the all-gathers for fsdp-sharded params, and
the tp collectives, all riding ICI.
"""

from __future__ import annotations

import dataclasses
import functools
import logging
from typing import Any, Callable, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

logger = logging.getLogger(__name__)


def _named(mesh: Any, spec_tree: Any) -> Any:
    """Map a PartitionSpec pytree to NamedShardings, dropping axes the mesh
    doesn't have (so one spec tree serves dp-only and dp×fsdp×tp meshes)."""

    def fix(spec: P) -> NamedSharding:
        parts = []
        for entry in spec:
            if entry is None:
                parts.append(None)
            elif isinstance(entry, (tuple, list)):
                kept = tuple(a for a in entry if a in mesh.axis_names)
                parts.append(kept if kept else None)
            else:
                parts.append(entry if entry in mesh.axis_names else None)
        return NamedSharding(mesh, P(*parts))

    return jax.tree.map(
        fix, spec_tree, is_leaf=lambda x: isinstance(x, P)
    )


def _prune_indivisible(sh: NamedSharding, x: Any) -> NamedSharding:
    """Drop spec axes whose mesh size doesn't divide the array dimension
    (e.g. 2 experts on an ep=8 mesh) — the leaf degrades to replicated on
    that dimension instead of failing sharding validation."""
    mesh = sh.mesh
    if len(tuple(sh.spec)) > np.ndim(x):
        raise ValueError(
            f"param spec {sh.spec} has more entries than array rank "
            f"{np.ndim(x)} (shape {np.shape(x)})"
        )
    parts = []
    for dim_size, entry in zip(
        np.shape(x), tuple(sh.spec) + (None,) * len(np.shape(x))
    ):
        axes = (entry,) if isinstance(entry, str) else tuple(entry or ())
        n = int(np.prod([mesh.shape[a] for a in axes])) if axes else 1
        keep = n > 1 and dim_size % n == 0
        if axes and n > 1 and not keep:
            logger.warning(
                "param spec axis %r (size %d) does not divide dim %d of "
                "shape %s — that dimension degrades to REPLICATED (memory "
                "cost: full copy per device group)",
                entry, n, dim_size, np.shape(x),
            )
        parts.append(entry if keep else None)
    return NamedSharding(mesh, P(*parts))


@dataclasses.dataclass
class TrainState:
    params: Any
    opt_state: Any
    step: int = 0


#: Valid ``optimizer_sharding`` values for the step factories.
OPTIMIZER_SHARDING = ("none", "zero1")


def _maybe_shard_optimizer(
    optimizer: Any,
    mesh: Any,
    param_spec_tree: Any,
    optimizer_sharding: str,
    grad_comm: str,
    stochastic_rounding: bool,
    grad_comm_block: int,
) -> Any:
    """Wrap ``optimizer`` in the distributed-optimizer subsystem when the
    config asks for it (``ddl_tpu.parallel.optimizer``): ``"zero1"``
    shards state + weight update over dp; ``grad_comm="int8"`` alone
    applies only the quantized wire format.  An already-wrapped
    ShardedOptimizer passes through untouched (make_multistep wraps once
    and reuses the instance for its inner make_train_step)."""
    from ddl_tpu.parallel.optimizer import ShardedOptimizer

    if optimizer_sharding not in OPTIMIZER_SHARDING:
        raise ValueError(
            f"optimizer_sharding must be one of {OPTIMIZER_SHARDING}, "
            f"got {optimizer_sharding!r}"
        )
    if isinstance(optimizer, ShardedOptimizer):
        return optimizer
    if optimizer_sharding == "none" and grad_comm == "fp32":
        return optimizer
    return ShardedOptimizer(
        optimizer,
        mesh,
        param_spec_tree,
        axis="dp" if optimizer_sharding == "zero1" else None,
        grad_comm=grad_comm,
        stochastic_rounding=stochastic_rounding,
        block=grad_comm_block or None,
    )


def _lead_extent(mesh: Any, batch_spec: P) -> int:
    """Mesh extent sharding the batch's LEADING axis (1 if unsharded)."""
    entry = tuple(batch_spec)[0] if tuple(batch_spec) else None
    axes = (entry,) if isinstance(entry, str) else tuple(entry or ())
    ext = 1
    for a in axes:
        if a in mesh.axis_names:
            ext *= mesh.shape[a]
    return ext


def _make_apply_step(loss_fn: Callable[..., jax.Array], optimizer: Any,
                     accum_steps: int = 1, lead_divisor: int = 1):
    """One loss/grad/update/apply step — shared by the single-step and
    multi-step (scan) factories so the update rule cannot diverge.

    ``accum_steps > 1``: gradient accumulation — the batch splits into
    ``accum_steps`` equal microbatches along the leading axis, grads
    average over a ``lax.scan``, and ONE optimizer update applies.  For
    a mean-reduction loss this is mathematically the full-batch step at
    1/``accum_steps`` of the activation memory (the standard trade when
    the global batch does not fit).
    """

    if accum_steps < 1:
        raise ValueError(f"accum_steps must be >= 1, got {accum_steps}")

    def _micro(b: Any) -> Any:
        if b.shape[0] % accum_steps:
            raise ValueError(
                f"batch leading dim {b.shape[0]} is not divisible by "
                f"accum_steps={accum_steps} (microbatches must be equal "
                "for exact accumulation)"
            )
        return b.reshape(
            (accum_steps, b.shape[0] // accum_steps) + b.shape[1:]
        )

    def _grads(params: Any, batch: Any):
        if accum_steps == 1:
            return jax.value_and_grad(loss_fn)(params, batch)

        lead = jax.tree.leaves(batch)[0].shape[0]
        if lead % accum_steps == 0 and (lead // accum_steps) % lead_divisor:
            # Not incorrect, but the dp split silently degrades: GSPMD
            # pads/reshards each microbatch inside the scan.  (Checked once
            # per trace, not per batch leaf.)
            logger.warning(
                "gradient accumulation: microbatch size %d is not "
                "divisible by the batch-sharding extent %d — per-"
                "microbatch data parallelism degrades to padding/"
                "resharding", lead // accum_steps, lead_divisor,
            )
        micro = jax.tree.map(_micro, batch)

        # Accumulate in fp32 regardless of the params dtype: with bf16
        # params, summing accum_steps bf16 grads rounds at every add and
        # the "mathematically the full-batch step" equivalence degrades.
        # Grads cast back to the param dtype after the 1/accum_steps scale
        # so the optimizer sees the same dtypes as the unaccumulated path.
        def acc_dtype(p: Any) -> Any:
            d = jnp.result_type(p)
            return jnp.float32 if jnp.issubdtype(d, jnp.inexact) else d

        def body(carry, mb):
            loss_acc, grads_acc = carry
            loss, grads = jax.value_and_grad(loss_fn)(params, mb)
            return (
                loss_acc + loss,
                jax.tree.map(
                    lambda a, g: a + g.astype(a.dtype), grads_acc, grads
                ),
            ), None

        zeros = jax.tree.map(
            lambda p: jnp.zeros(p.shape, acc_dtype(p)), params
        )
        (loss_sum, grads_sum), _ = jax.lax.scan(
            body, (jnp.zeros((), jnp.float32), zeros), micro
        )
        inv = 1.0 / accum_steps
        return loss_sum * inv, jax.tree.map(
            lambda g, p: (g * inv).astype(jnp.result_type(p)), grads_sum,
            params,
        )

    def apply_step(params: Any, opt_state: Any, batch: Any):
        import optax

        loss, grads = _grads(params, batch)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return apply_step


def _reshard(batch: Any, sh: Any) -> Any:
    # device_put reshards device-resident arrays on-device and uploads
    # host arrays — no host round trip in either case.
    return jax.tree.map(
        lambda b: b
        if isinstance(b, jax.Array) and b.sharding == sh
        else jax.device_put(b, sh),
        batch,
    )


def make_train_step(
    loss_fn: Callable[..., jax.Array],
    optimizer: Any,
    mesh: Any,
    param_spec_tree: Any,
    batch_spec: P = P(("dp",)),
    donate: bool = True,
    accum_steps: int = 1,
    optimizer_sharding: str = "none",
    grad_comm: str = "fp32",
    stochastic_rounding: bool = False,
    grad_comm_block: int = 0,
) -> Tuple[Callable[..., Any], Callable[..., TrainState]]:
    """Build (init_fn, step_fn) for a sharded training loop.

    - ``loss_fn(params, batch) -> scalar`` — pure; model/config closed over.
    - ``optimizer`` — an optax GradientTransformation.
    - ``param_spec_tree`` — PartitionSpecs matching the params pytree
      (axes absent from ``mesh`` are dropped, see :func:`_named`).
    - ``batch_spec`` — sharding of each batch leaf (default: dp over the
      leading axis; pass ``P(("dp",), "sp")`` for sequence-parallel token
      batches).
    - ``accum_steps`` — gradient accumulation: grads average over this
      many microbatches (leading-axis split) before ONE optimizer update
      (see :func:`_make_apply_step`); mathematically the full-batch step
      at a fraction of the activation memory.
    - ``optimizer_sharding`` — ``"zero1"`` shards the optimizer state and
      weight update over the dp axis (ZeRO-1;
      :class:`ddl_tpu.parallel.optimizer.ShardedOptimizer` — bit-exact
      vs replicated at fp32, ~dp× less state HBM); ``grad_comm="int8"``
      opts the gradient/update communication into the quantized wire
      format (gate with the loss-parity check; ``stochastic_rounding`` /
      ``grad_comm_block`` tune it).  All four mirror
      :class:`ddl_tpu.config.TrainConfig` fields.

    GSPMD derives every collective from these annotations; there is no
    hand-written psum anywhere.
    """
    optimizer = _maybe_shard_optimizer(
        optimizer, mesh, param_spec_tree, optimizer_sharding, grad_comm,
        stochastic_rounding, grad_comm_block,
    )
    param_sh = _named(mesh, param_spec_tree)
    batch_sh = _named(mesh, batch_spec)
    apply_step = _make_apply_step(
        loss_fn, optimizer, accum_steps, _lead_extent(mesh, batch_spec)
    )

    def init_fn(params: Any) -> TrainState:
        # Jitted identity, NOT device_put: device_put aliases buffers that
        # already live on a target device (e.g. replicated specs), and the
        # donated train step would then delete the caller's input tree.
        # A compiled copy guarantees fresh buffers the step may donate.
        sh = jax.tree.map(_prune_indivisible, param_sh, params)
        params = jax.jit(lambda t: t, out_shardings=sh)(params)
        # optax states are built leaf-wise from params (zeros_like etc.), so
        # moments inherit the param shardings — fsdp shards the optimizer
        # state for free (the ZeRO property).  Leaves NOT derived from
        # params (adam's scalar step count) come out pinned to one device;
        # reshard those to mesh-replicated so the whole state lives on one
        # device set (mixed sets break jit after checkpoint restore).
        opt_state = optimizer.init(params)
        replicated = NamedSharding(mesh, P())

        def on_mesh(x: Any) -> Any:
            sh = getattr(x, "sharding", None)
            if isinstance(sh, NamedSharding) and sh.mesh == mesh:
                return x
            return jax.device_put(x, replicated)

        opt_state = jax.tree.map(on_mesh, opt_state)
        return TrainState(params=params, opt_state=opt_state, step=0)

    donate_argnums = (0, 1) if donate else ()

    _step = functools.partial(jax.jit, donate_argnums=donate_argnums)(
        apply_step
    )

    def step_fn(state: TrainState, batch: Any) -> Tuple[TrainState, jax.Array]:
        batch = _reshard(batch, batch_sh)
        params, opt_state, loss = _step(state.params, state.opt_state, batch)
        return TrainState(params, opt_state, state.step + 1), loss

    return init_fn, step_fn


def make_multistep(
    loss_fn: Callable[..., jax.Array],
    optimizer: Any,
    mesh: Any,
    param_spec_tree: Any,
    batch_spec: P = P(("dp",)),
    n_steps: int = 8,
    donate: bool = True,
    accum_steps: int = 1,
    optimizer_sharding: str = "none",
    grad_comm: str = "fp32",
    stochastic_rounding: bool = False,
    grad_comm_block: int = 0,
) -> Tuple[Callable[..., Any], Callable[..., Tuple[TrainState, jax.Array]]]:
    """Like :func:`make_train_step`, but each call runs ``n_steps``
    optimizer steps chained in ONE jitted program (``lax.scan``).
    ``accum_steps`` applies per optimizer step, as in
    :func:`make_train_step`; the distributed-optimizer knobs
    (``optimizer_sharding`` / ``grad_comm`` / ``stochastic_rounding`` /
    ``grad_comm_block``) wrap the optimizer ONCE here and the wrapped
    instance serves both the init path and every scanned step.

    One dispatch per ``n_steps`` steps: on tunneled/async backends the
    per-call dispatch overhead (tens of ms through the axon tunnel)
    amortises away, and the steps are serialized by the params data
    dependence — so wall time per step is the true device time, which is
    also why the benchmark uses this for its timing (a python-loop
    measurement can under-report arbitrarily when ``block_until_ready``
    fails to cover the full async chain, the round-2 artifact).

    ``multi_step_fn(state, batch, per_step=False) -> (state,
    losses[n_steps])``; with ``per_step=True`` every batch leaf carries a
    leading ``n_steps`` axis (one batch per step), otherwise the single
    batch is reused by every step.
    """
    optimizer = _maybe_shard_optimizer(
        optimizer, mesh, param_spec_tree, optimizer_sharding, grad_comm,
        stochastic_rounding, grad_comm_block,
    )
    init_fn, _ = make_train_step(
        loss_fn, optimizer, mesh, param_spec_tree, batch_spec=batch_spec
    )
    apply_step = _make_apply_step(
        loss_fn, optimizer, accum_steps, _lead_extent(mesh, batch_spec)
    )
    batch_sh = _named(mesh, batch_spec)
    per_step_sh = _named(mesh, P(*((None,) + tuple(batch_spec))))

    @functools.partial(
        jax.jit,
        donate_argnums=(0, 1) if donate else (),
        static_argnums=(3,),
    )
    def _run(params: Any, opt_state: Any, batch: Any, per_step: bool):
        def body(carry, xs):
            params, opt_state = carry
            params, opt_state, loss = apply_step(
                params, opt_state, xs if per_step else batch
            )
            return (params, opt_state), loss

        xs = batch if per_step else None
        (params, opt_state), losses = jax.lax.scan(
            body, (params, opt_state), xs, length=n_steps
        )
        return params, opt_state, losses

    def multi_step_fn(state: TrainState, batch: Any, per_step: bool = False):
        batch = _reshard(batch, per_step_sh if per_step else batch_sh)
        params, opt_state, losses = _run(
            state.params, state.opt_state, batch, per_step
        )
        return TrainState(params, opt_state, state.step + n_steps), losses

    return init_fn, multi_step_fn
