"""Device mesh construction helpers.

The TPU-native replacement for the reference's communicator topology
(reference ``ddl/ddl_env.py:33-98``): where MPI split ``COMM_WORLD`` into
per-GPU blocks and cross-block "nth-pusher" rings, a TPU program lays out a
``jax.sharding.Mesh`` and lets XLA insert the collectives.  The mesh axes
used across ddl_tpu:

- ``dp``   — data parallel / loader instances (the analog of the
  reference's one-trainer-per-GPU blocks; the global-shuffle peer group,
  analog of ``comm_nth_pusher``, is this axis).
- ``fsdp`` — parameter sharding (ZeRO-style) for the model examples.
- ``tp``   — tensor parallel.
- ``sp``   — sequence/context parallel (ring attention).
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence

import numpy as np


def make_mesh(
    axes: Optional[Dict[str, int]] = None, devices: Optional[Sequence] = None
):
    """Build a Mesh with named axes; sizes must multiply to #devices.

    ``axes=None`` → a 1-axis ``dp`` mesh over every device.  An axis size
    of ``-1`` is inferred from the device count (like a reshape).
    """
    import jax
    from jax.sharding import Mesh

    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if axes is None:
        axes = {"dp": n}
    names = tuple(axes.keys())
    sizes = list(axes.values())
    if sizes.count(-1) > 1:
        raise ValueError("at most one axis size may be -1")
    known = int(np.prod([s for s in sizes if s != -1]))
    if -1 in sizes:
        if n % known:
            raise ValueError(f"{n} devices not divisible by {known}")
        sizes[sizes.index(-1)] = n // known
    if int(np.prod(sizes)) != n:
        raise ValueError(
            f"mesh axes {dict(zip(names, sizes))} need {int(np.prod(sizes))} "
            f"devices, have {n}"
        )
    arr = np.array(devices).reshape(sizes)
    return Mesh(arr, names)


def data_parallel_mesh(n: Optional[int] = None):
    """1-axis ``dp`` mesh over the first n (default: all) devices."""
    import jax

    devices = jax.devices()[: n or None]
    return make_mesh({"dp": len(devices)}, devices)
