"""Ring attention: sequence-parallel attention over the ``sp`` mesh axis.

Long-context support is first-class in ddl_tpu (the reference had no
attention at all — SURVEY §5.7 notes its only ring was the data-plane
``Sendrecv_replace`` exchange).  The design follows the public ring
attention recipe (Liu et al., blockwise attention with online softmax):

- The sequence is sharded across ``sp``: each device holds Q/K/V for its
  local block of tokens.
- K/V blocks rotate around the ring with ``lax.ppermute`` (one ICI hop per
  step) while each device accumulates its queries' attention over every
  block with a numerically stable running max / denominator — so the full
  T×T score matrix never materialises and memory stays O(T_local²).
- Causal masking uses global token positions, so the result is bit-for-bit
  the same attention as the single-device computation.
"""

from __future__ import annotations

import functools
import logging
from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax import lax

_NEG_INF = -1e30
logger = logging.getLogger(__name__)
_warned_replicated: set = set()  # one replicated-fallback warning per geometry


def _block_attend(q, k, v, q_pos, k_pos, causal: bool, scale: float,
                  kv_repeat: int = 1, seg_q=None, seg_k=None):
    """Scores and weighted values of one (Q-block, KV-block) pair.

    Returns (o_partial, row_max, row_sum) for online-softmax accumulation.
    q: (B, Tq, H, D); k/v: (B, Tk, H/kv_repeat, D); positions: (Tq,), (Tk,).
    GQA heads are expanded here, locally — the ring rotates the compact
    K/V, so ICI traffic stays 1/kv_repeat of the naive pre-expanded form.
    ``seg_q``/``seg_k`` (B, Tq)/(B, Tk): packed-sequence masking.
    """
    if kv_repeat > 1:
        k = jnp.repeat(k, kv_repeat, axis=2)
        v = jnp.repeat(v, kv_repeat, axis=2)
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if causal:
        mask = k_pos[None, None, None, :] > q_pos[None, None, :, None]
        s = jnp.where(mask, _NEG_INF, s)
    if seg_q is not None:
        segmask = seg_q[:, None, :, None] != seg_k[:, None, None, :]
        s = jnp.where(segmask, _NEG_INF, s)
    m = jnp.max(s, axis=-1)  # (B, H, Tq); _NEG_INF for fully masked rows
    # Subtract a zeroed max for fully masked rows so exp() sees finite
    # arguments, and zero their probabilities — but RETURN the true max:
    # clamping the running max to 0 would underflow exp(s) later for rows
    # whose real scores are strongly negative.
    safe_m = jnp.where(m <= _NEG_INF / 2, 0.0, m)
    p = jnp.exp(s - safe_m[..., None])
    p = jnp.where(s <= _NEG_INF / 2, 0.0, p)
    o = jnp.einsum("bhqk,bkhd->bqhd", p, v)
    return o, m, jnp.sum(p, axis=-1)


def ring_attention_shard(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str,
    causal: bool = True,
    kv_repeat: int = 1,
    use_flash: bool = False,
    segment_ids: Optional[jax.Array] = None,
) -> jax.Array:
    """Per-shard ring attention body (call under ``shard_map``).

    Args are this device's sequence block: q (B, T_local, H, D) and
    compact GQA k/v (B, T_local, H/kv_repeat, D).  The compact K/V blocks
    circulate ``sp`` times (GQA expansion happens locally per block, so
    ring ICI traffic is 1/kv_repeat of the expanded size); accumulation is
    the flash-attention online softmax generalised across ring steps.

    With ``use_flash`` each ring step's local attend runs the Pallas flash
    kernel (global-position offsets passed in for causal masking — fully
    future blocks skip their matmuls in-kernel) and steps merge by the
    logsumexp identity; otherwise the attend is plain XLA einsums.

    ``segment_ids`` (B, T_local): packed-sequence masking.  The key-side
    ids rotate around the ring WITH their K/V blocks, so every step masks
    the local queries against the arriving block's true document ids.
    """
    sp = lax.psum(1, axis_name)
    my_idx = lax.axis_index(axis_name)
    B, T, H, D = q.shape
    perm = [(j, (j + 1) % sp) for j in range(sp)]
    packed = segment_ids is not None
    seg_k0 = segment_ids if packed else None

    if use_flash:
        from ddl_tpu.ops import flash_attention_with_lse

        def step(carry, i):
            o_acc, lse_acc, k_cur, v_cur, seg_k_cur = carry
            src = (my_idx - i) % sp
            o_blk, lse_blk = flash_attention_with_lse(
                q, k_cur, v_cur, q_offset=my_idx * T, k_offset=src * T,
                causal=causal, kv_repeat=kv_repeat,
                segment_ids=segment_ids, kv_segment_ids=seg_k_cur,
            )
            # Merge two normalized partials via logsumexp.  The sentinel
            # for empty rows is the finite _NEG_INF, so weights must be
            # explicitly zeroed there (exp of sentinel differences is NOT
            # negligible: exp(-1e30 - (-1e30 + log2)) = 0.5).
            lse_new = jnp.logaddexp(lse_acc, lse_blk)  # (B, H, T)
            safe = jnp.where(lse_new <= _NEG_INF / 2, 0.0, lse_new)
            w_a = jnp.where(
                lse_acc <= _NEG_INF / 2, 0.0, jnp.exp(lse_acc - safe)
            ).transpose(0, 2, 1)[..., None]  # (B, T, H, 1)
            w_b = jnp.where(
                lse_blk <= _NEG_INF / 2, 0.0, jnp.exp(lse_blk - safe)
            ).transpose(0, 2, 1)[..., None]
            o_new = o_acc * w_a + o_blk.astype(jnp.float32) * w_b
            k_next = lax.ppermute(k_cur, axis_name, perm)
            v_next = lax.ppermute(v_cur, axis_name, perm)
            seg_k_next = (
                lax.ppermute(seg_k_cur, axis_name, perm) if packed else None
            )
            return (o_new, lse_new, k_next, v_next, seg_k_next), None

        o0 = jnp.zeros(q.shape, jnp.float32)
        lse0 = jnp.full((B, H, T), _NEG_INF, jnp.float32)
        (o, _, _, _, _), _ = lax.scan(
            step, (o0, lse0, k, v, seg_k0), jnp.arange(sp)
        )
        return o.astype(q.dtype)

    scale = 1.0 / (D**0.5)
    q_pos = my_idx * T + jnp.arange(T)

    def step(carry, i):
        o_acc, m_acc, l_acc, k_cur, v_cur, seg_k_cur = carry
        # Block arriving at ring step i originated at (my_idx - i) mod sp.
        src = (my_idx - i) % sp
        k_pos = src * T + jnp.arange(T)
        o_blk, m_blk, l_blk = _block_attend(
            q, k_cur, v_cur, q_pos, k_pos, causal, scale, kv_repeat,
            seg_q=segment_ids, seg_k=seg_k_cur,
        )
        m_new = jnp.maximum(m_acc, m_blk)
        alpha = jnp.exp(m_acc - m_new)  # rescale old accumulator
        beta = jnp.exp(m_blk - m_new)  # rescale new block
        l_new = l_acc * alpha + l_blk * beta
        o_new = (
            o_acc * alpha.transpose(0, 2, 1)[..., None]
            + o_blk * beta.transpose(0, 2, 1)[..., None]
        )
        k_next = lax.ppermute(k_cur, axis_name, perm)
        v_next = lax.ppermute(v_cur, axis_name, perm)
        seg_k_next = (
            lax.ppermute(seg_k_cur, axis_name, perm) if packed else None
        )
        return (o_new, m_new, l_new, k_next, v_next, seg_k_next), None

    o0 = jnp.zeros_like(q)
    m0 = jnp.full((B, H, T), _NEG_INF, dtype=q.dtype)
    l0 = jnp.zeros((B, H, T), dtype=q.dtype)
    (o, m, l, _, _, _), _ = lax.scan(
        step, (o0, m0, l0, k, v, seg_k0), jnp.arange(sp)
    )
    l = jnp.maximum(l, 1e-30)
    return o / l.transpose(0, 2, 1)[..., None]


def sharded_local_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Any,
    causal: bool = True,
    kv_repeat: int = 1,
    use_flash: bool = False,
    dp_axis: str = "dp",
    tp_axis: str = "tp",
    segment_ids: Optional[jax.Array] = None,
) -> jax.Array:
    """Batch/head-sharded attention for meshes WITHOUT a sequence axis.

    Attention is independent across batch and heads, so on a dp/tp mesh each
    device can run the whole (local) attention with zero collectives — but
    only if the computation is explicitly shard_mapped; left to GSPMD, a
    Pallas kernel is an opaque custom call and XLA would gather its operands.
    Axes that don't divide the corresponding dimension stay unsharded.
    ``segment_ids`` (B, T): packed-sequence masking, batch-sharded like q.
    """
    from ddl_tpu._compat import shard_map
    from jax.sharding import PartitionSpec as P

    from ddl_tpu.ops import flash_attention

    def impl(q, k, v, seg):
        if use_flash:
            return flash_attention(q, k, v, causal=causal,
                                   kv_repeat=kv_repeat, segment_ids=seg)
        return attention_reference(q, k, v, causal=causal,
                                   kv_repeat=kv_repeat, segment_ids=seg)

    B, _, H, _ = q.shape
    Hkv = k.shape[2]
    bax = dp_axis if (
        dp_axis in mesh.axis_names
        and mesh.shape[dp_axis] > 1
        and B % mesh.shape[dp_axis] == 0
    ) else None
    hax = tp_axis if (
        tp_axis in mesh.axis_names
        and mesh.shape[tp_axis] > 1
        and H % mesh.shape[tp_axis] == 0
        and Hkv % mesh.shape[tp_axis] == 0
    ) else None
    if bax is None and hax is None:
        if mesh.size > 1:
            # Real sharding was requested and none applies — warn, once per
            # geometry (per-trace repetition was pure spam, VERDICT r2
            # Weak #4).  Single-device meshes are first-class (SURVEY Q9):
            # replicated-on-1-device is simply correct, debug only.
            key = (tuple(mesh.axis_names), tuple(mesh.devices.shape), B, H)
            if key not in _warned_replicated:
                _warned_replicated.add(key)
                logger.warning(
                    "sharded_local_attention: neither %r (batch %d) nor %r "
                    "(heads %d/%d) is a shardable mesh axis — attention "
                    "runs fully replicated on every device",
                    dp_axis, B, tp_axis, H, Hkv,
                )
        else:
            logger.debug(
                "sharded_local_attention: single-device mesh, local attention"
            )
        return impl(q, k, v, segment_ids)
    spec = P(bax, None, hax, None)
    seg_spec = P(bax, None)
    if segment_ids is None:
        return shard_map(
            lambda q, k, v: impl(q, k, v, None), mesh=mesh,
            in_specs=(spec, spec, spec), out_specs=spec, check_vma=False,
        )(q, k, v)
    return shard_map(
        impl, mesh=mesh, in_specs=(spec, spec, spec, seg_spec),
        out_specs=spec, check_vma=False,
    )(q, k, v, segment_ids)


def attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Optional[Any] = None,
    impl: str = "auto",
    causal: bool = True,
    kv_repeat: int = 1,
    axis: str = "sp",
    dp_axis: str = "dp",
    tp_axis: str = "tp",
    segment_ids: Optional[jax.Array] = None,
) -> jax.Array:
    """The single attention dispatcher — one source of truth for impl/mesh
    routing (models call this, not the individual strategies):

    - mesh with a >1-sized ``axis`` (sp) → ring attention over ICI,
    - any other mesh → batch/head-shard_mapped local attention,
    - no mesh → plain single-device attention;
    - ``impl``: "flash" / "dense" force the local kernel; "auto" uses the
      Pallas flash kernel on TPU backends and dense XLA elsewhere.
    - ``segment_ids`` (B, T): packed-sequence masking on every strategy
      (on the ring path the key-side ids rotate with their K/V blocks).
    """
    if impl not in ("auto", "flash", "dense"):
        raise ValueError(
            f"impl must be 'auto', 'flash', or 'dense', got {impl!r}"
        )
    use_flash = impl == "flash" or (
        impl == "auto" and jax.default_backend() == "tpu"
    )
    if mesh is not None and axis in mesh.axis_names and mesh.shape[axis] > 1:
        return ring_attention(
            q, k, v, mesh, causal=causal, axis=axis, dp_axis=dp_axis,
            kv_repeat=kv_repeat, use_flash=use_flash,
            segment_ids=segment_ids,
        )
    if mesh is not None:
        return sharded_local_attention(
            q, k, v, mesh, causal=causal, kv_repeat=kv_repeat,
            use_flash=use_flash, dp_axis=dp_axis, tp_axis=tp_axis,
            segment_ids=segment_ids,
        )
    if use_flash:
        from ddl_tpu.ops import flash_attention

        return flash_attention(q, k, v, causal=causal, kv_repeat=kv_repeat,
                               segment_ids=segment_ids)
    return attention_reference(q, k, v, causal=causal, kv_repeat=kv_repeat,
                               segment_ids=segment_ids)


@functools.partial(jax.jit, static_argnames=("causal", "kv_repeat"))
def attention_reference(q, k, v, causal: bool = True, kv_repeat: int = 1,
                        segment_ids=None):
    """Single-device full attention — the correctness oracle for tests.

    ``segment_ids`` (B, T): packed-sequence masking, tokens attend only
    within their own segment (matching ``ops.flash_attention``).
    """
    if kv_repeat > 1:
        k = jnp.repeat(k, kv_repeat, axis=2)
        v = jnp.repeat(v, kv_repeat, axis=2)
    B, T, H, D = q.shape
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) / (D**0.5)
    if causal:
        mask = jnp.arange(T)[None, :] > jnp.arange(T)[:, None]
        s = jnp.where(mask[None, None], _NEG_INF, s)
    if segment_ids is not None:
        seg = jnp.asarray(segment_ids)
        segmask = seg[:, :, None] != seg[:, None, :]  # (B, Tq, Tk)
        s = jnp.where(segmask[:, None], _NEG_INF, s)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Any,
    causal: bool = True,
    axis: str = "sp",
    dp_axis: Optional[str] = "dp",
    kv_repeat: int = 1,
    use_flash: bool = False,
    segment_ids: Optional[jax.Array] = None,
) -> jax.Array:
    """Sequence-parallel attention over global arrays.

    q: (B, T, H, D), k/v: (B, T, H/kv_repeat, D) logically global; B
    sharded over ``dp_axis`` (if present in the mesh), T sharded over
    ``axis``.  Falls back to the dense reference when the mesh has no
    ``axis`` or it has size 1.  ``segment_ids`` (B, T): packed-sequence
    masking; the key-side ids ride the ring with their K/V blocks.
    """
    from ddl_tpu._compat import shard_map
    from jax.sharding import PartitionSpec as P

    if axis not in mesh.axis_names or mesh.shape[axis] == 1:
        return attention_reference(q, k, v, causal=causal,
                                   kv_repeat=kv_repeat,
                                   segment_ids=segment_ids)
    batch_axis = dp_axis if (dp_axis and dp_axis in mesh.axis_names) else None
    spec = P(batch_axis, axis, None, None)
    seg_spec = P(batch_axis, axis)
    body = functools.partial(
        ring_attention_shard,
        axis_name=axis,
        causal=causal,
        kv_repeat=kv_repeat,
        use_flash=use_flash,
    )
    if segment_ids is None:
        fn = shard_map(
            body, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
            check_vma=False,
        )
        return fn(q, k, v)
    fn = shard_map(
        lambda q, k, v, seg: body(q, k, v, segment_ids=seg),
        mesh=mesh, in_specs=(spec, spec, spec, seg_spec), out_specs=spec,
        check_vma=False,
    )
    return fn(q, k, v, segment_ids)
