"""The machine-checked ``DDL_TPU_*`` environment-knob registry.

Every environment variable the framework reads is declared here — name,
type, default, export group, and a one-line doc — and every read in
``ddl_tpu/`` resolves through the typed accessors (:func:`raw`,
:func:`get`, :func:`flag`).  ``tools/ddl_verify`` pass **VP003**
enforces the contract statically: an undeclared read, a raw
``os.environ`` read bypassing the accessors, a spawn-boundary export
function missing one of its group's knobs, or a registered knob nothing
reads are all findings.  ``docs/CONFIG.md`` is generated from this
registry (``python -m ddl_tpu.envspec``) and a test asserts doc ↔
registry agreement, so the operator-facing table can never drift from
the code.

Three knob sources:

- ``env`` — knobs read directly by name somewhere in ``ddl_tpu/``.
- ``config`` — the ``DDL_TPU_<FIELD>`` family ``LoaderConfig.load``
  derives from its dataclass fields (``config.py`` ``_load_layered``).
- ``train`` — the ``DDL_TPU_TRAIN_<FIELD>`` family from ``TrainConfig``.

A knob may be both (``DDL_TPU_MODE`` is read literally in ``env.py``
AND layered by ``LoaderConfig.load``); the registry stores one entry
with the ``config_field`` annotation, and :func:`validate` asserts the
literal default and the dataclass default agree — the drift VP003's
export check catches across the spawn boundary, caught here across the
config boundary.

Sentinel-typed knobs (``default=None``) distinguish *unset* from any
set value; their call sites use :func:`raw` and keep their tri-state
logic (e.g. ``DDL_TPU_WIRE_DTYPE``: unset = per-reader capability
decides, ``"raw"`` = kill switch, lossy value = force the tier).
"""

from __future__ import annotations

import dataclasses
import os
from typing import Any, Dict, Iterable, List, Optional

from ddl_tpu.config import LoaderConfig, TrainConfig

#: Values (lowercased) a boolean knob treats as OFF; anything else set
#: is ON.  One shared falsy set — per-module copies drifted (the
#: original ``utils.env_flag`` contract, now registry-wide).
FALSY = ("0", "off", "false")


class UnknownKnobError(KeyError):
    """An env read named a ``DDL_TPU_*`` variable the registry does not
    declare — register it in :mod:`ddl_tpu.envspec` (VP003's runtime
    twin)."""


@dataclasses.dataclass(frozen=True)
class Knob:
    """One declared environment knob."""

    name: str
    type: str  # "bool" | "int" | "float" | "str"
    default: Any  # typed default; None = sentinel (unset is meaningful)
    doc: str
    #: Spawn-boundary mirror group: ``ddl_tpu.env._export_<group>_knobs``
    #: must cover every knob carrying its group (VP003 checks).
    export: Optional[str] = None
    #: LoaderConfig field this knob mirrors (the DDL_TPU_<FIELD> family).
    config_field: Optional[str] = None
    #: TrainConfig field this knob mirrors (DDL_TPU_TRAIN_<FIELD>).
    train_field: Optional[str] = None
    #: Read outside ddl_tpu/ (bench/test harness knobs) or only through
    #: a computed name (the config families): VP003 skips its
    #: "registered but never read" hygiene check.
    external: bool = False


def _K(name: str, type: str, default: Any, doc: str, **kw: Any) -> Knob:
    return Knob(name=name, type=type, default=default, doc=doc, **kw)


#: Explicit entries for every knob read by name in ``ddl_tpu/`` (plus
#: the documented harness knobs).  The config/train families are merged
#: in below from the dataclasses themselves, so a new config field can
#: never ship unregistered.
_EXPLICIT: List[Knob] = [
    # -- topology / spawn ------------------------------------------------
    _K("DDL_TPU_MODE", "str", "thread",
       "Producer realisation: thread | process | multihost.",
       config_field="mode"),
    _K("DDL_TPU_N_PRODUCERS", "int", 2,
       "Producer workers per consumer instance.",
       config_field="n_producers"),
    _K("DDL_TPU_NSLOTS", "int", 2,
       "Ring slots (window buffers) per producer.",
       config_field="nslots"),
    _K("DDL_TPU_HOST_ID", "int", None,
       "Physical host id of this consumer (unset = auto-detect: SLURM "
       "node vars, then procs-per-host arithmetic).",
       export="cluster", config_field="host_id"),
    _K("DDL_TPU_N_HOSTS", "int", None,
       "Physical host count (unset = auto-detect).",
       export="cluster", config_field="n_hosts"),
    _K("DDL_TPU_PROCS_PER_HOST", "int", None,
       "Consumer processes per host for host-identity arithmetic "
       "(unset = SLURM_NTASKS_PER_NODE, then 1).",
       export="cluster", config_field="procs_per_host"),
    # -- transport rings -------------------------------------------------
    _K("DDL_TPU_FORCE_PY_RING", "bool", False,
       "Force the pure-Python ring even where the native shm ring "
       "builds (test/debug escape hatch)."),
    _K("DDL_TPU_UNSAFE_PY_RING", "bool", False,
       "Allow the Python ring cross-process without the native build "
       "(testing only; spin-waits instead of futex waits)."),
    _K("DDL_TPU_INPLACE", "bool", True,
       "Write-once producer fills straight into live ring slots "
       "(0 = staging copy per window)."),
    _K("DDL_TPU_INTEGRITY", "bool", True,
       "Checksummed window trailers + drain-time verification "
       "(0/off disables)."),
    _K("DDL_TPU_MAX_REPLAYS", "int", 2,
       "Replay attempts per quarantined corrupt window before "
       "IntegrityError escalation."),
    # -- staging / ingest ------------------------------------------------
    _K("DDL_TPU_STAGED", "bool", True,
       "Staged-ingest engine (0 = inline device_put per batch)."),
    _K("DDL_TPU_SHM_STAGING", "bool", True,
       "Alias staging straight from shm ring slots (PROCESS mode)."),
    _K("DDL_TPU_STAGING_POOL_CAP", "int", 8,
       "StagingPool buffer cap per geometry."),
    _K("DDL_TPU_STAGING_QUEUE", "int", 4,
       "TransferExecutor queue depth (in-flight staged transfers)."),
    _K("DDL_TPU_STAGING_RETRIES", "int", 2,
       "Staged-transfer retries before the inline fallback."),
    _K("DDL_TPU_DISTRIBUTE", "str", "auto",
       "Device distribution tier: ici | xla | auto (auto = ici on "
       "accelerator meshes, xla on CPU)."),
    _K("DDL_TPU_ICI_INGEST", "bool", True,
       "auto-mode kill switch for the ICI fan-out tier (0 = xla)."),
    _K("DDL_TPU_FUSED", "bool", None,
       "Fused compute/ingest stream (unset = on where planned; 0 "
       "restores the synchronous step everywhere)."),
    # -- shard cache -----------------------------------------------------
    _K("DDL_TPU_CACHE", "bool", False,
       "Shard cache gate (docs/CACHING.md).",
       export="cache", config_field="cache"),
    _K("DDL_TPU_CACHE_RAM_MB", "int", 256,
       "RAM tier budget, MiB.", export="cache",
       config_field="cache_ram_mb"),
    _K("DDL_TPU_CACHE_SPILL_DIR", "str", None,
       "Disk spill directory (unset = RAM tier only).",
       export="cache", config_field="cache_spill_dir"),
    _K("DDL_TPU_CACHE_SPILL_MB", "int", 1024,
       "Disk spill budget, MiB.", export="cache",
       config_field="cache_spill_mb"),
    _K("DDL_TPU_CACHE_WARM", "bool", True,
       "Background warmer thread prefetching the shard schedule.",
       export="cache", config_field="cache_warm"),
    _K("DDL_TPU_CACHE_CODEC", "str", None,
       "Lossless codec for spilled cache entries (unset/none = raw "
       "bytes; zlib always available, zstd/lz4 gated on the host "
       "library).", export="cache", config_field="cache_codec"),
    _K("DDL_TPU_CACHE_RETRIES", "int", 3,
       "Backend fetch retries before IntegrityError."),
    _K("DDL_TPU_CACHE_BACKOFF_S", "float", 0.05,
       "Base backoff between backend fetch retries, seconds."),
    # -- wire format -----------------------------------------------------
    _K("DDL_TPU_WIRE_DTYPE", "str", None,
       "Wire transport override: raw = kill switch, bf16/int8 = force "
       "the lossy tier (unset = per-reader capability decides).",
       export="wire", config_field="wire_dtype"),
    _K("DDL_TPU_WIRE_CODEC", "str", None,
       "Lossless wire codec for the shuffle exchange + shard reads "
       "(none = explicit off; unset = no opinion).",
       export="wire", config_field="wire_codec"),
    # -- global shuffle --------------------------------------------------
    _K("DDL_TPU_DEVICE_SHUFFLE", "str", "auto",
       "Device-tier exchange gate: auto = engage when plannable (THREAD "
       "topology, raw wire, in-process fabric), 0/off/false = host "
       "exchange only.", export="shuffle", config_field="device_shuffle"),
    _K("DDL_TPU_SHUFFLE_IMPL", "str", "ring",
       "Device exchange implementation: ring = Pallas remote-DMA ring "
       "(double-buffered, slot-ridable), xla = jitted ppermute lanes.",
       export="shuffle", config_field="shuffle_impl"),
    # -- readers ---------------------------------------------------------
    _K("DDL_TPU_TFRECORD_CRC", "bool", True,
       "CRC32C verification of TFRecord length/payload frames."),
    # -- resilience ------------------------------------------------------
    _K("DDL_TPU_CKPT_ASYNC", "bool", True,
       "AsyncCheckpointer (D2H-only stall) vs synchronous writes."),
    _K("DDL_TPU_PREEMPT_NOTICE", "str", None,
       "Out-of-band preemption notice: set non-empty (optionally "
       "'<grace_s>') to trigger the graceful-drain ladder."),
    _K("DDL_TPU_PREEMPT_DEADLINE_S", "float", 30.0,
       "Default drain deadline after a preemption notice, seconds."),
    # -- control-plane survivability (cluster.supervision) ---------------
    _K("DDL_TPU_SUPERVISOR_LEASE_S", "float", 2.0,
       "Supervisor leadership lease budget, seconds: a standby "
       "promotes itself when the leader's lease goes unrenewed this "
       "long (ddl_tpu.cluster.supervision)."),
    _K("DDL_TPU_SUPERVISOR_STANDBYS", "int", 1,
       "Hot-standby supervisor count the HA tier provisions alongside "
       "the leader (ddl_tpu.cluster.supervision)."),
    _K("DDL_TPU_CTRL_RETRIES", "int", 5,
       "Acked control-envelope retry cap per send "
       "(ddl_tpu.transport.envelope); past it the send surfaces its "
       "last transport error."),
    _K("DDL_TPU_CTRL_BACKOFF_S", "float", 0.02,
       "Initial acked control-envelope retry backoff, seconds "
       "(doubles per retry; ddl_tpu.transport.envelope)."),
    _K("DDL_TPU_FABRIC_QUANTUM_BYTES", "int", 4194304,
       "DRR quantum of the fabric's resident fair-share scheduler, "
       "bytes of credit per job per replenish round "
       "(ddl_tpu.serve.fabric)."),
    _K("DDL_TPU_FABRIC_SNAPSHOT_EVERY", "int", 1,
       "Applied admission decisions between full scheduler snapshots "
       "in the supervisor journal (ddl_tpu.serve.fabric; 1 = every "
       "decision, the bit-exact failover default; 0 disables periodic "
       "snapshots)."),
    _K("DDL_TPU_FABRIC_ADMIT_TIMEOUT_S", "float", 30.0,
       "Default fabric admission deadline per window, seconds "
       "(ddl_tpu.serve.fabric.FabricJob.admit when the caller passes "
       "none)."),
    _K("DDL_TPU_FABRIC_DRAIN_SLO_S", "float", 2.0,
       "Preemption-drain SLO for fabric job revocation, seconds: how "
       "long revoke waits for in-flight granted windows to finish "
       "(ddl_tpu.serve.fabric)."),
    # -- self-tuning (ddl_tpu.tune) -------------------------------------
    _K("DDL_TPU_TUNE_DEADLINE_S", "float", 2.0,
       "Boot-time calibration budget, seconds (ddl_tpu.tune.Calibrator): "
       "probes not finished by then fall back to declared/default costs "
       "so calibration can never stall training start."),
    _K("DDL_TPU_TUNE_INTERVAL_S", "float", 1.0,
       "Steady-state KnobController poll cadence, seconds "
       "(ddl_tpu.tune.controller; the DDL018 deadline-loop period)."),
    _K("DDL_TPU_TUNE_SUSTAIN_S", "float", 2.0,
       "How long a tuning signal must stay beyond its band before the "
       "KnobController acts (hysteresis; the Autoscaler precedent)."),
    _K("DDL_TPU_TUNE_COOLDOWN_S", "float", 5.0,
       "Minimum spacing between KnobController knob changes, seconds "
       "(also the post-change observation window the never-worse guard "
       "judges before a revert)."),
    _K("DDL_TPU_TUNE_REVERT_TOL", "float", 0.05,
       "Never-worse guard tolerance: a knob change whose post-change "
       "window throughput drops more than this fraction below the "
       "pre-change window is reverted (ddl_tpu.tune.controller)."),
    _K("DDL_TPU_TUNE_PARITY_HEADROOM", "float", 0.5,
       "Lossy-wire safety margin: when max_rel_drift exceeds this "
       "fraction of the loss_parity tolerance, the controller flips "
       "the exchange wire back to raw (ddl_tpu.tune.controller)."),
    # -- chaos / observability ------------------------------------------
    _K("DDL_TPU_FAULT_PLAN", "str", None,
       "JSON-encoded FaultPlan armed at import (the spawn-boundary "
       "chaos carrier; ddl_tpu.faults)."),
    _K("DDL_TPU_TRACE", "int", None,
       "Span tracing armed at import with this event capacity "
       "(unset = tracing disarmed; ddl_tpu.obs.spans)."),
    _K("DDL_TPU_FLIGHT", "int", None,
       "Flight recorder armed at import with this ring capacity "
       "(unset = disarmed; ddl_tpu.obs.recorder)."),
    _K("DDL_TPU_FLIGHT_DIR", "str", None,
       "Flight-record dump directory (default /tmp/ddl_tpu_flight)."),
    _K("DDL_TPU_OBS_SHIP_EVERY", "int", 32,
       "Windows between periodic worker ObsReports (0 = disabled)."),
    # -- harness knobs (read by bench/tests, documented here) -----------
    _K("DDL_TPU_ONCHIP", "bool", False,
       "Enable @onchip tests / chip bench legs (needs a real TPU).",
       external=True),
]

#: One-line docs for config-family knobs that have no explicit entry
#: above (LoaderConfig fields are the source of the name + default).
_CONFIG_FIELD_DOCS: Dict[str, str] = {
    "batch_size": "Samples per batch served to the consumer.",
    "n_epochs": "Epochs before the loader signals exhaustion.",
    "global_shuffle_fraction_exchange":
        "Fraction of each window exchanged in the global shuffle.",
    "exchange_method": "Global-shuffle exchange algorithm.",
    "shuffle_seed": "Seed for the deterministic shuffle schedule.",
    "output": "Consumer output container: jax | numpy | torch.",
    "window_stream": "Zero-copy window streaming (Trainer.fit).",
    "ring_timeout_s": "Ring wait timeout before StallTimeoutError.",
    "stall_budget_s": "Watchdog stall budget per producer.",
    "checkpoint_dir": "Loader checkpoint directory (unset = off).",
    "checkpoint_every_epochs": "Checkpoint cadence (0 = disabled).",
    "prefetch_depth":
        "Device transfers kept in flight by prefetch() (tunable).",
}

_TRAIN_FIELD_DOCS: Dict[str, str] = {
    "remat": "Rematerialisation policy: none/full/selective/dots.",
    "schedule": "Pipeline schedule: gpipe | 1f1b.",
    "pp_chunks": "Stage chunks per device for 1f1b (0 = default).",
    "n_microbatches": "Microbatches per pipeline step.",
    "accum_steps": "Gradient-accumulation microbatches per update.",
    "optimizer_sharding": "Optimizer state sharding: none | zero1.",
    "grad_comm": "Gradient comm wire format: fp32 | int8.",
    "grad_comm_block": "int8 block size (0 = collectives default).",
    "stochastic_rounding": "Stochastic rounding on the int8 wire.",
}


def _annot_type(annot: Any) -> str:
    s = str(annot)
    if "bool" in s:
        return "bool"
    if "int" in s:
        return "int"
    if "float" in s:
        return "float"
    return "str"


def _build_registry() -> Dict[str, Knob]:
    reg: Dict[str, Knob] = {}
    for k in _EXPLICIT:
        if k.name in reg:
            raise ValueError(f"duplicate knob {k.name}")
        reg[k.name] = k
    # The DDL_TPU_<FIELD> / DDL_TPU_TRAIN_<FIELD> families, derived from
    # the dataclasses so a new config field auto-registers.
    for cls, docs, field_attr in (
        (LoaderConfig, _CONFIG_FIELD_DOCS, "config_field"),
        (TrainConfig, _TRAIN_FIELD_DOCS, "train_field"),
    ):
        for f in dataclasses.fields(cls):
            if f.name.startswith("_"):
                continue
            name = cls._ENV_PREFIX + f.name.upper()
            if name in reg:
                # Explicit entry covers it; validate() asserts the
                # annotations/defaults agree.
                continue
            reg[name] = Knob(
                name=name,
                type=_annot_type(f.type),
                default=f.default,
                doc=docs.get(
                    f.name, f"{cls.__name__}.{f.name} (see config.py)."
                ),
                external=True,  # read via the computed-prefix layering
                **{field_attr: f.name},
            )
    return reg


REGISTRY: Dict[str, Knob] = _build_registry()


def require(name: str) -> Knob:
    """The registry entry for ``name``, or :class:`UnknownKnobError`."""
    try:
        return REGISTRY[name]
    except KeyError:
        raise UnknownKnobError(
            f"unregistered env knob {name!r}: declare it in "
            "ddl_tpu/envspec.py (tools/ddl_verify VP003)"
        ) from None


def raw(name: str) -> Optional[str]:
    """The raw environment string for a REGISTERED knob (None = unset).

    The accessor for sentinel-typed knobs whose call sites keep their
    own tri-state logic; everything else uses :func:`get`/:func:`flag`.
    """
    require(name)
    return os.environ.get(name)


def get(name: str, override: Any = None) -> Any:
    """Typed read: explicit ``override`` wins, then the environment,
    then the registered default.  Empty-string values fall back to the
    default for non-str knobs (an exported-then-cleared mirror must not
    crash a worker on ``int("")``)."""
    knob = require(name)
    if override is not None:
        return override
    val = os.environ.get(name)
    if knob.type == "bool":
        if val is None or val == "":
            return bool(knob.default)
        return val.lower() not in FALSY
    if val is None or val == "":
        return knob.default
    if knob.type == "int":
        return int(val)
    if knob.type == "float":
        return float(val)
    return val


def flag(name: str, override: Optional[bool] = None) -> bool:
    """Boolean read (the historical ``utils.env_flag`` semantics:
    truthy unless ``0``/``off``/``false``, case-insensitive)."""
    val = get(name, override)
    return bool(val)


def export_group(group: str) -> List[Knob]:
    """Registered knobs a ``_export_<group>_knobs`` mirror must cover."""
    return [k for k in REGISTRY.values() if k.export == group]


def validate() -> None:
    """Cross-check explicit entries against the config dataclasses.

    Raises on drift: an explicit knob naming a ``config_field`` /
    ``train_field`` that does not exist, or whose registered default
    disagrees with the dataclass default.  Called from the tier-1
    reflection test, not at import (a broken registry must fail the
    gate loudly, not break production imports).
    """
    for cls, attr in ((LoaderConfig, "config_field"),
                      (TrainConfig, "train_field")):
        by_name = {f.name: f for f in dataclasses.fields(cls)}
        for knob in REGISTRY.values():
            fname = getattr(knob, attr)
            if fname is None:
                continue
            if fname not in by_name:
                raise AssertionError(
                    f"{knob.name} names unknown {cls.__name__} field "
                    f"{fname!r}"
                )
            f = by_name[fname]
            expect = cls._ENV_PREFIX + fname.upper()
            if knob.name != expect:
                raise AssertionError(
                    f"{knob.name} mirrors {cls.__name__}.{fname} but the "
                    f"layered loader reads {expect}"
                )
            if knob.default is not None and knob.default != f.default:
                # Sentinel knobs (default None) intentionally differ
                # from config sentinels (-1/0/""): skip those.
                if not (f.default in (-1, 0, "", None) and
                        knob.default is None):
                    raise AssertionError(
                        f"{knob.name} default {knob.default!r} != "
                        f"{cls.__name__}.{fname} default {f.default!r}"
                    )


def render_table() -> str:
    """The ``docs/CONFIG.md`` knob table, generated from the registry."""
    lines = [
        "# Environment knobs",
        "",
        "Generated from `ddl_tpu/envspec.py` "
        "(`python -m ddl_tpu.envspec > docs/CONFIG.md`); "
        "`tests/test_verify.py` asserts this file matches the registry, "
        "and `tools/ddl_verify` VP003 asserts every env read resolves "
        "through it.  Precedence everywhere: explicit config/kwargs win "
        "over the environment, which wins over the registered default.",
        "",
        "| Knob | Type | Default | Export mirror | Description |",
        "|---|---|---|---|---|",
    ]
    for name in sorted(REGISTRY):
        k = REGISTRY[name]
        default = "*(unset)*" if k.default is None else repr(k.default)
        export = f"`_export_{k.export}_knobs`" if k.export else ""
        doc = k.doc.replace("|", "\\|")  # literal pipes break the table
        lines.append(
            f"| `{k.name}` | {k.type} | {default} | {export} | {doc} |"
        )
    lines.append("")
    lines.append(
        f"{len(REGISTRY)} registered knobs "
        "(config-derived families included)."
    )
    lines.append("")
    return "\n".join(lines)


if __name__ == "__main__":  # pragma: no cover - doc generator
    print(render_table(), end="")
