"""Checkpoint / resume for train state and loader progress.

The reference had no checkpointing at all — its config carried a dead
``checkpt_epoch`` field nothing read (reference ``tests/run_ddl.py:260``,
SURVEY §5.4).  Here both halves of a run are restorable:

- :func:`save_train_state` / :func:`restore_train_state` — the params /
  optimizer pytree via Orbax (sharding-aware; restores onto the current
  mesh layout).
- :class:`LoaderCheckpoint` — the loader's logical clock (epoch, window
  target, batch-in-window, shuffle round), small JSON.  Restoring it
  resynchronises the epoch/rotation counters and — because the global
  shuffle permutation is a pure function of (seed, round) — the
  cross-instance exchange schedule continues exactly where it stopped.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
from typing import Any, Optional

from ddl_tpu.parallel.train import TrainState


def save_train_state(state: TrainState, path: str) -> None:
    """Persist params + optimizer state + step with Orbax."""
    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(
            os.path.join(path, f"step_{state.step}"),
            {"params": state.params, "opt_state": state.opt_state,
             "step": state.step},
            force=True,
        )


def latest_step(path: str) -> Optional[int]:
    path = os.path.abspath(path)
    if not os.path.isdir(path):
        return None
    steps = [
        int(d.split("_", 1)[1])
        for d in os.listdir(path)
        if d.startswith("step_") and d.split("_", 1)[1].isdigit()
    ]
    return max(steps) if steps else None


def restore_train_state(path: str, like: TrainState) -> TrainState:
    """Restore the newest checkpoint under ``path``.

    ``like`` provides the target structure AND shardings — restore lands
    directly on the current mesh (resharding if the mesh changed shape),
    the standard Orbax pattern.
    """
    import orbax.checkpoint as ocp

    step = latest_step(path)
    if step is None:
        raise FileNotFoundError(f"no checkpoints under {path!r}")
    template = {"params": like.params, "opt_state": like.opt_state,
                "step": like.step}
    with ocp.StandardCheckpointer() as ckptr:
        restored = ckptr.restore(
            os.path.join(os.path.abspath(path), f"step_{step}"), template
        )
    return TrainState(
        params=restored["params"],
        opt_state=restored["opt_state"],
        step=int(restored["step"]),
    )


def adopt_cache_manifest(path: str) -> bool:
    """Pre-spawn cache warm-start: read ONLY the cache manifest from the
    loader checkpoint at ``path`` and adopt it.

    PROCESS/MULTIHOST producer workers inherit their environment at
    spawn, so for them the manifest must be adopted **before**
    ``distributed_dataloader`` builds the worker set — call this at the
    top of a resuming main, before the decorator runs.  (THREAD mode
    does not need it: ``LoaderCheckpoint.apply`` attaches the tier to
    the live shared store.)  Returns False — resuming with a cold
    cache, never an error — when the checkpoint is missing/unreadable,
    carries no manifest, or the manifest is refused (schema mismatch,
    vanished directory, conflicting live tier).
    """
    try:
        ck = LoaderCheckpoint.load(path)
    except (OSError, ValueError, TypeError, KeyError):
        return False
    if not ck.cache_spill_dir:
        return False
    from ddl_tpu import cache as cache_mod

    return cache_mod.adopt_manifest(ck.cache_spill_dir, ck.cache_key_schema)


@dataclasses.dataclass
class LoaderCheckpoint:
    """The loader's logical position (enough to resume deterministically).

    ``shuffle_round`` tracks the global-shuffle schedule: pass the active
    shuffler (``DeviceGlobalShuffler`` or ``ThreadExchangeShuffler`` — any
    object with a ``_round`` counter) to ``capture``/``apply`` and, because
    the exchange permutation is a pure function of (seed, round), the
    cross-instance schedule continues exactly where it stopped.
    """

    epoch: int = 0
    target: int = 0
    batches_in_window: int = 0
    shuffle_round: int = 0
    #: Cache manifest (ISSUE 4): the shard cache's disk-tier directory
    #: plus the key-schema version it was written under.  ``apply``
    #: points the resumed run's cache at this spill dir
    #: (:func:`ddl_tpu.cache.adopt_manifest`), so epoch-1-after-resume
    #: reads decoded shards from disk instead of refetching from source.
    #: A schema mismatch is refused — content-addressed keys make stale
    #: entries unmatchable anyway, but a refused adoption is cheaper
    #: than a tier of guaranteed misses.
    cache_spill_dir: Optional[str] = None
    cache_key_schema: int = 0
    #: Cluster membership fence (ddl_tpu.cluster): the view epoch at
    #: capture time.  ``apply`` fast-forwards a resumed supervisor past
    #: it so views minted after restore can never be mistaken for
    #: pre-checkpoint ones (shard adoptions are epoch-fenced).
    cluster_epoch: int = 0

    @staticmethod
    def capture(
        loader: Any, shuffler: Any = None, cache: Any = None,
        cluster: Any = None,
    ) -> "LoaderCheckpoint":
        round_ = 0
        if shuffler is not None:
            # Public accessor first (the rejoin/exchange_round contract);
            # the private-field fallback keeps old duck-typed shufflers
            # working.
            round_ = getattr(
                shuffler, "exchange_round", getattr(shuffler, "_round", 0)
            )
        from ddl_tpu import cache as cache_mod

        # The active store only — capture must not build a store (or
        # decide cache policy) as a side effect of checkpointing.
        store = cache if cache is not None else cache_mod.active_store()
        spill = getattr(store, "spill_dir", None) if store else None
        # ``cluster`` is a ClusterSupervisor or a bare ClusterView.
        cluster_epoch = 0
        if cluster is not None:
            view = getattr(cluster, "view", cluster)
            cluster_epoch = int(getattr(view, "epoch", 0))
        return LoaderCheckpoint(
            cluster_epoch=cluster_epoch,
            epoch=loader._epoch,
            target=loader._target,
            batches_in_window=loader._batches_in_window,
            shuffle_round=int(round_),
            cache_spill_dir=spill,
            cache_key_schema=(
                cache_mod.KEY_SCHEMA_VERSION if spill else 0
            ),
        )

    def apply(
        self, loader: Any, shuffler: Any = None, cluster: Any = None
    ) -> None:
        loader._epoch = self.epoch
        loader._target = self.target
        loader._batches_in_window = self.batches_in_window
        if cluster is not None and self.cluster_epoch:
            restore = getattr(cluster, "restore_epoch", None)
            if callable(restore):
                restore(self.cluster_epoch)
        if self.cache_spill_dir:
            from ddl_tpu import cache as cache_mod

            if not cache_mod.adopt_manifest(
                self.cache_spill_dir, self.cache_key_schema
            ):
                logging.getLogger("ddl_tpu").warning(
                    "checkpoint cache manifest not adopted (%s, schema %d)"
                    " — resuming with a cold cache",
                    self.cache_spill_dir, self.cache_key_schema,
                )
        if shuffler is not None:
            rejoin = getattr(shuffler, "rejoin", None)
            if callable(rejoin):
                # The documented re-entry hook — a custom shuffler's real
                # round state may not be named _round.
                rejoin(self.shuffle_round)
            else:
                shuffler._round = self.shuffle_round

    def save(self, path: str) -> None:
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(dataclasses.asdict(self), f)
        os.replace(tmp, path)

    @staticmethod
    def load(path: str) -> "LoaderCheckpoint":
        with open(path) as f:
            return LoaderCheckpoint(**json.load(f))
