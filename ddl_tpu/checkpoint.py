"""Checkpoint / resume for train state and loader progress.

The reference had no checkpointing at all — its config carried a dead
``checkpt_epoch`` field nothing read (reference ``tests/run_ddl.py:260``,
SURVEY §5.4).  Here both halves of a run are restorable:

- :func:`save_train_state` / :func:`restore_train_state` — the params /
  optimizer pytree via Orbax (sharding-aware; restores onto the current
  mesh layout).  Durable since ISSUE 14: the save lands in a temp
  directory and is renamed into place only after a per-file crc32
  manifest is written, so a ``kill -9`` mid-write can never leave a
  half-written *newest* checkpoint, and
  :func:`latest_verified_step` verifies the manifest on read —
  torn or bit-rotted generations are quarantined (``.quarantined``,
  the cache-store pattern) and the previous verified generation is
  restored instead.
- :class:`LoaderCheckpoint` — the loader's logical clock (epoch, window
  target, batch-in-window, shuffle round), small JSON.  Restoring it
  resynchronises the epoch/rotation counters and — because the global
  shuffle permutation is a pure function of (seed, round) — the
  cross-instance exchange schedule continues exactly where it stopped.

The trainer-side *async* checkpoint tier (background writes, integrity
trailers, preemption drain) lives in :mod:`ddl_tpu.resilience` and
reuses :func:`atomic_file_write` — the ONE sanctioned write primitive
for checkpoint bytes (ddl-lint DDL022 enforces that every configured
checkpoint write routes through it).
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import zlib
from typing import Any, Optional

from ddl_tpu.parallel.train import TrainState

#: Per-generation integrity manifest written INSIDE every Orbax step
#: directory before the atomic rename: relpath -> {size, crc32}.
MANIFEST_NAME = "ddl_manifest.json"


def atomic_file_write(path: str, data: bytes, fsync: bool = True) -> None:
    """THE checkpoint-byte write primitive: temp file in the target's
    own directory, then ``os.replace`` — readers see the old bytes or
    the new bytes, never a torn mix, and a crash mid-write leaves only
    a ``.tmp.<pid>`` orphan no reader matches.  ``fsync=True`` flushes
    to stable storage before the rename (durability, not just
    atomicity).  Every configured checkpoint write must route through
    here (ddl-lint DDL022)."""
    path = os.path.abspath(path)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "wb") as f:  # ddl-lint: disable=DDL022
        # The helper itself is the one sanctioned bare write: the temp
        # name is unmatchable by any reader and replaced atomically.
        f.write(data)
        if fsync:
            f.flush()
            os.fsync(f.fileno())
    os.replace(tmp, path)
    if fsync:
        # The rename itself must survive power loss: fsync the
        # DIRECTORY entry too, or a "durably written" final checkpoint
        # can vanish on reboot with only its data blocks persisted.
        try:
            dfd = os.open(os.path.dirname(path), os.O_RDONLY)
            try:
                os.fsync(dfd)
            finally:
                os.close(dfd)
        except OSError:
            pass  # platform/filesystem without directory fsync


def _file_crc(path: str) -> int:
    crc = 0
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            crc = zlib.crc32(chunk, crc)
    return crc & 0xFFFFFFFF


def _write_manifest(step_dir: str) -> None:
    """Stamp ``MANIFEST_NAME`` over every file in ``step_dir`` (size +
    crc32 per file) — the per-generation verification record
    :func:`latest_verified_step` checks on read."""
    entries = {}
    for root, _dirs, files in os.walk(step_dir):
        for name in files:
            # Skip the manifest itself and atomic_file_write's
            # ``<name>.tmp.<pid>`` orphans (a crash mid-manifest in a
            # multi-process save leaves one; it must never be treated
            # as checkpoint payload).
            if name == MANIFEST_NAME or ".tmp." in name:
                continue
            full = os.path.join(root, name)
            rel = os.path.relpath(full, step_dir)
            entries[rel] = {
                "size": os.path.getsize(full), "crc32": _file_crc(full),
            }
    atomic_file_write(
        os.path.join(step_dir, MANIFEST_NAME),
        json.dumps({"version": 1, "files": entries}).encode(),
    )


def verify_step_dir(step_dir: str) -> Optional[str]:
    """Check a step directory against its manifest.  Returns a failure
    description, or None when every file matches (or the directory
    predates manifests — legacy generations stay restorable, logged)."""
    manifest = os.path.join(step_dir, MANIFEST_NAME)
    if not os.path.exists(manifest):
        logging.getLogger("ddl_tpu").warning(
            "checkpoint %s has no integrity manifest (pre-ISSUE-14 "
            "save) — accepting unverified", step_dir,
        )
        _metrics().incr("resilience.ckpt_unverified")
        return None
    try:
        with open(manifest) as f:
            entries = json.load(f)["files"]
    except (OSError, ValueError, KeyError) as e:
        return f"unreadable manifest: {e}"
    for rel, want in entries.items():
        full = os.path.join(step_dir, rel)
        if not os.path.exists(full):
            return f"missing file {rel}"
        size = os.path.getsize(full)
        if size != want["size"]:
            return f"{rel}: size {size} != manifest {want['size']} (torn)"
        if _file_crc(full) != want["crc32"]:
            return f"{rel}: crc32 mismatch (bit rot or partial write)"
    return None


def _metrics():
    from ddl_tpu.observability import metrics as default_metrics

    return default_metrics()


def quarantine_path(path: str, metrics=None) -> str:
    """Rename a corrupt checkpoint (file or step dir) out of the
    restore namespace — ``<path>.quarantined`` (the cache-store
    pattern), uniquified if a previous quarantine already holds the
    name.  Counts ``resilience.ckpt_quarantined`` on ``metrics`` (the
    process default when None).  Returns the quarantine path."""
    dest = f"{path}.quarantined"
    n = 1
    while os.path.exists(dest):
        dest = f"{path}.quarantined.{n}"
        n += 1
    m = metrics if metrics is not None else _metrics()
    try:
        os.replace(path, dest)
    except OSError:
        # A concurrent process (multi-host restore: every rank verifies)
        # may have quarantined it first — losing the race is fine, the
        # generation is out of the namespace either way.
        logging.getLogger("ddl_tpu").warning(
            "checkpoint quarantine rename of %s lost a race", path
        )
        return dest
    m.incr("resilience.ckpt_quarantined")
    logging.getLogger("ddl_tpu").error(
        "checkpoint %s failed verification — quarantined to %s",
        path, dest,
    )
    return dest


def save_train_state(state: TrainState, path: str) -> None:
    """Persist params + optimizer state + step with Orbax — atomically.

    The save lands in a ``.tmp.<pid>`` sibling directory, a per-file
    crc32 manifest is stamped inside it, and only then is the
    directory renamed to ``step_<n>`` — a crash at ANY point leaves
    either the previous generation set intact (a same-step overwrite
    parks the old copy under ``.old.<pid>`` rather than deleting it
    first, so even the rename gap cannot destroy the only copy) plus
    ignorable orphans, or the complete verified new generation.
    Never a half-written newest checkpoint (ISSUE 14 satellite).
    """
    import shutil

    import jax
    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    final = os.path.join(path, f"step_{state.step}")
    if jax.process_count() > 1:
        # Multi-process runs save COLLECTIVELY: every process must pass
        # the SAME path (Orbax coordinates shard writes + finalization
        # through its own tmp-dir + commit protocol, which is already
        # atomic).  Only the manifest is ours — process 0 stamps it
        # after the collective save completes.
        with ocp.StandardCheckpointer() as ckptr:
            ckptr.save(
                final,
                {"params": state.params, "opt_state": state.opt_state,
                 "step": state.step},
                force=True,
            )
        if jax.process_index() == 0:
            _write_manifest(final)
        return
    tmp = f"{final}.tmp.{os.getpid()}"
    if os.path.exists(tmp):
        shutil.rmtree(tmp)
    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(
            tmp,
            {"params": state.params, "opt_state": state.opt_state,
             "step": state.step},
            force=True,
        )
    _write_manifest(tmp)
    old = None
    if os.path.exists(final):
        # force=True semantics: replace the same-step generation whole —
        # but PARK the old one first instead of rmtree'ing it, so a
        # crash between "old gone" and "new renamed in" cannot destroy
        # the only copy of this step (the parked name matches no
        # reader; it is deleted only after the new generation is live).
        old = f"{final}.old.{os.getpid()}"
        if os.path.exists(old):
            shutil.rmtree(old)
        os.replace(final, old)
    os.replace(tmp, final)
    if old is not None:
        shutil.rmtree(old, ignore_errors=True)


def latest_verified_step(
    path: str, quarantine: bool = True
) -> Optional[int]:
    """The newest step under ``path`` whose integrity manifest
    verifies.  Unverifiable generations are quarantined
    (``.quarantined``) and SKIPPED — a torn newest checkpoint falls
    back to the previous verified one instead of poisoning the resume
    (ISSUE 14 satellite); exhaustion returns None (cold start), with
    the quarantine counter left loud in the metrics/logs.  Temp
    (``.tmp.<pid>``) and quarantined directories never match the
    ``step_<n>`` pattern, so partial writes are invisible here by
    construction."""
    path = os.path.abspath(path)
    if not os.path.isdir(path):
        return None
    steps = sorted(
        (
            int(d.split("_", 1)[1])
            for d in os.listdir(path)
            if d.startswith("step_") and d.split("_", 1)[1].isdigit()
        ),
        reverse=True,
    )
    for step in steps:
        step_dir = os.path.join(path, f"step_{step}")
        err = verify_step_dir(step_dir)
        if err is None:
            return step
        logging.getLogger("ddl_tpu").error(
            "checkpoint step_%d failed verification (%s)", step, err
        )
        if quarantine:
            quarantine_path(step_dir)
    return None


#: Back-compat alias — every pre-ISSUE-14 caller now verifies on read.
latest_step = latest_verified_step


def restore_train_state(
    path: str, like: TrainState, step: Optional[int] = None
) -> TrainState:
    """Restore the newest VERIFIED checkpoint under ``path``.

    ``like`` provides the target structure AND shardings — restore lands
    directly on the current mesh (resharding if the mesh changed shape),
    the standard Orbax pattern.  Generations failing their integrity
    manifest are quarantined and the previous verified one restores
    instead (:func:`latest_verified_step`).  Pass ``step`` when the
    caller already verified it — the manifest scan reads and CRCs every
    checkpoint byte, and doing that twice doubles restart I/O.
    """
    import orbax.checkpoint as ocp

    if step is None:
        step = latest_verified_step(path)
    if step is None:
        raise FileNotFoundError(f"no verified checkpoints under {path!r}")
    template = {"params": like.params, "opt_state": like.opt_state,
                "step": like.step}
    with ocp.StandardCheckpointer() as ckptr:
        restored = ckptr.restore(
            os.path.join(os.path.abspath(path), f"step_{step}"), template
        )
    return TrainState(
        params=restored["params"],
        opt_state=restored["opt_state"],
        step=int(restored["step"]),
    )


def adopt_cache_manifest(path: str) -> bool:
    """Pre-spawn cache warm-start: read ONLY the cache manifest from the
    loader checkpoint at ``path`` and adopt it.

    PROCESS/MULTIHOST producer workers inherit their environment at
    spawn, so for them the manifest must be adopted **before**
    ``distributed_dataloader`` builds the worker set — call this at the
    top of a resuming main, before the decorator runs.  (THREAD mode
    does not need it: ``LoaderCheckpoint.apply`` attaches the tier to
    the live shared store.)  Returns False — resuming with a cold
    cache, never an error — when the checkpoint is missing/unreadable,
    carries no manifest, or the manifest is refused (schema mismatch,
    vanished directory, conflicting live tier).
    """
    try:
        ck = LoaderCheckpoint.load(path)
    except (OSError, ValueError, TypeError, KeyError):
        return False
    if not ck.cache_spill_dir:
        return False
    from ddl_tpu import cache as cache_mod

    return cache_mod.adopt_manifest(ck.cache_spill_dir, ck.cache_key_schema)


@dataclasses.dataclass
class LoaderCheckpoint:
    """The loader's logical position (enough to resume deterministically).

    ``shuffle_round`` tracks the global-shuffle schedule: pass the active
    shuffler (``DeviceGlobalShuffler`` or ``ThreadExchangeShuffler`` — any
    object with a ``_round`` counter) to ``capture``/``apply`` and, because
    the exchange permutation is a pure function of (seed, round), the
    cross-instance schedule continues exactly where it stopped.
    """

    epoch: int = 0
    target: int = 0
    batches_in_window: int = 0
    shuffle_round: int = 0
    #: Cache manifest (ISSUE 4): the shard cache's disk-tier directory
    #: plus the key-schema version it was written under.  ``apply``
    #: points the resumed run's cache at this spill dir
    #: (:func:`ddl_tpu.cache.adopt_manifest`), so epoch-1-after-resume
    #: reads decoded shards from disk instead of refetching from source.
    #: A schema mismatch is refused — content-addressed keys make stale
    #: entries unmatchable anyway, but a refused adoption is cheaper
    #: than a tier of guaranteed misses.
    cache_spill_dir: Optional[str] = None
    cache_key_schema: int = 0
    #: Cluster membership fence (ddl_tpu.cluster): the view epoch at
    #: capture time.  ``apply`` fast-forwards a resumed supervisor past
    #: it so views minted after restore can never be mistaken for
    #: pre-checkpoint ones (shard adoptions are epoch-fenced).
    cluster_epoch: int = 0

    @staticmethod
    def capture(
        loader: Any, shuffler: Any = None, cache: Any = None,
        cluster: Any = None,
    ) -> "LoaderCheckpoint":
        round_ = 0
        if shuffler is not None:
            # Public accessor first (the rejoin/exchange_round contract);
            # the private-field fallback keeps old duck-typed shufflers
            # working.
            round_ = getattr(
                shuffler, "exchange_round", getattr(shuffler, "_round", 0)
            )
        from ddl_tpu import cache as cache_mod

        # The active store only — capture must not build a store (or
        # decide cache policy) as a side effect of checkpointing.
        store = cache if cache is not None else cache_mod.active_store()
        spill = getattr(store, "spill_dir", None) if store else None
        # ``cluster`` is a ClusterSupervisor or a bare ClusterView.
        cluster_epoch = 0
        if cluster is not None:
            view = getattr(cluster, "view", cluster)
            cluster_epoch = int(getattr(view, "epoch", 0))
        return LoaderCheckpoint(
            cluster_epoch=cluster_epoch,
            epoch=loader._epoch,
            target=loader._target,
            batches_in_window=loader._batches_in_window,
            shuffle_round=int(round_),
            cache_spill_dir=spill,
            cache_key_schema=(
                cache_mod.KEY_SCHEMA_VERSION if spill else 0
            ),
        )

    def apply(
        self, loader: Any, shuffler: Any = None, cluster: Any = None
    ) -> None:
        loader._epoch = self.epoch
        loader._target = self.target
        loader._batches_in_window = self.batches_in_window
        if cluster is not None and self.cluster_epoch:
            restore = getattr(cluster, "restore_epoch", None)
            if callable(restore):
                restore(self.cluster_epoch)
        if self.cache_spill_dir:
            from ddl_tpu import cache as cache_mod

            if not cache_mod.adopt_manifest(
                self.cache_spill_dir, self.cache_key_schema
            ):
                logging.getLogger("ddl_tpu").warning(
                    "checkpoint cache manifest not adopted (%s, schema %d)"
                    " — resuming with a cold cache",
                    self.cache_spill_dir, self.cache_key_schema,
                )
        if shuffler is not None:
            rejoin = getattr(shuffler, "rejoin", None)
            if callable(rejoin):
                # The documented re-entry hook — a custom shuffler's real
                # round state may not be named _round.
                rejoin(self.shuffle_round)
            else:
                shuffler._round = self.shuffle_round

    def save(self, path: str) -> None:
        # Atomic temp+rename (DDL022): the loader clock is read by every
        # resume — a torn half-written cursor would desynchronize the
        # data stream from the train state it is fenced to.
        atomic_file_write(
            path, json.dumps(dataclasses.asdict(self)).encode()
        )

    @staticmethod
    def load(path: str) -> "LoaderCheckpoint":
        with open(path) as f:
            return LoaderCheckpoint(**json.load(f))
