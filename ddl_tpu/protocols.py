"""Structural protocols for producer-loop hooks.

Parity: reference ``ddl/protocols.py:4-18`` defined ``CallbackProtocol`` with
a name bug — the protocol said ``exec_function`` while the dispatcher and the
implementations said ``execute_function`` (SURVEY Q2).  Fixed here: protocol,
dispatcher and skeleton all agree on ``execute_function``.
"""

from __future__ import annotations

from typing import Any, Protocol, runtime_checkable


@runtime_checkable
class CallbackProtocol(Protocol):
    """Hooks dispatched around the producer hot loop.

    Dispatch order per iteration (reference ``ddl/datapusher.py:147-170``):
    ``on_push_begin`` once, then per window refill: ``global_shuffle`` →
    ``execute_function`` → (handoff) → ``on_shuffle_end``; ``on_push_end``
    once at shutdown.  A callback may implement any subset; missing hooks
    are no-ops.
    """

    def on_push_begin(self, **kwargs: Any) -> Any: ...

    def global_shuffle(self, **kwargs: Any) -> Any: ...

    def execute_function(self, **kwargs: Any) -> Any: ...

    def on_shuffle_end(self, **kwargs: Any) -> Any: ...

    def on_push_end(self, **kwargs: Any) -> Any: ...


#: Hook names considered valid dispatch positions.  ``fast_forward`` is
#: dispatched once on elastic rejoin, before the hot loop resumes;
#: ``adopt_shards`` is dispatched when a cluster view change
#: re-partitions a dead host's shard range onto this producer
#: (``ddl_tpu.cluster``, ShardAdoption control message).
CALLBACK_POSITIONS: tuple[str, ...] = (
    "on_init",
    "post_init",
    "fast_forward",
    "adopt_shards",
    "on_push_begin",
    "global_shuffle",
    "execute_function",
    "on_shuffle_end",
    "on_push_end",
)
