"""Producer runtime: the worker-side window-fill loop.

Parity with reference ``ddl/datapusher.py``: construction performs the
metadata handshake and first fill (``datapusher.py:46-124``), then
``push_data`` runs the hot loop (``datapusher.py:147-170``):
``global_shuffle`` → ``execute_function`` → offer window → wait for it back.

TPU-native differences:

- The window the user fills (``my_ary``) is a private array; a committed
  copy lands in the next free ring slot.  With ``nslots>=2`` the producer
  refills while the consumer drains — the double-buffering the reference
  sketched but never built (reference ``ddl/mpi_dataloader.py:21-28``).
  Producer functions with ``inplace_fill = True`` skip the private array
  and write straight into ring slots (zero-copy fill); functions
  advertising ``supports_inplace_fill`` get the same slot view whenever
  no global shuffle needs a persistent ``my_ary`` and ``DDL_TPU_INPLACE``
  allows (write-once producers — acquire before fill, integrity trailer
  stamped strictly AFTER the fill, so a mid-fill crash can never commit
  a torn slot).
- The callback chain actually runs every callback (SURVEY Q1 fixed), so a
  registered global shuffler really executes.
- Shutdown arrives as :class:`ShutdownRequested` out of any blocked ring
  wait — the analog of the reference's Waitany-vs-Ibarrier race
  (reference ``ddl/connection.py:161-182``).
"""

from __future__ import annotations

import logging
from typing import Any, List, Optional

import numpy as np

from ddl_tpu import integrity
from ddl_tpu.datasetwrapper import DataProducerOnInitReturn
from ddl_tpu.exceptions import DoesNotMatchError, ShutdownRequested
from ddl_tpu.faults import armed_plan, fault_point
from ddl_tpu.obs import aggregate as obs_aggregate
from ddl_tpu.obs import spans as obs_spans
from ddl_tpu.observability import Metrics, metrics as default_metrics
from ddl_tpu.transport.connection import NOTHING, ProducerConnection
from ddl_tpu.types import (
    ControlEnvelope,
    MetaData_Consumer_To_Producer,
    MetaData_Producer_To_Consumer,
    ReplayRequest,
    RunMode,
    ShardAdoption,
    Topology,
    normalize_splits,
)
from ddl_tpu.utils import execute_callbacks, for_all_methods, with_logging

logger = logging.getLogger("ddl_tpu")

#: Default ring depth. 2 = double buffering; 1 = reference-style strict
#: alternation (one window per producer, consumer and producer ping-pong).
DEFAULT_NSLOTS = 2


def inplace_enabled(override: bool = None) -> bool:
    """The ``DDL_TPU_INPLACE`` gate (default ON): lets producers that
    advertise ``supports_inplace_fill`` write straight into ring slots.
    ``0`` is the escape hatch back to the private-array + commit-memcpy
    fill (debugging, byte-identity A/B) — it never affects producers
    that FORCE ``inplace_fill = True`` (that is their contract, not a
    preference)."""
    from ddl_tpu.utils import env_flag

    return env_flag("DDL_TPU_INPLACE", override)


def _abort_sentinel() -> str:
    """The consumer's ABORT broadcast string (lazy: env imports this
    module inside its spawn target, so a top-level import would cycle)."""
    from ddl_tpu.env import ABORT

    return ABORT


# DEBUG call tracing on every method, as the reference did
# (``for_all_methods(with_logging)``, reference ``datapusher.py:44``);
# ``_commit_window`` (per-window hot path) stays quiet.
@for_all_methods(
    with_logging,
    exclude=("_commit_window", "_stamp_and_commit", "_encode_and_commit",
             "_slot_array", "_poll_control"),
)
class DataPusher:
    """One producer worker: handshake, then fill windows until shutdown.

    Parity: reference ``ddl/datapusher.py:45-170``.
    """

    def __init__(
        self,
        connection: ProducerConnection,
        topology: Topology,
        producer_idx: int,
        nslots: int = DEFAULT_NSLOTS,
        metrics: Optional[Metrics] = None,
        shuffler_factory: Any = None,
        rejoin_ring: Any = None,
    ):
        """``rejoin_ring`` (elastic recovery): attach to a predecessor's
        surviving ring (shm name or in-process ring object) instead of
        creating one, and fast-forward the producer function to the data
        position the ring's committed count records."""
        self.connection = connection
        self.topology = topology
        self.producer_idx = producer_idx
        self.nslots = nslots
        self.metrics = metrics or default_metrics()
        self._iteration = 0
        # Last applied cluster view epoch (ShardAdoption fence).
        self._view_epoch = -1
        # Acked control-envelope unwrap (ddl_tpu.transport.envelope):
        # dedup by (incarnation, seq) + command fencing, with an ack
        # back per envelope so the consumer's retry loop terminates.
        from ddl_tpu.transport.envelope import EnvelopeReceiver

        self._envelope_rx = EnvelopeReceiver(producer_idx=producer_idx)
        # Cross-process observability shipping (ddl_tpu.obs): PROCESS
        # workers periodically send cumulative Metrics snapshots (+
        # armed-span deltas) back over this control channel; THREAD
        # producers share the consumer registry and never ship.
        self._obs_ship_every = (
            obs_aggregate.ship_every() if connection.cross_process else 0
        )
        self._obs_report_idx = 0

        # End-to-end window integrity (ddl_tpu.integrity): slots carry a
        # checksummed trailer header past the payload; the flag rides the
        # handshake reply so the consumer always agrees on slot layout.
        self._integrity = integrity.integrity_enabled()

        # -- handshake (reference datapusher.py:46-124) --------------------
        meta: MetaData_Consumer_To_Producer = connection.recv_metadata_as_producer()
        self.batch_size = meta.batch_size
        # The user's producer function is callbacks[0], exactly as in the
        # reference (datapusher.py:64); further callbacks append after it.
        self.callbacks: List[Any] = [meta.data_producer_function]
        # Per-job integrity namespace (ddl_tpu.serve.jobs): trailer
        # seqs are stamped at seq_base + iteration.  Rides the producer
        # function — the wire_dtype handshake pattern — so the base
        # crosses the spawn boundary with the function itself and the
        # consumer reads the identical attribute.
        self.seq_base = int(
            getattr(meta.data_producer_function, "seq_base", 0) or 0
        )

        init_ret = execute_callbacks(
            self.callbacks,
            "on_init",
            producer_idx=producer_idx,
            n_producers=topology.n_producers,
            instance_idx=topology.instance_idx,
            n_instances=topology.n_instances,
            batch_size=meta.batch_size,
        )
        if not isinstance(init_ret, DataProducerOnInitReturn):
            raise DoesNotMatchError(
                init_ret, "on_init must return DataProducerOnInitReturn"
            )
        self.shape = tuple(int(s) for s in init_ret.shape)
        self.dtype = np.dtype(init_ret.dtype)
        self.splits = normalize_splits(init_ret.splits, init_ret.nValues)
        if self.shape[0] != init_ret.nData:
            raise DoesNotMatchError(
                self.shape, f"shape[0] must equal nData={init_ret.nData}"
            )
        self.batches_per_window = init_ret.nData // meta.batch_size
        if self.batches_per_window < 1:
            raise DoesNotMatchError(
                meta.batch_size,
                f"batch_size {meta.batch_size} exceeds window nData "
                f"{init_ret.nData}",
            )
        self.window_nbytes = int(np.prod(self.shape)) * self.dtype.itemsize
        # Wire format (ddl_tpu.wire): the reader's per-capability
        # wire_dtype (env-overridable) selects what BYTES the slot
        # commit carries — raw, or the blockwise bf16/int8 encoding
        # with scales in the integrity trailer extension.  Lossy wire
        # needs the trailer (scales have nowhere else to travel) and a
        # float window; both are validated at handshake, not mid-run.
        from ddl_tpu import wire

        self.wire_dtype = wire.resolve_wire_dtype(
            getattr(meta.data_producer_function, "wire_dtype", "raw")
        )
        if self.wire_dtype != "raw":
            if not self._integrity:
                raise DoesNotMatchError(
                    self.wire_dtype,
                    "lossy wire_dtype needs DDL_TPU_INTEGRITY on (the "
                    "quantization scales travel in the slot trailer "
                    "extension next to the CRC)",
                )
            if not wire.lossy_supported(self.dtype):
                raise DoesNotMatchError(
                    self.dtype.name,
                    f"lossy wire_dtype {self.wire_dtype!r} needs a float "
                    "window dtype (use the lossless codec tier for "
                    "token/image shards)",
                )
        self._enc_nbytes = wire.encoded_nbytes(
            self.shape, self.dtype, self.wire_dtype
        )
        self._scale_nbytes = wire.scale_bytes_for(
            self.shape, self.wire_dtype
        )
        # Fill discipline: ``inplace_fill = True`` on the producer
        # function FORCES slot-view fills (the original contract);
        # ``supports_inplace_fill = True`` advertises write-once
        # capability and lets the pusher decide — in place whenever no
        # global shuffle needs a persistent private array and the
        # ``DDL_TPU_INPLACE`` gate is on.  Resolved AFTER the shuffler
        # below exists, since the shuffler is what forbids it.
        self._forced_inplace = bool(
            getattr(meta.data_producer_function, "inplace_fill", False)
        )
        self._auto_inplace = bool(
            getattr(meta.data_producer_function, "supports_inplace_fill", False)
        )
        self.inplace_fill = self._forced_inplace
        self._fill_slot: Optional[int] = None

        # Global shuffler: registered as an additional callback when the
        # topology and config ask for it (reference datapusher.py:89-108) —
        # and unlike the reference, it will actually run (Q1 fixed).
        self.shuffler = None
        if (
            topology.n_instances > 1
            and meta.global_shuffle_fraction_exchange > 0.0
            and shuffler_factory is not None
        ):
            num_exchange = int(
                init_ret.nData * meta.global_shuffle_fraction_exchange
            )
            if num_exchange > 0:
                if self.inplace_fill:
                    # The exchange would operate on nslots-stale slot
                    # content and its result would then be destroyed by
                    # the contractually required full rewrite — silently
                    # wrong data distribution, so reject the combination.
                    raise DoesNotMatchError(
                        type(meta.data_producer_function).__name__,
                        "global shuffle is incompatible with "
                        "inplace_fill producers (the exchange needs a "
                        "persistent my_ary; use the default copy fill)",
                    )
                self.shuffler = shuffler_factory(
                    topology=topology,
                    producer_idx=producer_idx,
                    num_exchange=num_exchange,
                    exchange_method=meta.exchange_method,
                )
                # Degradation events must land in THIS pipeline's
                # registry (factories stay picklable, so the registry
                # cannot ride through them — it is injected post-hoc).
                if hasattr(self.shuffler, "metrics"):
                    self.shuffler.metrics = self.metrics
                if rejoin_ring is not None:
                    # Rejoining a LIVE exchange needs POSITIVE capability:
                    # a replay-capable shuffler (round re-entry over a
                    # retention fabric — ThreadExchangeShuffler over
                    # Rendezvous/ShmRendezvous advertises it) and a ring
                    # deep enough that the last committed window cannot
                    # share a slot with the predecessor's in-flight
                    # (possibly torn) fill.  Anything else fails HERE, at
                    # handshake — as the pre-replay code did — instead of
                    # timing out at runtime or desyncing the schedule.
                    if not getattr(
                        self.shuffler, "supports_elastic_replay", False
                    ) or not callable(
                        getattr(self.shuffler, "rejoin", None)
                    ):
                        raise DoesNotMatchError(
                            type(self.shuffler).__name__,
                            "elastic respawn with global shuffle needs a "
                            "replay-capable shuffler (consumed-box "
                            "retention + a rejoin(round) re-entry "
                            "method); this one does not advertise "
                            "supports_elastic_replay / rejoin",
                        )
                    # (The matching nslots >= 2 torn-fill guard runs
                    # after ring attach, against the ATTACHED ring's
                    # real geometry — the ctor arg may disagree with
                    # what the predecessor created.)
                # Fail LOUDLY at handshake when the shuffler's fabric
                # declares a span too narrow to reach its exchange
                # partners, instead of every producer stalling against a
                # board its peers can't see (the reference's exchange ran
                # between OS processes via MPI, reference
                # shuffle.py:92-108 — host-side fabrics here have
                # narrower spans and must be matched).  Custom shufflers
                # WITHOUT a span attribute pass through unchecked — the
                # guard only rejects spans it positively knows are too
                # narrow, so pre-existing user fabrics keep working.
                span = getattr(self.shuffler, "span", None)
                if topology.mode is RunMode.MULTIHOST and span in (
                    "thread", "process",
                ):
                    raise DoesNotMatchError(
                        span,
                        "host-side global shuffle cannot span hosts "
                        "(exchange partners are other instances' "
                        "producer processes); use the trainer-side "
                        "device exchange (ddl_tpu.parallel."
                        "DeviceGlobalShuffler over the instance mesh "
                        "axis) for MULTIHOST runs — the producer-side "
                        "DeviceExchangeShuffler resolves its device "
                        "tier off outside THREAD topologies",
                    )
                if connection.cross_process and span == "thread":
                    raise DoesNotMatchError(
                        span,
                        "an in-process Rendezvous cannot reach producers "
                        "in other processes (each process waits on its "
                        "own private board until timeout); pass "
                        "ThreadExchangeShuffler.factory(rendezvous="
                        "ShmRendezvous(session)) with a shared session "
                        "string — DeviceExchangeShuffler.factory "
                        "accepts the same and runs the host exchange "
                        "over it across processes — or use the "
                        "trainer-side device exchange",
                    )
                self.callbacks.append(self.shuffler)

        # Wire-encoded commits need a RAW source array distinct from the
        # slot (the encode reads the float window and writes the int8/
        # bf16 payload — encoding a slot in place would destroy its own
        # input), so the lossy wire keeps the private-array fill: auto
        # inplace is silently skipped (the shuffle precedent), forced
        # inplace is a contract conflict and fails at handshake.
        if self.wire_dtype != "raw" and self._forced_inplace:
            raise DoesNotMatchError(
                type(meta.data_producer_function).__name__,
                "inplace_fill producers cannot use a lossy wire_dtype "
                "(the encode needs the raw window as its source; use "
                "the default copy fill or wire_dtype='raw')",
            )
        # Auto inplace (write-once producers): a shuffler needs my_ary to
        # persist across iterations (the exchange mutates it between
        # fills), so capability-advertising producers silently keep the
        # copying fill when one is active; otherwise they write straight
        # into ring slots unless DDL_TPU_INPLACE=0 opts out.
        if (
            self._auto_inplace
            and not self.inplace_fill
            and self.shuffler is None
            and self.wire_dtype == "raw"
            and inplace_enabled()
        ):
            self.inplace_fill = True
        if not self.inplace_fill:
            # Private window the user fills; commits copy it into ring slots.
            self.my_ary = np.zeros(self.shape, dtype=self.dtype)

        # Integrity slots are one trailer header larger than the payload;
        # geometry (shape/splits/payload) is untouched.  Wire-encoded
        # commits use strictly LESS of the slot (encoded payload +
        # header + scales < raw payload for every supported float
        # dtype), so slots stay raw-sized: a replayed/rejoined producer
        # never depends on the wire setting for its ring geometry.
        slot_bytes = self.window_nbytes + (
            integrity.HEADER_BYTES if self._integrity else 0
        )
        if self.wire_dtype != "raw" and (
            self._enc_nbytes + integrity.HEADER_BYTES + self._scale_nbytes
            > slot_bytes
        ):
            # Degenerate geometries CAN overflow: int8 with 1 value per
            # row pays a 4-byte scale per 1-byte payload (scales are
            # per-row-block), so "encoded < raw" does not hold for
            # every shape — refuse at handshake like every other
            # invalid wire config, never mid-run.
            raise DoesNotMatchError(
                self.shape,
                f"wire_dtype {self.wire_dtype!r} does not shrink this "
                f"window geometry (encoded {self._enc_nbytes} + trailer "
                f"{integrity.HEADER_BYTES + self._scale_nbytes} exceeds "
                f"the {slot_bytes}-byte slot); use wire_dtype='raw' for "
                "windows this narrow",
            )
        if rejoin_ring is not None:
            self.ring = connection.attach_ring(rejoin_ring)
            if self._integrity and self.ring.slot_bytes < slot_bytes:
                # The predecessor created this ring without integrity
                # headroom: the incarnations disagree on DDL_TPU_INTEGRITY
                # (env drift across a respawn) — fail at handshake rather
                # than stamping headers over the next slot's payload.
                raise DoesNotMatchError(
                    self.ring.slot_bytes,
                    "surviving ring has no integrity-header headroom; "
                    "respawned producer must run with the same "
                    "DDL_TPU_INTEGRITY setting as its predecessor",
                )
            if self.shuffler is not None and self.ring.nslots < 2:
                # Checked against the ATTACHED ring's REAL geometry (the
                # ctor arg may disagree with what the predecessor
                # created): with one slot the last committed window
                # shares the slot the predecessor was filling when it
                # died, so the state restore could read a torn fill.
                raise DoesNotMatchError(
                    self.ring.nslots,
                    "elastic respawn with global shuffle needs "
                    "nslots >= 2: with one slot the last committed "
                    "window shares the slot the predecessor was "
                    "filling when it died, so the state restore could "
                    "read a torn fill",
                )
        else:
            self.ring = connection.create_ring(nslots, slot_bytes)
        if self.inplace_fill:
            # Zero-copy fill: the user writes straight into ring slots.
            # (On a fresh ring the first slot is free immediately; on a
            # rejoined ring this waits for a free slot like any fill.)
            self._fill_slot = self.ring.acquire_fill()
            self.my_ary = self._slot_array(self._fill_slot)
        connection.send_metadata(
            MetaData_Producer_To_Consumer(
                producer_idx=producer_idx,
                n_data=init_ret.nData,
                n_values=init_ret.nValues,
                shape=self.shape,
                splits=self.splits,
                batches_per_window=self.batches_per_window,
                dtype=self.dtype.name,
                integrity=self._integrity,
                wire_dtype=self.wire_dtype,
            )
        )

        # First fill (reference datapusher.py:113-119).
        execute_callbacks(self.callbacks, "post_init", my_ary=self.my_ary)

        if rejoin_ring is not None:
            # Replay to the predecessor's data position: the ring's
            # committed count IS the number of windows already published
            # (a death between data-write and commit re-publishes that
            # window — the consumer never saw it).  With integrity
            # headers the LAST COMMITTED SLOT's header is the exact
            # logical position instead: after a quarantine replay the
            # raw committed count includes discarded re-commits, so
            # counting commits would overshoot the data stream.
            committed = int(self.ring.stats()["committed"])
            done = committed
            if self._integrity and committed:
                # Header offset follows the wire format: encoded slots
                # commit the ENCODED payload size, and the encoding is a
                # pure function of (geometry, wire_dtype) the respawn
                # re-derives — env drift across a respawn already fails
                # the integrity-headroom check above.
                hdr = integrity.read_header(
                    self.ring.slot_view((committed - 1) % self.ring.nslots),
                    self._enc_nbytes,
                )
                if hdr.valid_magic:
                    done = hdr.seq + 1
            if done:
                execute_callbacks(
                    self.callbacks, "fast_forward", n=done,
                    my_ary=self.my_ary,
                )
                if self.shuffler is not None:
                    # fast_forward regenerates the LOCAL data stream (and
                    # RNG position), but lanes exchanged IN by peers over
                    # past rounds are not locally recoverable.  The last
                    # committed ring slot holds the predecessor's exact
                    # post-iteration my_ary (copy-fill is guaranteed
                    # here — shuffle + inplace_fill is rejected above,
                    # and slots are only ever overwritten by this
                    # producer), so restore the full state from it.
                    last = (committed - 1) % self.ring.nslots
                    if self.wire_dtype != "raw":
                        # Encoded slot: the predecessor's exact my_ary is
                        # not recoverable (the wire is lossy); restore
                        # the DECODED window — the same values the
                        # consumer served, so the exchange schedule
                        # stays coherent at wire precision.
                        from ddl_tpu import wire

                        view = self.ring.slot_view(last)
                        hdr = integrity.read_header(view, self._enc_nbytes)
                        wire.decode_window(
                            view[: self._enc_nbytes],
                            integrity.read_scales(
                                view, self._enc_nbytes, hdr.scale_bytes
                            ) if hdr.scale_bytes else None,
                            self.shape, self.dtype, self.wire_dtype,
                            out=self.my_ary,
                        )
                    else:
                        np.copyto(self.my_ary, self._slot_array(last))
            if self.shuffler is not None:
                # Re-enter the exchange schedule at the committed round:
                # the permutation is a pure function of (seed, round),
                # the round is in every mailbox key (tag = 2*round), and
                # consumed round mailboxes are RETAINED by the fabric
                # (Rendezvous/ShmRendezvous take keeps a replay copy
                # until the next round retires it) — so replaying the
                # death round's exchange is idempotent whether or not
                # the predecessor completed it.  rejoin() is part of the
                # capability contract checked above — never a private
                # field poke.
                self.shuffler.rejoin(done)
            self._iteration = done
            logger.info(
                "producer %d: rejoined ring at window %d",
                producer_idx, done,
            )

    # -- hot loop (reference datapusher.py:147-170) ------------------------

    def _slot_array(self, slot: int) -> np.ndarray:
        return (
            self.ring.slot_view(slot)[: self.window_nbytes]
            .view(self.dtype)
            .reshape(self.shape)
        )

    def _stamp_and_commit(self, slot: int) -> None:
        """Stamp the integrity trailer (crc + seq + producer) and publish.

        The ``producer.commit`` injection point runs AFTER the header is
        written, against the payload view — flipped bytes therefore
        mismatch the committed CRC exactly the way real shared-memory
        corruption would, and the consumer's drain-time verify catches
        it (tests/test_faults.py).
        """
        view = self.ring.slot_view(slot)
        if self._integrity:
            payload = view[: self.window_nbytes]
            integrity.write_header(
                view,
                self.window_nbytes,
                seq=self.seq_base + self._iteration,
                producer_idx=self.producer_idx,
                crc=integrity.window_crc(payload),
            )
        fault_point(
            "producer.commit",
            producer_idx=self.producer_idx,
            view=view[: self.window_nbytes],
        )
        self.ring.commit(slot, self.window_nbytes)

    def _encode_and_commit(self, slot: int) -> None:
        """Wire-encoded commit (``ddl_tpu.wire``): the slot carries the
        blockwise bf16/int8 payload, the scales travel in the trailer
        extension next to the CRC, and the CRC covers the ENCODED bytes
        + scales — so the consumer's drain-time verify catches wire
        corruption exactly like raw corruption, and quarantine-and-
        replay re-encodes from the deterministic raw stream.  The
        ``wire.encode`` chaos site fires against the encoded payload
        AFTER the header is stamped (the ``producer.commit`` timing),
        so flipped wire bytes mismatch the committed CRC.
        """
        from ddl_tpu import wire

        view = self.ring.slot_view(slot)
        payload, scales = wire.encode_window(self.my_ary, self.wire_dtype)
        enc = self._enc_nbytes
        view[:enc] = payload
        if scales is not None:
            integrity.write_scales(view, enc, scales)
        # ONE fold implementation for both sides of the contract: the
        # drain-time verify recomputes exactly integrity.wire_crc.
        crc = integrity.wire_crc(view, enc, self._scale_nbytes)
        integrity.write_header(
            view, enc,
            seq=self.seq_base + self._iteration,
            producer_idx=self.producer_idx,
            crc=crc,
            wire_code=wire.WIRE_CODES[self.wire_dtype],
            scale_bytes=self._scale_nbytes,
        )
        fault_point(
            "wire.encode",
            producer_idx=self.producer_idx,
            view=view[:enc],
        )
        # Byte accounting lands at the CONSUMER edge's decode (the one
        # registry every mode shares — PROCESS producers' registries
        # never cross the spawn boundary, and THREAD's shared default
        # registry would double-count if both sides incremented).
        self.ring.commit(slot, enc)

    def _commit_window(self) -> None:
        """Publish the filled window and stage the next fill target."""
        if self.inplace_fill:
            # my_ary IS the slot: publish it, then point my_ary at the
            # next free slot for the coming refill.
            assert self._fill_slot is not None
            self._stamp_and_commit(self._fill_slot)
        elif self.wire_dtype != "raw":
            slot = self.ring.acquire_fill()  # raises ShutdownRequested on stop
            self._encode_and_commit(slot)
        else:
            slot = self.ring.acquire_fill()  # raises ShutdownRequested on stop
            np.copyto(self._slot_array(slot), self.my_ary)
            self._stamp_and_commit(slot)
        self.metrics.incr("producer.windows")
        self.metrics.incr("producer.bytes", self.window_nbytes)
        if self.inplace_fill:
            self._fill_slot = self.ring.acquire_fill()
            self.my_ary = self._slot_array(self._fill_slot)

    def _poll_control(self) -> None:
        """Drain pending control messages (non-blocking, once per window).

        The channel is idle after the handshake; command messages
        (:class:`ReplayRequest` — quarantined corrupt slot, rewind and
        re-commit; :class:`ShardAdoption` — cluster re-partition) arrive
        mid-run wrapped in :class:`ControlEnvelope` when the sender uses
        the acked seam, bare when legacy/fire-and-forget, plus the
        consumer's ABORT broadcast (treated as shutdown, like the ring
        flag it accompanies).  Envelopes are unwrapped through the
        dedup + fencing receiver and ALWAYS acked — a duplicate or a
        zombie ex-leader's fenced-off command is dropped unapplied, but
        the ack still terminates the sender's retry loop.
        """
        while True:
            msg = self.connection.channel.try_recv()
            if msg is NOTHING:
                return
            if isinstance(msg, ControlEnvelope):
                payload, ack = self._envelope_rx.accept(msg)
                if ack.dup:
                    self.metrics.incr("producer.ctrl_dup_dropped")
                if ack.fence_rejected:
                    self.metrics.incr("producer.ctrl_fence_dropped")
                try:
                    self.connection.channel.send(ack)
                except (OSError, ValueError):
                    # Consumer side gone mid-teardown: the ack is
                    # best-effort (its sender is dead anyway).
                    pass
                if payload is None:
                    continue
                msg = payload  # dispatch the inner command below
            if isinstance(msg, ReplayRequest):
                self._handle_replay(msg.seq)
            elif isinstance(msg, ShardAdoption):
                self._handle_adoption(msg)
            elif isinstance(msg, str) and msg == _abort_sentinel():
                raise ShutdownRequested("consumer abort broadcast")
            else:
                logger.warning(
                    "producer %d: ignoring unexpected control message %r",
                    self.producer_idx, type(msg).__name__,
                )

    def _handle_adoption(self, msg: ShardAdoption) -> None:
        """Apply a cluster view change (``ddl_tpu.cluster``): adopt the
        re-partitioned shard ranges and suspend/resume the exchange.

        Epoch-fenced: a message at or below the last applied view epoch
        is DROPPED — view changes are ordered by construction and a
        slow/duplicated view-N message must never undo view N+1.
        """
        applied = self._view_epoch
        if msg.view_epoch <= applied:
            logger.debug(
                "producer %d: dropping stale adoption (epoch %d <= %d)",
                self.producer_idx, msg.view_epoch, applied,
            )
            return
        self._view_epoch = msg.view_epoch
        logger.warning(
            "producer %d: adopting shard ranges %s at view epoch %d "
            "(peer %d/%d)",
            self.producer_idx, msg.ranges, msg.view_epoch,
            msg.peer_idx, msg.n_peers,
        )
        self.metrics.incr("producer.shard_adoptions")
        if msg.suspend_exchange is not None and self.shuffler is not None:
            # The ladder's shuffle rung: degrade to node-local while the
            # exchange permutation still names a dead host; resume at
            # the rejoin fence.
            if msg.suspend_exchange:
                suspend = getattr(self.shuffler, "suspend_exchange", None)
                if callable(suspend):
                    suspend()
            else:
                resume = getattr(self.shuffler, "resume_exchange", None)
                if callable(resume):
                    resume()
        execute_callbacks(
            self.callbacks,
            "adopt_shards",
            ranges=msg.ranges,
            view_epoch=msg.view_epoch,
            peer_idx=msg.peer_idx,
            n_peers=msg.n_peers,
            my_ary=self.my_ary,
        )

    def _handle_replay(self, seq: int) -> None:
        """Rewind the producer function to logical window ``seq`` and
        resume committing from there — the corrupt-slot re-request path
        (``ddl_tpu.integrity``).  Same deterministic-replay recipe as a
        respawned incarnation: ``on_init`` → ``post_init`` →
        ``fast_forward(seq)``; the consumer discards whatever this
        producer committed past ``seq`` before the request arrived.
        """
        if self.shuffler is not None:
            # Peer-exchanged lanes are not locally regenerable; the
            # consumer never requests replay in this configuration
            # (it raises IntegrityError instead) — refuse rather than
            # silently desync the exchange schedule.
            logger.error(
                "producer %d: ignoring replay request at %d (cross-"
                "instance exchange active; stream is not locally "
                "replayable)", self.producer_idx, seq,
            )
            return
        # The request carries the NAMESPACED seq (the consumer speaks
        # trailer seqs); the producer function's logical position is
        # the local half.
        seq = max(0, int(seq) - self.seq_base)
        logger.warning(
            "producer %d: replaying window stream from %d "
            "(corrupt-slot re-request; was at %d)",
            self.producer_idx, seq, self._iteration,
        )
        self.metrics.incr("producer.replays")
        execute_callbacks(
            self.callbacks,
            "on_init",
            producer_idx=self.producer_idx,
            n_producers=self.topology.n_producers,
            instance_idx=self.topology.instance_idx,
            n_instances=self.topology.n_instances,
            batch_size=self.batch_size,
        )
        execute_callbacks(self.callbacks, "post_init", my_ary=self.my_ary)
        if seq:
            execute_callbacks(
                self.callbacks, "fast_forward", n=seq, my_ary=self.my_ary
            )
        self._iteration = seq

    def push_data(self) -> None:
        execute_callbacks(self.callbacks, "on_push_begin")
        clean = False
        try:
            while True:
                # Order matches the reference loop (datapusher.py:152-166):
                # replay/abort poll, chaos injection point, exchange
                # across instances, then the user's refill/shuffle, then
                # hand the window to the consumer.
                self._poll_control()
                fault_point(
                    "producer.fill",
                    producer_idx=self.producer_idx,
                    should_abort=self.ring.is_shutdown,
                )
                # Window lifecycle span (ddl_tpu.obs): the fill stage —
                # exchange + user refill — keyed on the same
                # (producer_idx, seq) identity the integrity trailer
                # stamps.  One attribute read when tracing is disarmed.
                _span_t0 = obs_spans.t0()
                execute_callbacks(
                    self.callbacks,
                    "global_shuffle",
                    my_ary=self.my_ary,
                    iteration=self._iteration,
                    # Exchange waits must observe shutdown: the partner
                    # instance may already be tearing down and never post
                    # its half (the rendezvous analog of the reference's
                    # Waitany-vs-Ibarrier race, connection.py:161-182).
                    should_abort=self.ring.is_shutdown,
                )
                execute_callbacks(
                    self.callbacks,
                    "execute_function",
                    my_ary=self.my_ary,
                    iteration=self._iteration,
                )
                obs_spans.record(
                    "producer.fill", self.producer_idx, self._iteration,
                    _span_t0,
                )
                if self.inplace_fill and armed_plan() is not None:
                    # Chaos hook for the write-once path: fires with the
                    # slot fully written but NOT yet stamped/committed —
                    # a crash here leaves a torn slot (new payload under
                    # the previous occupant's stale trailer) that must
                    # never be served: stamp-after-fill means it is
                    # never committed, and the drain-time verify is the
                    # backstop if counting ever regressed.  The byte view
                    # costs a ring FFI call per window, so it is built
                    # only behind the armed check (the disarmed push loop
                    # stays zero-cost, faults.py's contract).
                    fault_point(
                        "pusher.inplace_fill",
                        producer_idx=self.producer_idx,
                        view=self.ring.slot_view(self._fill_slot)[
                            : self.window_nbytes
                        ],
                        should_abort=self.ring.is_shutdown,
                    )
                _span_t0 = obs_spans.t0()
                self._commit_window()
                # The commit span covers acquire_fill's free-slot wait
                # too — producer-side backpressure is exactly what a
                # trace of a slow consumer should show.
                obs_spans.record(
                    "producer.commit", self.producer_idx, self._iteration,
                    _span_t0,
                )
                execute_callbacks(
                    self.callbacks, "on_shuffle_end", iteration=self._iteration
                )
                self._iteration += 1
                self._maybe_ship_obs()
        except ShutdownRequested:
            clean = True
            logger.debug(
                "producer %d: shutdown after %d windows",
                self.producer_idx,
                self._iteration,
            )
        finally:
            execute_callbacks(self.callbacks, "on_push_end")
            self._finalize(clean=clean)

    def _maybe_ship_obs(self, final: bool = False) -> None:
        """Ship one cross-process ObsReport (ddl_tpu.obs aggregation)
        when due: every ``_obs_ship_every`` windows, plus a ``final``
        ship at shutdown so short runs still aggregate.  PROCESS mode
        only (``_obs_ship_every`` is 0 for THREAD producers, whose
        registry IS the consumer's).  A broken channel (consumer gone
        first during teardown) drops the report — observability must
        never escalate a clean shutdown."""
        every = self._obs_ship_every
        if every <= 0:
            return
        if not final and self._iteration % every:
            return
        self._obs_report_idx += 1
        report = obs_aggregate.build_report(
            self.producer_idx - 1,  # consumer-side 0-based ring index
            self._obs_report_idx,
            self.metrics,
            view_epoch=self._view_epoch,
        )
        try:
            self.connection.channel.send(report)
        except (OSError, ValueError) as e:
            logger.debug(
                "producer %d: obs report dropped (%s)",
                self.producer_idx, e,
            )

    def _finalize(self, clean: bool = True) -> None:
        if clean:
            # Final observability ship BEFORE the channel closes: the
            # consumer's shutdown drain is what closes the PROCESS-mode
            # blind spot for runs shorter than the periodic cadence.
            self._maybe_ship_obs(final=True)
        # A CRASHING producer must leave the shm ring linked: elastic
        # recovery (WorkerSet.respawn) attaches a replacement to it by
        # name.  Only a clean shutdown removes the name; the consumer's
        # finalize is the backstop for crashed-and-never-respawned rings.
        self.connection.finalize(unlink=clean)
