"""Observability: counters, gauges and stall accounting.

The reference had no metrics at all (SURVEY §5.5) — only DEBUG log lines.
The rebuild's north-star metrics (samples/sec/host ingest, input-pipeline
stall %, H2D bandwidth utilisation — BASELINE.md) need first-class
instrumentation, so every pipeline component records into a shared
:class:`Metrics` registry that the benchmark suite and user code can read.

Well-known name families (each component documents its own; the bench
JSON contract in ``tools/bench_smoke.py`` pins the load-bearing ones):
``consumer.*`` / ``ingest.*`` (drain + device feed — incl.
``ingest.release_wait``, forced transfer-completion waits before slot
release), ``trainer.*`` (``trainer.window_wait`` — the stream loop's
next-window waits, near zero when H2D overlaps the scans;
``trainer.ingest_overlap`` — acquire time measurably hidden under a
still-computing scan, the fused step's overlap proof; and the
``trainer.fused_windows`` counter — windows driven through the fused
compute/ingest loop, whose loader-side release gating rides
``ingest.fused_gated``), ``pp.*``
(``pp.bubble`` / ``pp.chunks`` gauges — the analytic bubble and chunk
count of the last-compiled pipeline schedule), ``staging.*`` (the
staged-ingest engine), ``watchdog.*`` / ``integrity.*`` / ``shuffle.*``
(robustness events), ``ici.*`` (the device-side distribution tier —
``ici.bytes``/``ici.windows``/``ici.fallbacks`` counters, the
``ici.fanout``/``ici.redistribute`` dispatch timers, the
``ici.peak_bytes`` gauge asserted by the redistribution planner, plus
the fused two-slot protocol's ``ici.fused_windows`` counter and
``ici.slots_in_flight`` landing-slot occupancy gauge — its ``.max``
high-water is the report's ``slots_in_flight``),
``opt.*`` (the distributed optimizer —
``opt.state_bytes_per_replica``/``opt.state_bytes_total`` gauges set at
init from the placed state, ``opt.grad_comm_bytes_raw``/
``opt.grad_comm_bytes_quantized`` per-step payload gauges set at trace
time, and the ``opt.gather``/``opt.scatter`` collective-leg timers),
``cache.*`` (the shard cache —
``cache.hits/misses/evictions/spills/spill_hits/spill_evictions/
quarantined/warmed/backend_retries/backend_failures`` counters plus
``cache.resident_bytes`` / ``cache.spill_bytes`` gauges, whose ``.max``
high-water marks ride along automatically), and ``cluster.*`` (the
multi-host control plane, ``ddl_tpu.cluster`` —
``cluster.view_changes/host_losses/rejoins/heartbeats/
heartbeats_dropped/shard_adoptions/cache_adoptions`` counters, the
``cluster.epoch``/``cluster.hosts`` gauges, plus the consumer-side
pool seam's ``consumer.pool_updates`` counter / ``consumer.pool_size``
gauge and the producer-side ``producer.shard_adoptions`` /
``shuffle.suspensions/resumes/suspended_rounds`` ladder counters), and
``serve.*`` (the multi-tenant ingest service, ``ddl_tpu.serve`` —
``serve.admissions/rounds/tenant_bursts/scale_ups/scale_downs/replans``
counters, the ``serve.admission_wait`` / ``serve.scale_up_reaction``
timers, the ``serve.tenants`` / ``serve.pool_hosts`` /
``serve.standby_hosts`` gauges, plus the per-tenant
``serve.stall.<tenant>`` admission-stall gauges; each tenant's own
traffic rides ``ingest.<tenant>.*`` — ``bytes``/``windows``/``bursts``
counters and the ``admission_wait`` timer — read back per tenant with
:meth:`Metrics.prefixed`), and ``wire.*`` (the data-plane wire format,
``ddl_tpu.wire`` — ``wire.encoded_bytes`` bytes that actually traveled
an encode-engaged wire (slot commits, exchange envelopes, the ICI
fan-out) next to ``wire.payload_bytes`` the same windows' logical raw
bytes, the ``wire.decoded_windows`` consumer-edge decode counter, and
the ladder counters ``wire.decode_fails`` / ``wire.fallbacks`` — a
"passing" run that silently dropped its exchange to raw encoding must
be visible in the BENCH_* trajectories.  Scope caveat, the standard
producer.* one: slot-path decode counters are CONSUMER-side and
surface in every mode, while the exchange wire's ladder events count
in the shuffler's own registry — shared with the consumer in THREAD
mode, per worker process in PROCESS mode, where the raw-latch also
logs at ERROR), and ``resilience.*`` (preemption tolerance,
``ddl_tpu.resilience`` — the ``notices``/``drains``/``final_ckpts``
drain-ladder counters with the ``resilience.drain`` timer and the
``drain_within_deadline`` gauge, the async checkpoint tier's
``ckpts``/``ckpt_skipped``/``ckpt_retired``/``ckpt_write_failures``
counters with the ``ckpt_submit`` (hot-path stall) vs ``ckpt_write``
(hidden) timer split and the ``ckpt_bytes`` gauge, the restore
ladder's ``ckpt_restores``/``ckpt_quarantined``/``ckpt_unverified``/
``ckpt_cold_starts`` counters, plus the legacy synchronous path's
``ckpt_sync`` timer; the serve-plane revocation rung rides
``serve.revocations``/``serve.revoked_waiters``/
``serve.revoked_inflight`` and per-tenant
``ingest.<tenant>.revocations``).
"""

from __future__ import annotations

import collections
import dataclasses
import threading
import time
from typing import Dict


@dataclasses.dataclass
class Timer:
    """Accumulates total seconds and call count for one labelled section."""

    total_s: float = 0.0
    count: int = 0

    def add(self, dt: float) -> None:
        self.total_s += dt
        self.count += 1


class Metrics:
    """Thread-safe counter/timer registry.

    Producers, the transport and the dataloader all record here; a single
    registry per pipeline is shared via :func:`metrics` (module default) or
    injected explicitly for tests.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._counters: Dict[str, float] = collections.defaultdict(float)
        self._timers: Dict[str, Timer] = collections.defaultdict(Timer)
        self._gauges: Dict[str, float] = {}
        self._t0 = time.perf_counter()

    def incr(self, name: str, value: float = 1.0) -> None:
        with self._lock:
            self._counters[name] += value

    def set_gauge(self, name: str, value: float) -> None:
        """Point-in-time level (queue depth, pool size).  The high-water
        mark rides along as ``<name>.max`` so a burst between snapshots
        is still visible in the bench JSON."""
        with self._lock:
            self._gauges[name] = value
            peak = self._gauges.get(f"{name}.max", value)
            self._gauges[f"{name}.max"] = max(peak, value)

    def gauge(self, name: str) -> float:
        with self._lock:
            return self._gauges.get(name, 0.0)

    def add_time(self, name: str, seconds: float) -> None:
        with self._lock:
            self._timers[name].add(seconds)

    def timed(self, name: str) -> "_TimedCtx":
        return _TimedCtx(self, name)

    def counter(self, name: str) -> float:
        with self._lock:
            return self._counters.get(name, 0.0)

    def timer(self, name: str) -> Timer:
        with self._lock:
            t = self._timers.get(name)
            return Timer(t.total_s, t.count) if t else Timer()

    def elapsed_s(self) -> float:
        return time.perf_counter() - self._t0

    def reset(self) -> None:
        with self._lock:
            self._counters.clear()
            self._timers.clear()
            self._gauges.clear()
            self._t0 = time.perf_counter()

    def snapshot(self) -> Dict[str, float]:
        """Flat dict of everything, for logging / bench JSON."""
        with self._lock:
            out: Dict[str, float] = dict(self._counters)
            for k, t in self._timers.items():
                out[f"{k}.total_s"] = t.total_s
                out[f"{k}.count"] = float(t.count)
            out.update(self._gauges)
            out["elapsed_s"] = time.perf_counter() - self._t0
            return out

    def prefixed(self, prefix: str) -> Dict[str, float]:
        """Counters + gauges under one name family (``prefix`` up to and
        including its trailing dot, e.g. ``"cache."``), keys stripped of
        the prefix — the bench assembles its per-subsystem JSON blocks
        from this instead of hand-listing every counter."""
        with self._lock:
            out: Dict[str, float] = {
                k[len(prefix):]: v
                for k, v in self._counters.items()
                if k.startswith(prefix)
            }
            out.update(
                (k[len(prefix):], v)
                for k, v in self._gauges.items()
                if k.startswith(prefix)
            )
            return out

    # Derived north-star metrics -------------------------------------------

    def rates(self) -> Dict[str, float]:
        """All derived rates over ONE elapsed snapshot.

        The single formula site: computing each rate with its own "now"
        (as the per-metric helpers below would if called in sequence)
        skews their ratios by the microseconds between calls, which is
        visible on short measurement spans — bytes/s and samples/s must
        agree exactly when their counters cover identical windows.
        """
        with self._lock:
            # ONE critical section for all three reads: a concurrent
            # finish() increments bytes then samples, and observing one
            # without the other would skew the ratio by a window.
            el = time.perf_counter() - self._t0
            samples = self._counters.get("consumer.samples", 0.0)
            nbytes = self._counters.get("ingest.bytes", 0.0)
            wait = self._timers.get("consumer.wait")
            stall = wait.total_s if wait else 0.0
        if el <= 0:
            return {
                "samples_per_sec": 0.0,
                "stall_fraction": 0.0,
                "ingest_bytes_per_sec": 0.0,
                "elapsed_s": el,
            }
        return {
            "samples_per_sec": samples / el,
            "stall_fraction": stall / el,
            "ingest_bytes_per_sec": nbytes / el,
            "elapsed_s": el,
        }

    def samples_per_sec(self) -> float:
        return self.rates()["samples_per_sec"]

    def stall_fraction(self) -> float:
        """Fraction of consumer wall time spent waiting on the pipeline."""
        return self.rates()["stall_fraction"]

    def ingest_bytes_per_sec(self) -> float:
        return self.rates()["ingest_bytes_per_sec"]


class _TimedCtx:
    def __init__(self, m: Metrics, name: str):
        self._m, self._name = m, name

    def __enter__(self) -> "_TimedCtx":
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc: object) -> None:
        self._m.add_time(self._name, time.perf_counter() - self._t0)


_default = Metrics()


def metrics() -> Metrics:
    """The process-default registry."""
    return _default
