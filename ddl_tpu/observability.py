"""Observability: counters, gauges, timers, histograms, stall accounting.

The reference had no metrics at all (SURVEY §5.5) — only DEBUG log lines.
The rebuild's north-star metrics (samples/sec/host ingest, input-pipeline
stall %, H2D bandwidth utilisation — BASELINE.md) need first-class
instrumentation, so every pipeline component records into a shared
:class:`Metrics` registry that the benchmark suite and user code can read.

The full well-known name-family reference (every ``consumer.*`` /
``ingest.*`` / ``trainer.*`` / ``staging.*`` / ``ici.*`` / ``opt.*`` /
``cache.*`` / ``cluster.*`` / ``serve.*`` / ``wire.*`` /
``resilience.*`` / ``obs.*`` name, its type, and its emitting site)
lives in **docs/OBSERVABILITY.md** — kept out of this docstring so the
table can be machine-checked: ``tests/test_obs.py`` asserts every
documented name has at least one emitting site in the tree, so a new
subsystem cannot document names it never emits.  The bench JSON
contract in ``tools/bench_smoke.py`` pins the load-bearing ones.

Beyond counters/gauges/timers, :meth:`Metrics.observe` records values
into fixed log-spaced bounded histograms (:data:`HIST_BUCKETS_PER_DECADE`
buckets per decade over [:data:`HIST_MIN`, :data:`HIST_MAX`)) and
:meth:`Metrics.quantile` reads percentiles back — the first-class home
for every p50/p99 the benches previously computed ad hoc.  PROCESS-mode
worker registries are merged into the consumer's under
``producer.<idx>.*`` via :meth:`Metrics.adopt` (the cross-process
aggregation seam — :mod:`ddl_tpu.obs`); per-window span tracing and the
chaos flight recorder also live in :mod:`ddl_tpu.obs`.
"""

from __future__ import annotations

import collections
import dataclasses
import math
import threading

from ddl_tpu.concurrency import named_lock
import time
from typing import Dict, List, Optional


@dataclasses.dataclass
class Timer:
    """Accumulates total seconds and call count for one labelled section."""

    total_s: float = 0.0
    count: int = 0

    def add(self, dt: float) -> None:
        self.total_s += dt
        self.count += 1


#: Histogram geometry: FIXED log-spaced buckets, identical in every
#: process — cross-process aggregation (ddl_tpu.obs) merges bucket
#: counts elementwise, which is only sound when every registry shares
#: one bucket layout.  6 buckets/decade ⇒ a bucket spans ×10^(1/6)
#: ≈ 1.47, so an interpolated quantile is exact to within ±47% — ample
#: for the order-of-magnitude questions p99s answer (and the reason
#: quantile() interpolates geometrically inside the bucket).
HIST_BUCKETS_PER_DECADE = 6
#: Values below HIST_MIN (including zero and negatives) land in the
#: underflow bucket; values >= HIST_MAX in the overflow bucket — the
#: histogram is BOUNDED by construction (DDL023's whole point).
HIST_MIN = 1e-7
HIST_MAX = 1e5
_HIST_DECADES = 12  # log10(HIST_MAX / HIST_MIN)
_HIST_N = HIST_BUCKETS_PER_DECADE * _HIST_DECADES  # finite buckets


def hist_bounds() -> List[float]:
    """Upper bounds of the finite buckets (shared, fixed layout)."""
    return [
        HIST_MIN * 10.0 ** ((i + 1) / HIST_BUCKETS_PER_DECADE)
        for i in range(_HIST_N)
    ]


class Histogram:
    """One bounded log-spaced histogram (see :func:`hist_bounds`).

    Layout: ``counts[0]`` is the underflow bucket (< HIST_MIN, incl. 0
    and negatives), ``counts[1+i]`` covers
    ``[HIST_MIN·10^(i/6), HIST_MIN·10^((i+1)/6))``, and ``counts[-1]``
    is the overflow bucket (>= HIST_MAX).  ``min``/``max`` track exact
    extremes so quantiles clamp to observed reality instead of bucket
    edges.  NOT thread-safe on its own — :class:`Metrics` serializes
    access under its registry lock.
    """

    __slots__ = ("counts", "count", "sum", "min", "max")

    def __init__(self) -> None:
        self.counts = [0] * (_HIST_N + 2)
        self.count = 0
        self.sum = 0.0
        self.min = math.inf
        self.max = -math.inf

    def observe(self, value: float) -> None:
        v = float(value)
        if v < HIST_MIN:
            idx = 0
        elif v >= HIST_MAX:
            idx = _HIST_N + 1
        else:
            idx = 1 + int(
                math.log10(v / HIST_MIN) * HIST_BUCKETS_PER_DECADE
            )
            # Float round-off at an exact bucket edge can land one off.
            idx = max(1, min(idx, _HIST_N))
        self.counts[idx] += 1
        self.count += 1
        self.sum += v
        if v < self.min:
            self.min = v
        if v > self.max:
            self.max = v

    def quantile(self, q: float) -> float:
        """Interpolated quantile (geometric within the bucket), clamped
        to the exact observed [min, max].  0.0 when empty."""
        if self.count == 0:
            return 0.0
        q = min(1.0, max(0.0, q))
        target = q * self.count
        seen = 0.0
        idx = len(self.counts) - 1
        for i, c in enumerate(self.counts):
            seen += c
            if seen >= target and c:
                idx = i
                break
        if idx == 0:
            lo, hi = 0.0, HIST_MIN
        elif idx == _HIST_N + 1:
            lo, hi = HIST_MAX, max(self.max, HIST_MAX)
        else:
            lo = HIST_MIN * 10.0 ** ((idx - 1) / HIST_BUCKETS_PER_DECADE)
            hi = HIST_MIN * 10.0 ** (idx / HIST_BUCKETS_PER_DECADE)
        # Geometric midpoint-ish interpolation by rank within the bucket.
        c = self.counts[idx]
        frac = (target - (seen - c)) / c if c else 0.5
        frac = min(1.0, max(0.0, frac))
        if lo <= 0.0:
            est = hi * frac
        else:
            est = lo * (hi / lo) ** frac
        return float(min(max(est, self.min), self.max))

    # -- cross-process merge/transport (ddl_tpu.obs) -----------------------

    def state(self) -> Dict[str, object]:
        """Portable snapshot (the ObsReport wire format)."""
        return {
            "counts": list(self.counts),
            "count": self.count,
            "sum": self.sum,
            "min": self.min if self.count else None,
            "max": self.max if self.count else None,
        }

    @classmethod
    def from_state(cls, d: Dict[str, object]) -> "Histogram":
        h = cls()
        counts = list(d.get("counts") or [])
        if len(counts) == len(h.counts):
            h.counts = [int(c) for c in counts]
        h.count = int(d.get("count", 0))
        h.sum = float(d.get("sum", 0.0))
        h.min = float(d["min"]) if d.get("min") is not None else math.inf
        h.max = float(d["max"]) if d.get("max") is not None else -math.inf
        return h


class Metrics:
    """Thread-safe counter/timer registry.

    Producers, the transport and the dataloader all record here; a single
    registry per pipeline is shared via :func:`metrics` (module default) or
    injected explicitly for tests.
    """

    def __init__(self) -> None:
        self._lock = named_lock("obs.metrics")
        self._counters: Dict[str, float] = collections.defaultdict(float)
        self._timers: Dict[str, Timer] = collections.defaultdict(Timer)
        self._gauges: Dict[str, float] = {}
        self._hists: Dict[str, Histogram] = {}
        # Cross-process aggregation (ddl_tpu.obs): prefix -> the LATEST
        # adopted flat snapshot / histogram states of a remote registry
        # (cumulative, so adoption REPLACES — bounded by the producer
        # set by construction).  # ddl-lint: disable=DDL013
        self._adopted: Dict[str, Dict[str, float]] = {}
        self._adopted_hists: Dict[str, Dict[str, Histogram]] = {}
        self._t0 = time.perf_counter()

    def incr(self, name: str, value: float = 1.0) -> None:
        with self._lock:
            self._counters[name] += value
        tap = _EVENT_TAP
        if tap is not None:
            tap("counter", name, value)

    def set_gauge(self, name: str, value: float) -> None:
        """Point-in-time level (queue depth, pool size).  The high-water
        mark rides along as ``<name>.max`` so a burst between snapshots
        is still visible in the bench JSON.  :meth:`clear_gauge` is the
        ONLY correct retirement path — zeroing the base gauge leaves
        the companion pinned at its old peak on purpose (that is what a
        high-water mark is), so a gauge family keyed by a dynamic name
        (``serve.stall.<tenant>``) must be cleared, not zeroed, when
        its owner goes away."""
        with self._lock:
            self._gauges[name] = value
            peak = self._gauges.get(f"{name}.max", value)
            self._gauges[f"{name}.max"] = max(peak, value)
        tap = _EVENT_TAP
        if tap is not None:
            tap("gauge", name, value)

    def clear_gauge(self, name: str) -> None:
        """Retire a gauge AND its ``.max`` high-water companion.

        The companion is derived state: leaving it behind after its
        base gauge is dropped makes a departed owner (an unregistered
        tenant, a torn-down pool) show up as a phantom ``<name>.max``
        entry in :meth:`prefixed`/:meth:`snapshot` between bench reps.
        """
        with self._lock:
            self._gauges.pop(name, None)
            self._gauges.pop(f"{name}.max", None)

    def gauge(self, name: str) -> float:
        with self._lock:
            return self._gauges.get(name, 0.0)

    def add_time(self, name: str, seconds: float) -> None:
        with self._lock:
            self._timers[name].add(seconds)
        tap = _EVENT_TAP
        if tap is not None:
            tap("timer", name, seconds)

    # -- histograms (fixed log-spaced buckets; docs/OBSERVABILITY.md) ------

    def observe(self, name: str, value: float) -> None:
        """Record one sample into the bounded log-spaced histogram
        ``name`` (created on first observe).  Per-window cost: one lock
        + one log10 — sanctioned in per-window paths, NOT in per-sample
        hot loops (ddl-lint DDL023)."""
        with self._lock:
            h = self._hists.get(name)
            if h is None:
                h = self._hists[name] = Histogram()
            h.observe(value)
        tap = _EVENT_TAP
        if tap is not None:
            tap("observe", name, value)

    def quantile(self, name: str, q: float) -> float:
        """Interpolated quantile of histogram ``name`` (0.0 when the
        histogram is empty or was never observed)."""
        with self._lock:
            h = self._hists.get(name)
            if h is None:
                # Adopted remote histograms (cross-process aggregation)
                # answer under their full prefixed name.
                for prefix, hists in self._adopted_hists.items():
                    if name.startswith(prefix):
                        h = hists.get(name[len(prefix):])
                        if h is not None:
                            break
            return h.quantile(q) if h is not None else 0.0

    def histogram(self, name: str) -> Optional[Histogram]:
        """A copy of histogram ``name`` (None when never observed)."""
        with self._lock:
            h = self._hists.get(name)
            return Histogram.from_state(h.state()) if h is not None else None

    def hist_names(self, prefix: str = "") -> List[str]:
        """Names of observed histograms under ``prefix`` (local +
        adopted, full prefixed names) — report assemblers enumerate
        dynamic families (``ingest.<tenant>.*``) with this."""
        with self._lock:
            out = [k for k in self._hists if k.startswith(prefix)]
            for apfx, hists in self._adopted_hists.items():
                out.extend(
                    f"{apfx}{k}"
                    for k in hists
                    if f"{apfx}{k}".startswith(prefix)
                )
            return sorted(set(out))

    def hist_state(self) -> Dict[str, Dict[str, object]]:
        """Portable state of every local histogram (the ObsReport wire
        format — ``Histogram.from_state`` round-trips it)."""
        with self._lock:
            return {k: h.state() for k, h in self._hists.items()}

    # -- cross-process aggregation (ddl_tpu.obs) ---------------------------

    def adopt(
        self,
        prefix: str,
        snapshot: Dict[str, float],
        hists: Optional[Dict[str, Dict[str, object]]] = None,
    ) -> None:
        """Merge a remote registry's cumulative :meth:`snapshot` (and
        optional :meth:`hist_state`) under ``prefix`` (e.g.
        ``"producer.0."``).  Adoption REPLACES the previous snapshot for
        that prefix — remote snapshots are cumulative, so replacement is
        the only merge that cannot double-count.  Adopted keys surface
        through :meth:`snapshot`, :meth:`prefixed`, :meth:`counter` and
        :meth:`quantile` under their prefixed names."""
        flat = {k: v for k, v in snapshot.items() if isinstance(v, (int, float))}
        parsed = (
            {k: Histogram.from_state(d) for k, d in hists.items()}
            if hists
            else {}
        )
        with self._lock:
            self._adopted[prefix] = flat
            self._adopted_hists[prefix] = parsed

    def adopted_prefixes(self) -> List[str]:
        with self._lock:
            return sorted(self._adopted)

    def timed(self, name: str) -> "_TimedCtx":
        return _TimedCtx(self, name)

    def counter(self, name: str) -> float:
        with self._lock:
            if name in self._counters:
                return self._counters[name]
            for prefix, snap in self._adopted.items():
                if name.startswith(prefix):
                    v = snap.get(name[len(prefix):])
                    if v is not None:
                        return float(v)
            return 0.0

    def timer(self, name: str) -> Timer:
        with self._lock:
            t = self._timers.get(name)
            return Timer(t.total_s, t.count) if t else Timer()

    def elapsed_s(self) -> float:
        return time.perf_counter() - self._t0

    def reset(self) -> None:
        """Zero the registry for a fresh measurement span.  Clears the
        ``.max`` gauge companions WITH their base gauges, the
        histograms, and adopted remote snapshots — a bench rep that
        resets between legs must never report the previous leg's
        high-water marks or percentiles (tests/test_obs.py pins this).
        """
        with self._lock:
            self._counters.clear()
            self._timers.clear()
            self._gauges.clear()
            self._hists.clear()
            self._adopted.clear()
            self._adopted_hists.clear()
            self._t0 = time.perf_counter()

    def snapshot(self) -> Dict[str, float]:
        """Flat dict of everything, for logging / bench JSON.

        Histograms surface as ``<name>.p50`` / ``<name>.p99`` /
        ``<name>.count`` summary keys (full bucket state travels via
        :meth:`hist_state`); adopted remote registries surface under
        their prefixes."""
        with self._lock:
            out: Dict[str, float] = dict(self._counters)
            for k, t in self._timers.items():
                out[f"{k}.total_s"] = t.total_s
                out[f"{k}.count"] = float(t.count)
            out.update(self._gauges)
            for k, h in self._hists.items():
                out[f"{k}.p50"] = h.quantile(0.5)
                out[f"{k}.p99"] = h.quantile(0.99)
                out[f"{k}.count"] = float(h.count)
            for prefix, snap in self._adopted.items():
                for k, v in snap.items():
                    out[f"{prefix}{k}"] = v
            out["elapsed_s"] = time.perf_counter() - self._t0
            return out

    def prefixed(self, prefix: str) -> Dict[str, float]:
        """Counters + gauges (and adopted remote keys) under one name
        family (``prefix`` up to and including its trailing dot, e.g.
        ``"cache."``), keys stripped of the prefix — the bench assembles
        its per-subsystem JSON blocks from this instead of hand-listing
        every counter."""
        with self._lock:
            out: Dict[str, float] = {
                k[len(prefix):]: v
                for k, v in self._counters.items()
                if k.startswith(prefix)
            }
            out.update(
                (k[len(prefix):], v)
                for k, v in self._gauges.items()
                if k.startswith(prefix)
            )
            for apfx, snap in self._adopted.items():
                for k, v in snap.items():
                    full = f"{apfx}{k}"
                    if full.startswith(prefix):
                        out[full[len(prefix):]] = v
            return out

    # Derived north-star metrics -------------------------------------------

    def rates(self) -> Dict[str, float]:
        """All derived rates over ONE elapsed snapshot.

        The single formula site: computing each rate with its own "now"
        (as the per-metric helpers below would if called in sequence)
        skews their ratios by the microseconds between calls, which is
        visible on short measurement spans — bytes/s and samples/s must
        agree exactly when their counters cover identical windows.
        """
        with self._lock:
            # ONE critical section for all three reads: a concurrent
            # finish() increments bytes then samples, and observing one
            # without the other would skew the ratio by a window.
            el = time.perf_counter() - self._t0
            samples = self._counters.get("consumer.samples", 0.0)
            nbytes = self._counters.get("ingest.bytes", 0.0)
            wait = self._timers.get("consumer.wait")
            stall = wait.total_s if wait else 0.0
        if el <= 0:
            return {
                "samples_per_sec": 0.0,
                "stall_fraction": 0.0,
                "ingest_bytes_per_sec": 0.0,
                "elapsed_s": el,
            }
        return {
            "samples_per_sec": samples / el,
            "stall_fraction": stall / el,
            "ingest_bytes_per_sec": nbytes / el,
            "elapsed_s": el,
        }

    def samples_per_sec(self) -> float:
        return self.rates()["samples_per_sec"]

    def stall_fraction(self) -> float:
        """Fraction of consumer wall time spent waiting on the pipeline."""
        return self.rates()["stall_fraction"]

    def ingest_bytes_per_sec(self) -> float:
        return self.rates()["ingest_bytes_per_sec"]


class _TimedCtx:
    def __init__(self, m: Metrics, name: str):
        self._m, self._name = m, name

    def __enter__(self) -> "_TimedCtx":
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc: object) -> None:
        self._m.add_time(self._name, time.perf_counter() - self._t0)


#: Optional metric-event tap (the chaos flight recorder's feed,
#: ddl_tpu/obs/recorder.py).  Read unlocked on every metric op — a
#: single module-attribute load is the entire disarmed cost (the
#: faults._ARMED pattern); called OUTSIDE the registry lock so a tap
#: can never deadlock a registry reader.
_EVENT_TAP = None


def install_event_tap(tap) -> None:
    """Install (or, with ``None``, remove) the process-wide metric-event
    tap: ``tap(kind, name, value)`` fires after every ``incr`` /
    ``set_gauge`` / ``add_time`` / ``observe`` on EVERY registry.  One
    tap at a time — the flight recorder owns this seam."""
    global _EVENT_TAP
    _EVENT_TAP = tap


_default = Metrics()


def metrics() -> Metrics:
    """The process-default registry."""
    return _default
