"""ddl_tpu — TPU-native distributed data loading framework.

A ground-up JAX/XLA re-design of the capabilities of ``maximilian-tech/ddl``
(an MPI-based distributed dataloader for PyTorch): dedicated producer workers
ingest/preprocess/shuffle data into shared-memory window rings; trainer
processes drain windows zero-copy and stream them into TPU HBM with
double-buffered device ingest; global shuffle rides XLA collectives over
ICI/DCN instead of MPI ``Sendrecv_replace``.

Public API preserves the reference's 5-symbol surface
(reference ``ddl/__init__.py:7-21``): ``ProducerFunctionSkeleton``,
``DataProducerOnInitReturn``, ``distributed_dataloader``,
``DistributedDataLoader``, ``Marker``.
"""

from ddl_tpu.datasetwrapper import (
    DataProducerOnInitReturn,
    ProducerFunctionSkeleton,
)
from ddl_tpu.types import Marker, RunMode, Topology

__version__ = "0.1.0"

__all__ = [
    "DataProducerOnInitReturn",
    "DistributedDataLoader",
    "Marker",
    "ProducerFunctionSkeleton",
    "RunMode",
    "Topology",
    "Trainer",
    "cluster",
    "distributed_dataloader",
    "resilience",
]


def __getattr__(name: str):
    # Lazy imports keep `import ddl_tpu` light and avoid import cycles.
    if name == "DistributedDataLoader":
        from ddl_tpu.dataloader import DistributedDataLoader

        return DistributedDataLoader
    if name == "distributed_dataloader":
        from ddl_tpu.env import distributed_dataloader

        return distributed_dataloader
    if name == "Trainer":
        from ddl_tpu.trainer import Trainer

        return Trainer
    if name == "cluster":
        # The multi-host elastic control plane (membership, placement,
        # loader-pool decoupling, recovery ladder).
        import ddl_tpu.cluster as cluster

        return cluster
    if name == "resilience":
        # Preemption-tolerant training (async integrity-checked
        # checkpoints, graceful drain-on-notice, verified restore).
        import ddl_tpu.resilience as resilience

        return resilience
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
