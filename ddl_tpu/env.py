"""Topology construction and the role-bifurcating decorator.

Parity with reference ``ddl/ddl_env.py``: there, every MPI rank ran the same
program and ``@distributed_dataloader`` split ranks into one consumer + N
producers per instance via communicator color arithmetic
(``ddl_env.py:33-128``).  TPU-native, there are no ranks to split — the
decorated main runs in the trainer process and the decorator *spawns* the
producer workers:

- THREAD mode: producers are daemon threads (single-process first-class —
  fixes SURVEY Q9).
- PROCESS mode: producers are spawned host processes; data rides the native
  shared-memory ring (the reference's one-node shm-domain constraint,
  ``ddl_env.py:72-73``, holds by construction).
- MULTIHOST mode: PROCESS per host; ``instance_idx``/``n_instances`` come
  from ``jax.distributed`` (`jax.process_index/process_count`), the analog
  of the reference's SLURM sniffing (``ddl_env.py:103-107``).

Environment knobs (the reference used SLURM vars): ``DDL_TPU_MODE``,
``DDL_TPU_N_PRODUCERS``, ``DDL_TPU_NSLOTS``; plus the shard-cache set
``DDL_TPU_CACHE`` / ``DDL_TPU_CACHE_RAM_MB`` / ``DDL_TPU_CACHE_SPILL_DIR``
/ ``DDL_TPU_CACHE_SPILL_MB`` / ``DDL_TPU_CACHE_WARM`` (parsed in
:mod:`ddl_tpu.cache`, mirrored by ``LoaderConfig`` fields, and exported
by :func:`_export_cache_knobs` ahead of the producer spawn so
PROCESS/MULTIHOST workers build the same store).
"""

from __future__ import annotations

import functools
import logging
import os
import threading
from typing import Any, Callable, List, Optional

from ddl_tpu import envspec
from ddl_tpu.exceptions import ShutdownRequested, TransportError
from ddl_tpu.faults import fault_point
from ddl_tpu.transport.connection import (
    ConsumerConnection,
    PipeChannel,
    ProducerConnection,
    ThreadChannel,
)
from ddl_tpu.types import DDL_Env, RunMode, Topology

logger = logging.getLogger("ddl_tpu")

#: Sentinel broadcast to producers when the consumer dies before handshake.
ABORT = "__ddl_tpu_abort__"


def detect_host_identity(
    n_instances: int = 1,
    instance_idx: int = 0,
    host_id: Optional[int] = None,
    n_hosts: Optional[int] = None,
) -> tuple[int, int]:
    """``(host_id, n_hosts)`` for this consumer process.

    Fixes the latent one-consumer-per-host skew: the original SLURM
    recipe (docs/DEPLOY.md) equated ``jax.process_index()`` with the
    host, which is wrong the moment a host runs more than one consumer
    process (one per chip is the common TPU layout) — the cluster
    membership view and the placement engine would then see 4x the real
    host count and "place" transport onto links that do not exist.
    Resolution order, later layers only filling gaps:

    1. explicit arguments (``LoaderConfig.host_id``/``n_hosts`` threaded
       through :func:`distributed_dataloader`),
    2. ``DDL_TPU_HOST_ID`` / ``DDL_TPU_N_HOSTS`` env,
    3. SLURM node identity (``SLURM_NODEID`` / ``SLURM_NNODES`` — per
       NODE, not per task, so co-located tasks agree),
    4. processes-per-host arithmetic over the process grid
       (``DDL_TPU_PROCS_PER_HOST``, else ``SLURM_NTASKS_PER_NODE``,
       else 1 — the historical host==instance reading).
    """
    def _env_int(name: str) -> Optional[int]:
        # DDL_TPU names go through the registry; SLURM names are not
        # ours to declare.
        raw = (envspec.raw(name) if name.startswith("DDL_TPU_")
               else os.environ.get(name))
        return int(raw) if raw not in (None, "") else None

    if host_id is None:
        host_id = _env_int("DDL_TPU_HOST_ID")
    if n_hosts is None:
        n_hosts = _env_int("DDL_TPU_N_HOSTS")
    if host_id is None and n_hosts is None:
        slurm_node = _env_int("SLURM_NODEID")
        slurm_nodes = _env_int("SLURM_NNODES")
        if slurm_node is not None and slurm_nodes is not None:
            host_id, n_hosts = slurm_node, slurm_nodes
    if host_id is None or n_hosts is None:
        pph = (
            _env_int("DDL_TPU_PROCS_PER_HOST")
            or _env_int("SLURM_NTASKS_PER_NODE")
            or 1
        )
        pph = max(1, pph)
        if n_hosts is None:
            n_hosts = max(1, (n_instances + pph - 1) // pph)
        if host_id is None:
            host_id = min(instance_idx // pph, n_hosts - 1)
    # Layers may have resolved independently (an explicit host_id with
    # an arithmetic n_hosts): widen n_hosts to cover the id instead of
    # crashing Topology validation on a half-set environment.
    if host_id >= n_hosts:
        n_hosts = host_id + 1
    return int(host_id), int(n_hosts)


def detect_topology(
    n_producers: Optional[int] = None,
    mode: Optional[RunMode | str] = None,
    host_id: Optional[int] = None,
    n_hosts: Optional[int] = None,
) -> Topology:
    """Build the topology from args + environment.

    The reference derived ``n_instances`` from SLURM env vars
    (``ddl_env.py:103-107``); here MULTIHOST mode derives it from the JAX
    process grid, and single-host modes use one instance.  Host identity
    (distinct from the process grid — several consumer processes may
    share a host) comes from :func:`detect_host_identity`.
    """
    if mode is None:
        mode = envspec.get("DDL_TPU_MODE")
    mode = RunMode(mode) if not isinstance(mode, RunMode) else mode
    if n_producers is None:
        n_producers = envspec.get("DDL_TPU_N_PRODUCERS")
    if mode is RunMode.MULTIHOST:
        import jax

        n_instances = jax.process_count()
        instance_idx = jax.process_index()
    else:
        n_instances, instance_idx = 1, 0
    host_id, n_hosts = detect_host_identity(
        n_instances, instance_idx, host_id=host_id, n_hosts=n_hosts
    )
    return Topology(
        n_instances=n_instances,
        instance_idx=instance_idx,
        n_producers=n_producers,
        mode=mode,
        host_id=host_id,
        n_hosts=n_hosts,
    )


def _producer_main(
    conn: ProducerConnection,
    topology: Topology,
    producer_idx: int,
    nslots: int,
    shuffler_factory: Any = None,
    rejoin_ring: Any = None,
) -> None:
    """Body of one producer worker (thread or process)."""
    from ddl_tpu.datapusher import DataPusher

    try:
        # Chaos hook: a crash here exercises the handshake-failure
        # shipping path (the consumer fails fast with a typed error
        # instead of timing out).
        fault_point("producer.handshake", producer_idx=producer_idx)
        pusher = DataPusher(
            conn,
            topology,
            producer_idx,
            nslots=nslots,
            shuffler_factory=shuffler_factory,
            rejoin_ring=rejoin_ring,
        )
    except TransportError as te:
        # Consumer aborted before/during handshake (ABORT sentinel arrives
        # as non-metadata). Nothing to clean up beyond the channel.  The
        # exception text still goes to DEBUG — a swallowed transport
        # failure that is NOT an abort (e.g. a failed ring attach) must be
        # diagnosable from producer logs.
        logger.debug("producer %d: handshake transport end: %s",
                     producer_idx, te)
        conn.channel.close()
        return
    except ShutdownRequested:
        # The run is tearing down while this producer was still in its
        # handshake (e.g. the ring shutdown flag tripped inside an
        # inplace-fill acquire): a clean, consumer-initiated exit — not a
        # failure to ship back.  Previously the broad handler below
        # swallowed this into a spurious "handshake failure" (DDL007).
        logger.debug("producer %d: shutdown during handshake", producer_idx)
        conn.channel.close()
        return
    except Exception as e:
        # Handshake-time user error (bad on_init, bad geometry): ship the
        # exception to the consumer so it fails fast instead of timing out.
        try:
            conn.channel.send(e)
        except (ShutdownRequested, KeyboardInterrupt):
            raise
        except Exception:
            # Exception not picklable (open handles, locks): ship a
            # picklable surrogate carrying the traceback text instead.
            import traceback

            try:
                conn.channel.send(
                    TransportError(
                        f"producer {producer_idx} handshake failure "
                        f"(original unpicklable):\n{traceback.format_exc()}"
                    )
                )
            except (OSError, ValueError):
                pass  # channel itself broken; the consumer will time out
        logger.exception("producer %d failed during handshake", producer_idx)
        return
    try:
        pusher.push_data()
    except Exception:
        # A crash in the user's refill loop: log it here (instead of an
        # unhandled-thread traceback) and surface it to the watchdog —
        # dead thread for THREAD mode, nonzero exit for PROCESS mode —
        # which aborts or respawns per its policy.
        logger.exception(
            "producer %d crashed in the push loop", producer_idx
        )
        if conn.cross_process:
            raise SystemExit(1)


def _process_entry(
    pipe_end: Any,
    topology: Topology,
    producer_idx: int,
    nslots: int,
    shuffler_factory: Any = None,
    rejoin_ring: Any = None,
) -> None:
    """Top-level spawn target (must be importable for pickling)."""
    conn = ProducerConnection(
        PipeChannel(pipe_end), producer_idx, cross_process=True
    )
    _producer_main(
        conn, topology, producer_idx, nslots, shuffler_factory, rejoin_ring
    )


def _export_cache_knobs(config: Any) -> None:
    """Mirror a LoaderConfig's shard-cache fields into the ``DDL_TPU_CACHE*``
    environment BEFORE producers spawn.

    The cache store is per process (``ddl_tpu.cache.default_store``):
    THREAD-mode workers share the consumer's, but PROCESS/MULTIHOST
    workers each build their own from the environment they inherit —
    without this export a config-enabled cache would silently apply to
    nobody in the modes that need it most.

    The mirror goes BOTH ways (config wins over env, the documented
    precedence): a config with ``cache=False`` exports the gate as off,
    and a cache-on config with no spill dir clears any stale
    ``DDL_TPU_CACHE_SPILL_DIR`` — otherwise a second run in the same
    process would silently inherit the previous run's export.  A bare
    ``config=None`` call states no cache opinion and leaves the
    environment (a first-class interface of its own) untouched.
    """
    if config is None:
        return
    if not getattr(config, "cache", False):
        if "DDL_TPU_CACHE" in os.environ:
            os.environ["DDL_TPU_CACHE"] = "0"
        return
    os.environ["DDL_TPU_CACHE"] = "1"
    os.environ["DDL_TPU_CACHE_RAM_MB"] = str(config.cache_ram_mb)
    os.environ["DDL_TPU_CACHE_SPILL_MB"] = str(config.cache_spill_mb)
    os.environ["DDL_TPU_CACHE_WARM"] = "1" if config.cache_warm else "0"
    if config.cache_spill_dir:
        os.environ["DDL_TPU_CACHE_SPILL_DIR"] = config.cache_spill_dir
    else:
        os.environ.pop("DDL_TPU_CACHE_SPILL_DIR", None)
    if getattr(config, "cache_codec", ""):
        os.environ["DDL_TPU_CACHE_CODEC"] = config.cache_codec
    else:
        os.environ.pop("DDL_TPU_CACHE_CODEC", None)


#: Cluster env vars THIS process exported from a config (never user-set
#: ones): a later run whose config states no opinion clears exactly
#: these, so one run's explicit identity cannot leak into the next —
#: the documented _export_cache_knobs precedent, made precise.
_exported_cluster_vars: set = set()


def _export_cluster_knobs(config: Any) -> None:
    """Mirror a LoaderConfig's host-identity fields into the
    ``DDL_TPU_HOST_ID``/``DDL_TPU_N_HOSTS``/``DDL_TPU_PROCS_PER_HOST``
    environment BEFORE producers spawn (the ``_export_cache_knobs``
    pattern): PROCESS/MULTIHOST workers re-derive host identity from
    the environment they inherit, and the cluster view each side builds
    must agree on host boundaries.  Sentinel values (-1/0 = auto) state
    no opinion: they leave USER-set environment untouched, but clear
    any export a previous config-driven run in this process made —
    otherwise the second run would silently inherit the first run's
    explicit identity as its "auto-detected" one.
    """
    if config is None:
        return
    for var, value, has_opinion in (
        ("DDL_TPU_HOST_ID", getattr(config, "host_id", -1),
         getattr(config, "host_id", -1) >= 0),
        ("DDL_TPU_N_HOSTS", getattr(config, "n_hosts", 0),
         getattr(config, "n_hosts", 0) > 0),
        ("DDL_TPU_PROCS_PER_HOST", getattr(config, "procs_per_host", 0),
         getattr(config, "procs_per_host", 0) > 0),
    ):
        if has_opinion:
            os.environ[var] = str(value)
            _exported_cluster_vars.add(var)
        elif var in _exported_cluster_vars:
            os.environ.pop(var, None)
            _exported_cluster_vars.discard(var)


#: Wire env vars THIS process exported from a config (never user-set
#: ones) — the _export_cluster_knobs precedent.
_exported_wire_vars: set = set()


def _export_wire_knobs(config: Any) -> None:
    """Mirror a LoaderConfig's wire-format fields into the
    ``DDL_TPU_WIRE_DTYPE``/``DDL_TPU_WIRE_CODEC`` environment BEFORE
    producers spawn (the ``_export_cache_knobs`` pattern): PROCESS/
    MULTIHOST workers resolve their wire dtype from the environment
    they inherit, and producer and consumer must agree on slot layout.
    Empty-string fields state no opinion (the per-reader capability
    decides): they leave USER-set environment untouched but clear this
    process's own prior exports.
    """
    if config is None:
        return
    for var, value in (
        ("DDL_TPU_WIRE_DTYPE", getattr(config, "wire_dtype", "")),
        ("DDL_TPU_WIRE_CODEC", getattr(config, "wire_codec", "")),
    ):
        if value:
            os.environ[var] = str(value)
            _exported_wire_vars.add(var)
        elif var in _exported_wire_vars:
            os.environ.pop(var, None)
            _exported_wire_vars.discard(var)


#: Shuffle env vars THIS process exported from a config (never user-set
#: ones) — the _export_wire_knobs precedent.
_exported_shuffle_vars: set = set()


def _export_shuffle_knobs(config: Any) -> None:
    """Mirror a LoaderConfig's device-shuffle fields into the
    ``DDL_TPU_DEVICE_SHUFFLE``/``DDL_TPU_SHUFFLE_IMPL`` environment
    BEFORE producers spawn (the ``_export_wire_knobs`` pattern):
    PROCESS/MULTIHOST workers resolve the gate from the environment
    they inherit.  Default-valued fields ("auto"/"ring") state no
    opinion: they leave USER-set environment untouched but clear this
    process's own prior exports.
    """
    if config is None:
        return
    for var, value, default in (
        ("DDL_TPU_DEVICE_SHUFFLE",
         getattr(config, "device_shuffle", "auto"), "auto"),
        ("DDL_TPU_SHUFFLE_IMPL",
         getattr(config, "shuffle_impl", "ring"), "ring"),
    ):
        if value and str(value) != default:
            os.environ[var] = str(value)
            _exported_shuffle_vars.add(var)
        elif var in _exported_shuffle_vars:
            os.environ.pop(var, None)
            _exported_shuffle_vars.discard(var)


#: Tuning env vars THIS process exported from a config (never user-set
#: ones) — the _export_wire_knobs precedent.
_exported_tune_vars: set = set()


def _export_tune_knobs(config: Any) -> None:
    """Mirror a LoaderConfig's tunable-knob fields into the environment
    (the ``_export_shuffle_knobs`` pattern) so the envspec seam every
    tuned call site reads (``DDL_TPU_PREFETCH_DEPTH``) sees the config
    — and so a ``TunedConfig`` overlay applied to the config before
    loader construction reaches PROCESS-mode workers too.  Default-
    valued fields state no opinion: they leave USER-set environment
    untouched but clear this process's own prior exports.
    """
    if config is None:
        return
    for var, value, default in (
        ("DDL_TPU_PREFETCH_DEPTH",
         getattr(config, "prefetch_depth", 2), 2),
    ):
        if value is not None and int(value) != default:
            os.environ[var] = str(value)
            _exported_tune_vars.add(var)
        elif var in _exported_tune_vars:
            os.environ.pop(var, None)
            _exported_tune_vars.discard(var)


class WorkerSet:
    """The spawned producer workers + consumer-side connection."""

    def __init__(self, topology: Topology, nslots: int,
                 shuffler_factory: Any = None):
        self.topology = topology
        self.nslots = nslots
        self.shuffler_factory = shuffler_factory
        self.threads: List[threading.Thread] = []
        self.processes: List[Any] = []
        channels = []
        if topology.mode is RunMode.THREAD:
            for idx in range(topology.n_producers):
                ch, t = self._spawn_thread(idx + 1)
                channels.append(ch)
                self.threads.append(t)
        else:
            for idx in range(topology.n_producers):
                ch, p = self._spawn_process(idx + 1)
                channels.append(ch)
                self.processes.append(p)
        self.connection = ConsumerConnection(channels)

    # The ONE worker-construction recipe, shared by __init__ and respawn
    # so the rarely-exercised recovery path cannot drift from the normal
    # spawn path.

    def _spawn_thread(self, producer_idx: int, rejoin_ring: Any = None):
        consumer_end, producer_end = ThreadChannel.pair()
        conn = ProducerConnection(
            producer_end, producer_idx, cross_process=False
        )
        t = threading.Thread(
            target=_producer_main,
            args=(conn, self.topology, producer_idx, self.nslots,
                  self.shuffler_factory, rejoin_ring),
            name=f"ddl-producer-{producer_idx}"
            + ("-respawn" if rejoin_ring is not None else ""),
            daemon=True,
        )
        t.start()
        return consumer_end, t

    def _spawn_process(self, producer_idx: int, rejoin_ring: Any = None):
        import multiprocessing as mp

        ctx = mp.get_context("spawn")
        parent_end, child_end = mp.Pipe(duplex=True)
        # shuffler_factory must be picklable: it crosses the spawn
        # boundary exactly like the user's producer function.
        p = ctx.Process(
            target=_process_entry,
            args=(child_end, self.topology, producer_idx, self.nslots,
                  self.shuffler_factory, rejoin_ring),
            name=f"ddl-producer-{producer_idx}"
            + ("-respawn" if rejoin_ring is not None else ""),
            daemon=True,
        )
        p.start()
        # Close the parent's copy of the child end so a dead producer
        # surfaces as EOF on the channel, not a timeout.
        child_end.close()
        return PipeChannel(parent_end), p

    def respawn(self, producer_idx: int) -> None:
        """Replace a dead producer with a fresh worker that rejoins the
        surviving ring (elastic recovery — the reference had none,
        SURVEY §5.3: a lost rank deadlocked the job).

        The replacement re-handshakes over a new channel, attaches to the
        predecessor's ring, and fast-forwards its producer function to
        the data position the ring's committed count records — the
        consumer's drain loop never notices beyond the stall.
        """
        i = producer_idx - 1
        if not (0 <= i < self.topology.n_producers):
            raise ValueError(f"no producer {producer_idx}")
        ring_ref = getattr(self.connection.replies[i], "ring_ref", None)
        if ring_ref is None:
            raise TransportError(
                f"producer {producer_idx} never completed its first "
                "handshake; nothing to rejoin"
            )
        if self.topology.mode is RunMode.THREAD:
            if self.threads[i].is_alive():
                # A hung thread cannot be killed; a second producer on the
                # same SPSC ring would corrupt it.
                raise TransportError(
                    f"producer thread {producer_idx} is still alive; "
                    "only dead thread producers can be respawned"
                )
            new_ch, t = self._spawn_thread(producer_idx, rejoin_ring=ring_ref)
            self.threads[i] = t
        else:
            old = self.processes[i]
            if old.is_alive():  # stalled rather than dead: replace it
                old.terminate()
                old.join(10)
                if old.is_alive():
                    old.kill()
                    old.join(10)
                if old.is_alive():
                    # Unkillable (e.g. blocked in an uninterruptible
                    # syscall): a second producer on the same SPSC ring
                    # would corrupt it.
                    raise TransportError(
                        f"producer process {producer_idx} survived "
                        "SIGKILL; cannot safely attach a replacement"
                    )
            new_ch, p = self._spawn_process(producer_idx, rejoin_ring=ring_ref)
            self.processes[i] = p
        self.connection.rejoin_producer(producer_idx, new_ch)
        logger.info("respawned producer %d", producer_idx)

    def abort(self) -> None:
        """Wake producers that may still be blocked in the handshake."""
        for ch in self.connection.channels:
            try:
                ch.send(ABORT)
            except (OSError, ValueError):
                # A dead producer's pipe: EOF/broken-pipe here is the
                # expected case abort() exists for.  Narrow on purpose
                # (DDL007): shutdown signals keep propagating.
                pass
        self.connection.shutdown_operation()

    def join(self, timeout_s: float = 30.0) -> None:
        for t in self.threads:
            t.join(timeout_s)
        for p in self.processes:
            p.join(timeout_s)
            if p.is_alive():  # pragma: no cover - last resort
                p.terminate()


def distributed_dataloader(
    func: Optional[Callable[..., Any]] = None,
    *,
    n_producers: Optional[int] = None,
    mode: Optional[RunMode | str] = None,
    nslots: Optional[int] = None,
    shuffler_factory: Any = None,
    config: Any = None,
) -> Callable[..., Any]:
    """Decorator running ``func`` as the consumer with producers alongside.

    API parity: reference ``ddl/ddl_env.py:100-128`` appended
    ``(mpi_env, connection)`` to the user function's args; here a single
    :class:`DDL_Env` (topology + consumer connection) is appended.
    Returns ``func``'s return value after all producers have exited.

    ``config`` (a :class:`ddl_tpu.config.LoaderConfig`) supplies topology
    defaults — explicit keyword arguments win over it, and both win over
    the ``DDL_TPU_*`` environment fallbacks inside
    :func:`detect_topology`.

    PROCESS/MULTIHOST modes use ``multiprocessing`` spawn: call the
    decorated main under ``if __name__ == "__main__":`` (standard spawn
    requirement), or the re-imported script will recursively spawn.
    """
    host_id = n_hosts = None
    if config is not None:
        n_producers = (
            config.n_producers if n_producers is None else n_producers
        )
        mode = config.mode if mode is None else mode
        nslots = config.nslots if nslots is None else nslots
        # Host identity (ddl_tpu.cluster): config sentinels (-1/0) mean
        # auto-detect inside detect_topology; explicit values win.
        host_id = config.host_id if getattr(config, "host_id", -1) >= 0 else None
        n_hosts = config.n_hosts if getattr(config, "n_hosts", 0) > 0 else None

    def deco(f: Callable[..., Any]) -> Callable[..., Any]:
        @functools.wraps(f)
        def wrapper(*args: Any, **kwargs: Any) -> Any:
            _export_cluster_knobs(config)
            topology = detect_topology(n_producers, mode, host_id, n_hosts)
            depth = nslots or envspec.get("DDL_TPU_NSLOTS")
            _export_cache_knobs(config)
            _export_wire_knobs(config)
            _export_shuffle_knobs(config)
            _export_tune_knobs(config)
            workers = WorkerSet(topology, depth, shuffler_factory)
            env = DDL_Env(
                topology=topology, connection=workers.connection,
                workers=workers,
            )
            logger.info(
                "ddl_tpu: %s mode, %d producer(s), instance %d/%d, %d slot(s)",
                topology.mode.value,
                topology.n_producers,
                topology.instance_idx,
                topology.n_instances,
                depth,
            )
            try:
                result = f(*args, env, **kwargs)
            finally:
                # Idempotent: wakes producers still blocked anywhere —
                # pre-handshake (ABORT sentinel) or in a ring wait
                # (shutdown flag). Producers already exited ignore both.
                workers.abort()
                workers.join(timeout_s=30.0)
            return result

        return wrapper

    if func is not None:
        return deco(func)
    return deco
