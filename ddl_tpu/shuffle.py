"""Global shuffle: cross-instance sample exchange.

Parity with reference ``ddl/shuffle.py``: between window refills, the k-th
producer of every instance exchanges a slice of its samples with partner
instances chosen by a *shared* random permutation — every peer derives the
same permutation independently from a common seed (reference
``shuffle.py:28-30``), so no coordination round is needed.  The permutation
must have no self-sends and no 2-cycles (reference ``shuffle.py:52-72``),
except n=2 where the swap is the only option (reference ``shuffle.py:44-48``).

Four transports implement the exchange, by span:

- :class:`Rendezvous` (span ``"thread"``) — in-process board for
  THREAD-mode simulated multi-instance topologies and unit tests.
- :class:`ShmRendezvous` (span ``"process"``) — /dev/shm mailbox files
  with atomic rename, for PROCESS-mode producers in different OS
  processes on ONE host (the reference's exchange ran between producer
  *processes*, reference ``shuffle.py:92-108`` over ``comm_nth_pusher``).
- :class:`DeviceExchangeFabric` (span ``"device"``) — the producer-side
  device tier (:class:`DeviceExchangeShuffler`): lanes land once on the
  ring devices and the permutation exchange itself rides ICI as a
  Pallas remote-DMA ring or an XLA ``ppermute``
  (``ddl_tpu.ops.device_shuffle``), byte-identical to the host paths
  and latching back to them on any device failure.
- ``ddl_tpu.parallel.collectives`` (span ``"global"``) — the
  trainer-side window hook: ``ppermute`` / ``all_to_all`` over the
  instance mesh axis riding ICI/DCN, replacing the reference's
  ``Sendrecv_replace`` (``shuffle.py:92-108``).  The ONLY host-spanning
  option: host-side rendezvous cannot cross hosts, and ``DataPusher``
  rejects that combination at handshake rather than stalling.

Unlike the reference — where the registered shuffler was unreachable dead
code (SURVEY Q1) and the alternative strategy lived in a commented-out
string (Q8) — both strategies here are real, dispatched, and tested.
"""

from __future__ import annotations

import logging
import os
import re
import threading

from ddl_tpu.concurrency import named_condition, named_lock
import time
import uuid
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from ddl_tpu.exceptions import DDLError, InjectedFault, ShutdownRequested
from ddl_tpu.faults import fault_point
from ddl_tpu.observability import metrics as default_metrics
from ddl_tpu.types import Topology

logger = logging.getLogger("ddl_tpu")

#: Permutation search bound (reference ``shuffle.py:74-79`` used 1000 and
#: SystemExit; we raise a typed error instead).
_MAX_TRIES = 1000

#: Valid exchange strategies (reference anticipated plumbing for more,
#: ``datapusher.py:96-106``).
EXCHANGE_METHODS = ("sendrecv_replace", "all_to_all")


def exchange_permutation(n: int, seed: int, round_: int) -> np.ndarray:
    """The shared partner permutation for one exchange round.

    Every same-index producer across instances calls this with identical
    arguments and gets the identical permutation — the decentralised
    agreement trick of reference ``shuffle.py:28-48``.

    Properties (validated): ``p[i] != i`` (no self-sends) and, for n > 2,
    ``p[p[i]] != i`` (no 2-cycles — a 2-cycle would swap the same rows
    straight back on the reverse lane).  n == 2 returns the swap; n == 1
    the identity (no exchange possible).
    """
    if n <= 1:
        return np.arange(n)
    if n == 2:
        return np.array([1, 0])
    rng = np.random.default_rng([seed & 0x7FFFFFFF, round_ & 0x7FFFFFFF])
    for _ in range(_MAX_TRIES):
        p = rng.permutation(n)
        if np.any(p == np.arange(n)):
            continue
        if np.any(p[p] == np.arange(n)):
            continue
        return p
    raise DDLError(
        f"no valid exchange permutation found for n={n} after {_MAX_TRIES} tries"
    )


def inverse_permutation(p: np.ndarray) -> np.ndarray:
    inv = np.empty_like(p)
    inv[p] = np.arange(len(p))
    return inv


def exchange_slices(num_exchange: int) -> Tuple[slice, slice]:
    """The two row lanes of one exchange round.

    Lane A (rows ``[0, half)``) travels *forward* along the permutation;
    lane B (rows ``[half, 2*half)``) travels *backward* — the reference's
    two ``Sendrecv_replace`` calls with swapped dest/source
    (``shuffle.py:95-108``).
    """
    half = num_exchange // 2
    return slice(0, half), slice(half, 2 * half)


class Rendezvous:
    """In-process exchange fabric: one board per producer-index, shared by
    all simulated instances.  Thread-safe; used by ThreadExchangeShuffler.
    Public: pass a fresh instance per run to
    ``ThreadExchangeShuffler.factory(rendezvous=...)`` when wiring
    multiple instances in one process (examples/global_shuffle.py)."""

    #: Reach of this fabric: same-process threads only.  ``DataPusher``
    #: rejects a "thread" rendezvous behind a cross-process connection —
    #: each spawned worker would wait on its own private board forever.
    span = "thread"

    def __init__(self) -> None:
        self._lock = named_condition("shuffle.exchange.cond")
        self._boxes: Dict[Tuple[int, int, int], np.ndarray] = {}
        self._done: Dict[Tuple[int, int, int], np.ndarray] = {}

    def put(self, key: Tuple[int, int, int], rows: np.ndarray) -> None:
        with self._lock:
            self._boxes[key] = rows
            self._lock.notify_all()

    def take(self, key: Tuple[int, int, int], timeout_s: float = 60.0,
             should_abort: Optional[Callable[[], bool]] = None) -> np.ndarray:
        """Blocking take, interruptible: a peer whose run is shutting down
        may never post its half of the exchange, so the wait polls
        ``should_abort`` (e.g. the ring's shutdown flag) and raises
        :class:`ShutdownRequested` instead of stranding the producer for
        the full timeout (the §3.5 any-time-cancellability property the
        ring waits already have).

        Consumed boxes are RETAINED (moved to a done-set) until
        :meth:`retire`: a respawned producer replaying its crashed
        predecessor's round takes the same key again and must see the
        same rows (elastic × shuffle — the exchange becomes idempotent
        per (key, round)).  Bounded: the shuffler retires round r-1's
        keys when round r starts.
        """
        deadline = time.monotonic() + timeout_s
        with self._lock:
            while key not in self._boxes:
                if key in self._done:  # replayed take (respawned producer)
                    return self._done[key]
                if should_abort is not None and should_abort():
                    raise ShutdownRequested()
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise DDLError(
                        f"exchange rendezvous timed out waiting for {key}"
                    )
                self._lock.wait(timeout=min(0.1, remaining))
            rows = self._boxes.pop(key)
            self._done[key] = rows
            return rows

    def discard(self, key: Tuple[int, int, int]) -> None:
        """Best-effort removal of a posted box (abort-path cleanup)."""
        with self._lock:
            self._boxes.pop(key, None)

    def retire(self, key: Tuple[int, int, int]) -> None:
        """Drop a consumed box from the done-set (the round can no longer
        be replayed once its successor round has begun).  Also drops a
        LIVE box under the same key: at retire time the reader has long
        consumed the original, so a live box can only be a respawned
        partner's replayed re-put (which nobody will ever take — tags
        are monotonic) and would otherwise leak."""
        with self._lock:
            self._done.pop(key, None)
            self._boxes.pop(key, None)


_default_rendezvous = Rendezvous()


#: Minimum age before a crashed run's rendezvous dir is fair game for
#: the sweep below.  Age alone is NOT sufficient to sweep — see the
#: pid-liveness check in :func:`_sweep_stale_sessions`.
STALE_SESSION_S = 3600.0

#: Directory-name prefix for every ShmRendezvous session dir — shared by
#: the minting side (:attr:`ShmRendezvous._dir`) and the sweep's matcher
#: so a rename cannot silently turn the sweep into a no-op.
_RDV_PREFIX = "ddl-rdv-"

#: Session names minted by :func:`make_session`: ``{prefix}-{pid}-{hex12}``.
#: The embedded pid is the sweep's liveness signal.
_SESSION_RE = re.compile(
    rf"^{re.escape(_RDV_PREFIX)}.+-(\d+)-[0-9a-f]{{12}}$"
)


def _sweep_stale_sessions(root: str) -> None:
    """Best-effort removal of abandoned ``ddl-rdv-*`` session dirs.

    /dev/shm is RAM-backed: a crashed or killed run whose ``cleanup()``
    never ran would otherwise leak its mailboxes until reboot,
    accumulating on long-lived hosts (ADVICE r4).  A dir is swept only
    when ALL of:

    - its name matches :func:`make_session`'s shape (hand-named sessions
      are the caller's to clean — we cannot infer their liveness);
    - the minting process is DEAD (``kill(pid, 0)`` → ESRCH).  Mtime
      alone would misfire on a healthy run whose exchange cadence is
      slower than the age cutoff, and producers of a live run are
      children of the minting process, so a dead minter means a dead
      run (pid reuse only ever delays the sweep — conservative);
    - it is older than :data:`STALE_SESSION_S`, so a session whose
      minter handed off and exited immediately is still grace-perioded.

    Runs once per (process, root) from the first mailbox creation.
    """
    import shutil

    cutoff = time.time() - STALE_SESSION_S
    try:
        entries = list(os.scandir(root))
    except OSError:
        return
    for ent in entries:
        m = _SESSION_RE.match(ent.name)
        if not m:
            continue
        try:
            if not ent.is_dir(follow_symlinks=False):
                continue
            if ent.stat(follow_symlinks=False).st_mtime >= cutoff:
                continue
            os.kill(int(m.group(1)), 0)  # raises if the minter is gone
        except ProcessLookupError:
            shutil.rmtree(ent.path, ignore_errors=True)
        except OSError:
            continue


#: Roots already swept by this process (sweep once per process+root).
_swept_roots: set = set()
_sweep_lock = named_lock("shuffle.sweep")


def make_session(prefix: str = "ddl") -> str:
    """A rendezvous session name unique enough to survive crashed prior
    runs (stale mailbox files from an old run with the same session would
    be popped as this run's round 0).  The embedded pid doubles as the
    liveness signal for :func:`_sweep_stale_sessions`."""
    return f"{prefix}-{os.getpid()}-{uuid.uuid4().hex[:12]}"


class ShmRendezvous:
    """Cross-process exchange fabric: mailbox files on /dev/shm (tmpfs).

    The PROCESS-mode realisation of the reference's cross-process producer
    exchange (reference ``shuffle.py:92-108`` rode MPI ``Sendrecv_replace``
    between pusher processes).  Every producer process of every instance
    on ONE host constructs ``ShmRendezvous(session)`` with the same
    session string (the object is picklable — it carries only the string
    — so the normal path is passing one factory through
    ``distributed_dataloader``/``DataPusher`` spawn arguments).

    Correctness needs no shared-memory ordering assumptions: ``put``
    writes the payload to a temp file and atomically ``os.rename``s it to
    the key's mailbox name; ``take`` polls for the name, reads, unlinks.
    File-system syscalls give the happens-before edge, on any ISA (unlike
    :class:`PyShmRing <ddl_tpu.transport.shm_ring.PyShmRing>`'s TSO gate).
    Each key has exactly one writer and one reader by permutation
    construction (no self-sends), so no further locking is needed.

    NOT host-spanning: /dev/shm is per-host.  MULTIHOST topologies must
    use the device exchange (``ddl_tpu.parallel.DeviceGlobalShuffler``);
    ``DataPusher`` enforces this at handshake.
    """

    span = "process"

    def __init__(self, session: str, root: str = "/dev/shm") -> None:
        self.session = session
        self.root = root
        # Directory creation is LAZY (first put): constructing the object
        # must be side-effect free so a handshake-time span rejection does
        # not strand an empty session directory per failed launch.

    @property
    def _dir(self) -> str:
        return os.path.join(self.root, f"{_RDV_PREFIX}{self.session}")

    def _path(self, key: Tuple[int, int, int]) -> str:
        return os.path.join(
            self._dir, f"p{key[0]}-t{key[1]}-d{key[2]}.npy"
        )

    def put(self, key: Tuple[int, int, int], rows: np.ndarray) -> None:
        # First mailbox creation in this process for this root also
        # reclaims sessions abandoned by crashed prior runs — hung off
        # the rendezvous (which knows its root) so non-default roots are
        # swept too, not just /dev/shm.
        with _sweep_lock:
            if self.root not in _swept_roots:
                _swept_roots.add(self.root)
                _sweep_stale_sessions(self.root)
        os.makedirs(self._dir, exist_ok=True)
        path = self._path(key)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "wb") as f:
            np.save(f, rows)
        os.rename(tmp, path)  # atomic publish

    def take(self, key: Tuple[int, int, int], timeout_s: float = 60.0,
             should_abort: Optional[Callable[[], bool]] = None) -> np.ndarray:
        """Blocking take with the same abort semantics as
        :meth:`Rendezvous.take` (a shutting-down peer may never post).

        Consumed mailboxes are RETAINED as ``<name>.done`` (atomic
        rename) until :meth:`retire` — a respawned producer replaying
        its crashed predecessor's round re-takes the same key and must
        see the same rows (see :meth:`Rendezvous.take`)."""
        path = self._path(key)
        done = f"{path}.done"
        # Replay probe ONCE, before the wait loop: a retained copy can
        # only exist before this take starts (each key has a single
        # reader lineage — the respawn replacing a dead predecessor),
        # so re-probing per spin would just double the poll syscalls.
        try:
            with open(done, "rb") as f:
                return np.load(f)
        except FileNotFoundError:
            pass
        deadline = time.monotonic() + timeout_s
        sleep_s = 0.0002
        while True:
            if should_abort is not None and should_abort():
                raise ShutdownRequested()
            try:
                with open(path, "rb") as f:
                    rows = np.load(f)
                os.replace(path, done)  # retained for replay, not unlinked
                return rows
            except FileNotFoundError:
                pass
            if time.monotonic() > deadline:
                raise DDLError(
                    f"exchange rendezvous timed out waiting for {key} "
                    f"(session {self.session!r})"
                )
            time.sleep(sleep_s)
            sleep_s = min(sleep_s * 2, 0.05)

    def discard(self, key: Tuple[int, int, int]) -> None:
        try:
            os.unlink(self._path(key))
        except OSError:
            pass

    def retire(self, key: Tuple[int, int, int]) -> None:
        """Drop the retained ``.done`` copy (replay window closed) and any
        live box under the same key — at retire time a live box can only
        be a respawned partner's replayed re-put, never taken (tags are
        monotonic), which would otherwise leak until ``cleanup()``."""
        for victim in (f"{self._path(key)}.done", self._path(key)):
            try:
                os.unlink(victim)
            except OSError:
                pass

    def cleanup(self) -> None:
        """Remove the whole session directory (post-run, best effort)."""
        import shutil

        shutil.rmtree(self._dir, ignore_errors=True)


class ThreadExchangeShuffler:
    """Producer callback performing the cross-instance exchange in-process.

    Registered by ``DataPusher`` when ``n_instances > 1`` and the consumer
    requested a nonzero exchange fraction (reference ``datapusher.py:89-108``)
    — and, with the fixed dispatcher, it actually runs each iteration.
    """

    #: Consecutive peer losses tolerated (each degrading one round to a
    #: node-local shuffle) before the exchange is disabled for the rest
    #: of the run — the documented degradation ladder's terminal rung
    #: for shuffle (docs/ROBUSTNESS.md).
    DEFAULT_MAX_PEER_LOSSES = 2

    def __init__(
        self,
        topology: Topology,
        producer_idx: int,
        num_exchange: int,
        exchange_method: str = "sendrecv_replace",
        rendezvous: Any = None,  # Rendezvous | ShmRendezvous (put/take/discard)
        seed: int = 0,
        exchange_timeout_s: float = 60.0,
        degrade_on_peer_loss: bool = True,
        max_peer_losses: Optional[int] = None,
        wire_dtype: Optional[str] = None,
        codec: Optional[str] = None,
        codec_level: int = 3,
    ):
        if exchange_method not in EXCHANGE_METHODS:
            raise NotImplementedError(
                f"exchange_method {exchange_method!r}; valid: {EXCHANGE_METHODS}"
            )
        # Exchange wire format (ddl_tpu.wire): the lanes travel the
        # rendezvous fabric (thread board / shm mailboxes — the DCN
        # analog in PROCESS topologies) as self-describing envelopes —
        # blockwise bf16/int8 and/or codec-compressed — instead of raw
        # fp32 rows.  Self-describing matters: the DECODER needs no
        # out-of-band agreement, so a peer that latched the raw
        # fallback still interoperates.  Defaults resolve from the
        # DDL_TPU_WIRE_DTYPE / DDL_TPU_WIRE_CODEC env (the same knobs
        # the slot wire honors); raw + no codec keeps the pre-wire
        # byte-for-byte puts.
        from ddl_tpu import wire as _wire

        self.wire_dtype = _wire.resolve_wire_dtype(wire_dtype)
        self.codec = _wire.resolve_wire_codec(codec)
        self.codec_level = int(codec_level)
        # Per-shuffler raw fallback latch: a persistent decode failure
        # (DECODE_FAIL budget exhausted, foreign-codec peer) drops THIS
        # producer's outgoing encoding to raw for the rest of the run
        # (wire.fallbacks) — incoming envelopes still decode fine.
        self._wire_raw = False
        self.topology = topology
        self.producer_idx = producer_idx
        self.num_exchange = num_exchange
        self.exchange_method = exchange_method
        self.seed = seed
        self.exchange_timeout_s = exchange_timeout_s
        #: ``True`` (default): a lost exchange partner degrades the round
        #: to a node-local shuffle with a loud warning + metric instead
        #: of stalling the pipeline until timeout-death.  ``False``
        #: restores raise-on-loss for callers that prefer to crash.
        self.degrade_on_peer_loss = degrade_on_peer_loss
        self.max_peer_losses = (
            self.DEFAULT_MAX_PEER_LOSSES
            if max_peer_losses is None
            else max_peer_losses
        )
        self.metrics = default_metrics()
        self._peer_losses = 0  # consecutive; reset by a healthy round
        self._degraded = False  # terminal: exchange disabled for the run
        # Reversible degrade (cross-host elastic ladder): while True,
        # every round shuffles node-locally — the exchange permutation
        # still names a departed host and would stall each round until
        # timeout.  Unlike _degraded this rung EXITS: resume_exchange()
        # at the rejoin fence (ddl_tpu.cluster.elastic).
        self._suspended = False
        self._rdv = rendezvous or _default_rendezvous
        self._round = 0
        # Outgoing keys of the last two rounds: swept when their replay
        # window closes (see global_shuffle) so a respawned producer's
        # re-put of an already-consumed box cannot leak past two rounds.
        self._sent: List[Tuple[int, Tuple[int, int, int]]] = []

    @property
    def span(self) -> str:
        """Reach of the underlying rendezvous fabric ("thread"/"process"/
        "global") — validated against the topology at the pusher
        handshake."""
        return getattr(self._rdv, "span", "thread")

    @property
    def supports_elastic_replay(self) -> bool:
        """True when the fabric retains consumed boxes for replay
        (``retire`` is the capability marker): the pusher allows a
        respawned producer to rejoin the exchange schedule only behind
        this — a fabric without retention would strand the replayed
        take until timeout (see DataPusher's rejoin handshake)."""
        return hasattr(self._rdv, "retire")

    @property
    def exchange_round(self) -> int:
        """Completed exchange rounds — the public counter checkpoints
        read (``LoaderCheckpoint.capture``)."""
        return self._round

    @property
    def exchange_suspended(self) -> bool:
        return self._suspended

    def suspend_exchange(self) -> None:
        """Cross-host ladder rung: degrade every round to the seeded
        node-local shuffle until :meth:`resume_exchange` (a cluster view
        change removed an exchange peer's host; docs/ROBUSTNESS.md).
        Idempotent; the round counter keeps advancing so checkpoints
        and the eventual resume stay schedule-coherent."""
        if not self._suspended:
            self._suspended = True
            self.metrics.incr("shuffle.suspensions")
            logger.warning(
                "global shuffle: exchange SUSPENDED (cluster view "
                "change) — shuffling node-locally until rejoin"
            )

    def resume_exchange(self) -> None:
        """Exit the suspension rung (host rejoined at a new epoch
        fence).  The consecutive-loss ladder restarts clean — losses
        counted against the pre-suspension view prove nothing about the
        rejoined one."""
        if self._suspended:
            self._suspended = False
            self._peer_losses = 0
            self.metrics.incr("shuffle.resumes")
            logger.warning(
                "global shuffle: exchange RESUMED at round %d", self._round
            )

    def rejoin(self, round_: int) -> None:
        """Re-enter the exchange schedule at ``round_`` (elastic rejoin:
        the ring-committed window count; checkpoint resume passes the
        restored round).  Part of the ``supports_elastic_replay``
        contract — the pusher and ``LoaderCheckpoint.apply`` call THIS,
        never a private round field, so a conforming custom shuffler
        implements its own round re-entry here."""
        self._round = int(round_)

    def _wire_active(self, rows: np.ndarray) -> Tuple[str, Optional[str]]:
        """The (wire_dtype, codec) this put actually uses: the raw
        latch wins, lossy needs float rows (token/int lanes keep raw —
        the codec still applies), raw+None is the pre-wire fast path."""
        if self._wire_raw:
            return "raw", None
        from ddl_tpu import wire as _wire

        wd = self.wire_dtype
        if wd != "raw" and not _wire.lossy_supported(rows.dtype):
            wd = "raw"
        return wd, self.codec

    def _encode_lane(self, rows: np.ndarray) -> np.ndarray:
        wd, codec = self._wire_active(rows)
        if wd == "raw" and codec is None:
            return rows.copy()  # pre-wire behavior, byte-for-byte
        from ddl_tpu import wire as _wire

        return _wire.pack_rows(
            rows, wd, codec=codec, level=self.codec_level,
            metrics=self.metrics,
        )

    def _decode_lane(self, rows: np.ndarray) -> np.ndarray:
        """Decode a taken lane: raw arrays pass through (a peer on the
        raw fallback — or a pre-wire peer — interoperates), envelopes
        unpack with ONE bounded retry; a persistent decode failure
        latches this producer's outgoing encoding to raw
        (``wire.fallbacks``) and raises — the round then degrades to
        the node-local shuffle via the existing peer-loss rung."""
        from ddl_tpu import wire as _wire
        from ddl_tpu.exceptions import DecodeError

        if not (
            rows.ndim == 1
            and rows.dtype == np.uint8
            and rows.nbytes >= 4
            and int.from_bytes(rows[:4].tobytes(), "little")
            == _wire._PACK_MAGIC
        ):
            return rows  # raw lane
        for attempt in (1, 2):
            try:
                return _wire.unpack_rows(rows, metrics=self.metrics)
            except DecodeError:
                self.metrics.incr("wire.decode_fails")
                if attempt == 2:
                    if not self._wire_raw:
                        self._wire_raw = True
                        self.metrics.incr("wire.fallbacks")
                        logger.error(
                            "global shuffle: exchange wire decode failed "
                            "twice — this producer sends RAW lanes for "
                            "the rest of the run"
                        )
                    raise

    def _local_shuffle(self, my_ary: np.ndarray) -> None:
        """Node-local fallback: a deterministic in-place row permutation
        seeded by (seed, producer, round) — preserves this producer's row
        multiset exactly (no loss, no duplication) while the exchange
        fabric is unavailable."""
        rng = np.random.default_rng(
            [self.seed & 0x7FFFFFFF, self.producer_idx, self._round]
        )
        rng.shuffle(my_ary)

    def _degrade_round(self, my_ary: np.ndarray, why: Exception) -> None:
        """Degradation ladder, shuffle rung: count the loss, shuffle
        locally, and after ``max_peer_losses`` consecutive losses disable
        the exchange for the rest of the run (stalling every remaining
        round against a dead peer would serve nothing)."""
        self._peer_losses += 1
        self.metrics.incr("shuffle.degraded")
        logger.error(
            "global shuffle: exchange peer lost in round %d (%s) — "
            "degrading to node-local shuffle (loss %d/%d)",
            self._round, why, self._peer_losses, self.max_peer_losses,
        )
        if self._peer_losses >= self.max_peer_losses and not self._degraded:
            self._degraded = True
            logger.error(
                "global shuffle: %d consecutive peer losses — exchange "
                "DISABLED for the rest of the run; data mixing is now "
                "node-local only", self._peer_losses,
            )
        self._local_shuffle(my_ary)

    def global_shuffle(self, my_ary: np.ndarray, should_abort: Any = None,
                       **kwargs: Any) -> None:
        n = self.topology.n_instances
        me = self.topology.instance_idx
        if n <= 1 or self.num_exchange < 2:
            return
        if self._degraded or self._suspended:
            # Terminal rung (repeated peer loss) or the reversible
            # cluster-suspension rung: keep mixing locally, keep the
            # round counter advancing (checkpoints and the eventual
            # resume stay schedule-coherent).
            if self._suspended:
                self.metrics.incr("shuffle.suspended_rounds")
            self._local_shuffle(my_ary)
            self._round += 1
            return
        p = exchange_permutation(n, self.seed + self.producer_idx, self._round)
        pinv = inverse_permutation(p)
        lane_a, lane_b = exchange_slices(self.num_exchange)
        tag = self._round * 2
        # Round r-1's replay window closes now: retire the retained
        # copies of the boxes this producer consumed last round (fabrics
        # without retention, e.g. custom user fabrics, are skipped).
        retire = getattr(self._rdv, "retire", None)
        if retire is not None and self._round > 0:
            retire((self.producer_idx, tag - 2, me))
            retire((self.producer_idx, tag - 1, me))
        # Sweep OUR outgoing boxes whose replay window has closed: in the
        # normal case the partner consumed them (no-op), but a respawned
        # producer's re-put of a box its partner had already taken AND
        # retired would otherwise linger forever (the partner retires
        # each incoming key exactly once).  ONLY safe for n == 2: there
        # the partner is the same every round, so my reaching round r
        # proves it completed round r-1 and consumed my r-2 boxes.  With
        # n > 2 cross-instance round skew is unbounded (peers only
        # synchronise with their ROUND partners) and the sweep could
        # discard a lagging partner's still-unconsumed box, stranding it
        # until timeout — there the re-put residual (<= 2 boxes per
        # respawn) is left for cleanup()/the stale-session sweep.
        if self._sent and n == 2:
            live = []
            for r, key in self._sent:
                if r <= self._round - 2:
                    self._rdv.discard(key)
                else:
                    live.append((r, key))
            self._sent = live
        # Lane A forward: i -> p[i]; lane B backward: i -> pinv[i].
        for lane, dest, src, t in (
            (lane_a, int(p[me]), int(pinv[me]), tag),
            (lane_b, int(pinv[me]), int(p[me]), tag + 1),
        ):
            put_key = (self.producer_idx, t, dest)
            self._rdv.put(put_key, self._encode_lane(my_ary[lane]))
            if n == 2:  # the sweep only runs (and is only safe) at n == 2
                self._sent.append((self._round, put_key))
            try:
                fault_point(
                    "shuffle.exchange", producer_idx=self.producer_idx
                )
                my_ary[lane] = self._decode_lane(
                    self._rdv.take(
                        (self.producer_idx, t, me),
                        timeout_s=self.exchange_timeout_s,
                        should_abort=should_abort,
                    )
                )
            except ShutdownRequested:
                # Clean teardown: retract our half so a later run on the
                # same rendezvous cannot pop this round's stale rows as
                # its own round 0.  (A producer that CRASHES mid-exchange
                # can still leave a box behind — pass a fresh Rendezvous
                # per run where that matters rather than the module
                # default.)
                self._rdv.discard(put_key)
                raise
            except DDLError as e:
                # The partner never showed (dead peer / injected loss):
                # retract our half, then degrade this round to a
                # node-local shuffle instead of stalling the pipeline —
                # unless the caller opted back into raise-on-loss.
                self._rdv.discard(put_key)
                if not self.degrade_on_peer_loss:
                    raise
                self._degrade_round(my_ary, e)
                self._round += 1
                return
        self._peer_losses = 0  # a healthy round resets the ladder
        self._round += 1

    # Factory signature expected by DataPusher's shuffler_factory hook.
    @classmethod
    def factory(
        cls,
        rendezvous: Any = None,
        seed: int = 0,
        exchange_timeout_s: float = 60.0,
        degrade_on_peer_loss: bool = True,
        max_peer_losses: Optional[int] = None,
        wire_dtype: Optional[str] = None,
        codec: Optional[str] = None,
        codec_level: int = 3,
    ):
        return ExchangeShufflerFactory(
            rendezvous=rendezvous,
            seed=seed,
            exchange_timeout_s=exchange_timeout_s,
            degrade_on_peer_loss=degrade_on_peer_loss,
            max_peer_losses=max_peer_losses,
            wire_dtype=wire_dtype,
            codec=codec,
            codec_level=codec_level,
        )


class ExchangeShufflerFactory:
    """Picklable shuffler factory.

    PROCESS mode ships the factory to spawned producer workers by pickle
    (exactly like the user's producer function crosses the spawn
    boundary), so it must be a module-level class, not a closure.  Pass a
    :class:`ShmRendezvous` for cross-process exchange; the in-process
    :class:`Rendezvous` is not picklable by design (its reach is one
    process)."""

    def __init__(
        self,
        rendezvous: Any = None,
        seed: int = 0,
        exchange_timeout_s: float = 60.0,
        degrade_on_peer_loss: bool = True,
        max_peer_losses: Optional[int] = None,
        wire_dtype: Optional[str] = None,
        codec: Optional[str] = None,
        codec_level: int = 3,
    ):
        self.rendezvous = rendezvous
        self.seed = seed
        self.exchange_timeout_s = exchange_timeout_s
        self.degrade_on_peer_loss = degrade_on_peer_loss
        self.max_peer_losses = max_peer_losses
        self.wire_dtype = wire_dtype
        self.codec = codec
        self.codec_level = codec_level

    def __call__(
        self,
        topology: Topology,
        producer_idx: int,
        num_exchange: int,
        exchange_method: str = "sendrecv_replace",
    ) -> ThreadExchangeShuffler:
        return ThreadExchangeShuffler(
            topology,
            producer_idx,
            num_exchange,
            exchange_method,
            rendezvous=self.rendezvous,
            seed=self.seed,
            exchange_timeout_s=self.exchange_timeout_s,
            degrade_on_peer_loss=self.degrade_on_peer_loss,
            max_peer_losses=self.max_peer_losses,
            wire_dtype=self.wire_dtype,
            codec=self.codec,
            codec_level=self.codec_level,
        )


# -- device-side exchange tier (ddl_tpu.ops.device_shuffle) -------------------


class DeviceExchangeError(DDLError):
    """The device exchange leg failed (DMA failure, unplannable
    geometry, injected fault): every participant of the round sees it
    and latches the HOST exchange for the shuffler's life
    (``shuffle.device_fallbacks``) — distinct from a peer timeout,
    which degrades one round to the seeded node-local shuffle."""


class _DeviceRound:
    """One (producer_idx, round) exchange round on the fabric board."""

    __slots__ = ("n", "seed", "round_", "posts", "results", "error")

    def __init__(self, n: int, seed: int, round_: int) -> None:
        self.n = n
        self.seed = seed
        self.round_ = round_
        self.posts: Dict[int, np.ndarray] = {}
        self.results: Optional[Dict[int, np.ndarray]] = None
        self.error: Optional[BaseException] = None


class DeviceExchangeFabric:
    """In-process coordination board for the device exchange.

    Every instance's k-th producer posts its lane block per round; the
    arrival that completes the set runs the DEVICE leg (land blocks on
    the ring devices, one ``exchange_start``/``exchange_wait`` round
    over ICI, fetch results) and publishes per-instance results — one
    collective per round instead of ``2n`` host mailbox hops.

    Reach: producers in THIS process (the THREAD-mode realisation,
    which is also where the consumer's devices are addressable).  The
    factory drops the fabric at the pickle boundary, so PROCESS/
    MULTIHOST workers resolve the device tier off and run the host
    exchange — same bytes, by the shared-seed construction.

    Round results are RETAINED until round ``r + 2`` starts (the host
    fabrics' ``retire`` window), so a respawned producer replaying its
    crashed predecessor's round re-takes the same result —
    ``supports_elastic_replay`` holds for the device tier too.
    """

    span = "device"

    def __init__(self, devices: Optional[Sequence[Any]] = None,
                 impl: Optional[str] = None,
                 interpret: Optional[bool] = None) -> None:
        from ddl_tpu import envspec

        self.impl = impl or envspec.get("DDL_TPU_SHUFFLE_IMPL")
        if self.impl not in ("ring", "xla"):
            raise ValueError(
                f"shuffle_impl must be ring|xla, got {self.impl!r}"
            )
        self.interpret = interpret
        self._devices = tuple(devices) if devices is not None else None
        self._cond = named_condition("shuffle.device.cond")
        # (producer_idx, round) -> _DeviceRound; swept two rounds behind
        # the newest (the retire window), so growth is bounded by
        # 2 * n_producers.  # ddl-lint: disable=DDL013
        self._rounds: Dict[Tuple[int, int], _DeviceRound] = {}

    # -- geometry ------------------------------------------------------------

    def _ring_devices(self, n: int) -> Tuple[Any, ...]:
        """The first ``n`` addressable devices as the exchange ring
        (resolved lazily: constructing the fabric must not import
        jax)."""
        if self._devices is None:
            import jax

            self._devices = tuple(jax.devices())
        if len(self._devices) < n:
            raise DDLError(
                f"device exchange unplannable: ring needs {n} devices "
                f"for {n} instances, have {len(self._devices)}"
            )
        return self._devices[:n]

    # -- the exchange --------------------------------------------------------

    def exchange(self, *, producer_idx: int, round_: int,
                 instance_idx: int, n: int, block: np.ndarray, seed: int,
                 timeout_s: float = 60.0,
                 should_abort: Optional[Callable[[], bool]] = None,
                 ) -> np.ndarray:
        """Post this instance's lane block for ``round_`` and return the
        exchanged block.  Raises :class:`ShutdownRequested` (abort),
        :class:`DeviceExchangeError` (device leg failed — caller
        latches the host fallback), or :class:`DDLError` (a peer never
        posted — caller degrades the round node-locally, exactly the
        host path's peer-loss rung)."""
        key = (producer_idx, round_)
        # Chaos site, hit once per participant per round: ICI_DMA_FAIL
        # poisons the ROUND (a DMA failure is collective — every
        # participant must latch the host fallback together, with lanes
        # unmutated, so the host re-run is byte-identical);
        # SHUFFLE_PEER_LOSS raises DDLError before this participant
        # posts, so its peers time out — the seeded node-local rung.
        try:
            fault_point(
                "shuffle.device_exchange", producer_idx=producer_idx,
                should_abort=should_abort,
            )
        except InjectedFault as e:
            self._fail_round(key, n, seed, e)
            raise DeviceExchangeError(str(e)) from e
        run_leg = False
        with self._cond:
            self._sweep_rounds(producer_idx, round_)
            rnd = self._rounds.get(key)
            if rnd is None:
                rnd = _DeviceRound(n, seed, round_)
                self._rounds[key] = rnd
            if rnd.error is not None:
                raise DeviceExchangeError(str(rnd.error)) from rnd.error
            if rnd.results is not None:
                # Replayed take (respawned producer re-entering a
                # completed round): idempotent per (key, instance).
                return rnd.results[instance_idx]
            rnd.posts[instance_idx] = block
            run_leg = len(rnd.posts) == n
            self._cond.notify_all()
        if run_leg:
            self._run_device_leg(rnd)
        deadline = time.monotonic() + timeout_s
        extended = False
        with self._cond:
            while rnd.results is None and rnd.error is None:
                if should_abort is not None and should_abort():
                    # Retract our half if the round has not filled (the
                    # host path's discard-on-shutdown), so a later run
                    # cannot adopt this round's stale post.
                    if len(rnd.posts) < rnd.n:
                        rnd.posts.pop(instance_idx, None)
                    raise ShutdownRequested()
                if not extended and len(rnd.posts) == rnd.n:
                    # All peers posted: the leader is running the device
                    # leg — the peer-loss clock no longer applies; give
                    # the leg its own full budget once.
                    deadline = time.monotonic() + timeout_s
                    extended = True
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    if len(rnd.posts) == rnd.n:
                        raise DeviceExchangeError(
                            f"device exchange leg stalled at round "
                            f"{round_} (producer {producer_idx})"
                        )
                    rnd.posts.pop(instance_idx, None)
                    raise DDLError(
                        f"device exchange timed out waiting for peers "
                        f"at round {round_} (producer {producer_idx}: "
                        f"{len(rnd.posts)}/{rnd.n} posted)"
                    )
                self._cond.wait(timeout=min(0.1, remaining))
            if rnd.error is not None:
                raise DeviceExchangeError(str(rnd.error)) from rnd.error
            return rnd.results[instance_idx]

    # -- internals -----------------------------------------------------------

    def _fail_round(self, key: Tuple[int, int], n: int, seed: int,
                    err: BaseException) -> None:
        with self._cond:
            rnd = self._rounds.get(key)
            if rnd is None:
                rnd = _DeviceRound(n, seed, key[1])
                self._rounds[key] = rnd
            if rnd.results is None and rnd.error is None:
                rnd.error = err
            self._cond.notify_all()

    def _sweep_rounds(self, producer_idx: int, round_: int) -> None:
        """Drop this producer's rounds older than ``round_ - 1`` (the
        replay window closes one round behind, as on the host fabrics'
        ``retire``).  Caller holds the condition lock."""
        stale = [
            k for k in self._rounds
            if k[0] == producer_idx and k[1] < round_ - 1
        ]
        for k in stale:
            del self._rounds[k]

    def _run_device_leg(self, rnd: _DeviceRound) -> None:
        """The arrival that completed the round runs the collective.
        ANY failure here (unplannable geometry, a DMA error surfacing at
        the sync point, a dtype the mesh cannot hold) is published to
        every participant — they all latch the host fallback together."""
        try:
            results = self._device_exchange(rnd)
        except (ShutdownRequested, KeyboardInterrupt):
            # Teardown interrupts propagate; waiting peers hit the
            # leg-stall timeout and latch the host fallback.
            raise
        except Exception as e:  # published, not swallowed
            with self._cond:
                if rnd.results is None and rnd.error is None:
                    rnd.error = e
                self._cond.notify_all()
            return
        with self._cond:
            if rnd.error is None:
                rnd.results = results
            self._cond.notify_all()

    def _device_exchange(self, rnd: _DeviceRound) -> Dict[int, np.ndarray]:
        # Lazy: the fabric is importable (and picklable factories must
        # construct) without pulling jax/pallas into light processes.
        from ddl_tpu.ops import device_shuffle as _dsh

        n = rnd.n
        devices = self._ring_devices(n)
        blocks = []
        shape = dtype = None
        for i in range(n):
            if i not in rnd.posts:
                raise DDLError(
                    f"device exchange round {rnd.round_} missing "
                    f"instance {i}'s lanes"
                )
            b = rnd.posts[i]
            if shape is None:
                shape, dtype = b.shape, b.dtype
            elif b.shape != shape or b.dtype != dtype:
                raise DDLError(
                    f"device exchange round {rnd.round_}: instance {i} "
                    f"posted {b.shape}/{b.dtype}, expected "
                    f"{shape}/{dtype}"
                )
            blocks.append(b)
        p = exchange_permutation(n, rnd.seed, rnd.round_)
        gin = _dsh.as_exchange_input(blocks, devices)
        # Alternating landing slots (distinct collective-id pairs) keep
        # round r+1's ring program off round r's barrier semaphores when
        # the exchange rides a landing slot under the fused step.
        ticket = _dsh.exchange_start(
            self.impl, gin, devices, p,
            slot=rnd.round_ % _dsh.N_SLOTS, interpret=self.interpret,
        )
        # sync=True: an async DMA failure must surface HERE, inside the
        # fallback ladder, not at some later consumer's sync point.
        out = _dsh.exchange_wait(ticket, sync=True)
        blocks_out = _dsh.exchange_output_blocks(out, devices)
        return {i: blocks_out[i] for i in range(n)}


class DeviceExchangeShuffler(ThreadExchangeShuffler):
    """The device-tier exchange shuffler: same contract, same bytes,
    one collective instead of ``2n`` host mailbox hops.

    Subclasses :class:`ThreadExchangeShuffler`, inheriting the entire
    degradation ladder (suspend/resume, peer-loss degrade, elastic
    rejoin, wire fallback) — the device tier wraps ONLY the healthy
    round's transport.  Byte identity with the host path is by
    construction: both derive the permutation from
    ``exchange_permutation(n, seed + producer_idx, round)`` and move
    the same two lanes, so for a given seed the post-exchange pools are
    equal byte-for-byte (the tier-1 parity suite proves it on the CPU
    virtual mesh in interpret mode).

    Resolution (construction time, not a fallback): the device tier
    engages only when a fabric is present (the factory drops it at the
    pickle boundary, so PROCESS/MULTIHOST workers run the host path),
    the topology is THREAD-realised (the fabric's reach), the
    ``DDL_TPU_DEVICE_SHUFFLE`` gate is not off, and the wire resolves
    raw with no codec (the device legs move raw rows over ICI; an
    explicitly forced lossy/codec wire keeps the host path — on-device
    re-quantization would break exact byte identity).

    Fallback (latched for the shuffler's life, ``shuffle.device_
    fallbacks``): unplannable geometry or any device-leg failure —
    every round participant latches together and re-runs the SAME
    round over the host fabric with lanes unmutated, byte-identically.
    A peer that never posts degrades the round to the seeded node-local
    shuffle, exactly the host path's rung.
    """

    def __init__(
        self,
        topology: Topology,
        producer_idx: int,
        num_exchange: int,
        exchange_method: str = "sendrecv_replace",
        rendezvous: Any = None,
        fabric: Optional[DeviceExchangeFabric] = None,
        device_shuffle: Optional[str] = None,
        **kwargs: Any,
    ):
        super().__init__(
            topology, producer_idx, num_exchange, exchange_method,
            rendezvous=rendezvous, **kwargs,
        )
        from ddl_tpu import envspec
        from ddl_tpu.types import RunMode

        gate = (
            device_shuffle
            if device_shuffle is not None
            else (envspec.raw("DDL_TPU_DEVICE_SHUFFLE") or "auto")
        )
        self._fabric = fabric
        self._device_latched = False  # terminal: host exchange for life
        why = None
        if str(gate).lower() in envspec.FALSY:
            why = "DDL_TPU_DEVICE_SHUFFLE gate is off"
        elif fabric is None:
            why = (
                "no fabric (crossed a spawn boundary, or none was "
                "constructed)"
            )
        elif topology.mode is not RunMode.THREAD:
            why = (
                f"{topology.mode.value} topology: the in-process fabric "
                "cannot reach producers in other processes"
            )
        elif self.wire_dtype != "raw" or self.codec is not None:
            why = (
                f"wire ({self.wire_dtype}/{self.codec}) forced: device "
                "legs move raw rows over ICI"
            )
        self._device_ok = why is None
        if why is not None and fabric is not None:
            logger.debug(
                "device shuffle resolved OFF for producer %d: %s",
                producer_idx, why,
            )

    @property
    def span(self) -> str:
        """``"device"`` while the device tier is engaged, else the host
        fabric's span (the handshake validates whichever transport will
        actually carry the lanes)."""
        if self._device_ok and not self._device_latched:
            return "device"
        return super().span

    @property
    def device_exchange_active(self) -> bool:
        return self._device_ok and not self._device_latched

    def _latch_host(self, why: BaseException) -> None:
        self._device_latched = True
        self.metrics.incr("shuffle.device_fallbacks")
        logger.error(
            "device shuffle: exchange leg failed at round %d (%s) — "
            "latching the HOST exchange for the rest of the run",
            self._round, why,
        )

    def global_shuffle(self, my_ary: np.ndarray, should_abort: Any = None,
                       **kwargs: Any) -> None:
        n = self.topology.n_instances
        if n <= 1 or self.num_exchange < 2:
            return
        if (
            not self._device_ok
            or self._device_latched
            or self._degraded
            or self._suspended
        ):
            # Host tier (resolution-off / latched) or the inherited
            # degrade/suspend rungs — the base class owns all of them.
            return super().global_shuffle(my_ary, should_abort, **kwargs)
        lane_a, lane_b = exchange_slices(self.num_exchange)
        half = lane_a.stop
        # Both lanes travel as one 2D block; trailing dims flatten into
        # columns (the device kernel is 2D) and unflatten on return.
        block = np.ascontiguousarray(
            my_ary[: 2 * half].reshape(2 * half, -1)
        )
        try:
            out = self._fabric.exchange(
                producer_idx=self.producer_idx,
                round_=self._round,
                instance_idx=self.topology.instance_idx,
                n=n,
                block=block,
                seed=self.seed + self.producer_idx,
                timeout_s=self.exchange_timeout_s,
                should_abort=should_abort,
            )
        except ShutdownRequested:
            raise
        except DeviceExchangeError as e:
            # Device leg failed for the whole round: latch the host
            # exchange for life and re-run the SAME round over it —
            # lanes are unmutated, so the bytes equal a host-only run.
            self._latch_host(e)
            return super().global_shuffle(my_ary, should_abort, **kwargs)
        except DDLError as e:
            # A peer never posted: the host path's peer-loss rung,
            # byte-identical because the node-local shuffle depends
            # only on (seed, producer, round).
            if not self.degrade_on_peer_loss:
                raise
            self._degrade_round(my_ary, e)
            self._round += 1
            return
        my_ary[: 2 * half] = out.reshape(my_ary[: 2 * half].shape)
        self.metrics.incr("shuffle.device_rounds")
        self._peer_losses = 0  # a healthy round resets the ladder
        self._round += 1

    @classmethod
    def factory(
        cls,
        rendezvous: Any = None,
        fabric: Optional[DeviceExchangeFabric] = None,
        device_shuffle: Optional[str] = None,
        shuffle_impl: Optional[str] = None,
        **kwargs: Any,
    ) -> "DeviceExchangeShufflerFactory":
        return DeviceExchangeShufflerFactory(
            rendezvous=rendezvous, fabric=fabric,
            device_shuffle=device_shuffle, shuffle_impl=shuffle_impl,
            **kwargs,
        )


class DeviceExchangeShufflerFactory(ExchangeShufflerFactory):
    """Picklable device-shuffler factory.

    Constructs one :class:`DeviceExchangeFabric` (shared by every
    producer it builds in this process) unless given one.  The fabric
    is an in-process coordination board (named condition + device
    handles), so :meth:`__getstate__` DROPS it at the pickle boundary:
    PROCESS/MULTIHOST workers construct with the device tier resolved
    off and run the host exchange over the factory's ``rendezvous`` —
    the streams stay byte-identical and no ``shuffle.device_fallbacks``
    is counted (resolution is not a fallback)."""

    def __init__(
        self,
        rendezvous: Any = None,
        fabric: Optional[DeviceExchangeFabric] = None,
        device_shuffle: Optional[str] = None,
        shuffle_impl: Optional[str] = None,
        **kwargs: Any,
    ):
        super().__init__(rendezvous=rendezvous, **kwargs)
        self.fabric = (
            fabric
            if fabric is not None
            else DeviceExchangeFabric(impl=shuffle_impl)
        )
        self.device_shuffle = device_shuffle

    def __getstate__(self) -> Dict[str, Any]:
        state = self.__dict__.copy()
        state["fabric"] = None  # in-process reach only; see class doc
        return state

    def __call__(
        self,
        topology: Topology,
        producer_idx: int,
        num_exchange: int,
        exchange_method: str = "sendrecv_replace",
    ) -> DeviceExchangeShuffler:
        return DeviceExchangeShuffler(
            topology,
            producer_idx,
            num_exchange,
            exchange_method,
            rendezvous=self.rendezvous,
            fabric=self.fabric,
            device_shuffle=self.device_shuffle,
            seed=self.seed,
            exchange_timeout_s=self.exchange_timeout_s,
            degrade_on_peer_loss=self.degrade_on_peer_loss,
            max_peer_losses=self.max_peer_losses,
            wire_dtype=self.wire_dtype,
            codec=self.codec,
            codec_level=self.codec_level,
        )
