"""JAX version compatibility shims.

The repo targets jax>=0.8 (top-level ``jax.shard_map`` with the
``check_vma`` kwarg) but must also run on 0.4.x attaches where the same
transform lives at ``jax.experimental.shard_map.shard_map`` and the kwarg
is spelled ``check_rep``.  Every shard_map call site imports from here so
the probe runs once and the call signature stays the modern one.
"""

from __future__ import annotations

from typing import Any

try:  # jax >= 0.8: top-level export, `check_vma` kwarg
    from jax import shard_map as _shard_map  # type: ignore[attr-defined]

    _CHECK_KW = "check_vma"
except ImportError:  # jax 0.4.x: experimental module, `check_rep` kwarg
    from jax.experimental.shard_map import shard_map as _shard_map

    _CHECK_KW = "check_rep"


def shard_map(
    f: Any, *, mesh: Any, in_specs: Any, out_specs: Any,
    check_vma: bool = True,
) -> Any:
    """``jax.shard_map`` with the modern signature on any supported jax."""
    return _shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        **{_CHECK_KW: check_vma},
    )
