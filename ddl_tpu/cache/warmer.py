"""Background cache warmer: prefetch upcoming shards off the fill path.

The same overlap-the-slow-path rationale as MPMD pipelining applied to
storage: shard fetch+decode latency should be hidden behind the warm
tier, not paid on the producer's window-refill path.  A
:class:`CacheWarmer` walks the reader's shard list **in epoch order**
(the order refills will ask for them) on one daemon thread, loading
whatever is not yet cached until a byte budget is spent.

Shutdown contract (the part that usually rots): ``close()`` sets a stop
event and joins with a bound.  The loop checks the event between jobs,
every loader is handed a ``should_abort`` callback so a prefetch blocked
in backend retry/backoff aborts promptly
(:class:`~ddl_tpu.exceptions.ShutdownRequested` propagates out of
:func:`~ddl_tpu.cache.backends.open_with_retry`), and the thread treats
that signal as a clean exit — no leaked threads, no stranded sleeps.
Warming is best-effort by design: any other loader failure logs and
skips that shard (the fill path will retry it with the full ladder).
"""

from __future__ import annotations

import logging
import threading
from typing import Callable, Optional, Sequence, Tuple, Union

import numpy as np

from ddl_tpu.cache.store import CacheKey, CacheStore
from ddl_tpu.exceptions import ShutdownRequested

logger = logging.getLogger("ddl_tpu")

#: One prefetch job: the entry's key — either a literal :class:`CacheKey`
#: or a zero-arg thunk producing one, resolved ON the warmer thread
#: (key construction can stat/round-trip the backend for a fingerprint;
#: a thousand-shard list must not pay that on the producer's init path)
#: — plus a loader called as ``loader(should_abort)`` returning the
#: decoded shard array.
WarmJob = Tuple[
    Union[CacheKey, Callable[[], CacheKey]],
    Callable[[Callable[[], bool]], np.ndarray],
]


class CacheWarmer:
    """Prefetch ``jobs`` into ``store`` on a background daemon thread.

    ``budget_bytes`` bounds how much the warmer itself loads (defaults
    to the store's RAM budget — warming past it would only evict what
    was just warmed).  Already-cached entries are skipped via
    ``store.contains`` (no hit/miss skew).
    """

    def __init__(
        self,
        store: CacheStore,
        jobs: Sequence[WarmJob],
        budget_bytes: Optional[int] = None,
        name: str = "ddl-cache-warmer",
    ):
        self._store = store
        self._jobs = list(jobs)
        self._budget = (
            store.ram_budget_bytes if budget_bytes is None else int(budget_bytes)
        )
        self._stop = threading.Event()
        self._warmed_bytes = 0
        self._thread = threading.Thread(
            target=self._run, name=name, daemon=True
        )
        self._thread.start()

    # -- introspection -----------------------------------------------------

    def should_abort(self) -> bool:
        return self._stop.is_set()

    @property
    def alive(self) -> bool:
        return self._thread.is_alive()

    @property
    def warmed_bytes(self) -> int:
        return self._warmed_bytes

    # -- lifecycle ---------------------------------------------------------

    def _run(self) -> None:
        try:
            for key_ref, loader in self._jobs:
                if self._stop.is_set():
                    raise ShutdownRequested("cache warmer stopped")
                if self._warmed_bytes >= self._budget:
                    logger.debug(
                        "cache warmer: budget spent (%d bytes), stopping",
                        self._warmed_bytes,
                    )
                    break
                try:
                    key = key_ref() if callable(key_ref) else key_ref
                    if self._store.contains(key):
                        continue
                    arr = loader(self._stop.is_set)
                except ShutdownRequested:
                    raise
                except Exception:
                    # Best-effort: the fill path will retry this shard
                    # with the full retry/quarantine ladder and its own
                    # error surfacing; the warmer just moves on.
                    logger.exception(
                        "cache warmer: prefetch failed; shard left cold"
                    )
                    continue
                self._store.put(key, arr)
                self._warmed_bytes += int(arr.nbytes)
                self._store.metrics.incr("cache.warmed")
        except ShutdownRequested:
            logger.debug("cache warmer: clean shutdown mid-prefetch")

    def close(self, timeout_s: float = 10.0) -> bool:
        """Stop and join (bounded).  Returns True when the thread exited
        within the bound; idempotent."""
        self._stop.set()
        self._thread.join(timeout_s)
        return not self._thread.is_alive()
