"""The multi-tier shard cache: byte-budgeted RAM LRU + verified disk spill.

The paper's producers re-read and re-decode every shard from source on
every window refill and every epoch — the reference has no storage reuse
at all.  :class:`CacheStore` closes that gap with two tiers:

- **RAM tier** — an LRU of decoded shard arrays under a byte budget.
  Entries are stored read-only (``writeable=False``) so a reader that
  accidentally shuffles a cached array in place fails loudly instead of
  corrupting every later epoch.
- **Disk spill tier** — write-through: every insert is also persisted
  under ``spill_dir`` as an atomic temp-file+``os.replace`` write (a RAM
  eviction is then just a drop — the bytes are already safe), so the
  disk tier holds *everything* decoded so far, not only what RAM
  pressure happened to push out.  Every entry reuses the ring-slot
  crc32 trailer machinery from :mod:`ddl_tpu.integrity` (payload CRC +
  a digest-derived ``seq`` tag, :func:`~ddl_tpu.integrity.blob_seq`) and
  is verified on read: a corrupt or aliased file is **quarantined**
  (renamed aside, counted) and reported as a miss, so the caller
  refetches from source — corruption can degrade throughput, never
  data.  The disk tier survives the process, which is what lets a
  resumed run warm-start (``LoaderCheckpoint`` records the spill dir).

Keys are content-addressed (:class:`CacheKey`): the source fingerprint
(size+mtime via the backend), the shard id, the reader class + its
decode-relevant params, and a transform version — change any of them
and the digest moves, so stale entries can never alias fresh data.

Observability: ``cache.hits/misses/evictions/spills/spill_hits/
spill_evictions/quarantined`` counters and ``cache.resident_bytes`` /
``cache.spill_bytes`` gauges in the shared :class:`Metrics` registry,
surfaced by ``north_star_report`` and the bench's ``cache`` block.
"""

from __future__ import annotations

import collections
import dataclasses
import functools
import hashlib
import json
import logging
import os
import struct
import threading

from ddl_tpu.concurrency import named_lock, named_rlock
from typing import Callable, Dict, Optional

import numpy as np

from ddl_tpu import integrity
from ddl_tpu.faults import fault_point
from ddl_tpu.observability import Metrics, metrics as default_metrics

logger = logging.getLogger("ddl_tpu")

#: Bump when the key construction or disk-entry layout changes: old spill
#: dirs stop matching (checkpoint manifests carry this, so a resumed run
#: never adopts a tier written under a different schema).
KEY_SCHEMA_VERSION = 1

#: Disk-entry suffix (``<digest>.ddlc`` under the spill dir).
SPILL_SUFFIX = ".ddlc"
#: Quarantined corrupt entries are renamed to ``<digest>.quarantined``
#: (kept for post-mortem, never re-read).
QUARANTINE_SUFFIX = ".quarantined"
#: Only the newest this-many quarantined files are retained — recurring
#: corruption on a flaky disk must not grow the spill dir without bound
#: (the exact DDL013 shape, one rung down).
QUARANTINE_KEEP = 4

_META_LEN_FMT = "<I"
_META_LEN_BYTES = struct.calcsize(_META_LEN_FMT)


@dataclasses.dataclass(frozen=True)
class CacheKey:
    """Content-addressed identity of one decoded shard.

    ``source`` is the backend's content fingerprint (size+mtime), not
    the path — a rewritten shard file gets a new key.  ``shard`` is the
    shard id (its path).  ``reader`` is the reader class plus every
    parameter that changes the decoded bytes (``image_size`` for the
    WebDataset reader, ``feature_key`` for TFRecord).  ``transform`` is
    the reader's decode-logic version tag, bumped when the decode
    implementation itself changes shape or content.
    """

    source: str
    shard: str
    reader: str
    transform: str = ""

    @functools.cached_property
    def digest(self) -> str:
        """Hex sha256 over the schema version + every key field."""
        blob = json.dumps(
            {
                "schema": KEY_SCHEMA_VERSION,
                "source": self.source,
                "shard": self.shard,
                "reader": self.reader,
                "transform": self.transform,
            },
            sort_keys=True,
        ).encode()
        return hashlib.sha256(blob).hexdigest()


class CacheStore:
    """Byte-budgeted RAM LRU over an integrity-checked disk spill tier.

    Thread-safe (one RLock): producer threads, the background warmer and
    the consumer may all hit one store.  Not picklable — PROCESS-mode
    workers each build their own from the ``DDL_TPU_CACHE*`` environment
    (:func:`ddl_tpu.cache.default_store`); passing a store instance into
    a producer constructor is the THREAD-mode / test path.
    """

    def __init__(
        self,
        ram_budget_bytes: int = 256 << 20,
        spill_dir: Optional[str] = None,
        spill_budget_bytes: int = 1 << 30,
        metrics: Optional[Metrics] = None,
        codec: Optional[str] = None,
        codec_level: int = 6,
    ):
        self.ram_budget_bytes = int(ram_budget_bytes)
        self.spill_dir = os.path.abspath(spill_dir) if spill_dir else None
        self.spill_budget_bytes = int(spill_budget_bytes)
        self.metrics = metrics or default_metrics()
        # Lossless codec for DISK-tier entries (ddl_tpu.wire): spill
        # files store the codec-compressed payload (the crc trailer
        # covers the stored bytes, so verification is unchanged), decoded
        # on promote — the same spill budget then holds ~ratio× more
        # shards.  The RAM tier stays decoded: a hit must stay a view.
        # Validated here (fail at construction, not first spill); a
        # decode failure on read rides the existing quarantine+refetch
        # rung.
        self.codec = codec if codec and codec != "none" else None
        if self.codec:
            from ddl_tpu import wire as _wire

            _wire.get_codec(self.codec)
        self.codec_level = int(codec_level)
        # Two locks so a pure RAM-tier hit never waits on disk I/O:
        # _lock guards the LRU bookkeeping only; _spill_lock serializes
        # disk-tier writes/trims/quarantines and their accounting.
        # Order (also declared in [tool.ddl_lint] lock_order): _lock may
        # be held when _spill_lock is taken (eviction spill-backstop),
        # never the reverse.
        self._lock = named_rlock("cache.store")
        self._spill_lock = named_lock("cache.store.spill")
        # LRU: digest -> read-only decoded array; bounded by the byte
        # budget via _evict_over_budget (DDL013's whole point).
        self._ram: "collections.OrderedDict[str, np.ndarray]" = (
            collections.OrderedDict()
        )
        self._ram_bytes = 0
        self._spill_bytes = 0
        if self.spill_dir:
            os.makedirs(self.spill_dir, exist_ok=True)
            # Warm start: adopt whatever a previous run spilled (resume
            # path — keys are content-addressed, so stale files simply
            # never match; over-budget remnants trim on first spill).
            self._spill_bytes = self._scan_spill_bytes()
            self.metrics.set_gauge("cache.spill_bytes", self._spill_bytes)

    def _scan_spill_bytes(self) -> int:
        total = 0
        for name in os.listdir(self.spill_dir):
            if name.endswith(SPILL_SUFFIX):
                try:
                    total += os.path.getsize(
                        os.path.join(self.spill_dir, name)
                    )
                except OSError:
                    pass
        return total

    def attach_spill_dir(self, spill_dir: str) -> bool:
        """Late-bind a disk tier onto a RAM-only store.

        The checkpoint-manifest adoption path for an ALREADY-BUILT store:
        THREAD-mode resume applies the loader checkpoint after the
        loader (and with it the shared process store) exists, so the
        manifest must be attachable in place.  Existing entries in the
        directory are adopted (content-addressed keys make that safe).
        Refused when a *different* spill dir is already attached —
        adoption never silently re-routes a live tier.
        """
        spill_dir = os.path.abspath(spill_dir)
        with self._spill_lock:
            if self.spill_dir is not None:
                return self.spill_dir == spill_dir
            try:
                os.makedirs(spill_dir, exist_ok=True)
            except OSError:
                return False
            self.spill_dir = spill_dir
            self._spill_bytes = self._scan_spill_bytes()
            self.metrics.set_gauge("cache.spill_bytes", self._spill_bytes)
        return True

    def __deepcopy__(self, memo) -> "CacheStore":
        # THREAD-mode channels deep-copy shipped producer functions to
        # simulate the process boundary; the store is deliberately
        # SHARED process state (one RAM tier per host, all thread
        # producers hitting it), so the copy is the instance.  PROCESS
        # mode must not ship stores at all — pickling one fails loudly
        # (locks don't pickle) and workers build their own from the
        # environment instead (``default_store``).
        return self

    # -- public API --------------------------------------------------------

    def get(self, key: CacheKey) -> Optional[np.ndarray]:
        """RAM tier, then disk tier; ``None`` on miss (caller refetches).

        A disk hit is verified (CRC + digest-derived seq) and promoted
        into the RAM tier; a corrupt disk entry is quarantined and
        reported as a miss — the degradation ladder's first rung.  The
        disk read/verify runs OUTSIDE the LRU lock (entries publish
        atomically and are content-addressed, so unlocked I/O is safe):
        one thread's multi-hundred-MB disk promote never stalls another
        thread's RAM hit.
        """
        digest = key.digest
        with self._lock:
            arr = self._ram.get(digest)
            if arr is not None:
                self._ram.move_to_end(digest)
                self.metrics.incr("cache.hits")
                return arr
        arr = self._disk_get(digest)
        if arr is not None:
            self.metrics.incr("cache.hits")
            self.metrics.incr("cache.spill_hits")
            with self._lock:
                return self._insert(digest, arr, from_disk=True)
        self.metrics.incr("cache.misses")
        return None

    def put(self, key: CacheKey, arr: np.ndarray) -> np.ndarray:
        """Insert ``arr`` under ``key``; returns the stored (read-only)
        array — callers should use the return value so every consumer
        shares one resident copy.

        The store takes OWNERSHIP of ``arr``: it is marked read-only in
        place (when already contiguous, no copy is made — the caller's
        reference and the resident entry are the same object).  Pass a
        copy if you need to keep mutating your buffer; the in-tree
        readers always hand over freshly decoded arrays.  The
        write-through disk persist also runs outside the LRU lock.
        """
        digest = key.digest
        with self._lock:
            existing = self._ram.get(digest)
            if existing is not None:
                self._ram.move_to_end(digest)
                return existing
        arr = np.ascontiguousarray(arr)
        arr.setflags(write=False)
        self._spill(digest, arr)
        with self._lock:
            existing = self._ram.get(digest)
            if existing is not None:  # raced another inserter: share theirs
                self._ram.move_to_end(digest)
                return existing
            return self._insert(digest, arr, persisted=True)

    def get_or_load(
        self, key: CacheKey, loader: Callable[[], np.ndarray]
    ) -> np.ndarray:
        """``get`` or fetch-decode-insert via ``loader`` on miss."""
        arr = self.get(key)
        if arr is None:
            arr = self.put(key, loader())
        return arr

    def contains(self, key: CacheKey) -> bool:
        """Tier membership WITHOUT touching hit/miss counters (the
        warmer's skip-already-warm probe must not skew the ratios the
        bench reports)."""
        digest = key.digest
        with self._lock:
            if digest in self._ram:
                return True
        return bool(
            self.spill_dir
            and os.path.exists(self._spill_path(digest))
        )

    @property
    def resident_bytes(self) -> int:
        with self._lock:
            return self._ram_bytes

    def stats(self) -> Dict[str, float]:
        """Point-in-time tier sizes (counters live in ``self.metrics``)."""
        with self._lock:
            return {
                "entries": float(len(self._ram)),
                "resident_bytes": float(self._ram_bytes),
                "spill_bytes": float(self._spill_bytes),
            }

    def clear(self) -> None:
        """Drop the RAM tier (disk entries stay — they re-verify on read)."""
        with self._lock:
            self._ram.clear()
            self._ram_bytes = 0
            self.metrics.set_gauge("cache.resident_bytes", 0)

    # -- RAM tier ----------------------------------------------------------

    def _insert(
        self,
        digest: str,
        arr: np.ndarray,
        from_disk: bool = False,
        persisted: bool = False,
    ) -> np.ndarray:
        # Caller holds _lock.  Re-check residency FIRST: two threads can
        # race a miss on the same digest (e.g. concurrent disk promotes,
        # or a promote racing a put) and both reach here — inserting
        # twice would overwrite the entry but add its nbytes to
        # _ram_bytes twice, permanently shrinking the effective budget.
        existing = self._ram.get(digest)
        if existing is not None:
            self._ram.move_to_end(digest)
            return existing
        # Read-only residents: an in-place shuffle on a cached array
        # would silently corrupt every later epoch's "hit".
        arr.setflags(write=False)
        # Write-through (no-op without a spill dir, for an entry that
        # came FROM disk, or one ``put`` already persisted pre-lock):
        # once written, a later RAM eviction is a pure drop and a
        # process exit loses nothing the manifest points at.
        if not from_disk and not persisted:
            self._spill(digest, arr)
        if arr.nbytes > self.ram_budget_bytes:
            # Oversized for the RAM tier entirely: disk-only residency.
            return arr
        self._ram[digest] = arr
        self._ram_bytes += arr.nbytes
        self._evict_over_budget()
        self.metrics.set_gauge("cache.resident_bytes", self._ram_bytes)
        return arr

    def _evict_over_budget(self) -> None:
        while self._ram_bytes > self.ram_budget_bytes and len(self._ram) > 1:
            old_digest, old = self._ram.popitem(last=False)
            self._ram_bytes -= old.nbytes
            self.metrics.incr("cache.evictions")
            # Backstop only: write-through already persisted the entry
            # at insert (the exists-check makes this a stat), but an
            # insert whose spill failed transiently gets a second try.
            self._spill(old_digest, old)

    # -- disk tier ---------------------------------------------------------

    def _spill_path(self, digest: str) -> str:
        return os.path.join(self.spill_dir or "", digest + SPILL_SUFFIX)

    def _spill(self, digest: str, arr: np.ndarray) -> None:
        if not self.spill_dir:
            return
        path = self._spill_path(digest)
        if os.path.exists(path):
            return  # content-addressed: same digest == same bytes
        meta_d = {
            "schema": KEY_SCHEMA_VERSION,
            "dtype": arr.dtype.str,
            "shape": list(arr.shape),
        }
        payload = np.ascontiguousarray(arr).view(np.uint8).reshape(-1)
        if self.codec:
            from ddl_tpu import wire as _wire

            packed = _wire.get_codec(self.codec).encode_bytes(
                payload.tobytes(), level=self.codec_level
            )
            payload = np.frombuffer(packed, np.uint8)
            meta_d["codec"] = self.codec
        meta = json.dumps(meta_d).encode()
        off = _META_LEN_BYTES + len(meta)
        total = off + payload.nbytes + integrity.HEADER_BYTES
        if total > self.spill_budget_bytes:
            # Oversized for the whole tier (symmetric to the RAM tier's
            # guard): writing it would only make the trim below evict
            # every valid entry AND the new file itself, every miss.
            logger.warning(
                "cache: entry %s… (%d bytes) exceeds the spill budget "
                "(%d); not persisted",
                digest[:12], total, self.spill_budget_bytes,
            )
            return
        blob = np.empty(total, np.uint8)
        blob[:_META_LEN_BYTES] = np.frombuffer(
            struct.pack(_META_LEN_FMT, len(meta)), np.uint8
        )
        blob[_META_LEN_BYTES:off] = np.frombuffer(meta, np.uint8)
        blob[off : off + payload.nbytes] = payload
        integrity.write_header(
            blob[off:],
            payload.nbytes,
            seq=integrity.blob_seq(digest),
            producer_idx=0,
            crc=integrity.window_crc(payload),
        )
        # Atomic publish: a crash mid-write leaves only a temp file a
        # later run ignores; readers can never observe a torn entry.
        tmp = f"{path}.tmp.{os.getpid()}.{threading.get_ident()}"
        with self._spill_lock:
            if os.path.exists(path):
                # Re-check under the lock: a concurrent insert of the
                # same digest won the race between the cheap unlocked
                # check above and here — writing again would be
                # harmless (same bytes) but would double-count
                # _spill_bytes and trigger phantom trims.
                return
            try:
                blob.tofile(tmp)
                os.replace(tmp, path)
            except OSError as e:
                logger.warning(
                    "cache: spill of %s failed: %s", digest[:12], e
                )
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
                return
            self._spill_bytes += blob.nbytes
            self.metrics.incr("cache.spills")
            self._trim_spill_tier()
            self.metrics.set_gauge("cache.spill_bytes", self._spill_bytes)

    def _trim_spill_tier(self) -> None:
        """Oldest-first disk eviction when the spill tier is over budget
        (caller holds ``_spill_lock``)."""
        if not self.spill_dir or self._spill_bytes <= self.spill_budget_bytes:
            return
        entries = []
        for name in os.listdir(self.spill_dir):
            if not name.endswith(SPILL_SUFFIX):
                continue
            p = os.path.join(self.spill_dir, name)
            try:
                st = os.stat(p)
            except OSError:
                continue
            entries.append((st.st_mtime_ns, st.st_size, p))
        entries.sort()
        for _, size, p in entries:
            if self._spill_bytes <= self.spill_budget_bytes:
                break
            try:
                os.unlink(p)
            except OSError:
                continue
            self._spill_bytes -= size
            self.metrics.incr("cache.spill_evictions")

    def _disk_get(self, digest: str) -> Optional[np.ndarray]:
        if not self.spill_dir:
            return None
        path = self._spill_path(digest)
        try:
            raw = np.fromfile(path, np.uint8)
        except (OSError, FileNotFoundError):
            return None
        # Chaos hook: flips bytes in the just-read entry, exercising the
        # quarantine-and-refetch rung below exactly as at-rest disk
        # corruption would.
        fault_point("cache.disk_read", view=raw)
        try:
            if len(raw) < _META_LEN_BYTES:
                raise ValueError("short entry (no meta length)")
            (meta_len,) = struct.unpack(
                _META_LEN_FMT, raw[:_META_LEN_BYTES].tobytes()
            )
            off = _META_LEN_BYTES + meta_len
            payload_bytes = len(raw) - off - integrity.HEADER_BYTES
            if meta_len <= 0 or payload_bytes < 0:
                raise ValueError("truncated entry")
            meta = json.loads(raw[_META_LEN_BYTES:off].tobytes())
            if meta.get("schema") != KEY_SCHEMA_VERSION:
                raise ValueError(f"key-schema {meta.get('schema')} entry")
            err = integrity.verify_window(
                raw[off:],
                payload_bytes,
                expect_seq=integrity.blob_seq(digest),
                expect_producer=0,
            )
            if err:
                raise ValueError(err)
            stored = raw[off : off + payload_bytes]
            if meta.get("codec"):
                # Compressed entry: the crc above verified the STORED
                # bytes; a codec failure past it (truncated history,
                # foreign codec) quarantines + refetches like any
                # corrupt entry.  Decode is bounded by the shape the
                # meta declares.
                from ddl_tpu import wire as _wire
                from ddl_tpu.exceptions import DecodeError

                dtype = np.dtype(meta["dtype"])
                bound = int(np.prod(meta["shape"])) * dtype.itemsize
                try:
                    stored = np.frombuffer(
                        _wire.get_codec(meta["codec"]).decode_bytes(
                            stored.tobytes(), max_output=bound
                        ),
                        np.uint8,
                    )
                except DecodeError as e:
                    raise ValueError(f"codec decode failed: {e}") from e
            arr = (
                stored
                .view(np.dtype(meta["dtype"]))
                .reshape(meta["shape"])
            )
        except (ValueError, KeyError, TypeError, json.JSONDecodeError) as e:
            self._quarantine(path, digest, str(e))
            return None
        return arr

    def _quarantine(self, path: str, digest: str, reason: str) -> None:
        """Move a corrupt disk entry aside (kept for post-mortem, never
        re-read) and count it; the caller reports a miss and the reader
        refetches from source."""
        logger.warning(
            "cache: quarantining corrupt disk entry %s…: %s",
            digest[:12], reason,
        )
        self.metrics.incr("cache.quarantined")
        with self._spill_lock:
            try:
                size = os.path.getsize(path)
                os.replace(
                    path, path[: -len(SPILL_SUFFIX)] + QUARANTINE_SUFFIX
                )
                self._spill_bytes = max(0, self._spill_bytes - size)
                self.metrics.set_gauge(
                    "cache.spill_bytes", self._spill_bytes
                )
            except OSError:
                pass
            # Retention bound: keep only the newest QUARANTINE_KEEP
            # post-mortem files (they live outside the budget
            # accounting, so without this a flaky disk grows the
            # directory forever).
            q = []
            for name in os.listdir(self.spill_dir or ""):
                if not name.endswith(QUARANTINE_SUFFIX):
                    continue
                p = os.path.join(self.spill_dir, name)
                try:
                    q.append((os.stat(p).st_mtime_ns, p))
                except OSError:
                    continue
            q.sort()
            for _, p in q[: max(0, len(q) - QUARANTINE_KEEP)]:
                try:
                    os.unlink(p)
                except OSError:
                    pass
