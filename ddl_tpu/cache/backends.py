"""Pluggable storage backends: every shard read goes through one of these.

The reference (and our pre-cache readers) opened shard files with bare
``open``/``np.load`` — no seam to put a remote store, a latency model, or
a failure model behind.  :class:`StorageBackend` is that seam: a tiny
protocol (``open`` / ``fingerprint``) the file-based producers in
``ddl_tpu.readers`` and the cache tier call for every shard byte they
touch.

- :class:`LocalBackend` — the local filesystem (the production default).
- :class:`ThrottledBackend` — wraps another backend with configurable
  per-open latency and a deterministic transient-failure schedule.  It
  exists so the bench's cold-vs-warm A/B and the chaos suite exercise a
  realistic *slow, flaky* source without needing network access: a warm
  cache tier only proves itself against a source that actually costs
  something.

Transient failures surface as :class:`~ddl_tpu.exceptions.BackendFetchError`;
:func:`open_with_retry` is the one retry/backoff policy site (bounded
attempts, exponential backoff, shutdown-observing sleeps) — exhaustion
escalates to :class:`~ddl_tpu.exceptions.IntegrityError`, the "persistent
backend failure" rung of the degradation ladder (docs/CACHING.md).
"""

from __future__ import annotations

import os
import threading

from ddl_tpu.concurrency import named_lock
import time
from typing import BinaryIO, Optional, Protocol, runtime_checkable

from ddl_tpu.exceptions import (
    BackendFetchError,
    IntegrityError,
    ShutdownRequested,
)
from ddl_tpu.faults import fault_point


@runtime_checkable
class StorageBackend(Protocol):
    """What the cache and the file-based readers need from a shard store.

    Deliberately minimal — two methods.  Whole-shard reads are spelled
    ``open(path).read()`` by callers; a parallel ``fetch`` method would
    be a second code path nothing exercises.
    """

    def open(self, path: str) -> BinaryIO:
        """Open ``path`` for streaming binary reads (seekable)."""
        ...

    def fingerprint(self, path: str) -> str:
        """A cheap content-version fingerprint for ``path``.

        Cache keys embed it (:class:`ddl_tpu.cache.CacheKey`), so a
        rewritten shard can never alias a stale cached decode.
        """
        ...


class LocalBackend:
    """The local filesystem (production default)."""

    name = "local"

    def open(self, path: str) -> BinaryIO:
        return open(path, "rb")

    def fingerprint(self, path: str) -> str:
        st = os.stat(path)
        return f"local:{st.st_size}:{st.st_mtime_ns}"


class ThrottledBackend:
    """A backend wrapper simulating a slow, transiently flaky remote store.

    ``latency_s`` sleeps on every ``open``/``fetch`` (the remote
    round-trip); ``fail_every=N`` makes every N-th open raise
    :class:`BackendFetchError` *once* (the retry's next attempt is a new
    open and passes) — deterministic, so chaos tests can assert exact
    retry counts.  ``fingerprint`` delegates unchanged: the key must
    reflect the *content*, not the transport in front of it.

    Picklable (producers ship to PROCESS-mode workers by pickle): the
    open counter and its lock are per-process state and reset on
    unpickle.
    """

    name = "throttled"

    def __init__(
        self,
        inner: Optional[StorageBackend] = None,
        latency_s: float = 0.0,
        fail_every: int = 0,
    ):
        self.inner = inner or LocalBackend()
        self.latency_s = float(latency_s)
        self.fail_every = int(fail_every)
        self._opens = 0
        self._lock = named_lock("cache.backend")

    # -- pickling (locks don't cross the spawn boundary) -------------------

    def __getstate__(self):
        return {
            "inner": self.inner,
            "latency_s": self.latency_s,
            "fail_every": self.fail_every,
        }

    def __setstate__(self, state):
        self.__init__(**state)

    # -- the throttle ------------------------------------------------------

    def _gate(self, path: str) -> None:
        with self._lock:
            self._opens += 1
            n = self._opens
        if self.fail_every and n % self.fail_every == 0:
            raise BackendFetchError(
                f"simulated transient fetch failure for {path!r} "
                f"(open #{n}, fail_every={self.fail_every})"
            )
        if self.latency_s > 0:
            time.sleep(self.latency_s)

    @property
    def opens(self) -> int:
        """Total opens observed (cold-epoch accounting in tests/bench)."""
        with self._lock:
            return self._opens

    def open(self, path: str) -> BinaryIO:
        self._gate(path)
        return self.inner.open(path)

    def fingerprint(self, path: str) -> str:
        return self.inner.fingerprint(path)


#: Default codec inferred per file suffix by :class:`CodecBackend`.
CODEC_SUFFIXES = {".zz": "zlib", ".gz": "zlib", ".zst": "zstd", ".lz4": "lz4"}


class CodecBackend:
    """A backend wrapper that decodes codec-compressed shard files on
    the producer fill path (``ddl_tpu.wire`` lossless tier).

    ``open(path)`` reads the inner backend's bytes and, when the path
    carries a known codec suffix (``shard_000.npy.zz`` → zlib) or
    ``codec=`` forces one, returns the DECODED bytes as a seekable
    stream — so every shard reader (``np.load``, the tar walker, the
    TFRecord iterator) consumes compressed shards transparently, and
    the decode happens exactly once per fetch, before the write-once
    fill (never per row).  The decode is bounded (``max_output``) and a
    failure raises :class:`BackendFetchError` — deliberately the
    TRANSIENT type, so a torn partial object from a flaky remote store
    rides :func:`open_with_retry`'s existing bounded retry/backoff
    ladder and only a *persistent* decode failure escalates to
    :class:`IntegrityError` (the ``wire.decode`` chaos site fires per
    attempt, so ``DECODE_FAIL`` exercises exactly that ladder).

    ``fingerprint`` folds the codec tag next to the inner fingerprint:
    a shard recompressed under a different codec can never alias its
    cached decode.  Picklable (PROCESS-mode producers ship backends by
    pickle): carries only names and bounds.
    """

    name = "codec"

    def __init__(
        self,
        inner: Optional[StorageBackend] = None,
        codec: Optional[str] = None,
        max_output: int = 1 << 31,
    ):
        self.inner = inner or LocalBackend()
        self.codec = codec
        self.max_output = int(max_output)
        if codec:
            from ddl_tpu import wire

            wire.get_codec(codec)  # fail at construction, not first shard

    def _codec_for(self, path: str) -> Optional[str]:
        if self.codec:
            return self.codec
        for suffix, name in CODEC_SUFFIXES.items():
            if path.endswith(suffix):
                return name
        return None

    def open(self, path: str) -> BinaryIO:
        import io

        from ddl_tpu import wire
        from ddl_tpu.exceptions import DecodeError

        name = self._codec_for(path)
        if name is None:
            return self.inner.open(path)
        with self.inner.open(path) as f:
            raw = f.read()
        try:
            fault_point("wire.decode")
            return io.BytesIO(
                wire.get_codec(name).decode_bytes(
                    raw, max_output=self.max_output
                )
            )
        except DecodeError as e:
            # The TRANSIENT type on purpose: open_with_retry's bounded
            # retry re-fetches (a torn partial object heals); only a
            # persistent failure escalates to IntegrityError there.
            raise BackendFetchError(
                f"codec decode of {path!r} failed ({name}): {e}"
            ) from e

    def fingerprint(self, path: str) -> str:
        name = self._codec_for(path)
        inner = self.inner.fingerprint(path)
        return f"{inner}:codec={name}" if name else inner


def open_with_retry(
    backend: StorageBackend,
    path: str,
    retries: int = 3,
    backoff_s: float = 0.05,
    metrics=None,
    should_abort=None,
) -> BinaryIO:
    """Open ``path`` on ``backend`` with bounded retry + exponential backoff.

    The ONE retry-policy site for shard fetches (producer cold reads,
    cache-miss refills, warmer prefetches).  Transient failures
    (:class:`BackendFetchError`, ``OSError``) retry up to ``retries``
    times with ``backoff_s * 2**attempt`` sleeps; exhaustion raises
    :class:`IntegrityError` — by then the bytes are provably
    unfetchable, the terminal rung of the ladder.  Backoff sleeps
    observe ``should_abort`` so a shutting-down warmer never serves out
    a full backoff schedule (raises :class:`ShutdownRequested`).

    The ``backend.fetch`` chaos injection point fires before every
    attempt, so an armed ``BACKEND_FETCH_FAIL`` plan exercises exactly
    this policy.
    """
    attempt = 0
    while True:
        if should_abort is not None and should_abort():
            raise ShutdownRequested(f"fetch of {path!r} aborted")
        try:
            fault_point("backend.fetch", should_abort=should_abort)
            return backend.open(path)
        except (BackendFetchError, OSError) as e:
            attempt += 1
            if metrics is not None:
                metrics.incr("cache.backend_retries")
            if attempt > retries:
                if metrics is not None:
                    metrics.incr("cache.backend_failures")
                raise IntegrityError(
                    f"persistent backend failure fetching {path!r} "
                    f"({attempt} attempts, backend "
                    f"{getattr(backend, 'name', type(backend).__name__)}): {e}"
                ) from e
            delay = backoff_s * (2 ** (attempt - 1))
            deadline = time.monotonic() + delay
            while time.monotonic() < deadline:
                if should_abort is not None and should_abort():
                    raise ShutdownRequested(
                        f"fetch retry backoff for {path!r} aborted"
                    )
                time.sleep(min(0.01, delay))
