"""ddl_tpu.cache — content-addressed multi-tier shard cache.

The storage abstraction the reference never had: every file-based
producer in :mod:`ddl_tpu.readers` fetches shard bytes through a
pluggable :class:`StorageBackend` and (when enabled) keeps decoded
shards in a :class:`CacheStore` — a byte-budgeted RAM LRU over an
integrity-checked disk spill tier — so epoch ≥ 2 skips both the fetch
*and* the decode.  A background :class:`CacheWarmer` prefetches the next
shards in epoch order.  docs/CACHING.md has the full design (tiers, key
schema, knobs, failure ladder).

Environment knobs (mirrored by ``LoaderConfig`` fields of the same
lower-case names; :func:`ddl_tpu.env.distributed_dataloader` exports a
config's cache fields back into the environment so PROCESS-mode workers
inherit them):

=============================  ============================================
``DDL_TPU_CACHE``              gate (default **off**; ``1`` enables)
``DDL_TPU_CACHE_RAM_MB``       RAM-tier byte budget (default 256)
``DDL_TPU_CACHE_SPILL_DIR``    disk-tier directory (unset = no disk tier)
``DDL_TPU_CACHE_SPILL_MB``     disk-tier byte budget (default 1024)
``DDL_TPU_CACHE_WARM``         background warmer gate (default on)
``DDL_TPU_CACHE_RETRIES``      backend fetch retry budget (default 3)
``DDL_TPU_CACHE_BACKOFF_S``    base retry backoff seconds (default 0.05)
=============================  ============================================

The default store is **per process** (PROCESS-mode producers each build
their own from the environment; THREAD-mode workers share the
consumer's).  Tests inject explicit ``CacheStore``/backend instances
through the reader constructors instead.
"""

from __future__ import annotations

import os
import threading

from ddl_tpu import envspec
from ddl_tpu.concurrency import named_lock
from typing import Optional

from ddl_tpu.cache.backends import (  # noqa: F401  (public re-exports)
    CodecBackend,
    LocalBackend,
    StorageBackend,
    ThrottledBackend,
    open_with_retry,
)
from ddl_tpu.cache.store import (  # noqa: F401
    KEY_SCHEMA_VERSION,
    CacheKey,
    CacheStore,
)
from ddl_tpu.cache.warmer import CacheWarmer  # noqa: F401
from ddl_tpu.utils import env_flag

__all__ = [
    "CacheKey",
    "CacheStore",
    "CacheWarmer",
    "KEY_SCHEMA_VERSION",
    "CodecBackend",
    "LocalBackend",
    "StorageBackend",
    "ThrottledBackend",
    "active_store",
    "adopt_manifest",
    "cache_enabled",
    "default_store",
    "open_with_retry",
    "reset_default_store",
    "settings_from_env",
    "warm_enabled",
]


def cache_enabled(override: Optional[bool] = None) -> bool:
    """The ``DDL_TPU_CACHE`` gate — default **off** (opt-in: the cache
    spends host RAM/disk, which is the operator's call)."""
    return env_flag("DDL_TPU_CACHE", override)


def warm_enabled(override: Optional[bool] = None) -> bool:
    """The ``DDL_TPU_CACHE_WARM`` gate (default on; only consulted when
    the cache itself is enabled)."""
    return env_flag("DDL_TPU_CACHE_WARM", override)


def settings_from_env() -> dict:
    """The ``DDL_TPU_CACHE*`` knob set, parsed (one site; config.py's
    fields mirror these names minus the prefix)."""
    spill_dir = envspec.raw("DDL_TPU_CACHE_SPILL_DIR") or None
    return {
        "ram_budget_bytes": envspec.get("DDL_TPU_CACHE_RAM_MB") << 20,
        "spill_dir": spill_dir,
        "spill_budget_bytes": envspec.get("DDL_TPU_CACHE_SPILL_MB") << 20,
        # Disk-tier codec (ddl_tpu.wire): spill entries stored
        # compressed under the same byte budget.  Empty/"none" = off.
        "codec": envspec.raw("DDL_TPU_CACHE_CODEC") or None,
    }


def retry_settings_from_env() -> dict:
    return {
        "retries": envspec.get("DDL_TPU_CACHE_RETRIES"),
        "backoff_s": envspec.get("DDL_TPU_CACHE_BACKOFF_S"),
    }


_default_store: Optional[CacheStore] = None
_store_lock = named_lock("cache.registry")


def default_store() -> CacheStore:
    """The process-default :class:`CacheStore`, built once from the
    environment.  THREAD-mode producers (and the consumer) share it;
    each PROCESS-mode worker builds its own on first shard read."""
    global _default_store
    with _store_lock:
        if _default_store is None:
            _default_store = CacheStore(**settings_from_env())
        return _default_store


def active_store() -> Optional[CacheStore]:
    """The default store if one was already built, else ``None`` —
    checkpoint capture must not conjure a store as a side effect."""
    with _store_lock:
        return _default_store


def reset_default_store() -> None:
    """Drop the process-default store (tests re-gate the environment)."""
    global _default_store
    with _store_lock:
        _default_store = None


def adopt_manifest(spill_dir: str, key_schema: int) -> bool:
    """Adopt a checkpoint's cache manifest so the resumed run warm-starts
    from a previous run's disk tier instead of refetching every shard.

    Two mechanisms, because adoption can arrive before OR after the
    store exists:

    - the env var carries it forward: workers (and a default store)
      built *after* this call pick the spill dir up — PROCESS-mode
      producers inherit their environment at spawn, so for them the
      manifest must be adopted **before** ``distributed_dataloader``
      runs (:func:`ddl_tpu.checkpoint.adopt_cache_manifest` is the
      pre-spawn helper);
    - a default store **already built** RAM-only gets the tier attached
      in place (:meth:`CacheStore.attach_spill_dir`) — the THREAD-mode
      resume shape, where ``LoaderCheckpoint.apply`` runs after the
      loader (and the shared store) exists.

    Refused (returns False) when the manifest was written under a
    different key schema, the directory is gone, or a live store
    already points at a *different* spill dir — adoption must never
    silently re-route a live tier.
    """
    if key_schema != KEY_SCHEMA_VERSION:
        return False
    if not spill_dir or not os.path.isdir(spill_dir):
        return False
    with _store_lock:
        store = _default_store
    if store is not None and not store.attach_spill_dir(spill_dir):
        return False
    os.environ["DDL_TPU_CACHE_SPILL_DIR"] = spill_dir
    return True
