"""Staged ingest engine: reusable staging buffers + background transfer.

The inline ingest path pays two hidden costs per batch (SURVEY §8.3, the
host→HBM hop): a fresh ``np.array(copy=True)`` allocation — page faults +
allocator churn at exactly the per-batch cadence — and the fact that the
copy runs on the *consumer* thread, serialized against the compute it is
supposed to feed.  This module removes both:

- :class:`StagingPool` — shape/dtype-keyed recycled host buffers.  A
  staging copy lands in a pooled buffer; the buffer returns to the pool
  once the ``device_put`` sourcing it has completed, checked by a
  deferred non-blocking sweep (``jax.Array.is_ready``), never a blocking
  wait.  The per-batch allocation disappears after warmup
  (``staging.pool_hits`` / ``staging.pool_misses`` count it).
- :class:`TransferExecutor` — ONE background worker draining a bounded
  queue of copy→transfer jobs, so the slot→staging memcpy and the
  ``device_put`` dispatch overlap the caller's compute.  Each job yields
  a :class:`StagedTransfer` handle with two completion edges:
  ``copy_done`` (the transfer source no longer references the ring slot
  — the consumer may release the slot back to the producer EARLY) and
  ``ready`` (the device value is available to pop).

``DDL_TPU_STAGED=0`` disables the whole engine — every consumer falls
back to the previous inline copy path (the escape hatch for debugging
and A/B measurement; ``bench.py`` reports both sides).

Safety note: recycling a staging buffer is only sound when ``device_put``
*copies* its host source.  The CPU PJRT client aliases a compatible host
buffer instead — and it does so PER BUFFER (64-byte-aligned allocations
alias, unaligned ones copy; measured on this attach), so no one-time
probe can decide.  The pool therefore checks each transfer's device
buffers against the staging buffer's address range
(``unsafe_buffer_pointer``) and permanently DROPS any buffer the client
aliased instead of recycling it (the client keeps the memory alive; the
pool counts the loss in ``staging.pool_alias_drops``).  On accelerators
the put is a genuine host→HBM transfer, the check never fires, and every
buffer recycles.
"""

from __future__ import annotations

import collections
import logging
import os
import threading

from ddl_tpu import envspec
from ddl_tpu.concurrency import named_condition, named_lock
import time
from typing import Any, Callable, Deque, Dict, List, Optional, Tuple

import numpy as np

from ddl_tpu import integrity
from ddl_tpu.exceptions import (
    IntegrityError,
    ShutdownRequested,
    StallTimeoutError,
)
from ddl_tpu.faults import fault_point
from ddl_tpu.obs import spans as obs_spans
from ddl_tpu.observability import Metrics, metrics as default_metrics

logger = logging.getLogger("ddl_tpu")

#: Per-(shape, dtype) cap on retained free buffers.  Beyond it a released
#: buffer is dropped to the allocator — a pool must bound worst-case host
#: memory (lookahead depth + in-flight transfers is the working set).
DEFAULT_POOL_CAP = 8

#: Bounded executor queue depth: backpressure instead of unbounded
#: host-memory growth when the producer side outruns the device link.
DEFAULT_QUEUE_DEPTH = 4

#: Bounded retries per staged job phase (copy / transfer) before the
#: degradation ladder falls back to the sanctioned inline path
#: (``DDL_TPU_STAGING_RETRIES`` overrides; docs/ROBUSTNESS.md).
DEFAULT_MAX_RETRIES = 2

#: Exponential-backoff base/cap between retries.  The cap keeps a
#: persistently failing link from turning each window into a minutes-long
#: stall before the fallback engages.
_RETRY_BACKOFF_BASE_S = 0.05
_RETRY_BACKOFF_CAP_S = 1.0


def _flat_u8(arr: np.ndarray) -> Optional[np.ndarray]:
    """Flat uint8 alias of an array (for byte-level fault injection);
    None when the layout does not allow one."""
    try:
        return arr.reshape(-1).view(np.uint8)
    except (ValueError, AttributeError):
        return None


def staged_enabled(override: Optional[bool] = None) -> bool:
    """The ``DDL_TPU_STAGED`` gate (default ON; ``0`` = inline path)."""
    from ddl_tpu.utils import env_flag

    return env_flag("DDL_TPU_STAGED", override)


def shm_staging_enabled(override: Optional[bool] = None) -> bool:
    """The ``DDL_TPU_SHM_STAGING`` gate (default ON): lets staged
    window-stream jobs ALIAS the shm ring slot as their transfer source
    (no slot→staging memcpy) on clients whose ``device_put`` genuinely
    copies host memory.  ``0`` restores the copying pool everywhere."""
    from ddl_tpu.utils import env_flag

    return env_flag("DDL_TPU_SHM_STAGING", override)


class StagingPool:
    """Thread-safe pool of reusable host staging buffers.

    ``acquire`` hands out a buffer of exactly (shape, dtype) — recycled
    when one is free (``staging.pool_hits``), freshly allocated otherwise
    (``staging.pool_misses``).  Callers return buffers either directly
    (:meth:`release`) or deferred against an in-flight device transfer
    (:meth:`recycle_when_ready` + :meth:`sweep`).
    """

    def __init__(
        self,
        metrics: Optional[Metrics] = None,
        max_per_key: Optional[int] = None,
    ):
        self.metrics = metrics or default_metrics()
        self.max_per_key = (
            envspec.get("DDL_TPU_STAGING_POOL_CAP")
            if max_per_key is None
            else max_per_key
        )
        self._lock = named_lock("staging.pool")
        # Free-lists hold at most max_per_key buffers per geometry key
        # (release() drops beyond the cap), and a run's batch geometries
        # are a small closed set — bounded by construction.
        self._free: Dict[Tuple[Tuple[int, ...], Any], List[np.ndarray]] = {}  # ddl-lint: disable=DDL013
        #: FIFO of (device value to poll, buffer, dispatch timestamp).
        self._inflight: Deque[Tuple[Any, np.ndarray, float]] = (
            collections.deque()
        )
        #: (address, shape, dtype) triples PROVEN to be copied (not
        #: aliased) by the client — skips the per-transfer alias walk.
        self._copied_keys: set = set()

    # -- acquire / release -------------------------------------------------

    def acquire(self, shape: Tuple[int, ...], dtype: Any) -> np.ndarray:
        key = (tuple(shape), np.dtype(dtype))
        with self._lock:
            free = self._free.get(key)
            if free:
                buf = free.pop()
                hit = True
            else:
                buf = None
                hit = False
        if hit:
            self.metrics.incr("staging.pool_hits")
            return buf  # type: ignore[return-value]
        self.metrics.incr("staging.pool_misses")
        return np.empty(key[0], key[1])

    def release(self, buf: np.ndarray) -> None:
        """Return a buffer nothing references anymore."""
        key = (buf.shape, buf.dtype)
        with self._lock:
            free = self._free.setdefault(key, [])
            if len(free) < self.max_per_key:
                free.append(buf)

    def set_max_per_key(self, max_per_key: int) -> None:
        """Retune the per-geometry free-list cap live (ddl_tpu.tune).

        Shrinking trims each free-list immediately — the controller's
        revert must actually return memory, not wait for organic churn;
        growing simply lets future releases keep more.
        """
        cap = max(1, int(max_per_key))
        with self._lock:
            self.max_per_key = cap
            for free in self._free.values():
                del free[cap:]

    def recycle_when_ready(self, buf: np.ndarray, dev: Any) -> None:
        """Queue ``buf`` for recycling once ``dev``'s transfer completes.

        Non-blocking — the actual recycling happens in a later
        :meth:`sweep` (deferred ``on_ready`` check), so no caller ever
        waits on the link just to return memory.  A buffer the client
        ALIASED into ``dev`` (CPU zero-copy put) is dropped instead: the
        device value lives in that memory for as long as it exists, so
        reuse would corrupt it.
        """
        key = (buf.ctypes.data, buf.shape, buf.dtype)
        with self._lock:
            known_copied = key in self._copied_keys
        if not known_copied:
            if _may_alias(dev, buf):
                self.metrics.incr("staging.pool_alias_drops")
                return
            # The client's zero-copy decision is deterministic per
            # (address, layout) — alignment-based — so a buffer proven
            # copied once never needs the shard-pointer walk again
            # (measured ~0.1 ms per transfer).  Only the safe verdict is
            # cached: an address that once aliased may be freed and
            # reused, so it is re-checked every time.
            with self._lock:
                if len(self._copied_keys) > 4096:
                    self._copied_keys.clear()
                self._copied_keys.add(key)
        with self._lock:
            self._inflight.append((dev, buf, time.perf_counter()))

    def sweep(self, block: bool = False) -> int:
        """Recycle the FIFO prefix of in-flight buffers whose transfer
        has completed (``is_ready``); with ``block=True`` (shutdown /
        flush) wait for all of them.  Returns the number recycled.

        FIFO-prefix only: transfers dispatch in order on one stream, so a
        not-yet-ready head means the tail is not worth polling.  The
        observed dispatch→ready span accumulates into ``ingest.transfer``
        (an upper bound — sweep cadence adds slack — but an honest
        overlap measure where a dispatch-side timer would read ~0).
        """
        if not block and len(self._inflight) < 2:
            # Amortized fast path (no locks, no is_ready call): let a
            # lone in-flight transfer ride until the next submission —
            # the pool cap absorbs the one-deep recycling lag, and the
            # per-batch steal path stays lean.  len() on a deque is a
            # single GIL-atomic read.
            return 0
        done = 0
        while True:
            with self._lock:
                if not self._inflight:
                    break
                dev, buf, t0 = self._inflight[0]
                if not block and not _is_ready(dev):
                    break
                self._inflight.popleft()
            if block:
                _block_ready(dev)
            self.metrics.add_time(
                "ingest.transfer", time.perf_counter() - t0
            )
            self.release(buf)
            done += 1
        with self._lock:
            depth = len(self._inflight)
        self.metrics.set_gauge("staging.inflight", float(depth))
        return done

    def stats(self) -> Dict[str, float]:
        with self._lock:
            return {
                "free_buffers": float(
                    sum(len(v) for v in self._free.values())
                ),
                "inflight": float(len(self._inflight)),
            }


def _may_alias(dev: Any, buf: np.ndarray) -> bool:
    """Does any of ``dev``'s device buffers live inside ``buf``'s memory?

    The CPU PJRT client zero-copies 64-byte-aligned host arrays into
    device buffers (per-buffer, not per-client — measured), so this is
    checked per transfer via buffer addresses.  Anything unprovable
    (missing API, donated buffers) counts as aliasing — dropping a
    recyclable buffer costs one allocation; recycling an aliased one
    corrupts served data.
    """
    lo = buf.ctypes.data
    hi = lo + buf.nbytes
    try:
        shards = getattr(dev, "addressable_shards", None)
        if shards is None:
            return True
        for sh in shards:
            ptr = sh.data.unsafe_buffer_pointer()
            if lo <= ptr < hi:
                return True
        return False
    except (ShutdownRequested, KeyboardInterrupt):
        raise
    except Exception:
        # Unprovable (API missing on this client/version, deleted
        # buffer): err toward "aliases" — the cost is one dropped
        # recyclable buffer, never corruption.
        return True


def _is_ready(dev: Any) -> bool:
    is_ready = getattr(dev, "is_ready", None)
    if is_ready is None:
        return False  # unknown client: only a blocking sweep recycles
    return bool(is_ready())


def _block_ready(dev: Any) -> None:
    import jax

    jax.block_until_ready(dev)


class StagedTransfer:
    """Handle for one staged copy→transfer job.

    ``copy_done`` fires when the staging copy finished — the job no
    longer references the caller's source buffer (a ring-slot view), so
    the slot may be released early.  ``ready`` fires when the device
    value can be popped with :meth:`result`.

    ``salvage`` is the degradation-ladder handoff: when the transfer
    exhausted its bounded retries, the staged host buffer (whose copy
    DID land, and was CRC-verified when the caller asked) is retained
    here so the consumer can re-run the window down the sanctioned
    inline path — the failure costs latency, never data.
    """

    __slots__ = ("copy_done", "ready", "error", "salvage", "_value", "_job")

    def __init__(self) -> None:
        self.copy_done = threading.Event()
        self.ready = threading.Event()
        self.error: Optional[BaseException] = None
        self.salvage: Optional[np.ndarray] = None
        self._value: Any = None
        self._job: Any = None  # back-ref for work stealing

    def result(self, timeout_s: Optional[float] = None) -> Any:
        """The transferred device value; raises the job's error (e.g.
        :class:`ShutdownRequested` when the executor closed mid-queue)."""
        if not self.ready.wait(timeout_s):
            # StallTimeoutError (which is also a TimeoutError) so every
            # deadline failure on a framework path shares one hierarchy.
            raise StallTimeoutError(
                f"staged transfer not ready within {timeout_s}s"
            )
        if self.error is not None:
            raise self.error
        return self._value

    @property
    def worker_executed(self) -> bool:
        """Did the background worker (vs a stealing consumer) run this?"""
        return bool(self._job is not None and self._job.worker)


#: A transfer callable: staging buffer -> (consumer value, pollable
#: device array backing it).  The second element drives buffer recycling.
TransferFn = Callable[[np.ndarray], Tuple[Any, Any]]


class _Job:
    __slots__ = (
        "handle", "src", "transfer", "expected_crc", "claimed", "worker",
        "alias_src", "span_key",
    )

    def __init__(
        self,
        handle: StagedTransfer,
        src: np.ndarray,
        transfer: TransferFn,
        expected_crc: Optional[int] = None,
        alias_src: bool = False,
        span_key: Optional[Tuple[int, int]] = None,
    ):
        self.handle = handle
        self.src = src
        self.transfer = transfer
        #: Committed payload CRC (ddl_tpu.integrity): when set, the
        #: staging copy is re-verified against it before the source slot
        #: may be released — the second verification point of the
        #: end-to-end pipeline.
        self.expected_crc = expected_crc
        #: Zero-copy staging (shm-backed): the transfer sources ``src``
        #: — a live ring-slot view — directly, with no slot→staging
        #: memcpy.  ``copy_done`` then fires only once the device value
        #: no longer reads host memory (transfer completion), and the
        #: per-transfer alias check guards clients that would zero-copy
        #: the slot pages into the device array.
        self.alias_src = alias_src
        #: Window identity (producer_idx, seq) for lifecycle spans
        #: (ddl_tpu.obs): the copy/transfer phases run on whichever
        #: thread claims the job, so the key must travel WITH it.
        self.span_key = span_key
        self.claimed = False
        #: True when the background worker (not a stealing consumer)
        #: executed the job — the signal adaptive consumers use to judge
        #: whether offloading is actually buying overlap on this host.
        self.worker = False


class TransferExecutor:
    """Background worker + work-stealing for copy→transfer jobs.

    One worker thread drains a bounded deque from the NEWEST end; a
    consumer that needs a job's result NOW *steals* it from the oldest
    end — claims it and runs it on its own thread (:meth:`complete`).
    The ends are deliberately opposite: the consumer always wants the
    oldest job next, so a FIFO worker would race it for exactly that
    job and the consumer would pay worker-scheduling latency per pop
    (measured ~2 ms/批 on a saturated 2-core host).  With opposed ends
    each thread owns its own item: the consumer's path costs what the
    inline path costs, and the worker's lookahead work is pure overlap
    — staged degrades to inline-plus-one-claim-check when the host has
    no spare cycles, and genuinely overlaps when it does.

    The bounded deque backpressures :meth:`submit` instead of
    ballooning host memory when the producer side outruns the link.
    """

    def __init__(
        self,
        pool: StagingPool,
        metrics: Optional[Metrics] = None,
        max_queue: Optional[int] = None,
    ):
        self.pool = pool
        self.metrics = metrics or default_metrics()
        depth = (
            envspec.get("DDL_TPU_STAGING_QUEUE")
            if max_queue is None
            else max_queue
        )
        self._max_queue = max(1, depth)
        self._max_retries = envspec.get("DDL_TPU_STAGING_RETRIES")
        #: Set when a job exhausted its retry budget: the degradation
        #: ladder's "stop staging, go inline" latch, consulted by the
        #: lookahead consumers via ``StagedIngestEngine.faulted``.
        self.faulted = False
        #: Latched when a client PROVED it zero-copy-aliases host pages
        #: into device values (the per-transfer unsafe_buffer_pointer
        #: walk fired on an alias job): every later alias submission
        #: silently degrades to the copying pool — correctness first,
        #: the memcpy saving only where it is safe.
        self.alias_unsafe = False
        self._dq: Deque[_Job] = collections.deque()
        self._cv = named_condition("staging.executor.cv")
        self._closed = False
        self._thread: Optional[threading.Thread] = None
        #: The job the worker is currently executing (plain attribute:
        #: single writer, GIL-atomic reads) — flush_copies waits on it.
        self._active: Optional[_Job] = None
        #: Queue depth at which the worker starts taking jobs (from the
        #: newest end).  2 leaves the oldest job for the consumer's
        #: steal; tests set 1 to make the worker eager/deterministic.
        #: Clamped to max_queue: a threshold the queue can never reach
        #: (DDL_TPU_STAGING_QUEUE=1) would deadlock submit against a
        #: worker that never drains.
        self.worker_min_depth = min(2, self._max_queue)

    def set_max_queue(self, max_queue: int) -> None:
        """Retune the submission-queue bound live (ddl_tpu.tune).

        Re-clamps ``worker_min_depth`` (the deadlock guard above must
        track the new bound) and wakes every waiter: submitters blocked
        against the old, smaller bound re-check and proceed immediately
        when the queue grew.
        """
        with self._cv:
            self._max_queue = max(1, int(max_queue))
            self.worker_min_depth = min(
                self.worker_min_depth, self._max_queue
            )
            self._cv.notify_all()

    def submit(
        self,
        src: np.ndarray,
        transfer: TransferFn,
        expected_crc: Optional[int] = None,
        alias_src: bool = False,
        span_key: Optional[Tuple[int, int]] = None,
    ) -> StagedTransfer:
        """Enqueue one job: copy ``src`` into a pooled buffer, then run
        ``transfer`` on it.  ``src`` may be a live ring-slot view — the
        caller must keep the slot acquired until ``handle.copy_done``.
        ``expected_crc`` (the committed window CRC) re-verifies the copy
        before that release.  Blocks when the queue is full
        (backpressure).

        ``alias_src=True`` (shm-backed staging) skips the slot→staging
        memcpy entirely: the transfer sources ``src`` directly and
        ``copy_done`` fires at transfer COMPLETION — the caller holds
        the slot for the DMA instead of one memcpy, and pays zero host
        copies.  Ignored (degraded to the copying pool) once a client
        proved it aliases host pages (``alias_unsafe``)."""
        handle = StagedTransfer()
        job = _Job(
            handle, src, transfer, expected_crc,
            alias_src=alias_src and not self.alias_unsafe,
            span_key=span_key,
        )
        handle._job = job
        with self._cv:
            if self._closed:
                raise ShutdownRequested("transfer executor is closed")
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._run, name="ddl-staging", daemon=True
                )
                self._thread.start()
            while len(self._dq) >= self._max_queue and not self._closed:
                self._cv.wait(0.5)
            if self._closed:
                raise ShutdownRequested("transfer executor is closed")
            self._dq.append(job)
            depth = len(self._dq)
            if depth >= self.worker_min_depth:
                # Waking the worker below its take-threshold is a pure
                # context switch per submit.
                self._cv.notify_all()
        # Per-submit publish (one uncontended lock, ~µs): .max tracking
        # happens inside set_gauge, so the high-water survives a
        # mid-run Metrics.reset() — an executor-local peak would stop
        # re-publishing after the steady-state span reset wiped it.
        self.metrics.set_gauge("staging.queue_depth", float(depth))
        return handle

    def complete(
        self, handle: StagedTransfer, timeout_s: Optional[float] = None
    ) -> Any:
        """The handle's result, stealing its job if still unclaimed.

        The pop primitive for FIFO consumers: never blocks on worker
        scheduling latency — an unstarted job runs inline on the caller;
        a worker-claimed one is a genuine (short) wait, counted into
        ``ingest.stall`` (a stolen execution is work, not a stall).
        """
        job = handle._job
        if job is not None and self._claim(job):
            self._execute(job)
            # The stealing thread must also recycle: in the regime where
            # the consumer steals every job (no spare cores), the worker
            # never runs and a worker-only sweep would leak every buffer
            # into the inflight deque (all-miss pool, unbounded hosts).
            self.pool.sweep()
            return handle.result(timeout_s)
        with self.metrics.timed("ingest.stall"):
            return handle.result(timeout_s)

    def flush_copies(self, timeout_s: float = 30.0) -> None:
        """Force every submitted job's STAGING COPY to completion.

        The slot-safety barrier: a consumer about to release a ring slot
        that queued jobs may still view calls this first — unclaimed
        jobs are claimed and run inline (their copies land in pooled
        buffers before the producer can overwrite the slot), and a job
        the worker has in flight is waited on via its ``copy_done``
        edge.  Cheap when everything already completed (one empty-deque
        check).
        """
        while True:
            with self._cv:
                job = self._dq.popleft() if self._dq else None
            if job is None:
                break
            if self._claim(job):
                self._execute(job)
        active = self._active
        if active is not None and not active.handle.copy_done.wait(timeout_s):
            # A barrier that silently fails would let the caller release
            # a slot the worker is still reading — corruption, not delay.
            raise StallTimeoutError(
                f"staging copy still in flight after {timeout_s}s; "
                "cannot safely release the source slot"
            )

    def has_capacity(self) -> bool:
        """Would :meth:`submit` accept a job without blocking right now?

        A single GIL-atomic deque read — lookahead producers poll this
        so their non-blocking deepening rounds never park inside
        submit's backpressure wait.
        """
        return len(self._dq) < self._max_queue

    def _claim(self, job: _Job) -> bool:
        """Atomically take ownership of a queued job (and unqueue it)."""
        with self._cv:
            if job.claimed:
                return False
            job.claimed = True
            try:
                self._dq.remove(job)
                if len(self._dq) == self._max_queue - 1:
                    # Freed a FULL queue: a submit may be blocked on
                    # capacity.  Any other wake here is a pure context
                    # switch (the worker re-checks its threshold and
                    # sleeps again) — measured ~0.2 ms per steal.
                    self._cv.notify_all()
            except ValueError:
                pass  # already popped by the worker
            return True

    def close(self) -> None:
        """Stop the worker; pending jobs fail with ShutdownRequested.

        Safe to call twice and from any thread.  Buffers of completed
        transfers are swept back (blocking) so a closed executor leaks
        nothing.
        """
        with self._cv:
            if self._closed:
                return
            self._closed = True
            t = self._thread
            self._cv.notify_all()
        if t is not None:
            t.join(timeout=30.0)
        # Fail whatever nobody claimed (the worker is gone; a concurrent
        # complete() that won a claim still finishes its job normally).
        while True:
            with self._cv:
                job = self._dq.popleft() if self._dq else None
            if job is None:
                break
            if not self._claim(job):
                continue
            job.handle.error = ShutdownRequested(
                "transfer executor closed mid-queue"
            )
            job.handle.copy_done.set()
            job.handle.ready.set()
        self.pool.sweep(block=True)

    @property
    def closed(self) -> bool:
        return self._closed

    # -- execution ---------------------------------------------------------

    def _retrying(self, phase: str, fn):
        """Run one job phase with bounded exponential-backoff retries.

        The degradation ladder's first rung: transient failures (flaky
        link, injected chaos) are retried ``_max_retries`` times with
        doubling backoff; exhaustion marks the executor ``faulted``
        (later windows route inline) and re-raises for the caller's
        salvage path.  Shutdown signals are never retried.
        """
        delay = _RETRY_BACKOFF_BASE_S
        for attempt in range(self._max_retries + 1):
            try:
                return fn()
            except (ShutdownRequested, KeyboardInterrupt):
                raise
            except Exception as e:
                if attempt >= self._max_retries or self._closed:
                    self.faulted = True
                    raise
                self.metrics.incr("staging.retries")
                logger.warning(
                    "staged %s failed (%s: %s) — retry %d/%d after %.2fs",
                    phase, type(e).__name__, e, attempt + 1,
                    self._max_retries, delay,
                )
                time.sleep(delay)
                delay = min(delay * 2, _RETRY_BACKOFF_CAP_S)

    def _execute(self, job: _Job) -> None:
        """Run one claimed job to completion (worker or stealing thread)."""
        handle = job.handle
        key = job.span_key or (None, None)

        def copy_phase():
            t0 = time.perf_counter()
            fault_point("staging.copy", view=_flat_u8(job.src))
            np.copyto(buf, job.src, casting="no")
            if job.expected_crc is not None:
                # Second integrity verification point: the slot is still
                # held, so a torn/overwritten copy is caught BEFORE the
                # early release hands the slot back to the producer (a
                # retry re-copies from the still-valid slot).
                flat = _flat_u8(buf)
                got = integrity.window_crc(flat) if flat is not None else None
                if got is not None and got != job.expected_crc:
                    self.metrics.incr("integrity.staging_verify_failures")
                    raise IntegrityError(
                        f"staging copy crc32 0x{got:08x} != committed "
                        f"0x{job.expected_crc:08x} (torn slot read)"
                    )
            self.metrics.add_time(
                "ingest.stage_copy", time.perf_counter() - t0
            )
            obs_spans.record("staging.copy", key[0], key[1], t0)

        def transfer_phase():
            fault_point("staging.transfer")
            # Identity context + profiler lane for the nested transfer
            # (put_window / batch put / ICI fan-out) — this phase runs
            # on whichever thread claimed the job, so the jax.profiler
            # annotation here is what lines the staged H2D up with the
            # SpanLog's staging.transfer lane by name.
            from ddl_tpu.profiling import annotate

            obs_spans.set_window(*key)
            try:
                with annotate("ddl.staging_transfer"):
                    return job.transfer(buf)
            finally:
                obs_spans.clear_window()

        try:
            if job.alias_src:
                handle._value = self._execute_alias(job)
                return
            buf = self.pool.acquire(job.src.shape, job.src.dtype)
            self._retrying("copy", copy_phase)
            handle.copy_done.set()  # source released: slot may free
            try:
                _span_t0 = obs_spans.t0()
                value, base = self._retrying("transfer", transfer_phase)
                obs_spans.record("staging.transfer", key[0], key[1], _span_t0)
            except (ShutdownRequested, KeyboardInterrupt):
                raise
            except Exception:
                # The copy landed (and verified): retain it so the
                # consumer can redo this window on the inline path —
                # degradation, not data loss.  The buffer leaves the
                # pool's custody for good.
                handle.salvage = buf
                raise
            self.pool.recycle_when_ready(buf, base)
            handle._value = value
        except (ShutdownRequested, KeyboardInterrupt) as e:
            # Clean teardown racing the queue: deliver to the consumer
            # (result() re-raises).  Swallowing here would hang result()
            # forever.
            handle.error = e
        except Exception as e:
            handle.error = e
        finally:
            handle.copy_done.set()
            handle.ready.set()

    def _execute_alias(self, job: _Job) -> Any:
        """Run one zero-copy (shm-backed) job: transfer straight from the
        ring-slot view, no staging memcpy.

        The slot stays the transfer's live source, so ``copy_done`` (the
        caller's release edge, set by ``_execute``'s ``finally``) may
        only fire once the device value stopped reading host memory:
        after a completion wait on a genuinely-copying client, or after
        the copying-pool fallback on one that aliased the slot pages
        into the device array (checked per transfer with the same
        ``unsafe_buffer_pointer`` walk the pool uses — the check firing
        latches ``alias_unsafe`` so later jobs skip straight to the
        pool).  The wait runs on the background worker (or a stealing
        consumer that needed the value NOW anyway), never adds a host
        memcpy, and its span lands in ``ingest.transfer``.
        """
        key = job.span_key or (None, None)

        def transfer_phase():
            fault_point("staging.transfer")
            from ddl_tpu.profiling import annotate

            obs_spans.set_window(*key)
            try:
                with annotate("ddl.staging_transfer"):
                    return job.transfer(job.src)
            finally:
                obs_spans.clear_window()

        def salvage_slot(buf: Optional[np.ndarray] = None) -> None:
            """Terminal transfer failure with the slot STILL HELD (this
            runs before ``_execute``'s ``finally`` fires ``copy_done``
            and lets the consumer release it): retain a host copy of the
            window so ``complete_or_salvage`` can redo it down the
            sanctioned inline path — the alias path must keep the
            copying path's degradation-ladder guarantee that a link
            failure costs latency, never data."""
            if buf is None:
                buf = self.pool.acquire(job.src.shape, job.src.dtype)
                np.copyto(buf, job.src, casting="no")
            job.handle.salvage = buf

        t0 = time.perf_counter()
        try:
            value, base = self._retrying("transfer", transfer_phase)
        except (ShutdownRequested, KeyboardInterrupt):
            raise
        except Exception:
            salvage_slot()
            raise
        if _may_alias(base, job.src):
            # The client zero-copied the slot pages into the device
            # value: releasing the slot would let the producer overwrite
            # data the device array still reads.  Redo through the
            # copying pool (the discarded first value holds no readers)
            # and stop submitting alias jobs on this client.
            self.alias_unsafe = True
            self.metrics.incr("staging.alias_fallbacks")
            logger.warning(
                "shm-backed staging: device client aliases host pages; "
                "falling back to the copying staging pool"
            )
            buf = self.pool.acquire(job.src.shape, job.src.dtype)
            np.copyto(buf, job.src, casting="no")
            try:
                value, base = self._retrying(
                    "transfer", lambda: job.transfer(buf)
                )
            except (ShutdownRequested, KeyboardInterrupt):
                raise
            except Exception:
                salvage_slot(buf)  # the copy already landed: keep it
                raise
            self.pool.recycle_when_ready(buf, base)
            return value
        _block_ready(base)
        self.metrics.add_time("ingest.transfer", time.perf_counter() - t0)
        obs_spans.record("staging.transfer", key[0], key[1], t0)
        self.metrics.incr("staging.alias_windows")
        return value

    def _run(self) -> None:
        while True:
            with self._cv:
                # Take work only at worker_min_depth (default 2: the
                # oldest job is ALWAYS left for the consumer to steal),
                # and from the NEWEST end.  A worker that raced the
                # consumer for the job it needs next would add
                # worker-scheduling latency to every pop on a saturated
                # host — this way the consumer's path costs what inline
                # costs, and whatever the worker finishes is pure
                # overlap on top.
                while (
                    len(self._dq) < self.worker_min_depth
                    and not self._closed
                ):
                    self._cv.wait(0.5)
                if self._closed:
                    break
                job = self._dq.pop()
                # Published under the SAME lock as the pop: at every
                # instant a live job is visible in the deque OR in
                # _active, so flush_copies cannot slip between the two
                # and miss a job about to read a releasing slot.
                self._active = job
                if len(self._dq) == self._max_queue - 1:
                    self._cv.notify_all()  # freed a full queue
            if not self._claim_popped(job):
                self._active = None
                continue
            job.worker = True
            self._execute(job)
            self._active = None
            # Opportunistic recycle of completed transfers — off the
            # consumer's critical path by construction (we ARE the
            # background thread).
            self.pool.sweep()

    def _claim_popped(self, job: _Job) -> bool:
        """Claim a job the worker already removed from the deque."""
        with self._cv:
            if job.claimed:
                return False
            job.claimed = True
            return True


class StagedIngestEngine:
    """Pool + executor pair owned by one :class:`DeviceIngestor`."""

    def __init__(self, metrics: Optional[Metrics] = None):
        self.metrics = metrics or default_metrics()
        self.pool = StagingPool(metrics=self.metrics)
        self.executor = TransferExecutor(self.pool, metrics=self.metrics)
        # Adaptive-offload state (see PrefetchIterator): lives HERE, not
        # on the iterator, because consumers build a fresh iterator per
        # epoch — per-iterator state would forget a starved worker every
        # few batches and re-pay the probe cost each epoch.
        self.stolen_streak = 0
        self.direct_left = 0

    @property
    def faulted(self) -> bool:
        """True once a staged job exhausted its retry budget: the
        degradation ladder routes every later window down the sanctioned
        inline path (windows()/PrefetchIterator consult this)."""
        return self.executor.faulted

    def complete_or_salvage(
        self,
        handle: StagedTransfer,
        inline_put: Callable[[np.ndarray], Any],
        timeout_s: Optional[float] = None,
    ) -> Any:
        """:meth:`TransferExecutor.complete` with the degradation-ladder
        fallback: a handle whose transfer exhausted its retries (but
        whose verified staging copy survives on ``handle.salvage``) is
        redone through ``inline_put`` — the failure costs latency, never
        data.  Shutdown signals and deadline expiries propagate; errors
        with nothing to salvage re-raise.  The one implementation for
        both lookahead consumers (``windows()`` and
        :class:`~ddl_tpu.ingest.PrefetchIterator`)."""
        try:
            return self.executor.complete(handle, timeout_s)
        except (ShutdownRequested, KeyboardInterrupt, StallTimeoutError):
            raise
        except Exception as e:
            if handle.salvage is None:
                raise
            logger.error(
                "staged transfer failed after retries (%s: %s) — "
                "falling back to the inline path", type(e).__name__, e,
            )
            self.metrics.incr("staging.inline_fallbacks")
            return inline_put(handle.salvage)

    def submit(
        self,
        src: np.ndarray,
        transfer: TransferFn,
        expected_crc: Optional[int] = None,
        alias_src: bool = False,
        span_key: Optional[Tuple[int, int]] = None,
    ) -> StagedTransfer:
        return self.executor.submit(
            src, transfer, expected_crc, alias_src=alias_src,
            span_key=span_key,
        )

    def close(self) -> None:
        self.executor.close()
