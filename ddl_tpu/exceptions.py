"""Exceptions for ddl_tpu.

Parity: the reference exposes a single ``DoesNotMatchError``
(reference ``ddl/exceptions.py:1``) whose constructor is broken (``__init``
typo, SURVEY Q3).  Here the hierarchy is real and the constructors work.
"""

from __future__ import annotations


class DDLError(Exception):
    """Base class for all ddl_tpu errors."""


class DoesNotMatchError(DDLError):
    """Topology or shape mismatch (reference ``ddl/exceptions.py:1``).

    Raised when the requested loader/trainer topology cannot be realised,
    e.g. a producer block that would span shared-memory domains
    (reference ``ddl/ddl_env.py:72-73``).
    """

    def __init__(self, value: object = None, message: str = ""):
        self.value = value
        self.message = message
        super().__init__(value, message)

    def __str__(self) -> str:  # pragma: no cover - cosmetic
        if self.message:
            return f"{self.value!r}: {self.message}"
        return repr(self.value)


class TransportError(DDLError):
    """A transport-level failure (ring corrupt, peer vanished, bad slot)."""


class ShutdownRequested(DDLError):
    """Internal control-flow signal: the pipeline is shutting down.

    The TPU-native replacement for the reference's ``WorkerInfo.STOP``
    sentinel (reference ``ddl/connection.py:12-16``): waits that observe a
    shutdown flag raise this instead of returning a status enum.
    """


class StallTimeoutError(TransportError, TimeoutError):
    """A blocking wait on the ring exceeded its deadline.

    The reference had no deadline at all — a lost peer deadlocked the job
    until the pytest 100 s timeout killed it (reference
    ``tests/test_ddl.py:8``).  Here every wait carries a configurable
    timeout so failure detection is built in.

    Subclasses ``TimeoutError`` too so that EVERY deadline failure on a
    framework path — ring waits, control recvs, staged-transfer pops —
    is catchable through one hierarchy (``except StallTimeoutError`` /
    ``except DDLError``) without breaking callers that guard with the
    builtin.
    """


class IntegrityError(DDLError):
    """Data failed an end-to-end integrity check.

    Raised when bytes provably changed between producer and consumer:
    a ring-slot window whose committed checksum no longer matches at
    drain (and replay could not heal it), a staged copy that diverged
    from its verified source, or a TFRecord whose framing CRCs fail
    (``ddl_tpu.readers.iter_tfrecords``).  Always carries enough context
    (file/offset or ring/window) to locate the corruption.
    """


class BackendFetchError(TransportError):
    """A storage-backend shard fetch failed (transient until proven not).

    Raised by :class:`ddl_tpu.cache.StorageBackend` implementations (and
    the ``backend.fetch`` fault-injection point) when a shard read fails
    in a way a retry might heal — the remote-store analog of a dropped
    connection.  The one retry-policy site,
    :func:`ddl_tpu.cache.open_with_retry`, catches it with bounded
    exponential backoff; exhaustion escalates to :class:`IntegrityError`
    (a *persistent* backend failure is a data-availability fault, not a
    transport hiccup).
    """


class DecodeError(DDLError):
    """A wire payload failed to decode (``ddl_tpu.wire``): a codec
    raised, an envelope field was malformed, or the declared output
    bound was exceeded.

    Also the real type the ``DECODE_FAIL`` fault kind raises at the
    ``wire.decode`` site, so chaos exercises the production ladder:
    bounded retry, then the raw fallback for that wire path
    (``wire.fallbacks``) — a shuffle round degrades to raw encoding, a
    compressed shard read escalates to :class:`BackendFetchError` and
    rides ``open_with_retry``'s existing retry/backoff discipline.
    """


class HostLostError(DDLError):
    """A whole host left the cluster view (lease expiry, declared loss,
    or the ``HOST_LOSS`` fault kind at ``cluster.heartbeat``).

    Carries the host id in ``args`` where the raiser knows it.  The
    membership control plane (:mod:`ddl_tpu.cluster`) catches it during
    a sweep and runs the epoch-fenced view change; it never escapes a
    healthy supervisor loop.
    """


class HeartbeatDropped(DDLError):
    """One heartbeat was lost in flight (the ``HEARTBEAT_DROP`` fault
    kind at ``cluster.heartbeat``, or a real transport hiccup an adapter
    chooses to report this way).

    The lease table treats a dropped beat as silence: the lease keeps
    aging and only EXPIRY — never a single drop — triggers a view
    change, so transient heartbeat loss under the lease budget is
    absorbed without membership churn.
    """


class TenantBurst(DDLError):
    """A tenant's demand spiked (the ``TENANT_BURST`` fault kind at
    ``serve.admit``, or a real admission adapter reporting a thundering
    herd this way).

    Carries ``burst_bytes`` — the phantom demand to charge.  The
    fair-share scheduler (:mod:`ddl_tpu.serve.tenancy`) absorbs it by
    charging the BURSTING tenant's own deficit and byte bucket: the
    spike is paid for out of the burster's share, so its neighbours'
    service rates are untouched (the isolation property the tenancy
    chaos leg asserts).
    """

    def __init__(self, message: str = "", burst_bytes: float = 0.0):
        self.burst_bytes = float(burst_bytes)
        super().__init__(message)


class PreemptionNotice(DDLError):
    """The platform announced this host's imminent preemption (the
    ``PREEMPT_NOTICE`` fault kind at ``resilience.notice``, a SIGTERM
    delivered to the trainer, or the ``DDL_TPU_PREEMPT_NOTICE`` env
    knob an operator/agent sets).

    Carries ``deadline_s`` — the grace budget the notice grants — when
    the raiser knows it.  The :class:`~ddl_tpu.resilience.
    PreemptionGuard` absorbs it at window boundaries and turns it into
    a deadline-bounded graceful drain (forced final checkpoint,
    in-flight tenant-window revocation, graceful host drain); it never
    escapes a guarded ``Trainer.fit``.
    """

    def __init__(self, message: str = "", deadline_s: float = 0.0):
        self.deadline_s = float(deadline_s)
        super().__init__(message)


class WindowsRevoked(StallTimeoutError):
    """A tenant's in-flight window grants were revoked under a drain
    SLO (``FairShareScheduler.revoke_inflight`` — the scale-down /
    preemption rung that stops waiting for tenant idleness).

    Subclasses :class:`StallTimeoutError` deliberately: a revoked
    admission wait surfaces through the loader's one acquire choke
    point exactly like a stall deadline (non-blocking deepening probes
    already treat it as not-committed-yet), while staying catchable as
    its own type so a tenant runtime can distinguish "you were
    preempted" from "the ring wedged".
    """


class SupervisorCrashed(DDLError):
    """The control-plane supervisor process died mid-lease (the
    ``SUPERVISOR_CRASH`` fault kind at ``cluster.supervise``, or a real
    leader loop tearing down).

    The HA tier (:mod:`ddl_tpu.cluster.supervision`) absorbs it: the
    leader's lease stops renewing, a standby observes expiry, replays
    the journal, and promotes itself under the next fencing term.  It
    never escapes a :class:`~ddl_tpu.cluster.supervision.SupervisorHA`
    step — an unsupervised (HA-less) deployment sees it as fatal, which
    is exactly the gap the HA tier exists to close.
    """


class ControlSendDropped(TransportError):
    """One control-channel send was lost on the wire (the
    ``CONTROL_MSG_DROP`` fault kind at ``transport.control_send``, or a
    real pipe hiccup an adapter reports this way).

    The acked envelope seam (:mod:`ddl_tpu.transport.envelope`) absorbs
    it: the send stays pending and is retried with exponential backoff
    until acked or the retry cap trips.  Raw fire-and-forget
    ``send_control`` callers see it as the message silently vanishing —
    which is why ddl-lint DDL025 pushes control sends through the seam.
    """


class NetworkPartitioned(TransportError):
    """The control network partitioned: this side can neither deliver
    nor receive control traffic for the duration (the
    ``NETWORK_PARTITION`` fault kind at ``transport.control_send`` /
    ``cluster.supervise``, or a real fabric event).

    During a partition the envelope seam keeps retrying under its cap;
    the supervisor lease on the far side keeps aging.  A heal after
    lease expiry produces the split-brain scenario the fencing term
    exists for: the old leader's post-heal commands carry a stale fence
    and are dropped at every applier (docs/ROBUSTNESS.md walkthrough).
    """


class AdmissionDropped(TransportError):
    """One fabric admission command was lost on the wire (the
    ``JOB_ADMISSION_DROP`` fault kind at ``serve.fabric.admit``, or a
    real transport hiccup on the admission control channel).

    The fabric client's acked envelope seam
    (:mod:`ddl_tpu.serve.fabric` over
    :mod:`ddl_tpu.transport.envelope`) absorbs it: the command stays
    pending, backoff retry re-wires it, and the fabric's journal-seeded
    dedup guarantees the scheduler ledger is mutated exactly once no
    matter how many deliveries the retries produce.
    """


class JobCrashed(DDLError):
    """A training job died mid-grant: ``admit`` returned, the window is
    in flight, and ``note_served`` will never arrive (the ``JOB_CRASH``
    fault kind at ``serve.fabric.grant``, or a real trainer crash an
    operator reports).

    The fabric absorbs it via :meth:`~ddl_tpu.serve.fabric.IngestFabric.
    job_crashed`: the crashed job's in-flight grants are released, its
    registration (and byte budget) removed, and its neighbours' shares
    untouched — the chaos matrix pins byte-correctness of the
    survivors.
    """


class CheckpointError(DDLError):
    """A checkpoint could not be durably written or flushed
    (``ddl_tpu.resilience``): the async writer's final forced flush
    failed, or a generation write raised past its retry.  Restore-side
    corruption is NOT this error — unverifiable generations are
    quarantined and skipped (cold start at exhaustion, with a loud
    counter), never raised to the trainer.
    """


class InjectedFault(DDLError):
    """A deliberate failure raised by the fault-injection engine.

    Only ever raised while a :class:`ddl_tpu.faults.FaultPlan` is armed —
    production paths can neither construct nor observe it.  Distinct
    from real error types so the chaos suite can tell an injected crash
    from a genuine one leaking out of the machinery under test.
    """


class LoaderStateError(DDLError, RuntimeError):
    """The loader was driven from an invalid state (finalized loader,
    superseded ``windows()`` stream, batch iteration over abandoned
    staged windows).  Subclasses ``RuntimeError`` for backwards
    compatibility with callers that guarded on the builtin."""
