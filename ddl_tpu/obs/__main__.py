"""``python -m ddl_tpu.obs`` — post-mortem tooling for obs artifacts.

Subcommands:

``dump <flight-record.json> [--metrics N] [--windows N]``
    Pretty-print a flight-recorder artifact: header (reason, faulted
    window, time, pid), a per-window stage waterfall reconstructed
    from the recorded span events, and the last-N metric deltas — so
    reading a post-mortem never requires hand-writing JSON spelunking.

``trace <flight-record.json> -o out.json``
    Re-export the span events inside a flight record as a
    Chrome/Perfetto trace (load in https://ui.perfetto.dev).
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Any, Dict, List


def _load(path: str) -> Dict[str, Any]:
    with open(path, "rb") as f:
        record = json.load(f)
    version = int(record.get("version", -1))
    from ddl_tpu.obs.recorder import DUMP_VERSION

    if version > DUMP_VERSION:
        raise SystemExit(
            f"{path}: flight-record version {version} is newer than "
            f"this tool understands ({DUMP_VERSION})"
        )
    return record


def _bar(frac: float, width: int = 28) -> str:
    n = max(0, min(width, round(frac * width)))
    return "#" * n + "." * (width - n)


def _span_events(record: Dict[str, Any]) -> List[tuple]:
    """Recorder entries -> (t, stage, dur, producer_idx, seq)."""
    out = []
    for ev in record.get("events", []):
        t, kind, name, value, pidx, seq = ev
        if kind == "span":
            out.append((t, name, float(value), pidx, seq))
    return out


def cmd_dump(args: argparse.Namespace) -> int:
    record = _load(args.path)
    win = record.get("window", {})
    print(f"flight record  {args.path}")
    print(f"  reason       {record.get('reason')}")
    print(f"  time         {record.get('time')}   pid {record.get('pid')}")
    print(
        "  window       producer_idx="
        f"{win.get('producer_idx')} seq={win.get('seq')}"
    )
    dropped = record.get("events_dropped", 0)
    print(
        f"  ring         {len(record.get('events', []))} events"
        + (f" ({dropped} older dropped)" if dropped else "")
    )

    spans = _span_events(record)
    if spans:
        print("\nper-window stage waterfall (most recent "
              f"{args.windows} windows):")
        by_window: Dict[tuple, List[tuple]] = {}
        order: List[tuple] = []
        for t, stage, dur, pidx, seq in spans:
            key = (pidx, seq)
            if key not in by_window:
                by_window[key] = []
                order.append(key)
            by_window[key].append((t, stage, dur))
        for key in order[-args.windows:]:
            pidx, seq = key
            evs = sorted(by_window[key])
            t_base = evs[0][0]
            total = max(
                (t - t_base) + d for t, _s, d in evs
            ) or 1e-9
            print(f"  window p{pidx}/s{seq}  "
                  f"({total * 1e3:.2f} ms first-event -> last-end)")
            for t, stage, dur in evs:
                off = t - t_base
                print(
                    f"    {stage:<22} +{off * 1e3:8.2f} ms  "
                    f"{dur * 1e3:8.2f} ms  |{_bar(dur / total)}|"
                )
    else:
        print("\n(no span events in the ring — spans were not armed)")

    deltas = [
        ev for ev in record.get("events", []) if ev[1] != "span"
    ][-args.metrics:]
    if deltas:
        print(f"\nlast {len(deltas)} metric deltas:")
        t_end = record["events"][-1][0]
        for t, kind, name, value, _p, _s in deltas:
            print(
                f"  {t - t_end:9.3f}s  {kind:<8} {name:<40} {value:g}"
            )

    snap = record.get("metrics", {})
    if snap:
        interesting = sorted(
            k for k in snap
            if any(
                k.startswith(p)
                for p in (
                    "integrity.", "watchdog.", "wire.", "shuffle.",
                    "obs.", "resilience.",
                )
            )
            and snap[k]
        )
        if interesting:
            print("\nnonzero robustness counters at dump time:")
            for k in interesting:
                print(f"  {k:<44} {snap[k]:g}")
    return 0


def cmd_trace(args: argparse.Namespace) -> int:
    record = _load(args.path)
    events = [
        (t, t + dur, stage, pidx, seq, record.get("pid", 0))
        for t, stage, dur, pidx, seq in _span_events(record)
    ]
    if not events:
        print("no span events in the record", file=sys.stderr)
        return 1
    from ddl_tpu.obs.spans import write_chrome_trace

    write_chrome_trace(events, args.out)
    print(f"wrote {args.out} ({len(events)} events) — load in Perfetto")
    return 0


def main(argv: Any = None) -> int:
    parser = argparse.ArgumentParser(prog="python -m ddl_tpu.obs")
    sub = parser.add_subparsers(dest="cmd", required=True)
    p_dump = sub.add_parser("dump", help="pretty-print a flight record")
    p_dump.add_argument("path")
    p_dump.add_argument("--metrics", type=int, default=20,
                        help="metric deltas to show (default 20)")
    p_dump.add_argument("--windows", type=int, default=8,
                        help="recent windows to waterfall (default 8)")
    p_dump.set_defaults(fn=cmd_dump)
    p_trace = sub.add_parser(
        "trace", help="re-export a record's spans as a Chrome trace"
    )
    p_trace.add_argument("path")
    p_trace.add_argument("-o", "--out", default="flight-trace.json")
    p_trace.set_defaults(fn=cmd_trace)
    args = parser.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    raise SystemExit(main())
