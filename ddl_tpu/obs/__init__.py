"""ddl_tpu.obs — end-to-end data-plane tracing over the Metrics seam.

Four pieces (ISSUE 15; the reference had no metrics at all, SURVEY
§5.5 — the rebuild's counter/gauge registry left exactly the blind
spots this layer closes):

- **Window lifecycle spans** (:mod:`~ddl_tpu.obs.spans`): a bounded,
  lock-cheap, zero-cost-when-disarmed :class:`SpanLog` records
  timestamped stage events keyed on each window's integrity-trailer
  identity ``(producer_idx, seq)`` at the pipeline's choke points,
  exportable as Chrome/Perfetto ``trace_event`` JSON with
  thread-per-stage lanes and cross-process flow stitching.
- **Histograms** (:meth:`Metrics.observe` /
  :meth:`Metrics.quantile`, ``ddl_tpu.observability``): fixed
  log-spaced bounded buckets — first-class p50/p99s for window
  latency and admission waits.
- **Cross-process aggregation** (:mod:`~ddl_tpu.obs.aggregate`):
  PROCESS workers ship periodic snapshot + span-delta ObsReports over
  the existing control channel, merged into the consumer registry
  under ``producer.<idx>.*``.
- **Flight recorder** (:mod:`~ddl_tpu.obs.recorder`): a fixed-size
  ring of recent span/metric events, dumped atomically at failure
  sites (integrity corruption, fault trips, preemption notices,
  watchdog failures) — ``python -m ddl_tpu.obs dump <artifact>``
  pretty-prints the post-mortem.

Reference: docs/OBSERVABILITY.md (name families, span model, bucket
layout, aggregation topology, flight-record format, a Perfetto
walkthrough).  Overhead is priced by ``DDL_BENCH_MODE=obs`` (armed vs
disarmed, <= 2%, byte-identical — tools/bench_smoke.py enforces).
"""

from __future__ import annotations

from ddl_tpu.obs.aggregate import ReportMerger, build_report, ship_every
from ddl_tpu.obs.recorder import (
    FlightRecorder,
    armed_recorder,
    flight_dump,
)
from ddl_tpu.obs.recorder import armed as flight_armed
from ddl_tpu.obs.spans import (
    STAGES,
    SpanLog,
    chrome_trace,
    tracing,
    write_chrome_trace,
)

__all__ = [
    "FlightRecorder",
    "ReportMerger",
    "STAGES",
    "SpanLog",
    "armed_recorder",
    "build_report",
    "chrome_trace",
    "flight_armed",
    "flight_dump",
    "ship_every",
    "tracing",
    "write_chrome_trace",
]
