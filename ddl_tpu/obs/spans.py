"""Window-lifecycle span tracing: per-window stage events, exportable
as a Chrome/Perfetto ``trace_event`` timeline.

Every window already carries a globally unique identity in its
integrity trailer — ``(producer_idx, seq)`` (``ddl_tpu.integrity``;
``producer_idx`` is the 1-based trailer index, ``seq`` the logical
window number).  A :class:`SpanLog` records timestamped stage events
keyed on that identity at the pipeline's existing choke points
(producer fill / stamp-commit, consumer admission / acquire, wire
decode, staging copy / transfer, ICI fan-out, trainer consume, slot
release), so a surprising bench number or chaos row decomposes into a
per-window timeline instead of one opaque wall-clock delta.

Design constraints (the ``faults.armed()`` pattern, deliberately):

- **Zero cost disarmed.**  Every emission site reads ONE module
  attribute and returns.  :func:`t0` returns 0.0 without touching the
  clock when no log is armed; :func:`record` is a no-op.  The
  ``DDL_BENCH_MODE=obs`` armed-vs-disarmed A/B prices the armed side
  (<= 2% — tools/bench_smoke.py) and byte identity is asserted.
- **Bounded.**  The event buffer is a ``deque(maxlen=...)`` — a
  forgotten armed log on a week-long run drops oldest events instead
  of eating the host (ddl-lint DDL023 flags unbounded obs buffers).
- **Lock-cheap.**  One event is ONE ``deque.append`` of a tuple
  (GIL-atomic); no lock on the hot path.  Draining snapshots under a
  small lock.
- **Cross-process.**  ``DDL_TPU_TRACE`` carries arming across the
  spawn boundary (PROCESS producers arm on import, exactly like
  ``DDL_TPU_FAULT_PLAN``); their span batches ride the ObsReport
  control-channel shipping (``ddl_tpu.obs`` aggregation) back into the
  consumer's log, where :func:`chrome_trace` stitches the two
  processes' lanes by window id with flow arrows.

Per-window emission is sanctioned; per-sample emission is not
(ddl-lint DDL023) — a span per sample at 200k samples/s is the
observer destroying the experiment.
"""

from __future__ import annotations

import collections
import json
import os
import threading

from ddl_tpu import envspec
from ddl_tpu.concurrency import named_lock
import time
from typing import Any, Dict, Iterable, List, Optional, Tuple

#: One recorded event: (t0, t1, stage, producer_idx, seq, pid).
#: ``t1 is None`` marks an instant event (a point, not a span).
#: Timestamps are ``time.perf_counter()`` — CLOCK_MONOTONIC on Linux,
#: whose epoch is machine-wide, so producer-process and consumer
#: events land on one comparable axis without a handshake.
SpanEvent = Tuple[float, Optional[float], str, Optional[int], Optional[int], int]

#: Env var arming a default SpanLog in freshly spawned processes
#: (value: "1"/capacity).  The faults.PLAN_ENV pattern.
TRACE_ENV = "DDL_TPU_TRACE"

#: Default event capacity (tuples of 6 slots — ~100 B/event, so the
#: default ring tops out around 13 MB).
DEFAULT_CAPACITY = 1 << 17

#: Stage lanes, in waterfall order — the exporter assigns Perfetto
#: ``tid``s in this order so every trace reads top-to-bottom as the
#: window's life: fill -> commit -> admission -> acquire -> decode ->
#: staging -> transfer/fan-out -> consume -> release.  Stages also
#: name the jax.profiler ``profiling.annotate`` lanes where both
#: exist, so the two timelines line up by name.
STAGES = (
    "producer.fill",
    "producer.commit",
    "consumer.admission",
    "consumer.acquire",
    "wire.decode",
    "staging.copy",
    "staging.transfer",
    "ingest.transfer",
    "ici.fanout",
    "trainer.consume",
    "consumer.yield",
    "consumer.release",
)


class SpanLog:
    """Bounded, lock-cheap event log (see module docstring)."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        self.capacity = int(capacity)
        self._events: collections.deque = collections.deque(
            maxlen=self.capacity
        )
        self._lock = named_lock("obs.spans")
        #: Total appends ever (monotone) — ``appended - len(events)``
        #: is the dropped-oldest count; exports surface it so a
        #: truncated trace is never mistaken for a complete one.
        self.appended = 0
        # Shipping cursor state (cross-process aggregation): events
        # drained so far, so each ObsReport carries only the delta.
        self._shipped = 0

    def record(
        self,
        stage: str,
        producer_idx: Optional[int],
        seq: Optional[int],
        t0: float,
        t1: Optional[float] = None,
    ) -> None:
        self._events.append(
            (t0, t1, stage, producer_idx, seq, os.getpid())
        )
        self.appended += 1
        rec = _recorder()
        if rec is not None:
            rec.note("span", stage, t1 - t0 if t1 is not None else 0.0,
                     producer_idx=producer_idx, seq=seq)

    def record_many(self, events: Iterable[SpanEvent]) -> None:
        """Adopt a batch of already-formed events (cross-process
        aggregation: producer span deltas land here with their own
        pids intact)."""
        with self._lock:
            for ev in events:
                self._events.append(tuple(ev))
                self.appended += 1

    def events(self) -> List[SpanEvent]:
        with self._lock:
            return list(self._events)

    def drain_new(self) -> List[SpanEvent]:
        """Events appended since the last drain (the ObsReport shipping
        cursor).  Overflow-aware: when the ring dropped oldest events
        past the cursor, the drain returns what survives."""
        with self._lock:
            have = list(self._events)
            new_count = self.appended - self._shipped
            self._shipped = self.appended
            if new_count <= 0:
                return []
            return have[-min(new_count, len(have)):]

    def clear(self) -> None:
        with self._lock:
            self._events.clear()
            self.appended = 0
            self._shipped = 0

    def stage_totals(self) -> Dict[str, float]:
        """Total span seconds per stage (instants count 0) — the armed
        half of north_star_report's ``stage_breakdown``."""
        out: Dict[str, float] = {}
        for t0, t1, stage, _p, _s, _pid in self.events():
            if t1 is not None:
                out[stage] = out.get(stage, 0.0) + (t1 - t0)
        return out


#: The armed log, or None.  Read unlocked on every emission site — a
#: single module-attribute load is the entire disarmed cost.
_ARMED: Optional[SpanLog] = None

#: Thread-local current-window context: set by the window stream around
#: nested transfer/fan-out calls that have no identity of their own
#: (DeviceIngestor.put_window, IciDistributor.put), cleared after.
_CTX = threading.local()


def armed() -> bool:
    return _ARMED is not None


def log() -> Optional[SpanLog]:
    return _ARMED


def arm(span_log: Optional[SpanLog], export: bool = False) -> Optional[SpanLog]:
    """Arm ``span_log`` process-wide (``None`` disarms).  ``export=True``
    publishes :data:`TRACE_ENV` so PROCESS producers spawned afterwards
    arm their own log on import.  Returns the previously armed log."""
    global _ARMED
    prev = _ARMED
    _ARMED = span_log
    if export:
        if span_log is None:
            os.environ.pop(TRACE_ENV, None)
        else:
            os.environ[TRACE_ENV] = str(span_log.capacity)
    return prev


class tracing:
    """Context manager: arm a SpanLog for a scoped traced run.

    ::

        with obs.tracing(export=True) as span_log:
            run_pipeline()
        obs.write_chrome_trace(span_log.events(), "trace.json")

    Restores the previous log (and the env var) on exit, even when the
    pipeline under test raises — the ``faults.armed`` shape.
    """

    def __init__(
        self,
        span_log: Optional[SpanLog] = None,
        export: bool = False,
        capacity: int = DEFAULT_CAPACITY,
    ):
        self.span_log = span_log or SpanLog(capacity=capacity)
        self.export = export
        self._prev: Optional[SpanLog] = None
        self._prev_env: Optional[str] = None

    def __enter__(self) -> SpanLog:
        self._prev_env = envspec.raw(TRACE_ENV)
        self._prev = arm(self.span_log, export=self.export)
        return self.span_log

    def __exit__(self, *exc: Any) -> None:
        arm(self._prev)
        if self.export:
            if self._prev_env is None:
                os.environ.pop(TRACE_ENV, None)
            else:
                os.environ[TRACE_ENV] = self._prev_env


# -- emission primitives (the per-site API) --------------------------------


def t0() -> float:
    """Span start: the clock when armed, 0.0 (no clock read) disarmed."""
    return time.perf_counter() if _ARMED is not None else 0.0


def record(
    stage: str,
    producer_idx: Optional[int],
    seq: Optional[int],
    t_start: float,
    t_end: Optional[float] = None,
) -> None:
    """Record a completed span (``t_end`` defaults to now).  No-op (one
    attribute read) disarmed."""
    span_log = _ARMED
    if span_log is None:
        return
    span_log.record(
        stage, producer_idx, seq, t_start,
        time.perf_counter() if t_end is None else t_end,
    )


def mark(stage: str, producer_idx: Optional[int], seq: Optional[int]) -> None:
    """Record an instant event.  No-op disarmed."""
    span_log = _ARMED
    if span_log is None:
        return
    span_log.record(stage, producer_idx, seq, time.perf_counter(), None)


def set_window(producer_idx: Optional[int], seq: Optional[int]) -> None:
    """Publish the current thread's window identity for nested emission
    sites that cannot see it (put_window, the ICI distributor).  No-op
    disarmed."""
    if _ARMED is None:
        return
    _CTX.window = (producer_idx, seq)


def clear_window() -> None:
    if _ARMED is None:
        return
    _CTX.window = None


def current_window() -> Tuple[Optional[int], Optional[int]]:
    return getattr(_CTX, "window", None) or (None, None)


def _recorder():
    """The armed flight recorder, lazily resolved (import-cycle-free:
    recorder.py never imports spans)."""
    from ddl_tpu.obs import recorder

    return recorder.armed_recorder()


# -- Chrome/Perfetto export ------------------------------------------------

#: Stages emitted by producer-side code: flow arrows start at the LAST
#: producer-side event of a window and finish at the first
#: consumer-side one, stitching the two process lanes by window id.
_PRODUCER_STAGES = ("producer.fill", "producer.commit", "pusher.")


def _is_producer_stage(stage: str) -> bool:
    return any(stage.startswith(p) for p in _PRODUCER_STAGES)


def chrome_trace(events: Iterable[SpanEvent]) -> Dict[str, Any]:
    """Build a Chrome ``trace_event`` object (Perfetto-loadable).

    - One Perfetto *process* per OS pid seen in the events; one
      *thread lane* per stage, ordered by :data:`STAGES` so every
      window reads as a top-to-bottom waterfall.
    - Spans are ``ph: "X"`` complete events; instants are ``ph: "i"``.
    - Windows whose events span MORE THAN ONE pid (PROCESS-mode
      producer -> consumer) get flow arrows (``ph: "s"``/``"f"``,
      ``id`` = the window identity) from their last producer-side
      event to their first consumer-side one — the cross-process
      stitch.
    """
    evs = sorted(
        (e for e in events),
        key=lambda e: (e[0], e[1] if e[1] is not None else e[0]),
    )
    lane = {s: i for i, s in enumerate(STAGES)}
    next_lane = len(STAGES)
    trace: List[Dict[str, Any]] = []
    pids_named: set = set()
    lanes_named: set = set()
    # window id -> per-pid event lists for flow stitching
    by_window: Dict[Tuple[int, int], List[SpanEvent]] = {}

    for ev in evs:
        s0, s1, stage, pidx, seq, pid = ev
        if stage not in lane:
            lane[stage] = next_lane
            next_lane += 1
        tid = lane[stage]
        if pid not in pids_named:
            pids_named.add(pid)
            trace.append({
                "ph": "M", "name": "process_name", "pid": pid, "tid": 0,
                "args": {"name": f"ddl pid {pid}"},
            })
        if (pid, tid) not in lanes_named:
            lanes_named.add((pid, tid))
            trace.append({
                "ph": "M", "name": "thread_name", "pid": pid, "tid": tid,
                "args": {"name": stage},
            })
            trace.append({
                "ph": "M", "name": "thread_sort_index", "pid": pid,
                "tid": tid, "args": {"sort_index": tid},
            })
        args: Dict[str, Any] = {}
        if pidx is not None:
            args["producer_idx"] = pidx
            args["seq"] = seq
            args["window"] = f"p{pidx}/s{seq}"
            if seq is not None:
                by_window.setdefault((pidx, seq), []).append(ev)
        common = {
            "name": stage, "cat": "ddl", "pid": pid, "tid": tid,
            "ts": s0 * 1e6, "args": args,
        }
        if s1 is None:
            trace.append({**common, "ph": "i", "s": "t"})
        else:
            trace.append({**common, "ph": "X", "dur": (s1 - s0) * 1e6})

    # Flow arrows: producer process -> consumer process, per window.
    for (pidx, seq), wevs in sorted(by_window.items()):
        if len({e[5] for e in wevs}) < 2:
            continue  # single process: lanes already adjacent
        prod = [e for e in wevs if _is_producer_stage(e[2])]
        cons = [e for e in wevs if not _is_producer_stage(e[2])]
        if not prod or not cons:
            continue
        src = max(prod, key=lambda e: e[1] if e[1] is not None else e[0])
        dst = min(cons, key=lambda e: e[0])
        flow_id = (int(pidx) << 32) | (int(seq) & 0xFFFFFFFF)
        src_end = src[1] if src[1] is not None else src[0]
        trace.append({
            "ph": "s", "cat": "ddl.window", "name": "window",
            "id": flow_id, "pid": src[5], "tid": lane[src[2]],
            "ts": src_end * 1e6,
            "args": {"window": f"p{pidx}/s{seq}"},
        })
        trace.append({
            "ph": "f", "bp": "e", "cat": "ddl.window", "name": "window",
            "id": flow_id, "pid": dst[5], "tid": lane[dst[2]],
            "ts": dst[0] * 1e6,
            "args": {"window": f"p{pidx}/s{seq}"},
        })
    return {"traceEvents": trace, "displayTimeUnit": "ms"}


def write_chrome_trace(events: Iterable[SpanEvent], path: str) -> str:
    """Serialize :func:`chrome_trace` to ``path`` (atomic temp+rename —
    a trace is a post-mortem artifact, never worth a torn read)."""
    from ddl_tpu.checkpoint import atomic_file_write

    data = json.dumps(chrome_trace(events)).encode()
    atomic_file_write(path, data, fsync=False)
    return path


# Spawned producer processes arm themselves at import when the consumer
# exported a trace request (the faults.PLAN_ENV pattern): their span
# batches ride ObsReport shipping back into the consumer's log.
_env_trace = envspec.raw(TRACE_ENV)
if _env_trace:
    try:
        _cap = int(_env_trace)
    except ValueError:
        _cap = DEFAULT_CAPACITY
    _ARMED = SpanLog(capacity=_cap if _cap > 1 else DEFAULT_CAPACITY)
del _env_trace
