"""Chaos flight recorder: a bounded ring of recent span/metric events,
dumped atomically at failure sites for post-mortem analysis.

The chaos matrix and the chip campaigns keep producing rows where the
*outcome* is asserted (byte-identical, exactly-once) but the *incident*
itself leaves no artifact — when a real run trips the same path, the
only evidence is whatever log lines survived.  The recorder fixes that:
while armed, every span emission and every metric mutation (via
``observability.install_event_tap``) appends one tuple to a fixed-size
ring, and the failure sites — drain-time integrity corruption, fault-
site trips, preemption notices, watchdog failures — dump the ring plus
a full metrics snapshot to a JSON artifact via the ONE sanctioned
atomic write primitive (``checkpoint.atomic_file_write``, DDL022's
subject), naming the faulted window's ``(producer_idx, seq)``.

Reading a dump: ``python -m ddl_tpu.obs dump <artifact>`` pretty-prints
the per-window stage waterfall and the last-N metric deltas.

Bounded by construction (``deque(maxlen=...)`` — ddl-lint DDL023), and
dump-rate-limited (:data:`MAX_DUMPS`) so a persistent fault in a chaos
soak cannot fill the disk with thousands of identical post-mortems.
"""

from __future__ import annotations

import json
import logging
import os
import threading

from ddl_tpu import envspec
from ddl_tpu.concurrency import named_lock
import time
from collections import deque
from typing import Any, Dict, Optional

logger = logging.getLogger("ddl_tpu")

#: Env var arming a default recorder in freshly spawned processes
#: ("1" or a capacity).  Exported by :class:`armed` like
#: ``faults.PLAN_ENV``.
FLIGHT_ENV = "DDL_TPU_FLIGHT"

#: Where dumps land (created on first dump).
FLIGHT_DIR_ENV = "DDL_TPU_FLIGHT_DIR"
DEFAULT_FLIGHT_DIR = "ddl_flight"

DEFAULT_CAPACITY = 4096

#: Per-process dump budget: a persistent fault must leave evidence,
#: not a full disk.
MAX_DUMPS = 8

#: Dump format version (the CLI refuses unknown majors).
DUMP_VERSION = 1


class FlightRecorder:
    """Fixed-size ring of recent observability events (see module doc)."""

    def __init__(
        self,
        capacity: int = DEFAULT_CAPACITY,
        directory: Optional[str] = None,
    ):
        self.capacity = int(capacity)
        self._ring: deque = deque(maxlen=self.capacity)
        self.directory = (
            directory or envspec.raw(FLIGHT_DIR_ENV) or DEFAULT_FLIGHT_DIR
        )
        self._dump_lock = named_lock("obs.recorder.dump")
        self.dumps = 0
        self.noted = 0
        #: Paths written by this recorder (test/bench introspection).
        self.dumped_paths: deque = deque(maxlen=MAX_DUMPS)

    def note(
        self,
        kind: str,
        name: str,
        value: float,
        producer_idx: Optional[int] = None,
        seq: Optional[int] = None,
    ) -> None:
        """One ring entry (GIL-atomic append; no lock on the hot path)."""
        self._ring.append(
            (time.perf_counter(), kind, name, float(value),
             producer_idx, seq)
        )
        self.noted += 1

    def events(self) -> list:
        return list(self._ring)

    def dump(
        self,
        reason: str,
        producer_idx: Optional[int] = None,
        seq: Optional[int] = None,
        metrics: Any = None,
        extra: Optional[Dict[str, Any]] = None,
    ) -> Optional[str]:
        """Write one post-mortem artifact; returns its path (None when
        the per-process budget is exhausted).  Atomic temp+rename via
        ``checkpoint.atomic_file_write`` — a half-written post-mortem
        of a crash is worse than none."""
        from ddl_tpu.checkpoint import atomic_file_write
        from ddl_tpu.observability import metrics as default_metrics

        with self._dump_lock:
            if self.dumps >= MAX_DUMPS:
                return None
            self.dumps += 1
            n = self.dumps
        m = metrics if metrics is not None else default_metrics()
        slug = "".join(
            c if c.isalnum() or c in "-_" else "-" for c in reason
        )[:60]
        path = os.path.join(
            self.directory,
            f"flight-{os.getpid()}-{n:02d}-{slug}.json",
        )
        record = {
            "version": DUMP_VERSION,
            "reason": reason,
            "time": time.strftime("%Y-%m-%dT%H:%M:%S"),
            "pid": os.getpid(),
            "window": {"producer_idx": producer_idx, "seq": seq},
            "events": self.events(),
            "events_dropped": max(0, self.noted - len(self._ring)),
            "metrics": m.snapshot(),
        }
        if extra:
            record["extra"] = extra
        try:
            atomic_file_write(
                path, json.dumps(record).encode(), fsync=False
            )
        except OSError as e:  # pragma: no cover - disk-full etc.
            logger.error("flight-recorder dump failed: %s", e)
            return None
        self.dumped_paths.append(path)
        m.incr("obs.flight_dumps")
        logger.warning(
            "flight recorder: dumped %s (reason=%s window=%s/%s)",
            path, reason, producer_idx, seq,
        )
        return path


#: The armed recorder, or None — one module-attribute read per metric
#: event is the entire disarmed cost (the faults._ARMED pattern).
_ARMED: Optional[FlightRecorder] = None


def armed_recorder() -> Optional[FlightRecorder]:
    return _ARMED


def arm(
    rec: Optional[FlightRecorder], export: bool = False
) -> Optional[FlightRecorder]:
    """Arm ``rec`` process-wide (``None`` disarms) and install/remove
    the metric-event tap.  ``export=True`` publishes :data:`FLIGHT_ENV`
    (+ the dump dir) so PROCESS workers arm their own ring on import."""
    global _ARMED
    from ddl_tpu import observability

    prev = _ARMED
    _ARMED = rec
    observability.install_event_tap(
        rec.note if rec is not None else None
    )
    if export:
        if rec is None:
            os.environ.pop(FLIGHT_ENV, None)
            os.environ.pop(FLIGHT_DIR_ENV, None)
        else:
            os.environ[FLIGHT_ENV] = str(rec.capacity)
            os.environ[FLIGHT_DIR_ENV] = rec.directory
    return prev


class armed:
    """Context manager: arm a recorder for a scoped run (restores the
    previous recorder and env on exit — the ``faults.armed`` shape)."""

    def __init__(
        self,
        rec: Optional[FlightRecorder] = None,
        export: bool = False,
        directory: Optional[str] = None,
    ):
        self.rec = rec or FlightRecorder(directory=directory)
        self.export = export
        self._prev: Optional[FlightRecorder] = None
        self._prev_env: Optional[str] = None
        self._prev_dir: Optional[str] = None

    def __enter__(self) -> FlightRecorder:
        self._prev_env = envspec.raw(FLIGHT_ENV)
        self._prev_dir = envspec.raw(FLIGHT_DIR_ENV)
        self._prev = arm(self.rec, export=self.export)
        return self.rec

    def __exit__(self, *exc: Any) -> None:
        arm(self._prev)
        if self.export:
            for var, prev in (
                (FLIGHT_ENV, self._prev_env),
                (FLIGHT_DIR_ENV, self._prev_dir),
            ):
                if prev is None:
                    os.environ.pop(var, None)
                else:
                    os.environ[var] = prev


def flight_dump(
    reason: str,
    producer_idx: Optional[int] = None,
    seq: Optional[int] = None,
    metrics: Any = None,
    extra: Optional[Dict[str, Any]] = None,
) -> Optional[str]:
    """Dump the armed recorder (no-op when disarmed) — THE call failure
    sites make: integrity corruption, fault-site trips, preemption
    notices, watchdog failures."""
    rec = _ARMED
    if rec is None:
        return None
    return rec.dump(
        reason, producer_idx=producer_idx, seq=seq,
        metrics=metrics, extra=extra,
    )


def flight_note(kind: str, name: str, value: float) -> None:
    """Append one event to the armed recorder (no-op when disarmed) —
    the audit hook for NON-metric decisions that must appear in a
    post-mortem ring: ddl_tpu.tune notes every knob change here as
    ``("tune", knob, new_value)`` next to the signal values that
    triggered it, so a dump shows WHAT the controller did interleaved
    with WHY (the surrounding metric events)."""
    rec = _ARMED
    if rec is not None:
        rec.note(kind, name, value)


# Spawned processes arm themselves at import when the consumer exported
# a flight request (the faults.PLAN_ENV pattern).
_env_flight = envspec.raw(FLIGHT_ENV)
if _env_flight:
    try:
        _cap = int(_env_flight)
    except ValueError:
        _cap = DEFAULT_CAPACITY
    arm(FlightRecorder(capacity=_cap if _cap > 1 else DEFAULT_CAPACITY))
del _env_flight
