"""Cross-process metric/span aggregation: PROCESS-mode worker
registries merged into the consumer's under ``producer.<idx>.*``.

The Metrics docstring carried the caveat from day one: producer-side
counters live "per worker process in PROCESS mode" — the consumer's
registry (and therefore ``north_star_report``, the bench JSON, and
every BENCH_* trajectory) was blind to ``shuffle.*`` ladder events,
``wire.*`` exchange fallbacks, and producer fill timings whenever the
producers ran as spawned processes.  This module closes that blind
spot over the transport that already exists: workers periodically ship
an :class:`~ddl_tpu.types.ObsReport` (a cumulative
``Metrics.snapshot()`` + histogram states + armed-span deltas) over
the same control channel ``ReplayRequest``/``ShardAdoption`` ride, and
the consumer merges each report into its registry via
``Metrics.adopt`` — REPLACE-based (snapshots are cumulative) and
fenced (``report_idx`` monotone per producer; stale reports are
dropped, the ShardAdoption epoch-fence pattern).

Cost model: one snapshot + one pickle per :data:`ship_every` windows
per producer (default 32, ``DDL_TPU_OBS_SHIP_EVERY``; ``0`` disables)
plus a final ship at producer shutdown so short runs still aggregate.
THREAD-mode producers share the consumer registry already and never
ship.  The consumer drains reports non-blockingly at window
boundaries.
"""

from __future__ import annotations

import logging
import os

from ddl_tpu import envspec
from typing import Any, Dict, Optional

logger = logging.getLogger("ddl_tpu")

SHIP_ENV = "DDL_TPU_OBS_SHIP_EVERY"
DEFAULT_SHIP_EVERY = 32


def ship_every() -> int:
    """Windows between periodic worker ObsReports (0 = disabled)."""
    raw = envspec.raw(SHIP_ENV) or ""
    if not raw:
        return DEFAULT_SHIP_EVERY
    try:
        return max(0, int(raw))
    except ValueError:
        return DEFAULT_SHIP_EVERY


def build_report(
    producer_idx: int,
    report_idx: int,
    metrics: Any,
    view_epoch: int = 0,
) -> Any:
    """Assemble one worker-side ObsReport: cumulative snapshot +
    histogram states + (when spans are armed) the span delta since the
    last report."""
    from ddl_tpu.obs import spans
    from ddl_tpu.types import ObsReport

    span_log = spans.log()
    return ObsReport(
        producer_idx=producer_idx,
        report_idx=report_idx,
        pid=os.getpid(),
        snapshot=metrics.snapshot(),
        hists=metrics.hist_state(),
        spans=span_log.drain_new() if span_log is not None else [],
        view_epoch=view_epoch,
    )


class ReportMerger:
    """Consumer-side half: fence + merge ObsReports into a registry.

    One instance per loader; NOT thread-safe by design — reports are
    applied on the consumer thread at window boundaries, exactly like
    pool updates.
    """

    def __init__(self, metrics: Any, span_log_getter: Any = None):
        self.metrics = metrics
        # Injected so the merger always appends into the CURRENTLY
        # armed log (arming can change between reports).
        self._span_log_getter = span_log_getter
        # producer_idx -> (pid, last applied report_idx).  The fence is
        # PER INCARNATION: a respawned producer (fresh process, fresh
        # counter) must not be fenced out by its predecessor's higher
        # report_idx — the pid change resets the fence.  Bounded by
        # the producer set by construction.
        self._applied: Dict[int, tuple] = {}  # ddl-lint: disable=DDL013
        self.applied_reports = 0
        self.stale_dropped = 0

    def fence_state(self) -> Dict[int, tuple]:
        """Copy of the per-producer (pid, report_idx) fence — drain
        loops compare states to detect 'a fresh report from every
        producer arrived' and exit before their deadline."""
        return dict(self._applied)

    def apply(self, report: Any) -> bool:
        """Merge one report; False when dropped as stale."""
        pid, last = self._applied.get(report.producer_idx, (None, -1))
        if pid == report.pid and report.report_idx <= last:
            self.stale_dropped += 1
            self.metrics.incr("obs.reports_stale")
            return False
        self._applied[report.producer_idx] = (
            report.pid, report.report_idx,
        )
        self.metrics.adopt(
            f"producer.{report.producer_idx}.",
            report.snapshot,
            report.hists,
        )
        if report.spans:
            from ddl_tpu.obs import spans

            span_log = (
                self._span_log_getter()
                if self._span_log_getter is not None
                else spans.log()
            )
            if span_log is not None:
                span_log.record_many(report.spans)
        self.applied_reports += 1
        self.metrics.incr("obs.reports_applied")
        return True


def adopt_job(
    metrics: Any,
    job_id: str,
    snapshot: Dict[str, Any],
    hists: Optional[Dict[str, Any]] = None,
) -> None:
    """Merge one JOB's registry snapshot under ``job.<id>.*`` — the
    :class:`ReportMerger` ``producer.<idx>.*`` pattern one level up
    (ddl_tpu.serve.fabric): each training job's consumer ships its
    cumulative registry to the fabric tier, and fleet-wide dashboards
    read every job's ``ingest``/``cache``/``consumer`` families side by
    side without collisions.  REPLACE-based, like every adopt —
    snapshots are cumulative, so re-merging is idempotent."""
    metrics.adopt(f"job.{job_id}.", snapshot, hists or {})
