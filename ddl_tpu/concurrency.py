"""Named locks and the runtime lock-order sanitizer.

Every lock in the tree is constructed through :func:`named_lock` /
:func:`named_rlock` / :func:`named_condition` (ddl-lint DDL024 enforces
it), which buys two things:

- **Identity.**  ``tools/ddl_verify``'s whole-program passes key the
  static lock-acquisition graph on these names, so a cross-module
  inversion (the gap DDL008/DDL006 cannot see — each looks at one
  function body) is reportable as ``"staging.pool" -> "cache.store"``
  with a call-chain witness instead of an anonymous ``<locked _thread
  .lock object>``.
- **A runtime witness.**  When a :class:`LockOrderSanitizer` is armed
  (the ``faults.py`` arming pattern), the factories return thin proxies
  that record actual per-thread acquisition stacks and flag any
  acquisition that inverts :data:`LOCK_ORDER` — the TSan-style dynamic
  half of the VP001 static pass.  Violations carry both lock names, the
  thread, and the full held-stack, and dump through the PR-15 flight
  recorder so a chaos-run inversion leaves an artifact.

Design constraints (the fault-engine contract):

- **Zero cost disarmed.**  With no sanitizer armed the factories return
  the *raw* ``threading`` primitives — not a wrapper, the actual
  ``_thread.lock``/``RLock``/``Condition`` object.  The disarmed
  "overhead" is one module-attribute read at construction time and
  nothing at all per acquire.
- **Arm before construction.**  The sanitizer observes locks
  constructed while it is armed; arming after a pipeline is built
  watches nothing (tests arm first, then build — the ``faults.armed``
  usage shape).
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Tuple

#: The declared whole-program lock hierarchy, OUTERMOST first.  A thread
#: already holding a lock may only acquire locks that appear LATER in
#: this list (same name re-acquisition is reentrancy, allowed — named
#: re-entrant locks and sibling instances share a rank).  ``tools/
#: ddl_verify`` VP001 checks the static acquisition graph against this
#: order and that every ``named_*`` literal in the tree appears here;
#: the armed sanitizer enforces it on real executions.
LOCK_ORDER: Tuple[str, ...] = (
    # control plane (outermost: they fan out into everything below)
    "cluster.supervisor",
    "cluster.membership",
    # Fabric admission authority (serve.fabric): the apply path holds
    # the fabric lock while consulting the job registry and driving the
    # scheduler, never the reverse.
    "serve.fabric",
    "serve.fabric.jobs",
    "serve.tenancy.cond",
    "resilience.guard",
    # consumer-side orchestration
    "transport.connection",
    "resilience.ckpt.cv",
    "staging.executor.cv",
    "staging.pool",
    # data-plane rings and exchange
    "transport.shm.build",
    "transport.ring.cond",
    # Device-tier exchange board (DeviceExchangeFabric) ranks above the
    # host board: the device tier LATCHES to the host exchange, never
    # the reverse (the fallback re-run happens after the fabric lock is
    # released, but the rank still documents the one-way layering).
    "shuffle.device.cond",
    "shuffle.exchange.cond",
    "shuffle.sweep",
    # shard cache tiers
    "cache.registry",
    "cache.store",
    "cache.store.spill",
    "cache.backend",
    # leaf utilities: reachable from under ANY of the above (fault
    # points fire inside ring waits; metrics/span appends happen under
    # data-plane locks), so they must order innermost.
    "faults.plan",
    "obs.metrics",
    "obs.spans",
    "obs.recorder.dump",
)

_RANK: Dict[str, int] = {name: i for i, name in enumerate(LOCK_ORDER)}


class LockOrderViolation(RuntimeError):
    """An armed sanitizer observed an acquisition inverting LOCK_ORDER."""


class LockOrderSanitizer:
    """Records per-thread lock-acquisition stacks and flags inversions.

    ``violations`` is the witness list: one ``(acquiring, holding,
    thread_name, held_stack)`` tuple per observed inversion.  ``edges``
    records every distinct ``(holding_top, acquiring)`` pair seen, so a
    test can also assert the *observed* order agrees with the static
    graph.  ``strict=True`` raises :class:`LockOrderViolation` at the
    inversion site (the deterministic-repro mode); the default records
    and dumps a flight-recorder witness but lets the run proceed (the
    chaos-leg mode — the assertion happens at the end of the test).
    """

    def __init__(
        self,
        order: Optional[Tuple[str, ...]] = None,
        strict: bool = False,
    ):
        ranks = order if order is not None else LOCK_ORDER
        self.rank: Dict[str, int] = {n: i for i, n in enumerate(ranks)}
        self.strict = strict
        self.violations: List[Tuple[str, str, str, Tuple[str, ...]]] = []
        self.edges: set = set()
        #: Approximate acquisition count (racy increment by design — it
        #: exists so a test can assert the armed run was non-vacuous,
        #: not as a metric).
        self.n_acquisitions = 0
        self._tls = threading.local()
        # Bare lock on purpose (this module IS the factory): guards the
        # shared violation/edge records, never held across user code.
        self._lock = threading.Lock()  # ddl-lint: disable=DDL024

    # -- per-thread stack bookkeeping (called from the proxies) ------------

    def _stack(self) -> List[str]:
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        return stack

    def check(self, name: str) -> None:
        """Order check BEFORE the underlying acquire (never blocks)."""
        self.n_acquisitions += 1
        stack = self._stack()
        if not stack:
            return
        rank = self.rank.get(name)
        top = stack[-1]
        if top != name:
            with self._lock:
                self.edges.add((top, name))
        if rank is None:
            return
        for held in stack:
            held_rank = self.rank.get(held)
            if held == name or held_rank is None:
                continue  # reentrancy / unranked: no order claim
            if held_rank > rank:
                witness = (name, held, threading.current_thread().name,
                           tuple(stack))
                with self._lock:
                    self.violations.append(witness)
                self._flight_dump(name, held)
                if self.strict:
                    raise LockOrderViolation(
                        f"acquiring {name!r} while holding {held!r} "
                        f"inverts LOCK_ORDER (held stack: {stack})"
                    )

    def push(self, name: str) -> None:
        self._stack().append(name)

    def pop(self, name: str) -> None:
        stack = self._stack()
        # Release order may legitimately differ from acquire order
        # (cond.wait drops its own lock mid-stack): remove the newest
        # matching entry, not blindly the top.
        for i in range(len(stack) - 1, -1, -1):
            if stack[i] == name:
                del stack[i]
                return

    def _flight_dump(self, acquiring: str, holding: str) -> None:
        # Lazy import (the faults.py pattern): the sanitizer must not
        # pull the obs layer into processes that never arm a recorder.
        from ddl_tpu.obs import recorder as _flight

        if _flight.armed_recorder() is not None:
            _flight.flight_dump(
                f"lockorder.inversion.{holding}->{acquiring}"
            )


class _SanitizedLock:
    """Proxy over a ``threading.Lock``/``RLock`` reporting to a sanitizer."""

    __slots__ = ("name", "_inner", "_san")

    def __init__(self, name: str, inner: Any, san: LockOrderSanitizer):
        self.name = name
        self._inner = inner
        self._san = san

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        self._san.check(self.name)
        got = self._inner.acquire(blocking, timeout)
        if got:
            self._san.push(self.name)
        return got

    def release(self) -> None:
        self._inner.release()
        self._san.pop(self.name)

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self) -> "_SanitizedLock":
        self.acquire()
        return self

    def __exit__(self, *exc: Any) -> None:
        self.release()


class _SanitizedCondition:
    """Proxy over ``threading.Condition`` reporting to a sanitizer.

    ``wait``/``wait_for`` drop the lock inside the inner primitive, so
    the held-stack entry is popped for the duration of the wait and
    re-pushed (no re-check: the thread logically still owns its slot in
    the order) when the wait returns.
    """

    __slots__ = ("name", "_inner", "_san")

    def __init__(self, name: str, inner: Any, san: LockOrderSanitizer):
        self.name = name
        self._inner = inner
        self._san = san

    def acquire(self, *args: Any, **kw: Any) -> bool:
        self._san.check(self.name)
        got = self._inner.acquire(*args, **kw)
        if got:
            self._san.push(self.name)
        return got

    def release(self) -> None:
        self._inner.release()
        self._san.pop(self.name)

    def wait(self, timeout: Optional[float] = None) -> bool:
        self._san.pop(self.name)
        try:
            return self._inner.wait(timeout)
        finally:
            self._san.push(self.name)

    def wait_for(self, predicate: Any, timeout: Optional[float] = None) -> Any:
        self._san.pop(self.name)
        try:
            return self._inner.wait_for(predicate, timeout)
        finally:
            self._san.push(self.name)

    def notify(self, n: int = 1) -> None:
        self._inner.notify(n)

    def notify_all(self) -> None:
        self._inner.notify_all()

    def __enter__(self) -> "_SanitizedCondition":
        self.acquire()
        return self

    def __exit__(self, *exc: Any) -> None:
        self.release()


#: The armed sanitizer, or None.  Read once at lock CONSTRUCTION — the
#: entire disarmed cost (no per-acquire read: disarmed factories hand
#: back raw primitives).
_ARMED: Optional[LockOrderSanitizer] = None


def named_lock(name: str) -> Any:
    """A ``threading.Lock`` with a sanitizer identity.  Disarmed: the
    raw primitive."""
    san = _ARMED
    if san is None:
        return threading.Lock()  # ddl-lint: disable=DDL024
    return _SanitizedLock(name, threading.Lock(), san)  # ddl-lint: disable=DDL024


def named_rlock(name: str) -> Any:
    """A ``threading.RLock`` with a sanitizer identity (reentrant
    re-acquisition of the same name is never an inversion)."""
    san = _ARMED
    if san is None:
        return threading.RLock()  # ddl-lint: disable=DDL024
    return _SanitizedLock(name, threading.RLock(), san)  # ddl-lint: disable=DDL024


def named_condition(name: str) -> Any:
    """A ``threading.Condition`` (own lock) with a sanitizer identity."""
    san = _ARMED
    if san is None:
        return threading.Condition()  # ddl-lint: disable=DDL024
    return _SanitizedCondition(name, threading.Condition(), san)  # ddl-lint: disable=DDL024


def arm_sanitizer(
    san: Optional[LockOrderSanitizer],
) -> Optional[LockOrderSanitizer]:
    """Arm ``san`` process-wide (``None`` disarms); returns the previous
    one.  Only locks constructed while armed are sanitized."""
    global _ARMED
    prev = _ARMED
    _ARMED = san
    return prev


def armed_sanitizer() -> Optional[LockOrderSanitizer]:
    return _ARMED


class sanitized:
    """Context manager: arm a fresh sanitizer for a scoped run.

    ::

        with concurrency.sanitized() as san:
            run_pipeline()          # locks built inside are watched
        assert not san.violations

    Restores the previously armed sanitizer on exit, even when the run
    under test raises (the ``faults.armed`` shape).
    """

    def __init__(self, order: Optional[Tuple[str, ...]] = None,
                 strict: bool = False):
        self.sanitizer = LockOrderSanitizer(order=order, strict=strict)
        self._prev: Optional[LockOrderSanitizer] = None

    def __enter__(self) -> LockOrderSanitizer:
        self._prev = arm_sanitizer(self.sanitizer)
        return self.sanitizer

    def __exit__(self, *exc: Any) -> None:
        arm_sanitizer(self._prev)
