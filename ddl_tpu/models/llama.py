"""Llama-style decoder LM — the flagship model fed by the ddl_tpu loader.

The reference framework carried no models (SURVEY §0: "no model code"); the
driver's pod-scale config ("Llama-3-8B pretrain loop fed solely by the ddl
TPU backend", BASELINE.json configs[4]) requires a real transformer training
loop on the consumer side.  This is a TPU-first functional implementation:

- pure init/apply functions over a params pytree (jit/grad/shard friendly,
  no framework state),
- bfloat16 activations by default (MXU-native), fp32 RMSNorm accumulations,
- RoPE, grouped-query attention, SwiGLU — the Llama-3 block structure,
- sequence parallelism via ring attention when the mesh has an ``sp`` axis,
- parameter PartitionSpecs for fsdp/tp sharding (GSPMD inserts the
  collectives).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

Params = Dict[str, Any]


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab: int = 256
    d_model: int = 128
    n_layers: int = 2
    n_heads: int = 4
    n_kv_heads: int = 2
    d_ff: int = 352
    max_seq: int = 512
    rope_theta: float = 500000.0  # Llama-3 base frequency
    norm_eps: float = 1e-5
    dtype: Any = jnp.bfloat16
    #: Storage dtype of the params pytree (fp32 master weights by
    #: default; bf16 halves param+optimizer HBM for memory-bound
    #: geometries — gradient accumulation stays exact either way, the
    #: train step accumulates in fp32).
    param_dtype: Any = jnp.float32
    #: Rematerialise each transformer layer in the backward pass
    #: (``jax.checkpoint``): activation memory drops from O(n_layers)
    #: full layer internals to O(n_layers) residual-stream tensors plus
    #: ONE layer's internals — the standard FLOPs-for-HBM trade that
    #: lets long-sequence/big-model configs fit a single chip.
    remat: bool = False
    # "auto": Pallas flash attention on TPU, dense elsewhere; "flash"/"dense"
    # force one path.  Sequence-parallel meshes always use ring attention.
    attn_impl: str = "auto"

    def __post_init__(self) -> None:
        if self.attn_impl not in ("auto", "flash", "dense"):
            raise ValueError(
                f"attn_impl must be 'auto', 'flash', or 'dense', "
                f"got {self.attn_impl!r}"
            )

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    @staticmethod
    def llama3_8b() -> "LlamaConfig":
        """The reference-scale config (BASELINE.json configs[4])."""
        return LlamaConfig(
            vocab=128256, d_model=4096, n_layers=32, n_heads=32,
            n_kv_heads=8, d_ff=14336, max_seq=8192,
        )

    @staticmethod
    def tiny() -> "LlamaConfig":
        return LlamaConfig()


def _dense_init(k: jax.Array, fan_in: int, shape: Any, pdt: Any) -> jax.Array:
    """1/sqrt(fan_in)-scaled normal init in ``pdt`` storage — shared by
    every model family (moe reuses it like the norm/qkv blocks)."""
    return (
        jax.random.normal(k, shape, jnp.float32) / jnp.sqrt(fan_in)
    ).astype(pdt)


def init_params(cfg: LlamaConfig, key: jax.Array) -> Params:
    """Initialise a params pytree (``cfg.param_dtype`` storage; fp32
    master weights by default)."""
    keys = iter(jax.random.split(key, 4 + cfg.n_layers * 7))
    pdt = cfg.param_dtype

    def dense(k, fan_in, shape):
        return _dense_init(k, fan_in, shape, pdt)

    d, hd = cfg.d_model, cfg.head_dim
    layers = []
    for _ in range(cfg.n_layers):
        layers.append(
            {
                "attn_norm": jnp.ones((d,), pdt),
                "wq": dense(next(keys), d, (d, cfg.n_heads * hd)),
                "wk": dense(next(keys), d, (d, cfg.n_kv_heads * hd)),
                "wv": dense(next(keys), d, (d, cfg.n_kv_heads * hd)),
                "wo": dense(next(keys), cfg.n_heads * hd, (cfg.n_heads * hd, d)),
                "mlp_norm": jnp.ones((d,), pdt),
                "w_gate": dense(next(keys), d, (d, cfg.d_ff)),
                "w_up": dense(next(keys), d, (d, cfg.d_ff)),
                "w_down": dense(next(keys), cfg.d_ff, (cfg.d_ff, d)),
            }
        )
    return {
        "embed": dense(next(keys), d, (cfg.vocab, d)),
        "layers": layers,
        "final_norm": jnp.ones((d,), pdt),
        "lm_head": dense(next(keys), d, (d, cfg.vocab)),
    }


def param_specs(cfg: LlamaConfig) -> Params:
    """PartitionSpecs mirroring init_params: fsdp shards the d_model-ish
    axis, tp shards heads / ffn-hidden — the standard Megatron layout
    realised declaratively (GSPMD inserts all-reduce/all-gather)."""
    layer = {
        "attn_norm": P(None),
        "wq": P("fsdp", "tp"),
        "wk": P("fsdp", "tp"),
        "wv": P("fsdp", "tp"),
        "wo": P("tp", "fsdp"),
        "mlp_norm": P(None),
        "w_gate": P("fsdp", "tp"),
        "w_up": P("fsdp", "tp"),
        "w_down": P("tp", "fsdp"),
    }
    return {
        "embed": P(None, "fsdp"),
        "layers": [dict(layer) for _ in range(cfg.n_layers)],
        "final_norm": P(None),
        "lm_head": P("fsdp", "tp"),
    }


def _rms_norm(x: jax.Array, gain: jax.Array, eps: float) -> jax.Array:
    xf = x.astype(jnp.float32)
    scale = jax.lax.rsqrt(jnp.mean(xf * xf, axis=-1, keepdims=True) + eps)
    return (xf * scale * gain).astype(x.dtype)


def _rope(x: jax.Array, positions: jax.Array, theta: float) -> jax.Array:
    """Rotary embedding; x: (B, T, H, D), positions: (T,)."""
    d_half = x.shape[-1] // 2
    freqs = theta ** (-jnp.arange(0, d_half, dtype=jnp.float32) / d_half)
    angles = positions[:, None].astype(jnp.float32) * freqs[None, :]  # (T, Dh)
    cos = jnp.cos(angles)[None, :, None, :].astype(x.dtype)
    sin = jnp.sin(angles)[None, :, None, :].astype(x.dtype)
    x1, x2 = x[..., :d_half], x[..., d_half:]
    return jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)


def forward(
    params: Params,
    tokens: jax.Array,
    cfg: LlamaConfig,
    mesh: Optional[Any] = None,
    segment_ids: Optional[jax.Array] = None,
) -> jax.Array:
    """Next-token logits, (B, T, vocab).

    With a mesh carrying an ``sp`` axis of size > 1, attention runs as
    sequence-parallel ring attention (K/V rotating over ICI); otherwise
    dense causal attention.  RoPE positions are global either way (the
    token axis is only *sharded*, never re-indexed).

    ``segment_ids`` (B, T): packed-pretraining batches — attention stays
    within each packed document (kernel-level masking; RoPE positions
    remain row-global, the common packed-training convention).
    """
    from ddl_tpu.parallel.ring_attention import attention

    B, T = tokens.shape
    dt = cfg.dtype
    positions = jnp.arange(T)
    x = params["embed"].astype(dt)[tokens]  # (B, T, D)

    def layer_fn(x: jax.Array, layer: Params) -> jax.Array:
        h = _rms_norm(x, layer["attn_norm"], cfg.norm_eps)
        q, k, v = _attn_qkv(layer, h, cfg, positions)
        # GQA k/v stay compact: expansion happens inside the attention
        # block, so ring attention rotates 1/rep of the bytes over ICI.
        rep = cfg.n_heads // cfg.n_kv_heads
        attn = attention(
            q, k, v, mesh=mesh, impl=cfg.attn_impl, causal=True,
            kv_repeat=rep, segment_ids=segment_ids,
        )
        x = x + attn.reshape(B, T, -1) @ layer["wo"].astype(dt)
        return _mlp_block(layer, x, cfg)

    if cfg.remat:
        # Save only each layer's residual-stream input; recompute the
        # layer internals in the backward pass (HBM-for-FLOPs — the knob
        # that fits big-model/long-seq geometries on one chip).
        layer_fn = jax.checkpoint(layer_fn)
    for layer in params["layers"]:
        x = layer_fn(x, layer)

    x = _rms_norm(x, params["final_norm"], cfg.norm_eps)
    return (x @ params["lm_head"].astype(dt)).astype(jnp.float32)


def _attn_qkv(layer: Params, h: jax.Array, cfg: LlamaConfig,
              positions: jax.Array):
    """Project + rope one block's q/k/v (shared by train and decode)."""
    B, T = h.shape[:2]
    dt = h.dtype
    q = (h @ layer["wq"].astype(dt)).reshape(B, T, cfg.n_heads, cfg.head_dim)
    k = (h @ layer["wk"].astype(dt)).reshape(B, T, cfg.n_kv_heads,
                                             cfg.head_dim)
    v = (h @ layer["wv"].astype(dt)).reshape(B, T, cfg.n_kv_heads,
                                             cfg.head_dim)
    return (
        _rope(q, positions, cfg.rope_theta),
        _rope(k, positions, cfg.rope_theta),
        v,
    )


def _mlp_block(layer: Params, x: jax.Array, cfg: LlamaConfig) -> jax.Array:
    """SwiGLU MLP sub-block with residual (shared by train and decode)."""
    dt = x.dtype
    h = _rms_norm(x, layer["mlp_norm"], cfg.norm_eps)
    gate = jax.nn.silu(h @ layer["w_gate"].astype(dt))
    up = h @ layer["w_up"].astype(dt)
    return x + (gate * up) @ layer["w_down"].astype(dt)


def init_cache(cfg: LlamaConfig, batch: int, max_len: int) -> Params:
    """Per-layer KV cache buffers for autoregressive decoding."""
    shape = (batch, max_len, cfg.n_kv_heads, cfg.head_dim)
    return {
        "k": jnp.zeros((cfg.n_layers,) + shape, cfg.dtype),
        "v": jnp.zeros((cfg.n_layers,) + shape, cfg.dtype),
    }


def forward_with_cache(
    params: Params,
    tokens: jax.Array,
    cfg: LlamaConfig,
    cache: Params,
    pos: jax.Array,
    last_only: bool = False,
) -> tuple[jax.Array, Params]:
    """Process ``tokens`` (B, T) starting at position ``pos`` against a KV
    cache (prefill: T = prompt length at pos 0; decode: T = 1).

    Returns (logits, updated cache); logits are (B, T, vocab), or
    (B, 1, vocab) with ``last_only`` (prefill wants only the frontier —
    full-prompt fp32 logits are ~4 GB at llama3_8b/8k).  Attention is
    dense over the cache with a causal-position mask — decode steps are
    matmul-thin so flash buys nothing there — and attends the COMPACT
    GQA cache via a grouped einsum (no rep-expanded cache copy in the
    bandwidth-bound decode hot path).  The cache length is static
    (``init_cache`` max_len) for jit-stable shapes.
    """
    B, T = tokens.shape
    dt = cfg.dtype
    L = cache["k"].shape[2]
    positions = pos + jnp.arange(T)
    cache_idx = jnp.arange(L)
    x = params["embed"].astype(dt)[tokens]
    scale = 1.0 / (cfg.head_dim**0.5)
    rep = cfg.n_heads // cfg.n_kv_heads

    new_k, new_v = [], []
    for li, layer in enumerate(params["layers"]):
        h = _rms_norm(x, layer["attn_norm"], cfg.norm_eps)
        q, k, v = _attn_qkv(layer, h, cfg, positions)
        ck = jax.lax.dynamic_update_slice(
            cache["k"][li], k.astype(dt), (0, pos, 0, 0)
        )
        cv = jax.lax.dynamic_update_slice(
            cache["v"][li], v.astype(dt), (0, pos, 0, 0)
        )
        new_k.append(ck)
        new_v.append(cv)
        # Grouped-query attention against the compact cache: q regrouped
        # per KV head, scores (B, Hkv, rep, T, L).
        qg = q.reshape(B, T, cfg.n_kv_heads, rep, cfg.head_dim)
        s = jnp.einsum("bqkrd,bskd->bkrqs", qg, ck) * scale
        # Causal over absolute positions; cache slots past the frontier
        # (zeros) are masked the same way.
        mask = cache_idx[None, :] > positions[:, None]  # (T, L)
        s = jnp.where(mask[None, None, None], -1e30, s)
        p = jax.nn.softmax(s.astype(jnp.float32), axis=-1).astype(dt)
        attn = jnp.einsum("bkrqs,bskd->bqkrd", p, cv)
        x = x + attn.reshape(B, T, -1) @ layer["wo"].astype(dt)
        x = _mlp_block(layer, x, cfg)

    x = _rms_norm(x, params["final_norm"], cfg.norm_eps)
    if last_only:
        x = x[:, -1:]
    logits = (x @ params["lm_head"].astype(dt)).astype(jnp.float32)
    return logits, {"k": jnp.stack(new_k), "v": jnp.stack(new_v)}


def generate(
    params: Params,
    prompt: jax.Array,
    cfg: LlamaConfig,
    max_new_tokens: int,
    temperature: float = 0.0,
    key: Optional[jax.Array] = None,
) -> jax.Array:
    """Autoregressive generation: greedy (``temperature == 0``) or
    temperature sampling.  Returns (B, prompt_len + max_new_tokens).

    Sampling (``temperature > 0``) REQUIRES an explicit ``key`` — a
    silent default would make "sampled" generation deterministically
    identical across calls, an easy misuse trap for an inference API.

    Prefill runs the whole prompt in ONE cached forward (full-width
    matmuls on the MXU); decode steps run under ``lax.scan`` with a
    static-shape KV cache — no recompilation per step, no Python loop.
    """
    B, P_len = prompt.shape
    if max_new_tokens <= 0:
        return prompt
    total = P_len + max_new_tokens
    cache = init_cache(cfg, B, total)
    logits, cache = forward_with_cache(
        params, prompt, cfg, cache, jnp.int32(0), last_only=True
    )
    last = logits[:, -1]
    if key is None:
        if temperature > 0.0:
            raise ValueError(
                "temperature sampling requires an explicit PRNG key: "
                "pass key=jax.random.key(seed) (every call with the "
                "default key would sample the SAME tokens)"
            )
        key = jax.random.key(0)  # greedy path: keys are structural only

    def pick(logits_t, k):
        if temperature <= 0.0:
            return jnp.argmax(logits_t, axis=-1).astype(prompt.dtype)
        return jax.random.categorical(
            k, logits_t / temperature, axis=-1
        ).astype(prompt.dtype)

    def step(carry, k):
        cache, last_logits, pos = carry
        tok = pick(last_logits, k)
        logits_t, cache = forward_with_cache(
            params, tok[:, None], cfg, cache, pos
        )
        return (cache, logits_t[:, 0], pos + 1), tok

    # Scan max_new_tokens - 1 steps; the final token needs no forward of
    # its own (its logits would be discarded).
    keys = jax.random.split(key, max_new_tokens)
    (_, last, _), new_tokens = jax.lax.scan(
        step, (cache, last, jnp.int32(P_len)), keys[:-1],
    )
    final = pick(last, keys[-1])
    new = jnp.concatenate(
        [new_tokens.swapaxes(0, 1), final[:, None]], axis=1
    ) if max_new_tokens > 1 else final[:, None]
    return jnp.concatenate([prompt, new], axis=1)


def next_token_loss(
    params: Params,
    tokens: jax.Array,
    cfg: LlamaConfig,
    mesh: Optional[Any] = None,
    segment_ids: Optional[jax.Array] = None,
) -> jax.Array:
    """Mean cross-entropy of next-token prediction over (B, T) tokens.

    Targets are ``roll(tokens, -1)`` with the final position masked rather
    than a ``[:-1]`` slice — the sequence axis keeps its full length, so it
    stays evenly shardable over ``sp``.

    With ``segment_ids`` (packed batches), attention is segment-masked
    and the loss additionally drops positions whose next token belongs to
    a different document (the cross-document boundary predictions).
    """
    from ddl_tpu.models.losses import next_token_cross_entropy

    logits = forward(params, tokens, cfg, mesh, segment_ids=segment_ids)
    return next_token_cross_entropy(logits, tokens, segment_ids=segment_ids)
