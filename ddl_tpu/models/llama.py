"""Llama-style decoder LM — the flagship model fed by the ddl_tpu loader.

The reference framework carried no models (SURVEY §0: "no model code"); the
driver's pod-scale config ("Llama-3-8B pretrain loop fed solely by the ddl
TPU backend", BASELINE.json configs[4]) requires a real transformer training
loop on the consumer side.  This is a TPU-first functional implementation:

- pure init/apply functions over a params pytree (jit/grad/shard friendly,
  no framework state),
- bfloat16 activations by default (MXU-native), fp32 RMSNorm accumulations,
- RoPE, grouped-query attention, SwiGLU — the Llama-3 block structure,
- sequence parallelism via ring attention when the mesh has an ``sp`` axis,
- parameter PartitionSpecs for fsdp/tp sharding (GSPMD inserts the
  collectives).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

Params = Dict[str, Any]


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab: int = 256
    d_model: int = 128
    n_layers: int = 2
    n_heads: int = 4
    n_kv_heads: int = 2
    d_ff: int = 352
    max_seq: int = 512
    rope_theta: float = 500000.0  # Llama-3 base frequency
    norm_eps: float = 1e-5
    dtype: Any = jnp.bfloat16
    # "auto": Pallas flash attention on TPU, dense elsewhere; "flash"/"dense"
    # force one path.  Sequence-parallel meshes always use ring attention.
    attn_impl: str = "auto"

    def __post_init__(self) -> None:
        if self.attn_impl not in ("auto", "flash", "dense"):
            raise ValueError(
                f"attn_impl must be 'auto', 'flash', or 'dense', "
                f"got {self.attn_impl!r}"
            )

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    @staticmethod
    def llama3_8b() -> "LlamaConfig":
        """The reference-scale config (BASELINE.json configs[4])."""
        return LlamaConfig(
            vocab=128256, d_model=4096, n_layers=32, n_heads=32,
            n_kv_heads=8, d_ff=14336, max_seq=8192,
        )

    @staticmethod
    def tiny() -> "LlamaConfig":
        return LlamaConfig()


def init_params(cfg: LlamaConfig, key: jax.Array) -> Params:
    """Initialise a params pytree (fp32 master weights)."""
    keys = iter(jax.random.split(key, 4 + cfg.n_layers * 7))

    def dense(k, fan_in, shape):
        return jax.random.normal(k, shape, jnp.float32) / jnp.sqrt(fan_in)

    d, hd = cfg.d_model, cfg.head_dim
    layers = []
    for _ in range(cfg.n_layers):
        layers.append(
            {
                "attn_norm": jnp.ones((d,), jnp.float32),
                "wq": dense(next(keys), d, (d, cfg.n_heads * hd)),
                "wk": dense(next(keys), d, (d, cfg.n_kv_heads * hd)),
                "wv": dense(next(keys), d, (d, cfg.n_kv_heads * hd)),
                "wo": dense(next(keys), cfg.n_heads * hd, (cfg.n_heads * hd, d)),
                "mlp_norm": jnp.ones((d,), jnp.float32),
                "w_gate": dense(next(keys), d, (d, cfg.d_ff)),
                "w_up": dense(next(keys), d, (d, cfg.d_ff)),
                "w_down": dense(next(keys), cfg.d_ff, (cfg.d_ff, d)),
            }
        )
    return {
        "embed": dense(next(keys), d, (cfg.vocab, d)),
        "layers": layers,
        "final_norm": jnp.ones((d,), jnp.float32),
        "lm_head": dense(next(keys), d, (d, cfg.vocab)),
    }


def param_specs(cfg: LlamaConfig) -> Params:
    """PartitionSpecs mirroring init_params: fsdp shards the d_model-ish
    axis, tp shards heads / ffn-hidden — the standard Megatron layout
    realised declaratively (GSPMD inserts all-reduce/all-gather)."""
    layer = {
        "attn_norm": P(None),
        "wq": P("fsdp", "tp"),
        "wk": P("fsdp", "tp"),
        "wv": P("fsdp", "tp"),
        "wo": P("tp", "fsdp"),
        "mlp_norm": P(None),
        "w_gate": P("fsdp", "tp"),
        "w_up": P("fsdp", "tp"),
        "w_down": P("tp", "fsdp"),
    }
    return {
        "embed": P(None, "fsdp"),
        "layers": [dict(layer) for _ in range(cfg.n_layers)],
        "final_norm": P(None),
        "lm_head": P("fsdp", "tp"),
    }


def _rms_norm(x: jax.Array, gain: jax.Array, eps: float) -> jax.Array:
    xf = x.astype(jnp.float32)
    scale = jax.lax.rsqrt(jnp.mean(xf * xf, axis=-1, keepdims=True) + eps)
    return (xf * scale * gain).astype(x.dtype)


def _rope(x: jax.Array, positions: jax.Array, theta: float) -> jax.Array:
    """Rotary embedding; x: (B, T, H, D), positions: (T,)."""
    d_half = x.shape[-1] // 2
    freqs = theta ** (-jnp.arange(0, d_half, dtype=jnp.float32) / d_half)
    angles = positions[:, None].astype(jnp.float32) * freqs[None, :]  # (T, Dh)
    cos = jnp.cos(angles)[None, :, None, :].astype(x.dtype)
    sin = jnp.sin(angles)[None, :, None, :].astype(x.dtype)
    x1, x2 = x[..., :d_half], x[..., d_half:]
    return jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)


def forward(
    params: Params,
    tokens: jax.Array,
    cfg: LlamaConfig,
    mesh: Optional[Any] = None,
    segment_ids: Optional[jax.Array] = None,
) -> jax.Array:
    """Next-token logits, (B, T, vocab).

    With a mesh carrying an ``sp`` axis of size > 1, attention runs as
    sequence-parallel ring attention (K/V rotating over ICI); otherwise
    dense causal attention.  RoPE positions are global either way (the
    token axis is only *sharded*, never re-indexed).

    ``segment_ids`` (B, T): packed-pretraining batches — attention stays
    within each packed document (kernel-level masking; RoPE positions
    remain row-global, the common packed-training convention).
    """
    from ddl_tpu.parallel.ring_attention import attention

    B, T = tokens.shape
    dt = cfg.dtype
    positions = jnp.arange(T)
    x = params["embed"].astype(dt)[tokens]  # (B, T, D)

    for layer in params["layers"]:
        h = _rms_norm(x, layer["attn_norm"], cfg.norm_eps)
        q = (h @ layer["wq"].astype(dt)).reshape(B, T, cfg.n_heads, cfg.head_dim)
        k = (h @ layer["wk"].astype(dt)).reshape(B, T, cfg.n_kv_heads, cfg.head_dim)
        v = (h @ layer["wv"].astype(dt)).reshape(B, T, cfg.n_kv_heads, cfg.head_dim)
        q = _rope(q, positions, cfg.rope_theta)
        k = _rope(k, positions, cfg.rope_theta)
        # GQA k/v stay compact: expansion happens inside the attention
        # block, so ring attention rotates 1/rep of the bytes over ICI.
        rep = cfg.n_heads // cfg.n_kv_heads
        attn = attention(
            q, k, v, mesh=mesh, impl=cfg.attn_impl, causal=True,
            kv_repeat=rep, segment_ids=segment_ids,
        )
        x = x + attn.reshape(B, T, -1) @ layer["wo"].astype(dt)

        h = _rms_norm(x, layer["mlp_norm"], cfg.norm_eps)
        gate = jax.nn.silu(h @ layer["w_gate"].astype(dt))
        up = h @ layer["w_up"].astype(dt)
        x = x + (gate * up) @ layer["w_down"].astype(dt)

    x = _rms_norm(x, params["final_norm"], cfg.norm_eps)
    return (x @ params["lm_head"].astype(dt)).astype(jnp.float32)


def next_token_loss(
    params: Params,
    tokens: jax.Array,
    cfg: LlamaConfig,
    mesh: Optional[Any] = None,
    segment_ids: Optional[jax.Array] = None,
) -> jax.Array:
    """Mean cross-entropy of next-token prediction over (B, T) tokens.

    Targets are ``roll(tokens, -1)`` with the final position masked rather
    than a ``[:-1]`` slice — the sequence axis keeps its full length, so it
    stays evenly shardable over ``sp``.

    With ``segment_ids`` (packed batches), attention is segment-masked
    and the loss additionally drops positions whose next token belongs to
    a different document (the cross-document boundary predictions).
    """
    from ddl_tpu.models.losses import next_token_cross_entropy

    logits = forward(params, tokens, cfg, mesh, segment_ids=segment_ids)
    if segment_ids is None:
        return next_token_cross_entropy(logits, tokens)
    boundary = segment_ids != jnp.roll(segment_ids, -1, axis=1)
    return next_token_cross_entropy(logits, tokens, extra_mask=boundary)
