"""Llama-style decoder LM — the flagship model fed by the ddl_tpu loader.

The reference framework carried no models (SURVEY §0: "no model code"); the
driver's pod-scale config ("Llama-3-8B pretrain loop fed solely by the ddl
TPU backend", BASELINE.json configs[4]) requires a real transformer training
loop on the consumer side.  This is a TPU-first functional implementation:

- pure init/apply functions over a params pytree (jit/grad/shard friendly,
  no framework state),
- bfloat16 activations by default (MXU-native), fp32 RMSNorm accumulations,
- RoPE, grouped-query attention, SwiGLU — the Llama-3 block structure,
- sequence parallelism via ring attention when the mesh has an ``sp`` axis,
- parameter PartitionSpecs for fsdp/tp sharding (GSPMD inserts the
  collectives).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

Params = Dict[str, Any]


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab: int = 256
    d_model: int = 128
    n_layers: int = 2
    n_heads: int = 4
    n_kv_heads: int = 2
    d_ff: int = 352
    max_seq: int = 512
    rope_theta: float = 500000.0  # Llama-3 base frequency
    norm_eps: float = 1e-5
    dtype: Any = jnp.bfloat16
    #: Storage dtype of the params pytree (fp32 master weights by
    #: default; bf16 halves param+optimizer HBM for memory-bound
    #: geometries — gradient accumulation stays exact either way, the
    #: train step accumulates in fp32).
    param_dtype: Any = jnp.float32
    #: Rematerialisation policy for the backward pass
    #: (:mod:`ddl_tpu.models.remat`): ``"none"`` | ``"full"`` (save only
    #: each layer's residual-stream input, recompute everything — the
    #: classic FLOPs-for-HBM trade that lets long-sequence/big-model
    #: configs fit a single chip) | ``"selective"`` (additionally save
    #: the attention outputs so the backward never re-runs the attention
    #: kernel — buys back most of full-remat's MFU loss) | ``"dots"``
    #: (save all non-batched matmul outputs).  Bools accepted for back
    #: compat: ``True`` == ``"full"``, ``False`` == ``"none"``.
    remat: Any = False
    # "auto": Pallas flash attention on TPU, dense elsewhere; "flash"/"dense"
    # force one path.  Sequence-parallel meshes always use ring attention.
    attn_impl: str = "auto"

    def __post_init__(self) -> None:
        if self.attn_impl not in ("auto", "flash", "dense"):
            raise ValueError(
                f"attn_impl must be 'auto', 'flash', or 'dense', "
                f"got {self.attn_impl!r}"
            )
        from ddl_tpu.models import remat as _remat

        _remat.resolve(self.remat)  # fail on junk at config build time

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    @staticmethod
    def llama3_8b() -> "LlamaConfig":
        """The reference-scale config (BASELINE.json configs[4])."""
        return LlamaConfig(
            vocab=128256, d_model=4096, n_layers=32, n_heads=32,
            n_kv_heads=8, d_ff=14336, max_seq=8192,
        )

    @staticmethod
    def llama_4b() -> "LlamaConfig":
        """The ≥4B fits-only-with-zero1 geometry (ISSUE 8): ~4.6B params
        at the 8B config's layer shape, fp32 master weights.  On the
        v5e-32 layout (dp=8 × fsdp=4, 16 GiB/chip) the persistent
        residents (params + grads + adam moments) bust the per-chip HBM
        with the optimizer state replicated over dp and fit with ~6 GiB
        of activation headroom under ``optimizer_sharding="zero1"`` —
        the accounting test (tests/test_optimizer.py) and
        ``tools/probe_opt.py`` both price exactly this config."""
        return LlamaConfig(
            vocab=32768, d_model=4096, n_layers=20, n_heads=32,
            n_kv_heads=8, d_ff=14336, max_seq=4096,
        )

    @staticmethod
    def tiny() -> "LlamaConfig":
        return LlamaConfig()


def _dense_init(k: jax.Array, fan_in: int, shape: Any, pdt: Any) -> jax.Array:
    """1/sqrt(fan_in)-scaled normal init in ``pdt`` storage — shared by
    every model family (moe reuses it like the norm/qkv blocks)."""
    return (
        jax.random.normal(k, shape, jnp.float32) / jnp.sqrt(fan_in)
    ).astype(pdt)


def init_params(cfg: LlamaConfig, key: jax.Array) -> Params:
    """Initialise a params pytree (``cfg.param_dtype`` storage; fp32
    master weights by default)."""
    keys = iter(jax.random.split(key, 4 + cfg.n_layers * 7))
    pdt = cfg.param_dtype

    def dense(k, fan_in, shape):
        return _dense_init(k, fan_in, shape, pdt)

    d, hd = cfg.d_model, cfg.head_dim
    layers = []
    for _ in range(cfg.n_layers):
        layers.append(
            {
                "attn_norm": jnp.ones((d,), pdt),
                "wq": dense(next(keys), d, (d, cfg.n_heads * hd)),
                "wk": dense(next(keys), d, (d, cfg.n_kv_heads * hd)),
                "wv": dense(next(keys), d, (d, cfg.n_kv_heads * hd)),
                "wo": dense(next(keys), cfg.n_heads * hd, (cfg.n_heads * hd, d)),
                "mlp_norm": jnp.ones((d,), pdt),
                "w_gate": dense(next(keys), d, (d, cfg.d_ff)),
                "w_up": dense(next(keys), d, (d, cfg.d_ff)),
                "w_down": dense(next(keys), cfg.d_ff, (cfg.d_ff, d)),
            }
        )
    return {
        "embed": dense(next(keys), d, (cfg.vocab, d)),
        "layers": layers,
        "final_norm": jnp.ones((d,), pdt),
        "lm_head": dense(next(keys), d, (d, cfg.vocab)),
    }


def param_shapes(cfg: LlamaConfig) -> Params:
    """Abstract (ShapeDtypeStruct) params pytree via ``eval_shape`` —
    the zero-FLOP input for optimizer HBM accounting
    (:func:`ddl_tpu.parallel.optimizer.hbm_accounting`, the
    fits-only-with-zero1 test, ``tools/probe_opt.py``): a 4B-param
    layout prices without materialising a single weight."""
    return jax.eval_shape(lambda: init_params(cfg, jax.random.key(0)))


def param_specs(cfg: LlamaConfig) -> Params:
    """PartitionSpecs mirroring init_params: fsdp shards the d_model-ish
    axis, tp shards heads / ffn-hidden — the standard Megatron layout
    realised declaratively (GSPMD inserts all-reduce/all-gather)."""
    layer = {
        "attn_norm": P(None),
        "wq": P("fsdp", "tp"),
        "wk": P("fsdp", "tp"),
        "wv": P("fsdp", "tp"),
        "wo": P("tp", "fsdp"),
        "mlp_norm": P(None),
        "w_gate": P("fsdp", "tp"),
        "w_up": P("fsdp", "tp"),
        "w_down": P("tp", "fsdp"),
    }
    return {
        "embed": P(None, "fsdp"),
        "layers": [dict(layer) for _ in range(cfg.n_layers)],
        "final_norm": P(None),
        "lm_head": P("fsdp", "tp"),
    }


def _rms_norm(x: jax.Array, gain: jax.Array, eps: float) -> jax.Array:
    xf = x.astype(jnp.float32)
    scale = jax.lax.rsqrt(jnp.mean(xf * xf, axis=-1, keepdims=True) + eps)
    return (xf * scale * gain).astype(x.dtype)


def _rope(x: jax.Array, positions: jax.Array, theta: float) -> jax.Array:
    """Rotary embedding; x: (B, T, H, D), positions: (T,)."""
    d_half = x.shape[-1] // 2
    freqs = theta ** (-jnp.arange(0, d_half, dtype=jnp.float32) / d_half)
    angles = positions[:, None].astype(jnp.float32) * freqs[None, :]  # (T, Dh)
    cos = jnp.cos(angles)[None, :, None, :].astype(x.dtype)
    sin = jnp.sin(angles)[None, :, None, :].astype(x.dtype)
    x1, x2 = x[..., :d_half], x[..., d_half:]
    return jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)


def forward(
    params: Params,
    tokens: jax.Array,
    cfg: LlamaConfig,
    mesh: Optional[Any] = None,
    segment_ids: Optional[jax.Array] = None,
) -> jax.Array:
    """Next-token logits, (B, T, vocab).

    With a mesh carrying an ``sp`` axis of size > 1, attention runs as
    sequence-parallel ring attention (K/V rotating over ICI); otherwise
    dense causal attention.  RoPE positions are global either way (the
    token axis is only *sharded*, never re-indexed).

    ``segment_ids`` (B, T): packed-pretraining batches — attention stays
    within each packed document (kernel-level masking; RoPE positions
    remain row-global, the common packed-training convention).
    """
    B, T = tokens.shape
    dt = cfg.dtype
    positions = jnp.arange(T)
    x = params["embed"].astype(dt)[tokens]  # (B, T, D)

    def layer_fn(x: jax.Array, layer: Params) -> jax.Array:
        return _layer_apply(
            layer, x, cfg, positions, mesh=mesh, segment_ids=segment_ids
        )

    # The configured remat policy (none/full/selective/dots —
    # ddl_tpu.models.remat): what the backward pass saves vs recomputes.
    from ddl_tpu.models import remat as _remat

    layer_fn = _remat.wrap(layer_fn, cfg.remat)
    for layer in params["layers"]:
        x = layer_fn(x, layer)

    x = _rms_norm(x, params["final_norm"], cfg.norm_eps)
    return (x @ params["lm_head"].astype(dt)).astype(jnp.float32)


def _attn_block(
    layer: Params,
    x: jax.Array,
    cfg: Any,
    positions: jax.Array,
    mesh: Optional[Any] = None,
    segment_ids: Optional[jax.Array] = None,
) -> jax.Array:
    """Attention sub-block (norm → qkv/rope → attention → wo residual)
    on the residual stream — the train-side twin of
    :func:`_attn_with_cache`, shared by the llama AND moe blocks (only
    the MLP that follows differs, so attention semantics cannot drift
    between families)."""
    from ddl_tpu.parallel.ring_attention import attention

    B, T = x.shape[:2]
    dt = x.dtype
    h = _rms_norm(x, layer["attn_norm"], cfg.norm_eps)
    q, k, v = _attn_qkv(layer, h, cfg, positions)
    # GQA k/v stay compact: expansion happens inside the attention
    # block, so ring attention rotates 1/rep of the bytes over ICI.
    rep = cfg.n_heads // cfg.n_kv_heads
    attn = attention(
        q, k, v, mesh=mesh, impl=cfg.attn_impl, causal=True,
        kv_repeat=rep, segment_ids=segment_ids,
    )
    # Saveable under remat="selective" (identity otherwise): the
    # backward pass then never re-runs the attention kernel.
    from ddl_tpu.models import remat as _remat

    attn = _remat.tag_attn_out(attn)
    return x + attn.reshape(B, T, -1) @ layer["wo"].astype(dt)


def _layer_apply(
    layer: Params,
    x: jax.Array,
    cfg: LlamaConfig,
    positions: jax.Array,
    mesh: Optional[Any] = None,
    segment_ids: Optional[jax.Array] = None,
) -> jax.Array:
    """One transformer block on the residual stream — the single layer
    body shared by :func:`forward` and the pipeline-parallel
    :func:`forward_pp` (same math, so pp/non-pp cannot diverge)."""
    x = _attn_block(
        layer, x, cfg, positions, mesh=mesh, segment_ids=segment_ids
    )
    return _mlp_block(layer, x, cfg)


def _attn_qkv(layer: Params, h: jax.Array, cfg: LlamaConfig,
              positions: jax.Array, n_heads: Optional[int] = None,
              n_kv_heads: Optional[int] = None):
    """Project + rope one block's q/k/v (shared by train, decode and the
    tp-resident pipeline stage, which passes its LOCAL head counts —
    column-sharded projections yield contiguous head blocks)."""
    B, T = h.shape[:2]
    dt = h.dtype
    nh = cfg.n_heads if n_heads is None else n_heads
    nkv = cfg.n_kv_heads if n_kv_heads is None else n_kv_heads
    q = (h @ layer["wq"].astype(dt)).reshape(B, T, nh, cfg.head_dim)
    k = (h @ layer["wk"].astype(dt)).reshape(B, T, nkv, cfg.head_dim)
    v = (h @ layer["wv"].astype(dt)).reshape(B, T, nkv, cfg.head_dim)
    return (
        _rope(q, positions, cfg.rope_theta),
        _rope(k, positions, cfg.rope_theta),
        v,
    )


def _swiglu(layer: Params, h: jax.Array) -> jax.Array:
    """The SwiGLU core (no norm, no residual) — shared by the plain
    block and the tp-resident stage (whose row-sharded ``w_down`` makes
    this a PARTIAL sum completed by a psum)."""
    dt = h.dtype
    gate = jax.nn.silu(h @ layer["w_gate"].astype(dt))
    up = h @ layer["w_up"].astype(dt)
    return (gate * up) @ layer["w_down"].astype(dt)


def _mlp_block(layer: Params, x: jax.Array, cfg: LlamaConfig) -> jax.Array:
    """SwiGLU MLP sub-block with residual (shared by train and decode)."""
    return x + _swiglu(layer, _rms_norm(x, layer["mlp_norm"], cfg.norm_eps))


def init_cache(cfg: LlamaConfig, batch: int, max_len: int) -> Params:
    """Per-layer KV cache buffers for autoregressive decoding."""
    shape = (batch, max_len, cfg.n_kv_heads, cfg.head_dim)
    return {
        "k": jnp.zeros((cfg.n_layers,) + shape, cfg.dtype),
        "v": jnp.zeros((cfg.n_layers,) + shape, cfg.dtype),
    }


def forward_with_cache(
    params: Params,
    tokens: jax.Array,
    cfg: LlamaConfig,
    cache: Params,
    pos: jax.Array,
    last_only: bool = False,
) -> tuple[jax.Array, Params]:
    """Process ``tokens`` (B, T) starting at position ``pos`` against a KV
    cache (prefill: T = prompt length at pos 0; decode: T = 1).

    Returns (logits, updated cache); logits are (B, T, vocab), or
    (B, 1, vocab) with ``last_only`` (prefill wants only the frontier —
    full-prompt fp32 logits are ~4 GB at llama3_8b/8k).  Attention is
    dense over the cache with a causal-position mask — decode steps are
    matmul-thin so flash buys nothing there — and attends the COMPACT
    GQA cache via a grouped einsum (no rep-expanded cache copy in the
    bandwidth-bound decode hot path).  The cache length is static
    (``init_cache`` max_len) for jit-stable shapes.
    """
    dt = cfg.dtype
    positions = pos + jnp.arange(tokens.shape[1])
    cache_idx = jnp.arange(cache["k"].shape[2])
    x = params["embed"].astype(dt)[tokens]

    # The stacked cache buffers thread through the layers as one value
    # chain (each layer writes only its new-token slot), so XLA keeps
    # the update in place inside the decode scan — see _attn_with_cache.
    k_all, v_all = cache["k"], cache["v"]
    for li, layer in enumerate(params["layers"]):
        x, k_all, v_all = _attn_with_cache(
            layer, x, cfg, k_all, v_all, li, pos, positions, cache_idx,
        )
        x = _mlp_block(layer, x, cfg)

    x = _rms_norm(x, params["final_norm"], cfg.norm_eps)
    if last_only:
        x = x[:, -1:]
    logits = (x @ params["lm_head"].astype(dt)).astype(jnp.float32)
    return logits, {"k": k_all, "v": v_all}


def _attn_with_cache(
    layer: Params,
    x: jax.Array,
    cfg: Any,
    k_all: jax.Array,
    v_all: jax.Array,
    li: int,
    pos: jax.Array,
    positions: jax.Array,
    cache_idx: jax.Array,
):
    """Attention sub-block (norm → qkv → cache update → GQA attention →
    residual) against a static-length KV cache — shared by llama and moe
    decode (same cache math, different MLP sub-block).  Returns
    (x_after_attn, k_all, v_all).

    ``k_all``/``v_all`` are the STACKED (L, B, len, kv, hd) cache
    buffers; the update writes ONLY the (B, T, kv, hd) new-token slot
    at (li, :, pos) and the buffers thread through layer after layer as
    one value chain, so inside the decode scan XLA updates the cache
    in place instead of materializing a fresh full cache per step — at
    B=64/1.4B-params the stack-per-step layout cost ~4x the mandatory
    HBM traffic and throughput stopped scaling with batch.

    Grouped-query attention attends the COMPACT cache via a grouped
    einsum (q regrouped per KV head, scores (B, Hkv, rep, T, L)) — no
    rep-expanded cache copy in the bandwidth-bound decode hot path.
    """
    B, T = x.shape[:2]
    dt = x.dtype
    rep = cfg.n_heads // cfg.n_kv_heads
    scale = 1.0 / (cfg.head_dim**0.5)
    h = _rms_norm(x, layer["attn_norm"], cfg.norm_eps)
    q, k, v = _attn_qkv(layer, h, cfg, positions)
    k_all = jax.lax.dynamic_update_slice(
        k_all, k.astype(dt)[None], (li, 0, pos, 0, 0)
    )
    v_all = jax.lax.dynamic_update_slice(
        v_all, v.astype(dt)[None], (li, 0, pos, 0, 0)
    )
    ck, cv = k_all[li], v_all[li]  # fused slice reads of the updated chain
    qg = q.reshape(B, T, cfg.n_kv_heads, rep, cfg.head_dim)
    s = jnp.einsum("bqkrd,bskd->bkrqs", qg, ck) * scale
    # Causal over absolute positions; cache slots past the frontier
    # (zeros) are masked the same way.
    mask = cache_idx[None, :] > positions[:, None]  # (T, L)
    s = jnp.where(mask[None, None, None], -1e30, s)
    p = jax.nn.softmax(s.astype(jnp.float32), axis=-1).astype(dt)
    attn = jnp.einsum("bkrqs,bskd->bqkrd", p, cv)
    return x + attn.reshape(B, T, -1) @ layer["wo"].astype(dt), k_all, v_all


def generate(
    params: Params,
    prompt: jax.Array,
    cfg: LlamaConfig,
    max_new_tokens: int,
    temperature: float = 0.0,
    key: Optional[jax.Array] = None,
    top_k: Optional[int] = None,
    top_p: Optional[float] = None,
    eos_id: Optional[int] = None,
) -> jax.Array:
    """Autoregressive generation: greedy (``temperature == 0``) or
    temperature sampling, optionally filtered by ``top_k`` and/or
    nucleus ``top_p`` (temperature applied first, then the filters).
    With ``eos_id``, a row that emits it keeps emitting ``eos_id`` for
    the remaining positions (static shapes; truncate at the first EOS).
    Returns (B, prompt_len + max_new_tokens).

    Sampling (``temperature > 0``) REQUIRES an explicit ``key`` — a
    silent default would make "sampled" generation deterministically
    identical across calls, an easy misuse trap for an inference API.

    Prefill runs the whole prompt in ONE cached forward (full-width
    matmuls on the MXU); decode steps run under ``lax.scan`` with a
    static-shape KV cache — no recompilation per step, no Python loop.
    """
    return _generate(
        forward_with_cache, init_cache, params, prompt, cfg,
        max_new_tokens, temperature, key, top_k=top_k, top_p=top_p,
        eos_id=eos_id,
    )


def _sample_filter(
    logits_t: jax.Array, top_k: Optional[int], top_p: Optional[float]
) -> jax.Array:
    """Mask logits for top-k / nucleus (top-p) sampling — static-shape
    ops only, safe inside the decode scan.

    top-k keeps the k highest logits; top-p keeps the smallest prefix
    of the probability-sorted vocab whose mass reaches ``top_p`` (the
    first token is always kept, so the filter can never empty the
    support).  Both filters compose (applied in that order, the
    conventional stacking)."""
    if top_k is not None:
        kth = jax.lax.top_k(logits_t, top_k)[0][..., -1:]
        logits_t = jnp.where(logits_t < kth, -jnp.inf, logits_t)
    if top_p is not None:
        sorted_logits = jnp.sort(logits_t, axis=-1)[..., ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # Keep ranks whose PRECEDING mass is < top_p (rank 0 always).
        keep_sorted = jnp.concatenate(
            [jnp.ones_like(cum[..., :1], bool), cum[..., :-1] < top_p],
            axis=-1,
        )
        # Threshold logit: the smallest kept logit per row.
        cutoff = jnp.min(
            jnp.where(keep_sorted, sorted_logits, jnp.inf),
            axis=-1, keepdims=True,
        )
        logits_t = jnp.where(logits_t < cutoff, -jnp.inf, logits_t)
    return logits_t


def _generate(
    fwd_cache: Any,
    init_cache_fn: Any,
    params: Params,
    prompt: jax.Array,
    cfg: Any,
    max_new_tokens: int,
    temperature: float,
    key: Optional[jax.Array],
    top_k: Optional[int] = None,
    top_p: Optional[float] = None,
    eos_id: Optional[int] = None,
) -> jax.Array:
    """Family-agnostic generation core (llama and moe share it): prefill
    via one cached forward, then ``lax.scan`` decode steps over a
    static-shape cache.  ``fwd_cache(params, tokens, cfg, cache, pos,
    last_only=...) -> (logits, cache)`` and ``init_cache_fn(cfg, B, L)``
    are the family's decode hooks.  ``top_k``/``top_p`` filter the
    sampling distribution (:func:`_sample_filter`); both require
    ``temperature > 0``.

    ``eos_id``: once a row emits it, every later position in that row
    is ``eos_id`` too (the scan's shapes are static so the compute
    still runs; finished rows are masked, the standard TPU serving
    semantics — the caller truncates at the first EOS)."""
    if (top_k is not None or top_p is not None) and temperature <= 0.0:
        raise ValueError(
            "top_k/top_p filter the SAMPLING distribution — they have "
            "no effect on greedy decoding; pass temperature > 0"
        )
    # Validate filter values eagerly (static Python ints), before any
    # prefill compute or scan tracing is spent.
    if top_k is not None and top_k < 1:
        raise ValueError(f"top_k must be >= 1, got {top_k}")
    if top_p is not None and not 0.0 < top_p <= 1.0:
        raise ValueError(f"top_p must be in (0, 1], got {top_p}")
    if eos_id is not None and not 0 <= eos_id < cfg.vocab:
        # An out-of-range id can never be emitted, silently disabling
        # EOS handling (tokenizer/model vocab mismatch) — fail loudly.
        raise ValueError(
            f"eos_id {eos_id} outside the model vocab [0, {cfg.vocab})"
        )
    B, P_len = prompt.shape
    if max_new_tokens <= 0:
        return prompt
    total = P_len + max_new_tokens
    cache = init_cache_fn(cfg, B, total)
    logits, cache = fwd_cache(
        params, prompt, cfg, cache, jnp.int32(0), last_only=True
    )
    last = logits[:, -1]
    if key is None:
        if temperature > 0.0:
            raise ValueError(
                "temperature sampling requires an explicit PRNG key: "
                "pass key=jax.random.key(seed) (every call with the "
                "default key would sample the SAME tokens)"
            )
        key = jax.random.key(0)  # greedy path: keys are structural only

    def pick(logits_t, k):
        if temperature <= 0.0:
            return jnp.argmax(logits_t, axis=-1).astype(prompt.dtype)
        # Temperature first, then filters — top-p measures mass of the
        # TEMPERED distribution (the conventional ordering).
        filtered = _sample_filter(logits_t / temperature, top_k, top_p)
        return jax.random.categorical(k, filtered, axis=-1).astype(
            prompt.dtype
        )

    def step(carry, k):
        cache, last_logits, pos, done = carry
        tok = pick(last_logits, k)
        if eos_id is not None:
            tok = jnp.where(done, jnp.asarray(eos_id, tok.dtype), tok)
            done = done | (tok == eos_id)
        logits_t, cache = fwd_cache(
            params, tok[:, None], cfg, cache, pos
        )
        return (cache, logits_t[:, 0], pos + 1, done), tok

    # Scan max_new_tokens - 1 steps; the final token needs no forward of
    # its own (its logits would be discarded).
    keys = jax.random.split(key, max_new_tokens)
    done0 = jnp.zeros((B,), bool)
    (_, last, _, done), new_tokens = jax.lax.scan(
        step, (cache, last, jnp.int32(P_len), done0), keys[:-1],
    )
    final = pick(last, keys[-1])
    if eos_id is not None:
        final = jnp.where(done, jnp.asarray(eos_id, final.dtype), final)
    new = jnp.concatenate(
        [new_tokens.swapaxes(0, 1), final[:, None]], axis=1
    ) if max_new_tokens > 1 else final[:, None]
    return jnp.concatenate([prompt, new], axis=1)


def next_token_loss(
    params: Params,
    tokens: jax.Array,
    cfg: LlamaConfig,
    mesh: Optional[Any] = None,
    segment_ids: Optional[jax.Array] = None,
) -> jax.Array:
    """Mean cross-entropy of next-token prediction over (B, T) tokens.

    Targets are ``roll(tokens, -1)`` with the final position masked rather
    than a ``[:-1]`` slice — the sequence axis keeps its full length, so it
    stays evenly shardable over ``sp``.

    With ``segment_ids`` (packed batches), attention is segment-masked
    and the loss additionally drops positions whose next token belongs to
    a different document (the cross-document boundary predictions).
    """
    from ddl_tpu.models.losses import next_token_cross_entropy

    logits = forward(params, tokens, cfg, mesh, segment_ids=segment_ids)
    return next_token_cross_entropy(logits, tokens, segment_ids=segment_ids)


# -- pipeline parallelism ----------------------------------------------------


def stage_params(
    params: Params, n_stages: int, n_chunks: int = 1
) -> Params:
    """Rearrange a :func:`init_params` pytree for pipeline parallelism.

    The ``n_layers`` per-layer dicts regroup into ``n_stages`` equal
    stages and stack into leaves with leading ``(S, L/S)`` axes —
    :func:`ddl_tpu.parallel.pipeline_apply`'s stacked-stage layout, with
    the S axis sharded over ``pp`` so each device stores only its own
    stage's layers.  ``n_chunks > 1`` builds the interleaved
    ``(S, V, L/(S·V))`` layout for ``schedule="1f1b"`` (device d chunk c
    holds global stage c·S+d).  Embedding, final norm and lm head stay
    outside the pipe (they run replicated over pp, before/after the
    schedule).

    Inverse-free by design: training checkpoints save THIS layout; the
    non-pp layout is only an initialization convenience.
    """
    from ddl_tpu.parallel.pipeline import stack_layer_stages

    return {
        "embed": params["embed"],
        "stages": stack_layer_stages(
            params["layers"], n_stages, n_chunks=n_chunks
        ),
        "final_norm": params["final_norm"],
        "lm_head": params["lm_head"],
    }


def pp_param_specs(
    cfg: LlamaConfig, axis: str = "pp", n_chunks: int = 1
) -> Params:
    """PartitionSpecs for the :func:`stage_params` layout: ``pp`` shards
    the stage axis (at-rest storage is one stage per pp group), the
    chunk (1f1b only) and per-stage layer axes are unsharded, and the
    trailing axes keep the Megatron fsdp/tp layout of
    :func:`param_specs`."""
    from ddl_tpu.parallel.pipeline import stage_spec_tree

    return {
        "embed": P(None, "fsdp"),
        "stages": stage_spec_tree(
            param_specs(cfg)["layers"][0], axis, n_chunks=n_chunks
        ),
        "final_norm": P(None),
        "lm_head": P("fsdp", "tp"),
    }


def _layer_apply_tp_local(
    layer: Params,
    x: jax.Array,
    cfg: LlamaConfig,
    positions: jax.Array,
    tp_axis: str,
    n_tp: int,
) -> jax.Array:
    """One transformer block on LOCAL tensor-parallel weight shards
    (Megatron layout, explicit collectives) — the tp-resident pipeline
    stage body.  ``wq/wk/wv`` are column-sharded (each device computes
    its ``n_heads/tp`` heads end-to-end), ``wo`` row-sharded (partial
    residual contributions summed with ``psum``); ``w_gate/w_up``
    column-sharded (``d_ff/tp`` hidden), ``w_down`` row-sharded
    (``psum``).  Two psums per layer — the classic Megatron count —
    riding ICI inside the pipeline's shard_map.
    """
    from jax import lax

    from ddl_tpu.parallel.ring_attention import attention

    B, T = x.shape[:2]
    dt = x.dtype
    lh = cfg.n_heads // n_tp  # local query heads
    lkv = cfg.n_kv_heads // n_tp  # local KV heads
    h = _rms_norm(x, layer["attn_norm"], cfg.norm_eps)
    # The SAME projection/rope/SwiGLU helpers as the plain block — only
    # the head counts and the two completing psums differ, so tp-resident
    # numerics cannot drift from forward's.
    q, k, v = _attn_qkv(
        layer, h, cfg, positions, n_heads=lh, n_kv_heads=lkv
    )
    attn = attention(
        q, k, v, mesh=None, impl=cfg.attn_impl, causal=True,
        kv_repeat=lh // lkv,
    )
    from ddl_tpu.models import remat as _remat

    attn = _remat.tag_attn_out(attn)  # saveable under remat="selective"
    # Row-sharded wo: each device's head block contributes a PARTIAL
    # output projection; the psum completes the sum over heads.
    x = x + lax.psum(
        attn.reshape(B, T, -1) @ layer["wo"].astype(dt), tp_axis
    )
    h = _rms_norm(x, layer["mlp_norm"], cfg.norm_eps)
    return x + lax.psum(_swiglu(layer, h), tp_axis)


#: Per-stage inner PartitionSpecs for tp-RESIDENT pipeline stages
#: (leading per-stage layer axis unsharded; Megatron column/row layout
#: on the weight dims).  Only ``tp`` appears: fsdp still gathers at the
#: shard_map boundary (compute needs full d_model rows), it shards
#: at-rest storage only.
_TP_STAGE_SPECS = {
    "attn_norm": P(None, None),
    "wq": P(None, None, "tp"),
    "wk": P(None, None, "tp"),
    "wv": P(None, None, "tp"),
    "wo": P(None, "tp", None),
    "mlp_norm": P(None, None),
    "w_gate": P(None, None, "tp"),
    "w_up": P(None, None, "tp"),
    "w_down": P(None, "tp", None),
}


def forward_pp(
    params: Params,
    tokens: jax.Array,
    cfg: LlamaConfig,
    mesh: Any,
    n_microbatches: int,
    axis: str = "pp",
    schedule: str = "gpipe",
    n_chunks: "int | None" = None,
) -> jax.Array:
    """Next-token logits with the transformer blocks pipelined over the
    mesh's ``axis`` (microbatch schedule per ``schedule`` — gpipe, or
    the lower-bubble interleaved 1f1b with ``stage_params(...,
    n_chunks=)`` weights; :func:`ddl_tpu.parallel.pipeline_apply`).

    ``params`` is the :func:`stage_params` layout.  Each pipeline stage
    scans its ``L/S`` layers over the residual stream; attention inside a
    stage is single-device (dense or flash) — sequence parallelism does
    not compose with pp in this schedule (``segment_ids`` likewise
    unsupported here; use :func:`forward` for packed batches).

    Working-memory model (the honest cost account): each device holds
    its own stage's weights for the whole step, plus one microbatch's
    activations times the live scan depth.  With a ``tp`` axis in the
    mesh (and head counts divisible by it), stages run TENSOR-PARALLEL
    RESIDENT: weight shards stay local inside the shard_map and each
    layer completes with two explicit psums over tp (Megatron), so peak
    per-device weight memory is ``params/(S·tp)``.  Without tp it is
    ``params/S`` — fsdp on the trailing axes shards at-rest STORAGE
    only (compute needs full d_model rows, so it gathers at the
    shard_map boundary once per step).  At 8B, S=4: ~4 GiB bf16
    resident per device; S=4 × tp=4: ~1 GiB.
    """
    B, T = tokens.shape
    dt = cfg.dtype
    positions = jnp.arange(T)
    x = params["embed"].astype(dt)[tokens]

    n_tp = (
        mesh.shape["tp"]
        if "tp" in mesh.axis_names
        and axis in mesh.axis_names
        and mesh.shape.get(axis, 1) > 1  # pp=1 takes the sequential
        # fallback, which runs stage_fn outside shard_map where the
        # tp psums cannot resolve
        else 1
    )
    tp_resident = (
        n_tp > 1
        and cfg.n_heads % n_tp == 0
        and cfg.n_kv_heads % n_tp == 0
        and cfg.d_ff % n_tp == 0
    )

    if tp_resident:
        def one_layer(x: jax.Array, layer: Params) -> jax.Array:
            return _layer_apply_tp_local(
                layer, x, cfg, positions, "tp", n_tp
            )
    else:
        def one_layer(x: jax.Array, layer: Params) -> jax.Array:
            return _layer_apply(layer, x, cfg, positions, mesh=None)

    from ddl_tpu.models import remat as _remat

    layer_fn = _remat.wrap(one_layer, cfg.remat)

    def stage_fn(stage: Params, h: jax.Array) -> jax.Array:
        out, _ = jax.lax.scan(
            lambda c, lyr: (layer_fn(c, lyr), None), h, stage
        )
        return out

    from ddl_tpu.parallel.pipeline import pipeline_apply

    x = pipeline_apply(
        params["stages"], x, stage_fn, mesh, n_microbatches, axis=axis,
        stage_param_specs=_TP_STAGE_SPECS if tp_resident else None,
        schedule=schedule, n_chunks=n_chunks,
    )
    x = _rms_norm(x, params["final_norm"], cfg.norm_eps)
    return (x @ params["lm_head"].astype(dt)).astype(jnp.float32)


def next_token_loss_pp(
    params: Params,
    tokens: jax.Array,
    cfg: LlamaConfig,
    mesh: Any,
    n_microbatches: int,
    axis: str = "pp",
    schedule: str = "gpipe",
    n_chunks: "int | None" = None,
) -> jax.Array:
    """:func:`next_token_loss` over the pipelined forward — the loss to
    hand :func:`ddl_tpu.parallel.train.make_train_step` (or the Trainer)
    for a pp-axis mesh; backward runs the reverse schedule through
    ``jax.grad`` automatically."""
    from ddl_tpu.models.losses import next_token_cross_entropy

    logits = forward_pp(
        params, tokens, cfg, mesh, n_microbatches, axis=axis,
        schedule=schedule, n_chunks=n_chunks,
    )
    return next_token_cross_entropy(logits, tokens)
