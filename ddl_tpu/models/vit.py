"""Vision Transformer — the image-classification model family.

Closes the loop on the ImageNet/WebDataset ingest configs (BASELINE
configs[1-2]): :class:`ddl_tpu.readers.WebDatasetProducer` serves
``[pixels..., label]`` rows and this model trains on them through the
same GSPMD train-step factory and attention dispatcher as the language
models (non-causal attention — flash on TPU, dense elsewhere, ring
attention under an ``sp`` mesh axis for very long patch sequences).

TPU-first like ``models/llama.py``: pure init/apply over a params pytree,
bf16 activations with fp32 norm accumulations, convolution-free patch
embedding (reshape + one matmul — MXU-native), learned position
embeddings, mean-pooled head.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

Params = Dict[str, Any]


@dataclasses.dataclass(frozen=True)
class ViTConfig:
    image_size: int = 32
    patch_size: int = 4
    n_channels: int = 3
    d_model: int = 128
    n_layers: int = 4
    n_heads: int = 4
    d_ff: int = 512
    n_classes: int = 10
    norm_eps: float = 1e-5
    dtype: Any = jnp.bfloat16
    attn_impl: str = "auto"  # "auto" | "flash" | "dense"

    def __post_init__(self) -> None:
        if self.image_size % self.patch_size:
            raise ValueError(
                f"patch_size {self.patch_size} must divide image_size "
                f"{self.image_size}"
            )
        if self.attn_impl not in ("auto", "flash", "dense"):
            raise ValueError(f"bad attn_impl {self.attn_impl!r}")

    @property
    def n_patches(self) -> int:
        return (self.image_size // self.patch_size) ** 2

    @property
    def patch_dim(self) -> int:
        return self.patch_size * self.patch_size * self.n_channels

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads


def init_params(cfg: ViTConfig, key: jax.Array) -> Params:
    keys = iter(jax.random.split(key, 4 + cfg.n_layers * 7))

    def dense(k, fan_in, shape):
        return jax.random.normal(k, shape, jnp.float32) / jnp.sqrt(fan_in)

    d = cfg.d_model
    layers = []
    for _ in range(cfg.n_layers):
        layers.append(
            {
                "attn_norm": jnp.ones((d,), jnp.float32),
                "wq": dense(next(keys), d, (d, d)),
                "wk": dense(next(keys), d, (d, d)),
                "wv": dense(next(keys), d, (d, d)),
                "wo": dense(next(keys), d, (d, d)),
                "mlp_norm": jnp.ones((d,), jnp.float32),
                "w_up": dense(next(keys), d, (d, cfg.d_ff)),
                "w_down": dense(next(keys), cfg.d_ff, (cfg.d_ff, d)),
            }
        )
    return {
        "patch_embed": dense(next(keys), cfg.patch_dim, (cfg.patch_dim, d)),
        "pos_embed": 0.02
        * jax.random.normal(next(keys), (cfg.n_patches, d), jnp.float32),
        "layers": layers,
        "final_norm": jnp.ones((d,), jnp.float32),
        "head": dense(next(keys), d, (d, cfg.n_classes)),
    }


def param_shapes(cfg: ViTConfig) -> Params:
    """Abstract params pytree via ``eval_shape`` — the optimizer HBM
    accounting input (``parallel.optimizer.hbm_accounting``,
    ``tools/probe_opt.py``)."""
    return jax.eval_shape(lambda: init_params(cfg, jax.random.key(0)))


def param_specs(cfg: ViTConfig) -> Params:
    """fsdp shards the model axis, tp shards heads/ffn (Megatron layout)."""
    layer = {
        "attn_norm": P(None),
        "wq": P("fsdp", "tp"),
        "wk": P("fsdp", "tp"),
        "wv": P("fsdp", "tp"),
        "wo": P("tp", "fsdp"),
        "mlp_norm": P(None),
        "w_up": P("fsdp", "tp"),
        "w_down": P("tp", "fsdp"),
    }
    return {
        "patch_embed": P(None, "fsdp"),
        "pos_embed": P(None, "fsdp"),
        "layers": [dict(layer) for _ in range(cfg.n_layers)],
        "final_norm": P(None),
        "head": P("fsdp", None),
    }


def _rms_norm(x: jax.Array, gain: jax.Array, eps: float) -> jax.Array:
    xf = x.astype(jnp.float32)
    scale = jax.lax.rsqrt(jnp.mean(xf * xf, axis=-1, keepdims=True) + eps)
    return (xf * scale * gain).astype(x.dtype)


def patchify(images: jax.Array, cfg: ViTConfig) -> jax.Array:
    """(B, H, W, C) → (B, n_patches, patch_dim) by pure reshapes."""
    B = images.shape[0]
    p = cfg.patch_size
    g = cfg.image_size // p
    x = images.reshape(B, g, p, g, p, cfg.n_channels)
    x = x.transpose(0, 1, 3, 2, 4, 5)  # (B, g, g, p, p, C)
    return x.reshape(B, g * g, cfg.patch_dim)


def _layer_apply(
    layer: Params,
    x: jax.Array,
    cfg: ViTConfig,
    mesh: Optional[Any] = None,
) -> jax.Array:
    """One encoder block on the residual stream — shared by
    :func:`forward` and the pipelined :func:`forward_pp` (one body, so
    the two paths cannot diverge)."""
    from ddl_tpu.parallel.ring_attention import attention

    B, T = x.shape[:2]
    dt = x.dtype
    h = _rms_norm(x, layer["attn_norm"], cfg.norm_eps)
    q = (h @ layer["wq"].astype(dt)).reshape(B, T, cfg.n_heads,
                                             cfg.head_dim)
    k = (h @ layer["wk"].astype(dt)).reshape(B, T, cfg.n_heads,
                                             cfg.head_dim)
    v = (h @ layer["wv"].astype(dt)).reshape(B, T, cfg.n_heads,
                                             cfg.head_dim)
    attn = attention(
        q, k, v, mesh=mesh, impl=cfg.attn_impl, causal=False
    )
    x = x + attn.reshape(B, T, -1) @ layer["wo"].astype(dt)

    h = _rms_norm(x, layer["mlp_norm"], cfg.norm_eps)
    return x + jax.nn.gelu(h @ layer["w_up"].astype(dt)) @ layer[
        "w_down"
    ].astype(dt)


def _embed(params: Params, images: jax.Array, cfg: ViTConfig) -> jax.Array:
    """Patchify + project + position-embed (shared by both forwards)."""
    dt = cfg.dtype
    if images.ndim == 2:  # the loader's flattened pixel rows
        images = images.reshape(
            -1, cfg.image_size, cfg.image_size, cfg.n_channels
        )
    x = patchify(images.astype(dt), cfg) @ params["patch_embed"].astype(dt)
    return x + params["pos_embed"].astype(dt)[None]


def _head(params: Params, x: jax.Array, cfg: ViTConfig) -> jax.Array:
    """Final norm + mean pool + classification head (shared)."""
    x = _rms_norm(x, params["final_norm"], cfg.norm_eps)
    pooled = jnp.mean(x.astype(jnp.float32), axis=1)  # (B, d)
    return pooled @ params["head"]


def forward(
    params: Params,
    images: jax.Array,
    cfg: ViTConfig,
    mesh: Optional[Any] = None,
) -> jax.Array:
    """Class logits (B, n_classes); images (B, H, W, C) or flat
    (B, H*W*C)."""
    x = _embed(params, images, cfg)
    for layer in params["layers"]:
        x = _layer_apply(layer, x, cfg, mesh=mesh)
    return _head(params, x, cfg)


# -- pipeline parallelism ----------------------------------------------------


def stage_params(params: Params, n_stages: int) -> Params:
    """Regroup an :func:`init_params` pytree for pipeline parallelism —
    the same ``(S, L/S)`` stage layout as
    ``models.llama.stage_params`` (shared
    ``parallel.pipeline.stack_layer_stages``); embed and head stay
    outside the pipe."""
    from ddl_tpu.parallel.pipeline import stack_layer_stages

    return {
        "patch_embed": params["patch_embed"],
        "pos_embed": params["pos_embed"],
        "stages": stack_layer_stages(params["layers"], n_stages),
        "final_norm": params["final_norm"],
        "head": params["head"],
    }


def pp_param_specs(cfg: ViTConfig, axis: str = "pp") -> Params:
    """PartitionSpecs for the :func:`stage_params` layout."""
    from ddl_tpu.parallel.pipeline import stage_spec_tree

    return {
        "patch_embed": P(None, "fsdp"),
        "pos_embed": P(None, "fsdp"),
        "stages": stage_spec_tree(param_specs(cfg)["layers"][0], axis),
        "final_norm": P(None),
        "head": P("fsdp", None),
    }


def forward_pp(
    params: Params,
    images: jax.Array,
    cfg: ViTConfig,
    mesh: Any,
    n_microbatches: int,
    axis: str = "pp",
) -> jax.Array:
    """Class logits with the encoder blocks pipelined over ``axis``
    (GPipe schedule) — the image-family twin of
    ``models.llama.forward_pp``; attention inside a stage is
    single-device."""
    from ddl_tpu.parallel.pipeline import pipeline_apply

    x = _embed(params, images, cfg)

    def stage_fn(stage: Params, h: jax.Array) -> jax.Array:
        out, _ = jax.lax.scan(
            lambda c, lyr: (_layer_apply(lyr, c, cfg), None), h, stage
        )
        return out

    x = pipeline_apply(
        params["stages"], x, stage_fn, mesh, n_microbatches, axis=axis
    )
    return _head(params, x, cfg)


def classification_loss_pp(
    params: Params,
    batch: Any,
    cfg: ViTConfig,
    mesh: Any,
    n_microbatches: int,
    axis: str = "pp",
) -> jax.Array:
    """:func:`classification_loss` over the pipelined forward."""
    from ddl_tpu.models.losses import cross_entropy

    pixels, labels = batch[0], batch[1]
    logits = forward_pp(params, pixels, cfg, mesh, n_microbatches, axis=axis)
    return cross_entropy(logits, labels.reshape(-1))


def classification_loss(
    params: Params,
    batch: Any,
    cfg: ViTConfig,
    mesh: Optional[Any] = None,
) -> jax.Array:
    """Mean cross-entropy over the loader's ``(pixels, label)`` columns."""
    from ddl_tpu.models.losses import cross_entropy

    pixels, labels = batch[0], batch[1]
    logits = forward(params, pixels, cfg, mesh)
    return cross_entropy(logits, labels.reshape(-1))


def accuracy(
    params: Params, batch: Any, cfg: ViTConfig,
    mesh: Optional[Any] = None,
) -> jax.Array:
    pixels, labels = batch[0], batch[1]
    pred = jnp.argmax(forward(params, pixels, cfg, mesh), axis=-1)
    return jnp.mean((pred == labels.reshape(-1).astype(jnp.int32)))
