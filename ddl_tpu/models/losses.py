"""Shared loss primitives for the model families.

One cross-entropy implementation for llama/moe/vit: gather-then-logsumexp,
NOT log_softmax-then-gather — log_softmax would materialise a second full
(…, vocab) fp32 array only to keep one element per row, while logsumexp
is a fusable reduction (measured ~2ms/step on the v5e bench geometry).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def cross_entropy(
    logits: jax.Array,
    targets: jax.Array,
    mask: Optional[jax.Array] = None,
) -> jax.Array:
    """Mean CE of integer ``targets`` under ``logits`` over the last axis.

    ``logits``: (..., n_classes); ``targets``: (...) int; ``mask``
    (optional, broadcastable to targets' shape): positions with mask 0
    are excluded from the mean.
    """
    sel = jnp.take_along_axis(
        logits, targets[..., None].astype(jnp.int32), axis=-1
    )[..., 0]
    nll = jax.nn.logsumexp(logits, axis=-1) - sel
    if mask is None:
        return jnp.mean(nll)
    mask = jnp.broadcast_to(mask.astype(nll.dtype), nll.shape)
    return jnp.sum(nll * mask) / jnp.maximum(jnp.sum(mask), 1.0)


def next_token_cross_entropy(
    logits: jax.Array,
    tokens: jax.Array,
    extra_mask: Optional[jax.Array] = None,
    segment_ids: Optional[jax.Array] = None,
) -> jax.Array:
    """Mean CE of next-token prediction over (B, T) ``tokens``.

    Targets are ``roll(tokens, -1)`` with the final position masked
    rather than a ``[:-1]`` slice — the sequence axis keeps its full
    length, so it stays evenly shardable over ``sp``.  ``extra_mask``
    (B, T) True drops additional positions.  ``segment_ids`` (packed
    batches) drops cross-document boundary positions, where the "next
    token" belongs to another document — the one boundary convention
    shared by every model family.
    """
    T = tokens.shape[1]
    targets = jnp.roll(tokens, -1, axis=1)
    mask = jnp.broadcast_to((jnp.arange(T) < T - 1)[None, :], tokens.shape)
    if segment_ids is not None:
        boundary = segment_ids != jnp.roll(segment_ids, -1, axis=1)
        mask = mask & jnp.logical_not(boundary)
    if extra_mask is not None:
        mask = mask & jnp.logical_not(extra_mask)
    return cross_entropy(logits, targets, mask)
