"""Model zoo for the consumer-side training loops the loader feeds."""

from ddl_tpu.models import llama, moe, pointnet, vit

__all__ = ["llama", "moe", "pointnet", "vit"]
