"""Model zoo for the consumer-side training loops the loader feeds."""

from ddl_tpu.models import llama, pointnet

__all__ = ["llama", "pointnet"]
