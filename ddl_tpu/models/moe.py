"""Mixture-of-Experts decoder LM with expert parallelism.

The reference had no models and no expert parallelism (SURVEY §2.3 lists EP
as absent); ddl_tpu makes it a first-class mesh axis.  The design is the
TPU-idiomatic GShard/Switch formulation rather than gather/scatter token
routing: capacity-bounded dispatch/combine einsums with fully static
shapes, so XLA tiles every step onto the MXU and GSPMD inserts the ``ep``
all-to-alls from sharding annotations alone — there is no hand-written
collective and no data-dependent control flow.

- Router: top-k (default 2) softmax gating, probabilities renormalised over
  the chosen experts.
- Dispatch: per-expert capacity ``C = ceil(topk·N/E·capacity_factor)``;
  slot positions come from a cumulative sum over a slot-major one-hot mask
  (earlier top-k slots get priority), overflow tokens are dropped (their
  combine weight is zero — the residual stream carries them unchanged).
- Experts: stacked SwiGLU MLPs ``(E, D, F)``, sharded ``P("ep", "fsdp",
  "tp")`` so each device holds ``E/ep`` experts.
- Load-balance aux loss: the Switch formulation
  ``E · Σ_e fraction_dispatched(e) · mean_router_prob(e)``.

Attention/norms/RoPE reuse the llama building blocks and the shared
attention dispatcher (ring attention over ``sp``, Pallas flash kernel on
TPU).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ddl_tpu.models import llama as _llama

Params = Dict[str, Any]


@dataclasses.dataclass(frozen=True)
class MoeConfig:
    vocab: int = 256
    d_model: int = 128
    n_layers: int = 2
    n_heads: int = 4
    n_kv_heads: int = 2
    d_ff: int = 256  # per-expert hidden size
    n_experts: int = 4
    topk: int = 2
    capacity_factor: float = 1.25
    router_aux_weight: float = 0.01
    max_seq: int = 512
    rope_theta: float = 500000.0
    norm_eps: float = 1e-5
    dtype: Any = jnp.bfloat16
    #: Storage dtype of the params pytree (see LlamaConfig.param_dtype —
    #: bf16 halves param+optimizer HBM; expert stacks dominate MoE HBM).
    param_dtype: Any = jnp.float32
    #: Remat policy (none/full/selective/dots, bools for back compat —
    #: see :attr:`LlamaConfig.remat` / :mod:`ddl_tpu.models.remat`); the
    #: capacity-bounded dispatch/combine einsums are the big activations
    #: here, and "selective" keeps the attention outputs saved.
    remat: Any = False
    attn_impl: str = "auto"
    #: Expert-MLP dispatch implementation.  "einsum": the capacity-
    #: bounded GShard dispatch/combine formulation — fully static, and
    #: the layout GSPMD shards over the ``ep`` mesh axis.  "ragged":
    #: sort-based dropless routing over ``jax.lax.ragged_dot`` — the
    #: one-hot dispatch/combine einsums (which cost as many real FLOPs
    #: as the experts themselves at single-chip scale) are replaced by
    #: a sort + gather (measured 1.31x on chip at 889M params).
    #: Token-sharded meshes (dp/sp) run the routing per shard under
    #: shard_map (dropless, so local == global routing exactly);
    #: tp/fsdp shard weights and compose too.  Only ``ep`` is rejected
    #: — ragged group boundaries are contiguous local row ranges and
    #: cannot align with a sharded expert stack; use einsum for expert
    #: parallelism.  Scale guidance (chip-measured): neither impl is a
    #: single-chip answer at multi-B MoE scale — einsum's (N, E, C)
    #: dispatch one-hots dominate (4% MFU at 1.7B) and ragged's N·topk
    #: row duplication exhausts HBM; shard experts over ``ep`` there.
    moe_impl: str = "einsum"

    def __post_init__(self) -> None:
        from ddl_tpu.models import remat as _remat

        _remat.resolve(self.remat)  # fail on junk at config build time

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    def capacity(self, n_tokens: int) -> int:
        per_expert = self.topk * n_tokens / self.n_experts
        return max(1, math.ceil(per_expert * self.capacity_factor))

    @staticmethod
    def tiny() -> "MoeConfig":
        return MoeConfig()

    @staticmethod
    def mixtral_8x7b() -> "MoeConfig":
        """Mixtral-8x7B dimensions — the pod-scale EP design point."""
        return MoeConfig(
            vocab=32000, d_model=4096, n_layers=32, n_heads=32,
            n_kv_heads=8, d_ff=14336, n_experts=8, topk=2, max_seq=8192,
        )


def init_params(cfg: MoeConfig, key: jax.Array) -> Params:
    # 8 dense draws per layer + embed + lm_head.
    keys = iter(jax.random.split(key, 2 + cfg.n_layers * 8))
    pdt = cfg.param_dtype

    def dense(k, fan_in, shape):
        return _llama._dense_init(k, fan_in, shape, pdt)

    d, hd, E, F = cfg.d_model, cfg.head_dim, cfg.n_experts, cfg.d_ff
    layers = []
    for _ in range(cfg.n_layers):
        layers.append(
            {
                "attn_norm": jnp.ones((d,), pdt),
                "wq": dense(next(keys), d, (d, cfg.n_heads * hd)),
                "wk": dense(next(keys), d, (d, cfg.n_kv_heads * hd)),
                "wv": dense(next(keys), d, (d, cfg.n_kv_heads * hd)),
                "wo": dense(next(keys), cfg.n_heads * hd, (cfg.n_heads * hd, d)),
                "mlp_norm": jnp.ones((d,), pdt),
                "w_router": dense(next(keys), d, (d, E)),
                "w_gate": dense(next(keys), d, (E, d, F)),
                "w_up": dense(next(keys), d, (E, d, F)),
                "w_down": dense(next(keys), F, (E, F, d)),
            }
        )
    return {
        "embed": dense(next(keys), d, (cfg.vocab, d)),
        "layers": layers,
        "final_norm": jnp.ones((d,), pdt),
        "lm_head": dense(next(keys), d, (d, cfg.vocab)),
    }


def param_shapes(cfg: MoeConfig) -> Params:
    """Abstract params pytree via ``eval_shape`` — the optimizer HBM
    accounting input (``parallel.optimizer.hbm_accounting``,
    ``tools/probe_opt.py``); a Mixtral-scale layout prices without
    materialising the expert stacks."""
    return jax.eval_shape(lambda: init_params(cfg, jax.random.key(0)))


def param_specs(cfg: MoeConfig) -> Params:
    """Expert weights shard their leading E axis over ``ep``; within an
    expert the dense Megatron layout (fsdp × tp) applies.  Axes absent from
    the mesh are dropped by the train-step factory."""
    layer = {
        "attn_norm": P(None),
        "wq": P("fsdp", "tp"),
        "wk": P("fsdp", "tp"),
        "wv": P("fsdp", "tp"),
        "wo": P("tp", "fsdp"),
        "mlp_norm": P(None),
        "w_router": P(None, None),
        "w_gate": P("ep", "fsdp", "tp"),
        "w_up": P("ep", "fsdp", "tp"),
        "w_down": P("ep", "tp", "fsdp"),
    }
    return {
        "embed": P(None, "fsdp"),
        "layers": [dict(layer) for _ in range(cfg.n_layers)],
        "final_norm": P(None),
        "lm_head": P("fsdp", "tp"),
    }


def _router_topk(
    x: jax.Array, layer: Params, cfg: MoeConfig
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Shared router: softmax gate → top-k → renormalised gate weights.

    ONE implementation for both dispatch impls, so their 'identical
    routing' equivalence holds by construction.  Returns
    (probs (N, E) fp32, top_p (N, k) renormalised, top_e (N, k) ids).
    """
    logits = (x @ layer["w_router"].astype(x.dtype)).astype(jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1)
    top_p, top_e = jax.lax.top_k(probs, cfg.topk)
    top_p = top_p / jnp.maximum(jnp.sum(top_p, -1, keepdims=True), 1e-9)
    return probs, top_p, top_e


def _switch_aux(probs: jax.Array, top_e: jax.Array, E: int) -> jax.Array:
    """Switch load-balance loss on slot-0 dispatch decisions —
    ``E · Σ_e fraction_dispatched(e) · mean_router_prob(e)``."""
    frac_dispatched = jnp.mean(
        jax.nn.one_hot(top_e[:, 0], E, dtype=jnp.float32), axis=0
    )
    return E * jnp.sum(frac_dispatched * jnp.mean(probs, axis=0))


def moe_mlp(
    x: jax.Array, layer: Params, cfg: MoeConfig
) -> Tuple[jax.Array, jax.Array]:
    """Top-k routed SwiGLU experts over flat tokens x: (N, D).

    Returns (out (N, D), aux load-balance loss scalar).
    """
    N, D = x.shape
    E, k, C = cfg.n_experts, cfg.topk, cfg.capacity(N)
    dt = x.dtype

    probs, top_p, top_e = _router_topk(x, layer, cfg)

    mask = jax.nn.one_hot(top_e, E, dtype=jnp.float32)  # (N, k, E)
    # Slot-major priority: all slot-0 picks queue before any slot-1 pick.
    mask_f = mask.transpose(1, 0, 2).reshape(k * N, E)
    pos_f = jnp.cumsum(mask_f, axis=0) - mask_f  # arrival index per expert
    pos = (pos_f * mask_f).sum(-1).reshape(k, N).T.astype(jnp.int32)  # (N, k)
    keep = (pos < C) & (mask.sum(-1) > 0)  # (N, k) boolean

    gates = top_p * keep  # dropped tokens get zero combine weight
    # combine[n, e, c] = gate weight of token n at expert e slot c
    combine = jnp.einsum(
        "nk,nke,nkc->nec",
        gates,
        mask,
        jax.nn.one_hot(pos, C, dtype=jnp.float32),
    )
    dispatch = (combine > 0).astype(dt)  # (N, E, C)

    expert_in = jnp.einsum("nec,nd->ecd", dispatch, x)  # (E, C, D)
    gate = jax.nn.silu(
        jnp.einsum("ecd,edf->ecf", expert_in, layer["w_gate"].astype(dt))
    )
    up = jnp.einsum("ecd,edf->ecf", expert_in, layer["w_up"].astype(dt))
    expert_out = jnp.einsum(
        "ecf,efd->ecd", gate * up, layer["w_down"].astype(dt)
    )
    out = jnp.einsum("nec,ecd->nd", combine.astype(dt), expert_out)
    return out, _switch_aux(probs, top_e, E)


def _validate_impl_mesh(cfg: MoeConfig, mesh: Optional[Any]) -> None:
    """The ragged impl's expert groups are contiguous row ranges of a
    locally sorted copy list — they cannot align with an ``ep``-sharded
    expert stack, so reject that combination up front instead of
    letting GSPMD materialize a gathered stack silently.  Token-sharded
    axes (``dp``/``sp``) ARE supported: :func:`_routed_mlp` shard_maps
    the routing per shard.  tp/fsdp shard weights, not tokens — those
    compose fine."""
    if (
        cfg.moe_impl == "ragged"
        and mesh is not None
        and "ep" in getattr(mesh, "axis_names", ())
        and mesh.shape["ep"] > 1
    ):
        raise ValueError(
            "moe_impl='ragged' does not compose with an ep>1 mesh axis "
            "(expert groups are contiguous local row ranges); use the "
            "einsum impl for expert parallelism"
        )


def moe_mlp_ragged(
    x: jax.Array, layer: Params, cfg: MoeConfig
) -> Tuple[jax.Array, jax.Array]:
    """Sort-based dropless top-k routing over ``jax.lax.ragged_dot``.

    Each token contributes ``topk`` copies; copies are stably sorted by
    expert id, so each expert's rows form one contiguous group and the
    three expert matmuls run as ragged group-wise dots against the
    stacked ``(E, D, F)`` weights — no capacity, no drops, no N·E·C
    one-hot einsums.  The router, normalised top-k gates, and Switch
    aux loss are identical to :func:`moe_mlp`; outputs match it exactly
    whenever capacity does not bind there (routing is per-token).
    """
    N, D = x.shape
    E, k = cfg.n_experts, cfg.topk
    dt = x.dtype

    probs, top_p, top_e = _router_topk(x, layer, cfg)

    flat_e = top_e.reshape(-1)  # (N*k,) expert of copy i (token i//k)
    order = jnp.argsort(flat_e)  # stable: ties keep token order
    xs = jnp.take(x, order // k, axis=0)  # (N*k, D) grouped by expert
    group_sizes = jnp.bincount(flat_e, length=E).astype(jnp.int32)

    gate = jax.nn.silu(
        jax.lax.ragged_dot(xs, layer["w_gate"].astype(dt), group_sizes)
    )
    up = jax.lax.ragged_dot(xs, layer["w_up"].astype(dt), group_sizes)
    rows = jax.lax.ragged_dot(
        gate * up, layer["w_down"].astype(dt), group_sizes
    )  # (N*k, D), still expert-sorted

    inv = jnp.argsort(order)  # flat copy index -> its sorted row
    per_slot = jnp.take(rows, inv, axis=0).reshape(N, k, D)
    out = jnp.einsum("nk,nkd->nd", top_p.astype(dt), per_slot)
    return out, _switch_aux(probs, top_e, E)


def _moe_mlp_dispatch(
    x: jax.Array, layer: Params, cfg: MoeConfig
) -> Tuple[jax.Array, jax.Array]:
    if cfg.moe_impl == "ragged":
        return moe_mlp_ragged(x, layer, cfg)
    if cfg.moe_impl != "einsum":
        raise ValueError(
            f"unknown moe_impl {cfg.moe_impl!r} (want einsum|ragged)"
        )
    return moe_mlp(x, layer, cfg)


def _routed_mlp(
    h: jax.Array, layer: Params, cfg: MoeConfig, mesh: Optional[Any]
) -> Tuple[jax.Array, jax.Array]:
    """The MoE MLP on the (B, T, D) residual stream, mesh-aware.

    Ragged impl on a token-sharded mesh (``dp``/``sp`` axes): routing is
    per-token and the impl is dropless, so each shard sorts and routes
    its LOCAL tokens under ``shard_map`` — outputs are identical to the
    global computation, with zero collectives in the hot path (the same
    argument ``parallel.ring_attention.sharded_local_attention`` makes
    for batch-sharded attention; left to GSPMD, the global argsort/
    bincount would all-gather every token to every device per layer).
    A ``tp`` axis Megatron-splits the per-expert hidden dimension
    INSIDE the shard_map (gate/up column-sharded, down row-sharded,
    one ``psum`` over tp on the partial outputs) so tp devices divide
    the expert FLOPs rather than replicate them; tp that does not
    divide ``d_ff`` falls back to replicated expert compute.  ``ep``
    stays rejected — :func:`_validate_impl_mesh`.  On an fsdp mesh the
    shard_map boundary gathers a layer's expert stack per step, the
    same traffic fsdp training pays at each use point.  The aux loss
    becomes the shard-mean of per-shard Switch aux — the same
    load-balance pressure at shard granularity, not numerically equal
    to the global aux (it is not linear in token subsets;
    ``forward_pp`` documents the same for microbatch groups).
    """
    B, T, D = h.shape
    if cfg.moe_impl == "ragged" and mesh is not None:
        names = getattr(mesh, "axis_names", ())
        bax = "dp" if "dp" in names and mesh.shape["dp"] > 1 else None
        sax = "sp" if "sp" in names and mesh.shape["sp"] > 1 else None
        if (bax and B % mesh.shape["dp"] != 0) or (
            sax and T % mesh.shape["sp"] != 0
        ):
            raise ValueError(
                "moe_impl='ragged': dp/sp mesh axes must divide the "
                f"(B={B}, T={T}) token grid"
            )
        tax = (
            "tp"
            if "tp" in names
            and mesh.shape["tp"] > 1
            and cfg.d_ff % mesh.shape["tp"] == 0
            else None
        )
        if bax or sax or tax:
            from ddl_tpu._compat import shard_map

            token_axes = tuple(a for a in (bax, sax) if a)
            ff_specs = {
                "w_gate": P(None, None, tax),
                "w_up": P(None, None, tax),
                "w_down": P(None, tax, None),
            }
            # Only the entries the routed MLP reads cross the shard_map
            # boundary: passing the whole layer dict gathered the UNUSED
            # attention weights (wq/wk/wv/wo — replicated in_specs) to
            # every device per layer (advisor r5).  The router + expert
            # FFN stacks are the entire read set of moe_mlp_ragged.
            mlp_layer = {
                k: layer[k]
                for k in ("w_router", "w_gate", "w_up", "w_down")
            }
            layer_specs = {
                k: ff_specs.get(k, P()) for k in mlp_layer
            }

            def body(hs: jax.Array, lyr: Params):
                b, t, _ = hs.shape
                out, aux = moe_mlp_ragged(hs.reshape(b * t, -1), lyr, cfg)
                if tax:
                    # Each tp shard computed its d_ff slice; the down
                    # projections are partial sums over the hidden dim.
                    out = jax.lax.psum(out, tax)
                if token_axes:
                    aux = jax.lax.pmean(aux, token_axes)
                return out.reshape(b, t, -1), aux

            return shard_map(
                body, mesh=mesh,
                in_specs=(P(bax, sax, None), layer_specs),
                out_specs=(P(bax, sax, None), P()),
                check_vma=False,
            )(h, mlp_layer)
    out, aux = _moe_mlp_dispatch(h.reshape(B * T, -1), layer, cfg)
    return out.reshape(B, T, -1), aux


def _layer_apply(
    layer: Params,
    x: jax.Array,
    cfg: MoeConfig,
    positions: jax.Array,
    mesh: Optional[Any] = None,
    segment_ids: Optional[jax.Array] = None,
) -> Tuple[jax.Array, jax.Array]:
    """One MoE block on the residual stream → (x, router aux) — the
    single layer body shared by :func:`forward` and the pipelined
    :func:`forward_pp`.  The attention sub-block is llama's
    ``_attn_block`` (one implementation across families); only the MLP
    differs — routed experts instead of SwiGLU."""
    x = _llama._attn_block(
        layer, x, cfg, positions, mesh=mesh, segment_ids=segment_ids
    )
    h = _llama._rms_norm(x, layer["mlp_norm"], cfg.norm_eps)
    moe_out, aux = _routed_mlp(h, layer, cfg, mesh)
    return x + moe_out, aux


def forward(
    params: Params,
    tokens: jax.Array,
    cfg: MoeConfig,
    mesh: Optional[Any] = None,
    segment_ids: Optional[jax.Array] = None,
) -> Tuple[jax.Array, jax.Array]:
    """(logits (B, T, vocab), mean router aux loss).

    ``segment_ids`` (B, T): packed-batch attention masking, as in
    ``models.llama.forward``."""
    _validate_impl_mesh(cfg, mesh)
    dt = cfg.dtype
    positions = jnp.arange(tokens.shape[1])
    x = params["embed"].astype(dt)[tokens]
    aux_total = jnp.zeros((), jnp.float32)

    def layer_fn(x: jax.Array, layer: Params):
        return _layer_apply(
            layer, x, cfg, positions, mesh=mesh, segment_ids=segment_ids
        )

    # Configured remat policy (ddl_tpu.models.remat): "full" recomputes
    # the routing/dispatch/expert internals in the backward pass;
    # "selective" additionally keeps the attention outputs saved.
    from ddl_tpu.models import remat as _remat

    layer_fn = _remat.wrap(layer_fn, cfg.remat)
    for layer in params["layers"]:
        x, aux = layer_fn(x, layer)
        aux_total = aux_total + aux

    x = _llama._rms_norm(x, params["final_norm"], cfg.norm_eps)
    logits = (x @ params["lm_head"].astype(dt)).astype(jnp.float32)
    return logits, aux_total / cfg.n_layers


# -- pipeline parallelism ----------------------------------------------------


def stage_params(
    params: Params, n_stages: int, n_chunks: int = 1
) -> Params:
    """Regroup an :func:`init_params` pytree for pipeline parallelism —
    the shared ``(S, L/S)`` stage layout (interleaved ``(S, V,
    L/(S·V))`` when ``n_chunks > 1``, for ``schedule="1f1b"``;
    ``parallel.pipeline.stack_layer_stages``); embed and head stay
    outside the pipe.  Expert stacks keep their leading E axis inside
    each stage leaf: ``(S, [V,] L/S, E, ...)``."""
    from ddl_tpu.parallel.pipeline import stack_layer_stages

    return {
        "embed": params["embed"],
        "stages": stack_layer_stages(
            params["layers"], n_stages, n_chunks=n_chunks
        ),
        "final_norm": params["final_norm"],
        "lm_head": params["lm_head"],
    }


def pp_param_specs(
    cfg: MoeConfig, axis: str = "pp", n_chunks: int = 1
) -> Params:
    """PartitionSpecs for the :func:`stage_params` layout — ``pp``
    shards stages; within a stage the expert/Megatron layout of
    :func:`param_specs` applies (``ep`` still shards the expert axis of
    the at-rest storage)."""
    from ddl_tpu.parallel.pipeline import stage_spec_tree

    return {
        "embed": P(None, "fsdp"),
        "stages": stage_spec_tree(
            param_specs(cfg)["layers"][0], axis, n_chunks=n_chunks
        ),
        "final_norm": P(None),
        "lm_head": P("fsdp", "tp"),
    }


def forward_pp(
    params: Params,
    tokens: jax.Array,
    cfg: MoeConfig,
    mesh: Any,
    n_microbatches: int,
    axis: str = "pp",
    schedule: str = "gpipe",
    n_chunks: "int | None" = None,
) -> Tuple[jax.Array, jax.Array]:
    """(logits, mean router aux loss) with the MoE blocks pipelined over
    ``axis`` (``schedule``: gpipe, or interleaved 1f1b with
    ``stage_params(..., n_chunks=)`` weights).

    The router aux loss accumulates THROUGH the pipe: the activation
    pytree carries a per-row accumulator alongside the residual stream
    (``pipeline_apply`` hops every leaf together), each stage adds its
    layers' aux, and the caller averages over rows.  Capacity
    semantics: routing groups are the token sets ``moe_mlp`` sees —
    one dp shard of one microbatch under the auto dp batch spec
    (``C = ceil(topk·(mb/dp)·T/E·cf)``), the whole microbatch when dp
    does not shard it.  Logits match the non-pp forward exactly
    whenever capacity does not bind (routing is per-token); the aux is
    the mean of per-group aux — the same load-balance pressure at
    group granularity, not numerically equal to the full-batch aux
    (it is not linear in token subsets).
    """
    _validate_impl_mesh(cfg, mesh)
    names = getattr(mesh, "axis_names", ())
    if cfg.moe_impl == "ragged" and not (
        axis in names and mesh.shape[axis] > 1
    ):
        # Without a real pp axis, pipeline_apply falls back to a
        # sequential lax.map OUTSIDE shard_map (pipeline.py), where the
        # layer body runs with mesh=None — a token-sharded dp/sp axis
        # would then hit moe_mlp_ragged's global argsort under GSPMD
        # and all-gather every token per layer.  (With pp>1 the
        # pipeline's shard_map makes dp manual, so local routing is
        # correct and fast — same argument as _routed_mlp.)
        for ax in ("dp", "sp"):
            if ax in names and mesh.shape[ax] > 1:
                raise ValueError(
                    f"moe_impl='ragged' with forward_pp needs a real "
                    f"{axis}>1 mesh axis when {ax}>1 (the sequential "
                    "fallback would gather token shards); use the "
                    "einsum impl or a pipelined mesh"
                )
    B, T = tokens.shape
    dt = cfg.dtype
    positions = jnp.arange(T)
    x = params["embed"].astype(dt)[tokens]

    def one_layer(state, layer):
        h, aux_rows = state
        h, aux = _layer_apply(layer, h, cfg, positions, mesh=None)
        return h, aux_rows + aux.astype(aux_rows.dtype)

    from ddl_tpu.models import remat as _remat

    layer_fn = _remat.wrap(one_layer, cfg.remat)

    def stage_fn(stage: Params, state: Any) -> Any:
        out, _ = jax.lax.scan(
            lambda c, lyr: (layer_fn(c, lyr), None), state, stage
        )
        return out

    from ddl_tpu.parallel.pipeline import pipeline_apply

    x, aux_rows = pipeline_apply(
        params["stages"],
        (x, jnp.zeros((B,), jnp.float32)),
        stage_fn, mesh, n_microbatches, axis=axis,
        schedule=schedule, n_chunks=n_chunks,
    )
    x = _llama._rms_norm(x, params["final_norm"], cfg.norm_eps)
    logits = (x @ params["lm_head"].astype(dt)).astype(jnp.float32)
    # Every row of a microbatch carries that microbatch's summed aux;
    # the row-mean is the microbatch-mean, normalized per layer as in
    # the non-pp forward.
    return logits, jnp.mean(aux_rows) / cfg.n_layers


def next_token_loss_pp(
    params: Params,
    tokens: jax.Array,
    cfg: MoeConfig,
    mesh: Any,
    n_microbatches: int,
    axis: str = "pp",
    schedule: str = "gpipe",
    n_chunks: "int | None" = None,
) -> jax.Array:
    """Cross-entropy + weighted router aux over the pipelined forward."""
    from ddl_tpu.models.losses import next_token_cross_entropy

    logits, aux = forward_pp(
        params, tokens, cfg, mesh, n_microbatches, axis=axis,
        schedule=schedule, n_chunks=n_chunks,
    )
    ce = next_token_cross_entropy(logits, tokens)
    return ce + cfg.router_aux_weight * aux


# -- inference: KV-cache decode + generate -----------------------------------


def init_cache(cfg: MoeConfig, batch: int, max_len: int) -> Params:
    """Per-layer KV cache buffers for autoregressive decoding — THE
    llama cache layout (one delegation, so the layout backing the shared
    ``_attn_with_cache`` math cannot drift between families); the routed
    MLP needs no cache of its own, routing re-decides per decoded
    token."""
    return _llama.init_cache(cfg, batch, max_len)


def forward_with_cache(
    params: Params,
    tokens: jax.Array,
    cfg: MoeConfig,
    cache: Params,
    pos: jax.Array,
    last_only: bool = False,
) -> Tuple[jax.Array, Params]:
    """Cached MoE forward (prefill: T = prompt length; decode: T = 1).

    The attention sub-block is the shared cache math
    (``llama._attn_with_cache``: compact GQA cache, causal-position
    mask); each decoded token then routes through the SAME top-k gate
    and dispatch impl as training (``cfg.moe_impl``, via
    ``_moe_mlp_dispatch`` on the flat (B*T, D) tokens).

    Impl semantics.  ``ragged``: dropless — decode matches the full
    forward exactly, always.  ``einsum``: expert capacity is computed
    from the call's OWN token count; prefill routes the whole prompt
    jointly (identical N to the training forward, so prefill logits
    match it exactly, drops included), while stepwise decode routes B
    tokens per step with fresh capacity, matching the full forward
    exactly whenever capacity does not bind — under capacity pressure
    the decode path DROPS LESS than teacher forcing, never more.
    Returns (logits, updated cache); router aux loss is a training
    quantity and is not computed here.
    """
    B, T = tokens.shape
    dt = cfg.dtype
    positions = pos + jnp.arange(T)
    cache_idx = jnp.arange(cache["k"].shape[2])
    x = params["embed"].astype(dt)[tokens]

    # Stacked-cache value chain, as in llama.forward_with_cache: each
    # layer writes only its new-token slot so the scan updates in place.
    k_all, v_all = cache["k"], cache["v"]
    for li, layer in enumerate(params["layers"]):
        x, k_all, v_all = _llama._attn_with_cache(
            layer, x, cfg, k_all, v_all, li, pos, positions, cache_idx,
        )
        h = _llama._rms_norm(x, layer["mlp_norm"], cfg.norm_eps)
        moe_out, _aux = _moe_mlp_dispatch(h.reshape(B * T, -1), layer, cfg)
        x = x + moe_out.reshape(B, T, -1)

    x = _llama._rms_norm(x, params["final_norm"], cfg.norm_eps)
    if last_only:
        x = x[:, -1:]
    logits = (x @ params["lm_head"].astype(dt)).astype(jnp.float32)
    return logits, {"k": k_all, "v": v_all}


def generate(
    params: Params,
    prompt: jax.Array,
    cfg: MoeConfig,
    max_new_tokens: int,
    temperature: float = 0.0,
    key: Optional[jax.Array] = None,
    top_k: Optional[int] = None,
    top_p: Optional[float] = None,
    eos_id: Optional[int] = None,
) -> jax.Array:
    """Autoregressive MoE generation — same contract as
    ``models.llama.generate`` (greedy or explicit-key sampling with
    optional top-k / nucleus top-p filtering and EOS masking; prefill
    in one cached forward, scanned decode steps), completing inference
    parity across the model families."""
    return _llama._generate(
        forward_with_cache, init_cache, params, prompt, cfg,
        max_new_tokens, temperature, key, top_k=top_k, top_p=top_p,
        eos_id=eos_id,
    )


def next_token_loss(
    params: Params,
    tokens: jax.Array,
    cfg: MoeConfig,
    mesh: Optional[Any] = None,
    segment_ids: Optional[jax.Array] = None,
) -> jax.Array:
    """Cross-entropy + weighted router load-balance loss.

    With ``segment_ids`` (packed batches), attention is segment-masked
    and cross-document boundary predictions drop from the CE, matching
    ``models.llama.next_token_loss``."""
    from ddl_tpu.models.losses import next_token_cross_entropy

    logits, aux = forward(params, tokens, cfg, mesh, segment_ids=segment_ids)
    ce = next_token_cross_entropy(logits, tokens, segment_ids=segment_ids)
    return ce + cfg.router_aux_weight * aux
