"""Named rematerialisation policies, shared by every model family.

The old knob was all-or-nothing: ``remat=True`` wrapped each layer in a
bare ``jax.checkpoint``, recomputing EVERYTHING in the backward pass —
including the attention kernel, the most expensive op in the layer.  On
chip that bought HBM at a steep FLOPs price: the 1.39B bench config's
MFU fell from 0.6255 (285M, no remat) to 0.5574 under full-layer remat
(``BENCH_TPU_r05.json``, VERDICT r5 weak #3).

Policies (``LlamaConfig.remat`` / ``MoeConfig.remat``; bools still
accepted for back compat — ``True`` is ``"full"``, ``False`` is
``"none"``):

- ``"none"`` — save every layer intermediate (fastest step, most HBM).
- ``"full"`` — save only each layer's residual-stream input; recompute
  everything else in the backward pass (classic per-layer remat;
  ``policy=nothing_saveable`` is ``jax.checkpoint``'s default spelled
  explicitly, so the models' lint guard — every ``jax.checkpoint``
  names a policy — holds by construction).
- ``"selective"`` — save each layer's ATTENTION OUTPUT (the tensors
  tagged :data:`ATTN_OUT_NAME` by the shared attention blocks) and
  recompute the cheap rest: norms, qkv/rope projections, and the FFN.
  The backward pass then never re-runs the attention kernel — the
  standard Megatron-style selective trade that buys back most of the
  full-remat MFU loss at a fraction of full activation memory.
- ``"dots"`` — ``jax.checkpoint_policies.dots_with_no_batch_dims_
  saveable``: save every non-batched matmul output (all weight
  projections), recompute only elementwise ops and attention — the
  memory-heavier, FLOPs-lighter point between none and selective.

One wrap site per model family (:func:`wrap` around the layer body),
one tag site per attention block (:func:`tag_attn_out`) — the policy
semantics cannot drift between llama, moe, and the pipelined forwards.
"""

from __future__ import annotations

from typing import Any, Callable

#: Checkpoint name carried by every attention block's output tensor
#: (``checkpoint_name`` is an identity outside a policy-bearing
#: ``jax.checkpoint``, so tagging is unconditional and free).
ATTN_OUT_NAME = "ddl_attn_out"

#: Every accepted policy name, in cheapest-memory-first order.
POLICIES = ("none", "full", "selective", "dots")


def resolve(remat: Any) -> str:
    """Normalise a config's ``remat`` field to a policy name.

    Accepts the policy strings plus the legacy booleans (``True`` →
    ``"full"``, ``False``/``None`` → ``"none"``)."""
    if remat is None or remat is False:
        return "none"
    if remat is True:
        return "full"
    if remat in POLICIES:
        return str(remat)
    raise ValueError(
        f"remat must be a bool or one of {POLICIES}, got {remat!r}"
    )


def tag_attn_out(x: Any) -> Any:
    """Mark an attention block's output as saveable under the
    ``"selective"`` policy (identity everywhere else)."""
    from jax.ad_checkpoint import checkpoint_name

    return checkpoint_name(x, ATTN_OUT_NAME)


def _policy(name: str) -> Any:
    import jax

    cp = jax.checkpoint_policies
    if name == "full":
        return cp.nothing_saveable
    if name == "selective":
        return cp.save_only_these_names(ATTN_OUT_NAME)
    if name == "dots":
        return cp.dots_with_no_batch_dims_saveable
    raise ValueError(name)


def wrap(layer_fn: Callable[..., Any], remat: Any) -> Callable[..., Any]:
    """Apply the configured remat policy to a per-layer body.

    ``layer_fn`` is the function scanned over a model's layers (any
    signature/pytree in-out — ``jax.checkpoint`` handles both the
    llama ``x -> x`` and the moe ``(x, aux) -> (x, aux)`` shapes).
    """
    import jax

    name = resolve(remat)
    if name == "none":
        return layer_fn
    return jax.checkpoint(layer_fn, policy=_policy(name))
