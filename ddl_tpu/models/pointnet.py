"""Pointwise MLP regressor — the reference harness's model, made real.

The reference's example config carried vestigial ``ShapeNet``/
``ParameterNet`` MLP sections that nothing consumed (reference
``tests/run_ddl.py:269-298``, SURVEY §5.6); its "training" loop only
drained batches.  This model closes that loop: a CFD-style pointwise
regressor consuming the (pos, target, weight) column tuple the example
producer emits (reference ``tests/run_ddl.py:156-159``), trained per-point
— the workload the reference's data pipeline was built to feed.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

Params = Dict[str, Any]


@dataclasses.dataclass(frozen=True)
class PointNetConfig:
    n_inputs: int = 3  # point position columns
    n_outputs: int = 6  # field value columns
    hidden: Tuple[int, ...] = (64, 64)
    dtype: Any = jnp.float32


def init_params(cfg: PointNetConfig, key: jax.Array) -> Params:
    sizes = (cfg.n_inputs, *cfg.hidden, cfg.n_outputs)
    keys = jax.random.split(key, len(sizes) - 1)
    layers: List[Dict[str, jax.Array]] = []
    for k, fan_in, fan_out in zip(keys, sizes[:-1], sizes[1:]):
        layers.append(
            {
                "w": jax.random.normal(k, (fan_in, fan_out), jnp.float32)
                / jnp.sqrt(fan_in),
                "b": jnp.zeros((fan_out,), jnp.float32),
            }
        )
    return {"layers": layers}


def param_specs(cfg: PointNetConfig) -> Params:
    """Replicated params — the model is tiny; dp handles the scale."""
    return {
        "layers": [
            {"w": P(None, None), "b": P(None)} for _ in range(len(cfg.hidden) + 1)
        ]
    }


def forward(params: Params, x: jax.Array, cfg: PointNetConfig) -> jax.Array:
    h = x.astype(cfg.dtype)
    layers = params["layers"]
    for layer in layers[:-1]:
        h = jax.nn.gelu(h @ layer["w"] + layer["b"])
    out = h @ layers[-1]["w"] + layers[-1]["b"]
    return out


def weighted_mse_loss(
    params: Params,
    batch: Tuple[jax.Array, jax.Array, jax.Array],
    cfg: PointNetConfig,
) -> jax.Array:
    """Weighted MSE over (pos, target, weight) — the example producer's
    column tuple."""
    pos, target, weight = batch
    pred = forward(params, pos, cfg)
    err = (pred - target.astype(pred.dtype)) ** 2
    return jnp.mean(err * weight.astype(pred.dtype))
