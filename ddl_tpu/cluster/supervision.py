"""Survivable control plane: supervisor journaling + lease-based failover.

PR 15's chaos tier proved the *data* plane survives host loss; the
remaining single point of failure was the control plane itself — a dead
:class:`~ddl_tpu.cluster.membership.ClusterSupervisor` silently froze
membership (no sweeps, no adoptions) while the pipeline kept serving a
stale view.  This module makes the supervisor itself survivable, as
three layers (docs/ROBUSTNESS.md "Control-plane failover"):

- **Journal** (:class:`SupervisorJournal`).  Every control-plane
  decision — bootstrap view, view changes, rejoins, epoch restores,
  scheduler deficit/bucket snapshots, promotions — is appended as a
  self-delimiting record in the checkpoint blob format
  (``resilience/ckpt.py``): ``magic | u32 header-len | JSON header |
  32-byte integrity trailer``, CRC'd and seq-stamped.  Replay is
  torn-tail-tolerant: a record whose trailer fails verification (a
  crash mid-append) truncates the replay there — all preceding
  records are intact by construction.

- **Deterministic replay** (:func:`replay_journal`).  The supervisor is
  a state machine over the journal: views evolve only through the pure
  functions :func:`~ddl_tpu.cluster.membership.view_change` /
  :func:`~ddl_tpu.cluster.membership.view_rejoin`, so replaying the
  record sequence reconstructs the leader's exact view, epoch fence,
  departed-host set, fencing term, and latest scheduler snapshot.

- **Lease + fencing** (:class:`SupervisorHA`).  The leader renews a
  leadership lease every :meth:`SupervisorHA.step`; a standby promotes
  when the lease lapses (``DDL_TPU_SUPERVISOR_LEASE_S`` budget).
  Promotion replays the journal, rebuilds a fresh
  :class:`JournaledSupervisor`, adopts the scheduler snapshot, bumps
  the **fencing term**, and stamps it onto every control sender
  (:meth:`~ddl_tpu.transport.connection.ConsumerConnection.set_control_fence`)
  so each post-promotion command carries the new term.  A zombie
  ex-leader — alive but partitioned when its lease lapsed — keeps
  sending with the old term; every
  :class:`~ddl_tpu.transport.envelope.EnvelopeReceiver` drops those
  unapplied (but acks, so the zombie's retry loop drains).  Split
  brain is therefore harmless by construction: two "leaders" may both
  *send*, but only the newest term's commands *apply*.

Journal-on-notify caveat: records append from the supervisor's change
notification, after state mutates — a crash in the gap loses exactly
that record.  That is safe, not just tolerable: the successor replays
to one view earlier, and its OWN first sweep re-detects the dead host
through the same lease table, converging on a byte-identical view
(:func:`view_change` is pure).  The journal is a replay log, not a
write-ahead log, and never needs to be one.

Chaos coverage rides the ``cluster.supervise`` site inside
:meth:`SupervisorHA.step`: ``SUPERVISOR_CRASH`` kills the leader
mid-stream (lease lapses, standby promotes), ``NETWORK_PARTITION``
suppresses lease renewal without killing the leader — the split-brain
producer.  ``DDL_BENCH_MODE=failover`` A/Bs a mid-stream kill against
an uninterrupted run (byte-identical streams, zero watchdog failures,
fairness preserved); promotions and crashes are flight-recorded.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import threading
import time
from typing import Any, Callable, Dict, FrozenSet, List, Optional, Tuple

import numpy as np

from ddl_tpu import envspec, integrity
from ddl_tpu.cluster.membership import (
    ClusterSupervisor,
    ClusterView,
    HostInfo,
    view_change,
    view_rejoin,
)
from ddl_tpu.concurrency import named_rlock
from ddl_tpu.exceptions import (
    DDLError,
    NetworkPartitioned,
    ShutdownRequested,
    SupervisorCrashed,
)
from ddl_tpu.faults import fault_point
from ddl_tpu.observability import Metrics, metrics as default_metrics

logger = logging.getLogger("ddl_tpu")

#: Journal-record magic (8 bytes), ahead of the u32 header length —
#: same framing as the checkpoint generation blobs (``DDLRES1\0``),
#: distinct magic so a journal can never be mistaken for a checkpoint.
_MAGIC = b"DDLJRN1\0"

#: Trailer identity for journal records (the ring headers carry the
#: 1-based producer index there; 0 is unused by any producer).
_JOURNAL_PRODUCER = 0

# Record kinds (the header's "kind" field).
KIND_BOOTSTRAP = "bootstrap"
KIND_VIEW_CHANGE = "view_change"
KIND_REJOIN = "rejoin"
KIND_EPOCH_RESTORE = "epoch_restore"
KIND_SCHEDULER = "scheduler"
KIND_PROMOTION = "promotion"
# Ingest-fabric records (ddl_tpu.serve.fabric appends them; string
# literals here, not imports — the serve layer depends on cluster, and
# replay only collects, never interprets, the fabric's payloads).
KIND_JOB_ADMISSION = "job_admission"
KIND_JOB_REGISTRY = "job_registry"


# -- view (de)serialization ------------------------------------------------


def host_to_dict(h: HostInfo) -> dict:
    return {
        "host_id": h.host_id,
        "loader_ranks": list(h.loader_ranks),
        "trainer_ranks": list(h.trainer_ranks),
        "cache_spill_dir": h.cache_spill_dir,
    }


def host_from_dict(d: dict) -> HostInfo:
    return HostInfo(
        host_id=int(d["host_id"]),
        loader_ranks=tuple(int(r) for r in d["loader_ranks"]),
        trainer_ranks=tuple(int(r) for r in d["trainer_ranks"]),
        cache_spill_dir=d.get("cache_spill_dir"),
    )


def view_to_dict(v: ClusterView) -> dict:
    return {
        "epoch": v.epoch,
        "n_shards": v.n_shards,
        "hosts": [host_to_dict(h) for h in v.hosts],
        "shard_ranges": [
            [hid, [list(pair) for pair in ranges]]
            for hid, ranges in v.shard_ranges
        ],
    }


def view_from_dict(d: dict) -> ClusterView:
    return ClusterView(
        epoch=int(d["epoch"]),
        hosts=tuple(host_from_dict(h) for h in d["hosts"]),
        shard_ranges=tuple(
            (int(hid), tuple(tuple(int(x) for x in pair) for pair in ranges))
            for hid, ranges in d["shard_ranges"]
        ),
        n_shards=int(d["n_shards"]),
    )


# -- record framing --------------------------------------------------------


def _encode_record(seq: int, kind: str, data: dict) -> bytes:
    """One journal record: magic | u32 header-len | JSON header |
    32-byte integrity trailer (crc over everything before it, trailer
    seq = record index — a spliced/reordered journal fails replay)."""
    header = json.dumps(
        {"seq": int(seq), "kind": kind, "data": data}, sort_keys=True
    ).encode()
    payload_bytes = len(_MAGIC) + 4 + len(header)
    blob = np.empty(payload_bytes + integrity.HEADER_BYTES, dtype=np.uint8)
    off = len(_MAGIC)
    blob[:off] = np.frombuffer(_MAGIC, dtype=np.uint8)
    blob[off : off + 4] = np.frombuffer(
        np.uint32(len(header)).tobytes(), dtype=np.uint8
    )
    off += 4
    blob[off : off + len(header)] = np.frombuffer(header, dtype=np.uint8)
    crc = integrity.window_crc(blob[:payload_bytes])
    integrity.write_header(
        blob, payload_bytes, seq=int(seq),
        producer_idx=_JOURNAL_PRODUCER, crc=crc,
    )
    return blob.tobytes()


def _decode_records(raw: bytes) -> Tuple[List[dict], Optional[str]]:
    """Parse records until the torn tail.  Returns ``(records, tail)``
    where ``tail`` describes why parsing stopped early (None on a clean
    end-of-file).  Every returned record verified its trailer."""
    records: List[dict] = []
    off = 0
    n = len(raw)
    idx = 0
    while off < n:
        head_end = off + len(_MAGIC) + 4
        if head_end > n:
            return records, f"torn tail at byte {off}: truncated frame"
        if raw[off : off + len(_MAGIC)] != _MAGIC:
            return records, f"bad record magic at byte {off}"
        hlen = int(
            np.frombuffer(raw[off + len(_MAGIC) : head_end], np.uint32)[0]
        )
        payload_bytes = len(_MAGIC) + 4 + hlen
        total = payload_bytes + integrity.HEADER_BYTES
        if off + total > n:
            return records, f"torn tail at byte {off}: truncated record"
        view = np.frombuffer(raw[off : off + total], dtype=np.uint8)
        err = integrity.verify_window(
            view, payload_bytes,
            expect_seq=idx, expect_producer=_JOURNAL_PRODUCER,
        )
        if err is not None:
            return records, f"record {idx} at byte {off}: {err}"
        try:
            header = json.loads(
                raw[off + len(_MAGIC) + 4 : off + payload_bytes].decode()
            )
        except (ValueError, UnicodeDecodeError) as e:
            return records, f"record {idx}: undecodable header ({e})"
        records.append(header)
        off += total
        idx += 1
    return records, None


class SupervisorJournal:
    """Append-only, CRC-trailered control-plane journal on disk.

    Thread-safety: appends happen on the supervisor's sweep thread and
    (promotion records) the HA stepper — serialized by the caller's
    ``cluster.supervisor`` lock, so the journal itself carries no lock.
    Each append is flushed + fsynced: a record is either fully durable
    or detectably torn, never silently half-applied at replay.
    """

    def __init__(self, path: str):
        self.path = os.path.abspath(path)
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        self.next_seq = 0
        if os.path.exists(self.path):
            with open(self.path, "rb") as f:
                records, tail = _decode_records(f.read())
            self.next_seq = len(records)
            if tail is not None:
                # Truncate the torn tail so appends resume at a clean
                # frame boundary (the crashed leader's half-record).
                logger.warning("supervision: journal %s: %s — truncating",
                               self.path, tail)
                self._truncate_to(records)

    def _truncate_to(self, records: List[dict]) -> None:
        clean = b"".join(
            _encode_record(r["seq"], r["kind"], r["data"]) for r in records
        )
        with open(self.path, "wb") as f:
            f.write(clean)
            f.flush()
            os.fsync(f.fileno())

    def append(self, kind: str, data: dict) -> int:
        """Durably append one record; returns its seq (= record index)."""
        seq = self.next_seq
        blob = _encode_record(seq, kind, data)
        with open(self.path, "ab") as f:
            f.write(blob)
            f.flush()
            os.fsync(f.fileno())
        self.next_seq = seq + 1
        return seq

    def records(self) -> List[dict]:
        """Every intact record, in order (torn tail dropped)."""
        if not os.path.exists(self.path):
            return []
        with open(self.path, "rb") as f:
            records, _ = _decode_records(f.read())
        return records


# -- replay ----------------------------------------------------------------


@dataclasses.dataclass
class ReplayedState:
    """What a standby reconstructs from the journal at promotion."""

    view: Optional[ClusterView]
    term: int
    departed: List[HostInfo]
    scheduler_state: Optional[dict]
    records: int
    epoch_restores: int
    #: Ingest-fabric state (ddl_tpu.serve.fabric): the newest job-
    #: registry snapshot and every applied admission decision, in
    #: journal order — the successor authority's exactly-once seed.
    job_registry: Optional[dict] = None
    admissions: List[dict] = dataclasses.field(default_factory=list)


def replay_journal(journal: "SupervisorJournal | str") -> ReplayedState:
    """Deterministically re-run the journal's state machine.

    Views evolve ONLY through the pure :func:`view_change` /
    :func:`view_rejoin` — the same functions the leader ran — so the
    replayed view is byte-identical to the leader's last journaled
    view.  The newest scheduler snapshot wins (each snapshot is a full
    export, not a delta).
    """
    if isinstance(journal, str):
        journal = SupervisorJournal(journal)
    view: Optional[ClusterView] = None
    term = 0
    departed: Dict[int, HostInfo] = {}  # ddl-lint: disable=DDL013
    scheduler_state: Optional[dict] = None
    job_registry: Optional[dict] = None
    admissions: List[dict] = []
    epoch_restores = 0
    records = journal.records()
    for rec in records:
        kind, data = rec["kind"], rec["data"]
        if kind == KIND_BOOTSTRAP:
            view = view_from_dict(data["view"])
        elif kind == KIND_VIEW_CHANGE:
            if view is None:
                raise DDLError("journal: view_change before bootstrap")
            dead = frozenset(int(h) for h in data["dead"])
            for h in view.hosts:
                if h.host_id in dead:
                    departed[h.host_id] = h
            view = view_change(view, dead)
            if view.epoch != int(data["epoch"]):
                # Concurrent leader changes raced notification order;
                # the recorded epoch is authoritative for the fence.
                logger.warning(
                    "supervision: replay epoch drift (%d != journaled %d)",
                    view.epoch, int(data["epoch"]),
                )
                view = dataclasses.replace(view, epoch=int(data["epoch"]))
        elif kind == KIND_REJOIN:
            if view is None:
                raise DDLError("journal: rejoin before bootstrap")
            host = host_from_dict(data["host"])
            departed.pop(host.host_id, None)
            view = view_rejoin(view, host)
        elif kind == KIND_EPOCH_RESTORE:
            if view is not None and int(data["epoch"]) > view.epoch:
                view = dataclasses.replace(view, epoch=int(data["epoch"]))
            epoch_restores += 1
        elif kind == KIND_SCHEDULER:
            scheduler_state = data["state"]
        elif kind == KIND_JOB_REGISTRY:
            job_registry = data["state"]
        elif kind == KIND_JOB_ADMISSION:
            admissions.append(data)
        elif kind == KIND_PROMOTION:
            term = max(term, int(data["term"]))
        # Unknown kinds are skipped, not fatal: an older standby must
        # still replay a newer leader's journal (forward compatibility).
    return ReplayedState(
        view=view,
        term=term,
        departed=list(departed.values()),
        scheduler_state=scheduler_state,
        records=len(records),
        epoch_restores=epoch_restores,
        job_registry=job_registry,
        admissions=admissions,
    )


# -- the journaled supervisor ----------------------------------------------


class JournaledSupervisor(ClusterSupervisor):
    """A :class:`ClusterSupervisor` whose every decision is journaled.

    Drop-in: identical sweep/lease/view-change behaviour, plus a
    journal listener registered FIRST (before any elastic ladder
    listener) so the record lands before downstream actions fire.
    ``bootstrap=False`` skips the bootstrap record — promotion uses it
    when rebuilding from a replay (the journal already holds history).
    """

    def __init__(
        self,
        view: ClusterView,
        journal: "SupervisorJournal | str",
        bootstrap: bool = True,
        **kwargs: Any,
    ):
        super().__init__(view, **kwargs)
        self.journal = (
            SupervisorJournal(journal) if isinstance(journal, str)
            else journal
        )
        if bootstrap:
            self.journal.append(
                KIND_BOOTSTRAP, {"view": view_to_dict(view)}
            )
        # Registered before any external listener: ElasticCluster binds
        # its ladder listeners at construction, after this line runs.
        self.add_listener(self._journal_change)

    def _journal_change(
        self, old: ClusterView, new: ClusterView, dead: FrozenSet[int]
    ) -> None:
        if dead:
            self.journal.append(
                KIND_VIEW_CHANGE,
                {"dead": sorted(dead), "epoch": new.epoch},
            )
            return
        # A rejoin notification: the (single) host in new but not old.
        old_ids = {h.host_id for h in old.hosts}
        for h in new.hosts:
            if h.host_id not in old_ids:
                self.journal.append(KIND_REJOIN, {"host": host_to_dict(h)})
                return

    def restore_epoch(self, epoch: int) -> None:
        before = self.view.epoch
        super().restore_epoch(epoch)
        if self.view.epoch > before:
            self.journal.append(KIND_EPOCH_RESTORE, {"epoch": epoch})

    def journal_scheduler_state(self, scheduler: Any) -> int:
        """Snapshot a :class:`~ddl_tpu.serve.tenancy.FairShareScheduler`
        into the journal (full export, newest-wins at replay) so a
        promoted standby preserves per-tenant deficits and admission
        order — the fairness half of the failover contract."""
        state = scheduler.export_state()
        seq = self.journal.append(KIND_SCHEDULER, {"state": state})
        self.metrics.incr("cluster.scheduler_snapshots")
        return seq

    def journal_job_registry(self, registry: Any) -> int:
        """Snapshot a :class:`~ddl_tpu.serve.jobs.JobRegistry` into the
        journal (the scheduler-snapshot pattern) so a promoted standby
        reconstructs the fabric's job table beside its ledger."""
        state = registry.export_state()
        seq = self.journal.append(KIND_JOB_REGISTRY, {"state": state})
        self.metrics.incr("cluster.job_registry_snapshots")
        return seq


# -- lease-based failover --------------------------------------------------


class SupervisorHA:
    """Leader + standby tier over one shared journal.

    The deployment model: the leader and every standby see the same
    journal (shared filesystem — the same substrate the checkpoint
    generations already require) and the stepper drives
    :meth:`step` periodically.  In-process (tests, the failover bench)
    one ``SupervisorHA`` plays the whole tier: it renews the leader's
    lease each step, detects expiry, and promotes by journal replay.

    Fencing: the tier's ``term`` starts at 1 and bumps on every
    promotion.  :meth:`promote` stamps the new term onto the consumer
    connection's control senders, so every post-promotion command
    out-fences anything a zombie ex-leader still emits (the zombie's
    envelopes carry the old term and die, acked-but-unapplied, at each
    :class:`~ddl_tpu.transport.envelope.EnvelopeReceiver`).
    """

    def __init__(
        self,
        leader: JournaledSupervisor,
        elastic: Any = None,
        scheduler: Any = None,
        lease_s: Optional[float] = None,
        standbys: Optional[int] = None,
        node_id: int = 0,
        metrics: Optional[Metrics] = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        """``elastic`` (an :class:`~ddl_tpu.cluster.elastic
        .ElasticCluster`) and ``scheduler`` (a FairShareScheduler) are
        the rebind targets at promotion; either may be None.  ``node_id``
        identifies the stepping node at the ``cluster.supervise`` fault
        site (``producer_idx`` selector)."""
        self.leader: Optional[JournaledSupervisor] = leader
        self.journal = leader.journal
        self.elastic = elastic
        self.scheduler = scheduler
        self.lease_s = (
            float(envspec.get("DDL_TPU_SUPERVISOR_LEASE_S"))
            if lease_s is None else float(lease_s)
        )
        self.standbys = (
            int(envspec.get("DDL_TPU_SUPERVISOR_STANDBYS"))
            if standbys is None else int(standbys)
        )
        self.node_id = int(node_id)
        self.metrics = metrics or default_metrics()
        self._clock = clock
        self.term = 1
        self.promotions = 0
        self.last_takeover_s: Optional[float] = None
        #: The ex-leader after a promotion — split-brain tests drive its
        #: stale-term sends; production drops the reference eventually.
        self.deposed: Optional[JournaledSupervisor] = None
        self._lease_deadline = clock() + self.lease_s
        self._lease_lapsed_at: Optional[float] = None
        self._lock = named_rlock("cluster.supervisor")
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.poll_interval_s = leader.poll_interval_s
        self.metrics.set_gauge("cluster.term", self.term)

    # -- the HA pass -------------------------------------------------------

    def step(self, now: Optional[float] = None) -> Optional[ClusterView]:
        """One HA pass: sweep membership through the live leader and
        renew its lease; on lease expiry (the leader crashed, or a
        partition ate its renewals past the budget), promote a standby.
        Returns the view when a promotion produced one, else None."""
        now = self._clock() if now is None else now
        with self._lock:
            partitioned = False
            try:
                # Chaos site (producer_idx = the stepping node's id):
                # SUPERVISOR_CRASH kills the leader outright;
                # NETWORK_PARTITION suppresses this step's lease renewal
                # without killing it — the split-brain producer.
                # Must sit inside the critical section: it exists to
                # crash/delay mid-pass; disarmed it is one attr read.
                fault_point(  # ddl-verify: disable=VP002
                    "cluster.supervise", producer_idx=self.node_id
                )
            except SupervisorCrashed:
                if self.leader is not None:
                    self._leader_died("fault:SUPERVISOR_CRASH")
            except NetworkPartitioned:
                partitioned = True
                self.metrics.incr("cluster.partition_steps")
            if self.leader is not None and not partitioned:
                try:
                    self.leader.sweep(now)
                except (ShutdownRequested, KeyboardInterrupt):
                    raise
                except Exception:
                    # A sweep crash is a leader failure, not a monitor
                    # wedge: stop renewing and let the lease decide.
                    logger.exception("supervision: leader sweep raised")
                    self._leader_died("sweep-exception")
                else:
                    self._lease_deadline = now + self.lease_s
                    self.metrics.incr("cluster.lease_renewals")
                    return None
            if now < self._lease_deadline:
                return None  # within the lease budget: no churn yet
            if self._lease_lapsed_at is None:
                self._lease_lapsed_at = now
            return self.promote(now)

    def kill_leader(self) -> None:
        """Operator/chaos hammer: the leader is gone NOW (its lease
        still runs out the budget before a standby takes over)."""
        with self._lock:
            if self.leader is not None:
                self._leader_died("killed")

    def _leader_died(self, reason: str) -> None:
        self.deposed = self.leader
        self.leader = None
        self.metrics.incr("cluster.supervisor_crashes")
        logger.error("supervision: leader lost (%s) — lease expires in "
                     "%.3fs", reason, self._lease_deadline - self._clock())
        self._flight("supervisor.crashed", {"reason": reason})

    # -- promotion ---------------------------------------------------------

    def promote(self, now: Optional[float] = None) -> Optional[ClusterView]:
        """Promote a standby: replay the journal, rebuild the
        supervisor, adopt the scheduler snapshot, bump the fencing
        term, re-fence the control plane, and re-send the current
        view's adoptions through the acked envelope seam."""
        now = self._clock() if now is None else now
        with self._lock:
            if self.standbys < 1:
                # No standby provisioned: a fatal gap, surfaced loudly
                # once (the data plane owns the ensuing failure).
                self.metrics.incr("cluster.promotions_refused")
                logger.error(
                    "supervision: lease lapsed with zero standbys "
                    "(DDL_TPU_SUPERVISOR_STANDBYS=0) — cannot promote"
                )
                self._lease_deadline = now + self.lease_s
                return None
            t0 = self._clock()
            state = replay_journal(self.journal)
            if state.view is None:
                raise DDLError(
                    "supervision: journal holds no bootstrap view — "
                    "nothing to promote from"
                )
            if self.leader is not None:
                self.deposed = self.leader
            old_term = self.term
            self.term = max(self.term, state.term) + 1
            sup = JournaledSupervisor(
                state.view,
                journal=self.journal,
                bootstrap=False,  # history already journaled
                lease_s=self.lease_s,
                poll_interval_s=self.poll_interval_s,
                metrics=self.metrics,
                clock=self._clock,
                local_host_ids=(
                    set(self.deposed.local_host_ids)
                    if self.deposed is not None
                    and self.deposed.local_host_ids is not None
                    else None
                ),
            )
            sup._departed_hosts = list(state.departed)
            self.journal.append(
                KIND_PROMOTION,
                {"term": self.term, "epoch": state.view.epoch,
                 "node": self.node_id},
            )
            self.leader = sup
            self._lease_deadline = now + self.lease_s
            if self.scheduler is not None and state.scheduler_state:
                self.scheduler.adopt_state(state.scheduler_state)
                self.metrics.incr("cluster.scheduler_adoptions")
            if self.elastic is not None:
                self.elastic.rebind_supervisor(sup)
                conn = getattr(
                    getattr(self.elastic, "workers", None), "connection", None
                )
                if conn is not None:
                    # Every post-promotion command now out-fences the
                    # zombie; then re-ship the replayed view's adoptions
                    # (dedup'd at the producer if the old leader's last
                    # sends did land).
                    conn.set_control_fence(self.term)
                self.elastic._send_adoptions(state.view, None)
            self.promotions += 1
            lapsed = self._lease_lapsed_at
            self._lease_lapsed_at = None
            takeover = (self._clock() - t0) + (
                max(0.0, now - lapsed) if lapsed is not None else 0.0
            )
            self.last_takeover_s = takeover
            self.metrics.incr("cluster.promotions")
            self.metrics.set_gauge("cluster.term", self.term)
            self.metrics.set_gauge("cluster.takeover_s", takeover)
            logger.warning(
                "supervision: standby promoted — term %d -> %d, epoch %d, "
                "%d journal record(s) replayed, takeover %.3fs",
                old_term, self.term, state.view.epoch, state.records,
                takeover,
            )
            self._flight(
                "supervisor.promoted",
                {"term": self.term, "epoch": state.view.epoch,
                 "records": state.records, "takeover_s": round(takeover, 6)},
            )
            return state.view

    def _flight(self, reason: str, extra: dict) -> None:
        from ddl_tpu.obs import recorder as _flight

        if _flight.armed_recorder() is not None:
            _flight.flight_dump(reason, metrics=self.metrics, extra=extra)

    # -- optional background loop ------------------------------------------

    def start(self) -> "SupervisorHA":
        self._thread = threading.Thread(
            target=self._run, name="ddl-supervisor-ha", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(self.poll_interval_s * 2 + 1)

    def __enter__(self) -> "SupervisorHA":
        return self.start()

    def __exit__(self, *exc: Any) -> None:
        self.stop()

    def _run(self) -> None:
        # DDL018: bounded by the stop event's timed wait; every step
        # consults the leadership lease — never a free spin.
        while not self._stop.wait(self.poll_interval_s):
            try:
                self.step()
            except (ShutdownRequested, KeyboardInterrupt):
                return
            except Exception:
                # A crashing step must never disable failover itself.
                logger.exception("supervision: HA step raised; continuing")
                continue
