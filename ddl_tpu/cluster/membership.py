"""Membership control plane: host leases, epoch-fenced view changes.

The single-host watchdog (``ddl_tpu.watchdog``) detects a dead *worker*;
"millions of users" means surviving a dead *host* (ROADMAP item 3).
This module is the host-level half: every physical host in the run is a
:class:`HostInfo` row in a :class:`ClusterView`, its liveness is a lease
in a :class:`LeaseTable` refreshed by heartbeats layered over whatever
liveness signal exists (transport-channel/worker liveness locally, an
external beat feed across hosts), and a :class:`ClusterSupervisor`
sweep turns lease expiry into a **deterministic, epoch-fenced view
change**:

- *Deterministic*: the successor view is a pure function of (previous
  view, dead-host set) — :func:`view_change` — so every surviving
  consumer that observes the same failure computes byte-identical new
  shard assignments with **no coordination round** (the decentralised-
  agreement trick ``shuffle.exchange_permutation`` already uses for the
  exchange schedule, applied to membership).
- *Epoch-fenced*: every view carries a monotonically increasing
  ``epoch``; downstream appliers (loader pool updates, producer shard
  adoptions) ignore anything stamped with a stale epoch, so a slow
  message from view N can never undo view N+1.

Failure *declaration* is conservative: a host leaves the view only when
its lease expires (no beat for ``lease_s``), when a ``HOST_LOSS`` fault
fires at the ``cluster.heartbeat`` site, or when an operator/test calls
:meth:`ClusterSupervisor.declare_host_loss`.  A single dropped beat
(``HEARTBEAT_DROP``) only ages the lease — transient heartbeat loss
under the lease budget causes zero membership churn.
"""

from __future__ import annotations

import dataclasses
import logging
import threading

from ddl_tpu.concurrency import named_lock, named_rlock
import time
from typing import (
    Any,
    Callable,
    Dict,
    FrozenSet,
    Iterable,
    List,
    Optional,
    Tuple,
)

from ddl_tpu.exceptions import (
    DDLError,
    HeartbeatDropped,
    HostLostError,
    ShutdownRequested,
)
from ddl_tpu.faults import fault_point
from ddl_tpu.observability import Metrics, metrics as default_metrics
from ddl_tpu.cluster.pool import LoaderPool

logger = logging.getLogger("ddl_tpu")

#: Shard-range type: half-open ``(start, stop)`` shard-index pairs.
Ranges = Tuple[Tuple[int, int], ...]


@dataclasses.dataclass(frozen=True)
class HostInfo:
    """One physical host in the cluster view.

    ``loader_ranks`` are 1-based producer indices (the repo-wide rank
    convention: ring ``i`` belongs to producer ``i + 1``) registered as
    the LOADER pool contribution of this host; ``trainer_ranks`` are
    the consumer process indices it hosts.  The two sets are disjoint
    roles by design (MPMD-style decoupling, arXiv:2412.14374): a host
    may carry loader ranks, trainer ranks, or both, and the loader pool
    resizes without touching the trainer set.  ``cache_spill_dir`` is
    the host's shard-cache disk tier — on host loss the survivors adopt
    it for a warm start (docs/CACHING.md) when the path is reachable
    (shared filesystem; a host-local path simply fails adoption).
    """

    host_id: int
    loader_ranks: Tuple[int, ...] = ()
    trainer_ranks: Tuple[int, ...] = ()
    cache_spill_dir: Optional[str] = None


def partition_shards(n_shards: int, host_ids: List[int]) -> Dict[int, Ranges]:
    """Deterministic contiguous partition of ``range(n_shards)`` over
    ``host_ids`` (sorted): host k of H gets the k-th of H near-equal
    contiguous ranges.  The base assignment every view derives from —
    identical on every process by construction."""
    ids = sorted(set(host_ids))
    if not ids:
        raise DDLError("cannot partition shards over zero hosts")
    out: Dict[int, Ranges] = {}
    n = len(ids)
    base, extra = divmod(n_shards, n)
    start = 0
    for k, hid in enumerate(ids):
        size = base + (1 if k < extra else 0)
        out[hid] = ((start, start + size),) if size else ()
        start += size
    return out


@dataclasses.dataclass(frozen=True)
class ClusterView:
    """An epoch-stamped membership snapshot.

    ``hosts`` is sorted by ``host_id``; ``shard_ranges`` maps host_id →
    its range list (tuple-of-pairs, hashable).  Views are immutable —
    change happens only through :func:`view_change` / :func:`view_rejoin`
    which return a successor with ``epoch + 1``.
    """

    epoch: int
    hosts: Tuple[HostInfo, ...]
    shard_ranges: Tuple[Tuple[int, Ranges], ...]
    n_shards: int = 0

    @staticmethod
    def bootstrap(
        hosts: List[HostInfo], n_shards: int = 0, epoch: int = 0
    ) -> "ClusterView":
        """The initial view: hosts sorted, shards partitioned by the
        deterministic base assignment."""
        hosts = tuple(sorted(hosts, key=lambda h: h.host_id))
        if len({h.host_id for h in hosts}) != len(hosts):
            raise DDLError("duplicate host_id in cluster bootstrap")
        ranges = partition_shards(n_shards, [h.host_id for h in hosts])
        return ClusterView(
            epoch=epoch,
            hosts=hosts,
            shard_ranges=tuple(sorted(ranges.items())),
            n_shards=n_shards,
        )

    def host(self, host_id: int) -> Optional[HostInfo]:
        for h in self.hosts:
            if h.host_id == host_id:
                return h
        return None

    def ranges_of(self, host_id: int) -> Ranges:
        for hid, r in self.shard_ranges:
            if hid == host_id:
                return r
        return ()

    def host_of_rank(self, rank: int) -> Optional[HostInfo]:
        """The host carrying loader rank ``rank`` (1-based)."""
        for h in self.hosts:
            if rank in h.loader_ranks:
                return h
        return None

    def loader_pool(self) -> LoaderPool:
        """The loader pool this view publishes: every member host's
        loader ranks as 0-based ring targets, generation = epoch (the
        fence downstream appliers compare against)."""
        members = sorted(
            r - 1 for h in self.hosts for r in h.loader_ranks
        )
        return LoaderPool(members=tuple(members), generation=self.epoch)

    @property
    def loader_ranks(self) -> Tuple[int, ...]:
        return tuple(sorted(r for h in self.hosts for r in h.loader_ranks))

    @property
    def trainer_ranks(self) -> Tuple[int, ...]:
        return tuple(sorted(r for h in self.hosts for r in h.trainer_ranks))


def view_change(view: ClusterView, dead: FrozenSet[int]) -> ClusterView:
    """The successor view after ``dead`` hosts leave — a PURE function.

    Survivors keep their existing ranges (minimal data movement: only
    orphaned shards move); the dead hosts' range lists are dealt
    round-robin, in sorted order, onto survivors sorted by host_id.
    Every consumer computing this from the same (view, dead) pair gets
    the identical successor — the no-coordination agreement property
    the chaos tests assert.
    """
    dead = frozenset(dead)
    survivors = tuple(h for h in view.hosts if h.host_id not in dead)
    if not survivors:
        raise HostLostError(
            f"view change at epoch {view.epoch}: no surviving hosts "
            f"(dead={sorted(dead)})"
        )
    if not dead & {h.host_id for h in view.hosts}:
        return view  # nothing to do; the epoch fence must not advance
    ranges = {hid: list(r) for hid, r in view.shard_ranges if hid not in dead}
    orphaned: List[Tuple[int, int]] = []
    for hid, r in sorted(view.shard_ranges):
        if hid in dead:
            orphaned.extend(r)
    ids = sorted(h.host_id for h in survivors)
    for k, rng in enumerate(sorted(orphaned)):
        ranges.setdefault(ids[k % len(ids)], []).append(rng)
    return ClusterView(
        epoch=view.epoch + 1,
        hosts=survivors,
        shard_ranges=tuple(
            sorted((hid, tuple(sorted(r))) for hid, r in ranges.items())
        ),
        n_shards=view.n_shards,
    )


def view_rejoin(view: ClusterView, host: HostInfo) -> ClusterView:
    """The successor view after ``host`` (re)joins.

    Unlike :func:`view_change` — which moves only orphans — a rejoin
    re-partitions ALL shards from the deterministic base assignment:
    the epoch fence makes the wholesale move safe (every consumer and
    producer switches at the same fence), and it restores the balanced
    layout instead of accreting skew across loss/rejoin cycles.
    """
    if view.host(host.host_id) is not None:
        raise DDLError(f"host {host.host_id} is already in the view")
    hosts = tuple(
        sorted(view.hosts + (host,), key=lambda h: h.host_id)
    )
    ranges = partition_shards(view.n_shards, [h.host_id for h in hosts])
    return ClusterView(
        epoch=view.epoch + 1,
        hosts=hosts,
        shard_ranges=tuple(sorted(ranges.items())),
        n_shards=view.n_shards,
    )


class LeaseTable:
    """Host-id → lease-deadline map.  Thread-safe, clock-injectable.

    ``beat`` refreshes a lease; :meth:`expired` returns hosts whose
    lease lapsed.  Pure mechanism — the HEARTBEAT fault points and the
    view-change policy live in :class:`ClusterSupervisor`.
    """

    def __init__(self, lease_s: float = 5.0, clock: Callable[[], float] = time.monotonic):
        self.lease_s = float(lease_s)
        self._clock = clock
        self._lock = named_lock("cluster.membership")
        # host_id -> lease deadline; bounded by the registered host set
        # (register/release are the only growth/shrink sites).
        self._deadline: Dict[int, float] = {}  # ddl-lint: disable=DDL013

    def register(self, host_id: int, now: Optional[float] = None) -> None:
        now = self._clock() if now is None else now
        with self._lock:
            self._deadline[host_id] = now + self.lease_s

    def release(self, host_id: int) -> None:
        with self._lock:
            self._deadline.pop(host_id, None)

    def beat(self, host_id: int, now: Optional[float] = None) -> None:
        now = self._clock() if now is None else now
        with self._lock:
            if host_id in self._deadline:
                self._deadline[host_id] = now + self.lease_s

    def remaining(self, host_id: int, now: Optional[float] = None) -> float:
        now = self._clock() if now is None else now
        with self._lock:
            dl = self._deadline.get(host_id)
        return float("inf") if dl is None else dl - now

    def expired(self, now: Optional[float] = None) -> List[int]:
        now = self._clock() if now is None else now
        with self._lock:
            return sorted(
                hid for hid, dl in self._deadline.items() if now > dl
            )

    def registered(self) -> List[int]:
        with self._lock:
            return sorted(self._deadline)


class ClusterSupervisor:
    """Owns the current view + leases; sweeps liveness into view changes.

    Heartbeat *sources* are pluggable per host: any zero-arg callable
    returning truthy-while-alive (worker/process liveness via
    :func:`ddl_tpu.cluster.elastic.worker_alive_source`, transport
    channels via ``ControlChannel.alive``, a shared-filesystem beat
    file, ...).  A host WITHOUT a source is beaten externally through
    :meth:`beat` (e.g. a remote host's beat arriving over DCN).

    A source returning False does NOT declare the host dead — it merely
    stops refreshing the lease, and only lease EXPIRY (or an explicit
    :meth:`declare_host_loss`, or the ``HOST_LOSS`` fault) changes the
    view.  That gap is the recovery ladder's rung separation: a crashed
    producer whose watchdog respawn lands within ``lease_s`` revives
    the source before the lease lapses, so rung 1 (respawn) never
    escalates to rung 2 (host loss) by accident.  Size ``lease_s``
    above the watchdog's respawn latency (docs/ROBUSTNESS.md).
    """

    def __init__(
        self,
        view: ClusterView,
        lease_s: float = 5.0,
        poll_interval_s: float = 0.5,
        metrics: Optional[Metrics] = None,
        clock: Callable[[], float] = time.monotonic,
        local_host_ids: Optional[Iterable[int]] = None,
    ):
        """``local_host_ids`` names the hosts whose loader ranks are
        THIS process's ring indices (rank numbering is per process:
        every host's workers are locally ranks 1..n, so without the
        locality set a remote host's ranks would alias local ones —
        ``lost_ranks`` would then mute the watchdog for live LOCAL
        producers after a REMOTE loss).  ``None`` (default) means every
        view host is local — the single-process mock-host topologies.
        ``ElasticCluster(local_host_id=...)`` is the usual setter."""
        self.view = view
        self.local_host_ids: Optional[set] = (
            set(local_host_ids) if local_host_ids is not None else None
        )
        self.poll_interval_s = poll_interval_s
        self.metrics = metrics or default_metrics()
        self.leases = LeaseTable(lease_s, clock)
        self._clock = clock
        for h in view.hosts:
            self.leases.register(h.host_id)
        # host_id -> liveness callable: bounded by the view's host set
        # (attach_source is only ever called per member host).
        self._sources: Dict[int, Callable[[], bool]] = {}  # ddl-lint: disable=DDL013
        self._listeners: List[
            Callable[[ClusterView, ClusterView, FrozenSet[int]], None]
        ] = []
        self._rank_listeners: List[Callable[[int], None]] = []
        self._departed_hosts: List[HostInfo] = []
        self._no_survivor_logged = False
        self._lock = named_rlock("cluster.supervisor")
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.metrics.set_gauge("cluster.epoch", view.epoch)
        self.metrics.set_gauge("cluster.hosts", len(view.hosts))

    # -- wiring ------------------------------------------------------------

    def attach_source(self, host_id: int, alive: Callable[[], bool]) -> None:
        self._sources[host_id] = alive

    def add_listener(
        self,
        fn: Callable[[ClusterView, ClusterView, FrozenSet[int]], None],
    ) -> None:
        """``fn(old_view, new_view, dead_ids)`` after every view change
        (``dead_ids`` empty on rejoin).  Called on the sweeping thread —
        listeners must be quick and must not block on the consumer."""
        self._listeners.append(fn)

    def add_rank_listener(self, fn: Callable[[int], None]) -> None:
        """``fn(rank)`` after a loader rank is respawned (the watchdog's
        rung-1 recovery).  The elastic ladder uses it to re-ship the
        CURRENT view's shard adoption to the fresh incarnation — an
        adoption sent while the predecessor's channel was mid-swap is
        lost, and a survivor serving stale ranges would drop shards."""
        self._rank_listeners.append(fn)

    def rank_respawned(self, rank: int) -> None:
        """Report a respawned loader rank (called by the watchdog)."""
        for fn in self._rank_listeners:
            try:
                fn(rank)
            except (ShutdownRequested, KeyboardInterrupt):
                raise
            except Exception:
                logger.exception("cluster: rank-respawn listener raised")

    def is_local(self, host_id: int) -> bool:
        return self.local_host_ids is None or host_id in self.local_host_ids

    def lost_ranks(self) -> FrozenSet[int]:
        """LOCAL loader ranks (1-based ring indices of this process) of
        hosts that have LEFT the view — the watchdog consults this so a
        departed host's dead workers are the cluster ladder's to
        handle, not respawn fodder.  Remote hosts' ranks are excluded:
        rank numbering is per process, and a remote loss must never
        mute monitoring of the identically-numbered LOCAL workers."""
        with self._lock:
            return frozenset(
                r
                for h in self._departed_hosts
                if self.is_local(h.host_id)
                for r in h.loader_ranks
            )

    # -- the sweep ---------------------------------------------------------

    def beat(self, host_id: int, now: Optional[float] = None) -> None:
        """External heartbeat feed (cross-host: the remote host's beat
        arriving over whatever control plane exists there)."""
        self.leases.beat(host_id, now)
        self.metrics.incr("cluster.heartbeats")

    def sweep(self, now: Optional[float] = None) -> Optional[ClusterView]:
        """One liveness pass: refresh leases from attached sources, then
        turn expired leases into a view change.  Returns the new view
        when membership changed, else None.  Drives from the watchdog's
        monitor thread (``Watchdog(cluster=...)``) or :meth:`start`'s
        own loop."""
        now = self._clock() if now is None else now
        dead: set = set()
        for h in self.view.hosts:
            try:
                # Chaos site (producer_idx carries the HOST id):
                # HEARTBEAT_DROP loses this beat, HOST_LOSS declares the
                # host dead immediately.
                fault_point("cluster.heartbeat", producer_idx=h.host_id)
            except HeartbeatDropped:
                self.metrics.incr("cluster.heartbeats_dropped")
                continue  # the lease ages; only expiry changes the view
            except HostLostError:
                dead.add(h.host_id)
                continue
            src = self._sources.get(h.host_id)
            if src is None:
                continue  # externally beaten (see beat())
            if src():
                self.leases.beat(h.host_id, now)
                self.metrics.incr("cluster.heartbeats")
        live_ids = {h.host_id for h in self.view.hosts}
        dead |= set(self.leases.expired(now)) & live_ids
        if not dead:
            return None
        if dead >= live_ids:
            # A sweep must never empty the view: with zero survivors
            # there is no one to re-partition onto, and a crash-looping
            # monitor would bury the real failure.  Keep the view (the
            # data plane will surface its own error) and log ONCE.
            if not self._no_survivor_logged:
                self._no_survivor_logged = True
                logger.error(
                    "cluster: every host's lease lapsed (%s) — refusing "
                    "to empty the view; the data plane owns this failure",
                    sorted(dead),
                )
            self.metrics.incr("cluster.no_survivor_sweeps")
            return None
        return self._change_view(frozenset(dead))

    def declare_host_loss(
        self, host_id: int, graceful: bool = False
    ) -> ClusterView:
        """Operator/ladder declaration: the host is gone NOW (no lease
        wait) — e.g. the scheduler reported the node preempted.
        ``graceful`` marks a planned departure (the autoscaler's
        drain-then-release): the identical epoch-fenced view change
        runs, but it counts as ``cluster.host_drains`` — not
        ``cluster.host_losses``, the failure counter alerting keys on —
        and logs at WARNING, not ERROR."""
        return self._change_view(frozenset({host_id}), graceful=graceful)

    def _change_view(
        self, dead: FrozenSet[int], graceful: bool = False
    ) -> ClusterView:
        with self._lock:
            old = self.view
            # Chaos site: a crash here exercises the supervisor's
            # sweep-crash discrimination (the view must either change
            # completely or not at all — new is computed before any
            # state mutates).
            # The chaos site must sit INSIDE the critical section — it
            # exists to crash/delay mid-view-change and prove the sweep
            # sees all-or-nothing state.  fault_point is a disarmed
            # no-op outside chaos tests, and an armed delay is bounded
            # by the plan.
            fault_point("cluster.view_change")  # ddl-verify: disable=VP002
            new = view_change(old, dead)
            if new is old:
                return old
            self._departed_hosts.extend(
                h for h in old.hosts if h.host_id in dead
            )
            self.view = new
            for hid in dead:
                self.leases.release(hid)
        self.metrics.incr("cluster.view_changes")
        self.metrics.incr(
            "cluster.host_drains" if graceful else "cluster.host_losses",
            len(dead),
        )
        self.metrics.set_gauge("cluster.epoch", new.epoch)
        self.metrics.set_gauge("cluster.hosts", len(new.hosts))
        logger.log(
            logging.WARNING if graceful else logging.ERROR,
            "cluster: host(s) %s %s — view epoch %d -> %d, shard "
            "ranges re-partitioned over %d survivor(s)",
            sorted(dead), "drained" if graceful else "lost",
            old.epoch, new.epoch, len(new.hosts),
        )
        self._notify(old, new, dead)
        return new

    def rejoin(self, host: HostInfo) -> ClusterView:
        """Re-admit ``host`` at a fresh epoch fence (full deterministic
        re-partition — :func:`view_rejoin`); its lease starts fresh."""
        with self._lock:
            old = self.view
            new = view_rejoin(old, host)
            self.view = new
            self.leases.register(host.host_id)
            self._departed_hosts = [
                h for h in self._departed_hosts if h.host_id != host.host_id
            ]
        self.metrics.incr("cluster.view_changes")
        self.metrics.incr("cluster.rejoins")
        self.metrics.set_gauge("cluster.epoch", new.epoch)
        self.metrics.set_gauge("cluster.hosts", len(new.hosts))
        logger.warning(
            "cluster: host %d rejoined — view epoch %d -> %d",
            host.host_id, old.epoch, new.epoch,
        )
        self._notify(old, new, frozenset())
        return new

    def restore_epoch(self, epoch: int) -> None:
        """Checkpoint resume: fast-forward the epoch fence so views
        minted after restore can never be mistaken for pre-checkpoint
        ones (``LoaderCheckpoint.cluster_epoch``)."""
        with self._lock:
            if epoch > self.view.epoch:
                self.view = dataclasses.replace(self.view, epoch=epoch)
                self.metrics.set_gauge("cluster.epoch", epoch)

    def _notify(
        self, old: ClusterView, new: ClusterView, dead: FrozenSet[int]
    ) -> None:
        for fn in self._listeners:
            try:
                fn(old, new, dead)
            except (ShutdownRequested, KeyboardInterrupt):
                raise
            except Exception:
                # One listener's crash must not silence the others (or
                # kill the monitor thread) — the ladder keeps climbing.
                logger.exception("cluster: view-change listener raised")

    # -- optional background loop (the watchdog drives sweeps when one
    # is attached; standalone deployments use this) ------------------------

    def start(self) -> "ClusterSupervisor":
        self._thread = threading.Thread(
            target=self._run, name="ddl-cluster", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(self.poll_interval_s * 2 + 1)

    def __enter__(self) -> "ClusterSupervisor":
        return self.start()

    def __exit__(self, *exc: Any) -> None:
        self.stop()

    def _run(self) -> None:
        # DDL018: the loop is bounded by the stop event's timed wait and
        # every sweep consults lease expiry — never a free spin.
        while not self._stop.wait(self.poll_interval_s):
            try:
                self.sweep()
            except (ShutdownRequested, KeyboardInterrupt):
                return  # teardown reached the monitor: stop cleanly
            except Exception:
                # A crashing sweep must never silently disable host-loss
                # detection (the watchdog.sweep contract, host-level).
                logger.exception("cluster: sweep raised; continuing")
                continue

    def wait_for_epoch(self, epoch: int, timeout_s: float = 30.0) -> bool:
        """Block until the view reaches ``epoch`` (tests/bootstrap
        barriers).  DDL018-compliant: the wait is deadline-bounded."""
        deadline = self._clock() + timeout_s
        while self.view.epoch < epoch:
            if self._clock() >= deadline:
                return False
            time.sleep(0.01)
        return True
