"""Loader-pool decoupling seam: loader ranks as a resizable pool.

The paper's producer/consumer pairing is static: a loader's rank set is
fixed at handshake and the consumer rotates over ALL of it forever.
MPMD-style disaggregation (arXiv:2412.14374) wants the opposite —
loader ranks as a POOL, registered in the cluster view, that grows and
shrinks independently of the trainer ranks, with the consumer serving
"whatever pool the view publishes".

:class:`LoaderPool` is that published value: an immutable, generation-
stamped set of ring targets.  ``DistributedDataLoader.apply_pool``
consumes it (rotation restricted to members, stale generations
ignored); :meth:`ClusterView.loader_pool` mints it (generation ==
view epoch, so the membership fence and the pool fence are the same
number).
"""

from __future__ import annotations

import dataclasses
from typing import Iterable, Tuple

from ddl_tpu.exceptions import DDLError


@dataclasses.dataclass(frozen=True)
class LoaderPool:
    """An immutable set of active loader ring targets (0-based).

    ``generation`` is the epoch fence: appliers must ignore a pool whose
    generation is <= the last one they applied (a slow message from
    view N must never undo view N+1).
    """

    members: Tuple[int, ...]
    generation: int = 0

    def __post_init__(self) -> None:
        members = tuple(sorted(set(int(m) for m in self.members)))
        if any(m < 0 for m in members):
            raise DDLError(f"negative ring target in pool: {members}")
        object.__setattr__(self, "members", members)

    def __contains__(self, target: int) -> bool:
        return target in self.members

    def __len__(self) -> int:
        return len(self.members)

    def without(self, targets: Iterable[int]) -> "LoaderPool":
        gone = set(targets)
        return LoaderPool(
            members=tuple(m for m in self.members if m not in gone),
            generation=self.generation + 1,
        )

    def union(self, targets: Iterable[int]) -> "LoaderPool":
        return LoaderPool(
            members=tuple(set(self.members) | set(targets)),
            generation=self.generation + 1,
        )

    def next_member(self, after: int, include: bool = False) -> int:
        """The next pool member in cyclic target order strictly after
        ``after`` (or ``after`` itself when ``include`` and it is a
        member) — the rotation primitive the loader uses."""
        if not self.members:
            raise DDLError("loader pool is empty")
        if include and after in self.members:
            return after
        for m in self.members:
            if m > after:
                return m
        return self.members[0]
