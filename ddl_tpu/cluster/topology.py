"""Topology model: inter-host link costs, declared or probed.

Cloud Collectives (arXiv:2105.14088) showed that cloud fabrics are NOT
uniform — intra-rack/intra-island links can run an order of magnitude
faster than cross-island ones — and that simply *reordering ranks onto
the measured topology* recovers real bandwidth without touching the
collective algorithm.  ddl_tpu's window-transport pattern (each loader
host streams committed windows to a consumer host) is exactly such a
rank-placement problem, so this module gives the placement engine
(:mod:`ddl_tpu.cluster.placement`) its input: a host→host bandwidth
table, either **declared** (the operator knows the racks) or **probed**
(a pluggable pairwise transfer measured per link).

Off-pod there is no second host to probe against, so the default probe
transfer is an honest host-local stand-in (a real memcpy of the
payload); deployments pass a ``transfer`` callable that moves bytes over
the real fabric (docs/DEPLOY.md has the recipe).
"""

from __future__ import annotations

import time
from typing import Callable, Dict, Iterable, List, Optional, Tuple

import numpy as np

from ddl_tpu.exceptions import DDLError

#: Effectively-infinite bandwidth stand-in for a host talking to itself
#: (loopback never crosses the fabric).
LOCAL_BYTES_PER_S = 1e15


class LinkCosts:
    """Symmetric host→host bandwidth table (bytes/s).

    ``bytes_per_s(a, b)`` is the modeled/measured bandwidth of the
    a→b link; unknown pairs fall back to ``default_bytes_per_s`` (the
    conservative cross-island floor), ``a == b`` to
    :data:`LOCAL_BYTES_PER_S`.
    """

    def __init__(
        self,
        bandwidth: Dict[Tuple[int, int], float],
        default_bytes_per_s: float = 1e9,
        source: str = "declared",
    ):
        # Bounded by construction: populated once here from the caller's
        # matrix (n_hosts^2 pairs), never grown afterwards.
        self._bw: Dict[Tuple[int, int], float] = {}  # ddl-lint: disable=DDL013
        for (a, b), v in bandwidth.items():
            if v <= 0:
                raise DDLError(f"non-positive bandwidth for link {(a, b)}")
            self._bw[self._key(a, b)] = float(v)
        self.default_bytes_per_s = float(default_bytes_per_s)
        #: Provenance label carried into the bench JSON ("declared" /
        #: "probed") so a placement win can be traced to its cost input.
        self.source = source

    @staticmethod
    def _key(a: int, b: int) -> Tuple[int, int]:
        return (a, b) if a <= b else (b, a)

    def bytes_per_s(self, a: int, b: int) -> float:
        if a == b:
            return LOCAL_BYTES_PER_S
        return self._bw.get(self._key(a, b), self.default_bytes_per_s)

    def seconds(self, a: int, b: int, nbytes: int) -> float:
        return nbytes / self.bytes_per_s(a, b)

    def hosts(self) -> List[int]:
        out: set = set()
        for a, b in self._bw:
            out.add(a)
            out.add(b)
        return sorted(out)

    @property
    def n_links(self) -> int:
        return len(self._bw)

    @classmethod
    def islands(
        cls,
        groups: Iterable[Iterable[int]],
        intra_bytes_per_s: float,
        cross_bytes_per_s: float,
    ) -> "LinkCosts":
        """The canonical cloud shape: fast links within each island
        (rack / placement group), slow links across — the geometry
        Cloud Collectives measured.  Convenience for benches/tests."""
        groups = [list(g) for g in groups]
        bw: Dict[Tuple[int, int], float] = {}
        flat = [h for g in groups for h in g]
        for gi, g in enumerate(groups):
            for a in g:
                for b in flat:
                    if a >= b:
                        continue
                    intra = any(a in gg and b in gg for gg in groups)
                    bw[(a, b)] = (
                        intra_bytes_per_s if intra else cross_bytes_per_s
                    )
        return cls(bw, default_bytes_per_s=cross_bytes_per_s)


def _memcpy_transfer(a: int, b: int, payload: np.ndarray) -> None:
    """Default probe transfer: a host-local memcpy of the payload — the
    honest stand-in when no cross-host fabric is reachable (it measures
    THIS host's memory bandwidth, clearly labeled by the probe's
    ``source``)."""
    np.copyto(np.empty_like(payload), payload)


def probe_link_costs(
    hosts: List[int],
    transfer: Optional[Callable[[int, int, np.ndarray], None]] = None,
    payload_bytes: int = 1 << 20,
    reps: int = 3,
    timeout_s: float = 30.0,
) -> LinkCosts:
    """Measure pairwise link bandwidth over ``transfer``.

    ``transfer(a, b, payload)`` moves ``payload`` from host ``a`` to
    host ``b`` once (a real deployment wires a DCN send/recv or a
    jax.distributed broadcast pair here — docs/DEPLOY.md); best-of-
    ``reps`` wall time per pair becomes the link's bytes/s.  The probe
    is deadline-bounded: pairs not measured within ``timeout_s`` keep
    the default cost instead of stalling bootstrap (DDL018's rule —
    every cluster loop consults a deadline).
    """
    transfer = transfer or _memcpy_transfer
    payload = np.arange(
        max(1, payload_bytes // 4), dtype=np.float32
    )
    bw: Dict[Tuple[int, int], float] = {}
    deadline = time.monotonic() + timeout_s
    for i, a in enumerate(sorted(hosts)):
        for b in sorted(hosts)[i + 1:]:
            if time.monotonic() >= deadline:
                return LinkCosts(bw, source="probed-partial")
            best = 0.0
            for _ in range(max(1, reps)):
                t0 = time.perf_counter()
                transfer(a, b, payload)
                dt = time.perf_counter() - t0
                if dt > 0:
                    best = max(best, payload.nbytes / dt)
            if best > 0:
                bw[(a, b)] = best
    return LinkCosts(bw, source="probed")
