"""Placement engine: producer→consumer assignment over measured links.

The window-transport pattern is a bipartite placement problem: every
loader (producer) host streams its committed windows to one consumer
host, and on a non-uniform fabric (see :mod:`ddl_tpu.cluster.topology`)
WHICH consumer it streams to decides whether the transport rides an
intra-island link or crawls across islands.  Cloud Collectives
(arXiv:2105.14088) showed rank reordering onto the measured topology
recovers that bandwidth for free; :func:`plan_placement` is that
reordering for the loader tier.

Guarantees:

- **Balanced**: every consumer host receives ``ceil(P/C)`` producers at
  most (the ingest fan-in the trainer was provisioned for).
- **Never slower**: the naive (rank-order round-robin) assignment is
  always a candidate — when the greedy reorder does not beat it under
  the cost model, the naive assignment is returned with
  ``reordered=False``.  The bench's measured ratio therefore has a
  floor of ~1.0 by construction, and bench_smoke gates on it.
- **Deterministic**: ties break on sorted host ids, so every process
  planning from the same (view, costs) pair gets the same assignment —
  the same no-coordination property the membership layer has.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from ddl_tpu.cluster.membership import ClusterView
from ddl_tpu.cluster.topology import LinkCosts
from ddl_tpu.exceptions import DDLError

#: Assignment type: ``(producer_host, consumer_host)`` pairs, sorted by
#: producer host id.
Assignment = Tuple[Tuple[int, int], ...]


@dataclasses.dataclass(frozen=True)
class Placement:
    """One planned producer→consumer placement plus its modeled value."""

    assignment: Assignment
    modeled_bytes_per_s: float
    naive_bytes_per_s: float
    #: False when the naive order won (never-slower fallback engaged).
    reordered: bool

    @property
    def modeled_ratio(self) -> float:
        if self.naive_bytes_per_s <= 0:
            return 1.0
        return self.modeled_bytes_per_s / self.naive_bytes_per_s


def _roles(view: ClusterView) -> Tuple[List[int], List[int]]:
    producers = sorted(h.host_id for h in view.hosts if h.loader_ranks)
    consumers = sorted(h.host_id for h in view.hosts if h.trainer_ranks)
    if not consumers:
        # Colocated roles (every host both loads and trains): each host
        # is its own consumer candidate.
        consumers = sorted(h.host_id for h in view.hosts)
    if not producers:
        raise DDLError("placement: the view publishes no loader ranks")
    return producers, consumers


def modeled_bytes_per_s(
    assignment: Assignment, costs: LinkCosts
) -> float:
    """Aggregate transport rate under the shared-ingress model: each
    consumer's incoming streams share its ingress, so a pair's rate is
    its link bandwidth divided by the consumer's fan-in; the aggregate
    is the sum.  A model, not a measurement — :func:`measure_assignment`
    is the measurement."""
    fan_in: Dict[int, int] = {}
    for _p, c in assignment:
        fan_in[c] = fan_in.get(c, 0) + 1
    return float(
        sum(
            costs.bytes_per_s(p, c) / fan_in[c]
            for p, c in assignment
        )
    )


def naive_placement(view: ClusterView) -> Assignment:
    """The topology-blind baseline: producers in host-id order dealt
    round-robin onto consumers in host-id order — what a rank-ordered
    launch does today."""
    producers, consumers = _roles(view)
    return tuple(
        (p, consumers[i % len(consumers)])
        for i, p in enumerate(sorted(producers))
    )


def plan_placement(
    view: ClusterView, costs: LinkCosts
) -> Placement:
    """Greedy bandwidth-descending assignment with the never-slower
    fallback (module docstring has the guarantees)."""
    producers, consumers = _roles(view)
    cap = -(-len(producers) // len(consumers))  # ceil(P/C)
    edges = sorted(
        ((p, c) for p in producers for c in consumers),
        # Fastest links first; ties break deterministically on ids.
        key=lambda e: (-costs.bytes_per_s(e[0], e[1]), e[0], e[1]),
    )
    fan_in: Dict[int, int] = {c: 0 for c in consumers}
    chosen: Dict[int, int] = {}
    for p, c in edges:
        if p in chosen or fan_in[c] >= cap:
            continue
        chosen[p] = c
        fan_in[c] += 1
        if len(chosen) == len(producers):
            break
    planned: Assignment = tuple(sorted(chosen.items()))
    naive = naive_placement(view)
    planned_rate = modeled_bytes_per_s(planned, costs)
    naive_rate = modeled_bytes_per_s(naive, costs)
    if planned_rate < naive_rate:
        # Never-slower: the reorder lost under its own model (uniform
        # fabric, degenerate roles) — ship the naive order instead.
        return Placement(
            assignment=naive,
            modeled_bytes_per_s=naive_rate,
            naive_bytes_per_s=naive_rate,
            reordered=False,
        )
    return Placement(
        assignment=planned,
        modeled_bytes_per_s=planned_rate,
        naive_bytes_per_s=naive_rate,
        reordered=planned != naive,
    )


def costs_drift(old: LinkCosts, new: LinkCosts) -> float:
    """Max relative per-link bandwidth change between two cost tables.

    The drift signal the steady-state tuner watches (ddl_tpu.tune): a
    placement planned against ``old`` is stale when any link's measured
    speed moved by more than the caller's tolerance.  Compared over the
    union of hosts both tables know, so a link that appeared or vanished
    registers as drift through the default-cost fallback rather than
    being skipped.
    """
    hosts = sorted(set(old.hosts()) | set(new.hosts()))
    drift = 0.0
    for i, a in enumerate(hosts):
        for b in hosts[i + 1:]:
            o = old.bytes_per_s(a, b)
            n = new.bytes_per_s(a, b)
            drift = max(drift, abs(n - o) / max(o, 1e-9))
    return drift


def replan_on_drift(
    view: ClusterView,
    old_costs: LinkCosts,
    new_costs: LinkCosts,
    rel_tol: float = 0.25,
) -> Optional[Placement]:
    """Re-run :func:`plan_placement` iff measured costs drifted.

    Returns the fresh :class:`Placement` when :func:`costs_drift`
    exceeds ``rel_tol``, else ``None`` (the current placement stands) —
    the hysteresis that keeps a noisy probe from thrashing assignments.
    """
    if costs_drift(old_costs, new_costs) <= rel_tol:
        return None
    return plan_placement(view, new_costs)


class SimulatedFabric:
    """A measurable stand-in fabric: transfers really move the payload
    (memcpy) and really take ``nbytes / bytes_per_s(a, b)`` wall time
    (a sleep models the wire).  The placement bench measures naive vs
    planned assignments over it — same role the throttled storage
    backend plays for the cache bench (docs/CACHING.md).  On a real
    cluster, pass a real ``transfer`` to :func:`measure_assignment`
    instead."""

    def __init__(self, costs: LinkCosts, time_scale: float = 1.0):
        self.costs = costs
        self.time_scale = float(time_scale)

    def __call__(self, a: int, b: int, payload: np.ndarray) -> None:
        np.copyto(np.empty_like(payload), payload)
        wire_s = self.costs.seconds(a, b, payload.nbytes) * self.time_scale
        if wire_s > 0:
            time.sleep(wire_s)


def measure_assignment(
    assignment: Assignment,
    transfer: Callable[[int, int, np.ndarray], None],
    payload_bytes: int = 1 << 20,
    reps: int = 3,
    timeout_s: float = 60.0,
) -> float:
    """Measured bytes/s of one full window-transport round over
    ``transfer``: every pair moves one payload, wall-clocked end to end;
    best of ``reps`` rounds.  Deadline-bounded (DDL018): a wedged
    transfer ends the measurement with what was observed rather than
    stalling the bench."""
    if not assignment:
        raise DDLError("cannot measure an empty assignment")
    payload = np.arange(max(1, payload_bytes // 4), dtype=np.float32)
    total_bytes = payload.nbytes * len(assignment)
    best = 0.0
    deadline = time.monotonic() + timeout_s
    for _ in range(max(1, reps)):
        if time.monotonic() >= deadline:
            break
        t0 = time.perf_counter()
        for p, c in assignment:
            transfer(p, c, payload)
        dt = time.perf_counter() - t0
        if dt > 0:
            best = max(best, total_bytes / dt)
    return best


def placement_report(
    view: ClusterView,
    costs: LinkCosts,
    transfer: Optional[Callable[[int, int, np.ndarray], None]] = None,
    payload_bytes: int = 1 << 20,
    reps: int = 3,
) -> dict:
    """The bench's ``placement`` block body: plan, measure both
    assignments over ``transfer`` (default: the simulated fabric priced
    by ``costs``), report modeled + measured rates and the ratio.  The
    winner is never the slower measured assignment (the headline
    invariant bench_smoke enforces)."""
    plan = plan_placement(view, costs)
    naive = naive_placement(view)
    fabric = transfer or SimulatedFabric(costs)
    measured_naive = measure_assignment(
        naive, fabric, payload_bytes, reps
    )
    measured_plan = (
        measure_assignment(plan.assignment, fabric, payload_bytes, reps)
        if plan.assignment != naive
        else measured_naive
    )
    ratio = (measured_plan / measured_naive) if measured_naive > 0 else 1.0
    winner = "topology" if measured_plan >= measured_naive else "naive"
    return {
        "n_hosts": len(view.hosts),
        "n_links": costs.n_links,
        "cost_source": costs.source,
        "payload_bytes": int(payload_bytes),
        "assignment": [list(pair) for pair in plan.assignment],
        "naive_assignment": [list(pair) for pair in naive],
        "reordered": bool(plan.reordered),
        "modeled_ratio": round(plan.modeled_ratio, 3),
        "naive_bytes_per_s": round(measured_naive, 1),
        "topo_bytes_per_s": round(measured_plan, 1),
        "bytes_per_s": round(max(measured_plan, measured_naive), 1),
        "ratio": round(ratio, 3),
        "winner": winner,
    }
