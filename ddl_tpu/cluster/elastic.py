"""Cross-host elastic recovery: the ladder above the watchdog.

The single-host ladder (docs/ROBUSTNESS.md) ends at "respawn the dead
producer".  This module adds the host-level rungs:

1. **producer death, host alive** — the watchdog's rung, unchanged:
   respawn + deterministic replay.  The lease budget is sized so a
   respawn lands before the host's lease lapses (membership.py).
2. **whole-host death** — lease expiry / ``HOST_LOSS`` / declaration →
   the supervisor's epoch-fenced view change, which this module turns
   into pipeline actions:

   - the loader is handed the shrunken :class:`~ddl_tpu.cluster.pool.
     LoaderPool` the new view publishes (rotation drops the dead
     rings at the next window boundary; a consumer blocked on a dead
     ring is unblocked by target revocation);
   - each surviving LOCAL producer receives a :class:`~ddl_tpu.types.
     ShardAdoption` over its control channel: its host's post-change
     shard ranges, the view epoch as the fence, and
     ``suspend_exchange=True`` so the cross-instance shuffle degrades
     to node-local until rejoin (exchanging with a permutation that
     still names the dead host would stall every round);
   - the dead host's shard-cache disk tier is adopted for a warm start
     when its spill dir is reachable (``cache.adopt_manifest`` — the
     checkpoint-manifest machinery reused for failover).

3. **rejoin** — a recovered host re-enters at a fresh epoch fence:
   full deterministic re-partition, pool re-grown, exchange resumed.
"""

from __future__ import annotations

import logging
from typing import Any, FrozenSet, Iterable, Optional

from ddl_tpu.cluster.membership import ClusterSupervisor, ClusterView, HostInfo
from ddl_tpu.exceptions import DDLError, ShutdownRequested
from ddl_tpu.observability import Metrics, metrics as default_metrics
from ddl_tpu.types import ShardAdoption

logger = logging.getLogger("ddl_tpu")


def worker_alive_source(workers: Any, ranks: Iterable[int]):
    """A heartbeat source over a host's LOCAL workers: alive while ANY
    of its loader ranks still runs (``any``, not ``all`` — a single
    producer crash is the watchdog's rung 1, and its respawn revives
    the beat before the lease lapses; only a fully dead host stops
    beating).  Rank indices are 1-based, matching the repo convention.
    """
    idxs = sorted(int(r) - 1 for r in ranks)

    def alive() -> bool:
        for i in idxs:
            if workers.threads:
                if i < len(workers.threads) and workers.threads[i].is_alive():
                    return True
            elif workers.processes:
                p = workers.processes[i] if i < len(workers.processes) else None
                if p is not None and p.exitcode is None:
                    return True
        return False

    return alive


class ElasticCluster:
    """Binds a :class:`ClusterSupervisor` to live pipeline components.

    One instance per consumer process: it subscribes to view changes and
    translates them into the rung-2 actions above.  Components attach as
    they exist — a bench that only wants membership metrics attaches
    nothing; the full pipeline attaches workers (adoption channel
    access + liveness sources) and the loader (pool application).
    """

    def __init__(
        self,
        supervisor: ClusterSupervisor,
        workers: Any = None,
        loader: Any = None,
        metrics: Optional[Metrics] = None,
        adopt_cache: bool = True,
        local_host_id: "int | Iterable[int] | None" = None,
    ):
        """``local_host_id`` (int or iterable) names THIS process's
        host(s) in the view — required in real multi-host deployments
        where every host numbers its workers locally as ranks 1..n, so
        rank values alias across hosts.  ``None`` keeps the default
        everything-is-local reading (single-process mock-host
        topologies, where the view really does describe this process's
        rings)."""
        self.supervisor = supervisor
        self.workers = workers
        self.loader = None
        self.metrics = metrics or default_metrics()
        self.adopt_cache = adopt_cache
        if local_host_id is not None:
            ids = (
                {local_host_id}
                if isinstance(local_host_id, int)
                else set(local_host_id)
            )
            supervisor.local_host_ids = ids
        supervisor.add_listener(self._on_view_change)
        supervisor.add_rank_listener(self._on_rank_respawned)
        if workers is not None:
            self._attach_worker_sources()
        if loader is not None:
            self.attach_loader(loader)

    # -- wiring ------------------------------------------------------------

    def _local_hosts(self, view: ClusterView):
        return [
            h for h in view.hosts if self.supervisor.is_local(h.host_id)
        ]

    def _local_pool(self, view: ClusterView):
        """The pool slice THIS process consumes: local hosts' ranks
        only (remote ranks are other processes' ring indices)."""
        from ddl_tpu.cluster.pool import LoaderPool

        members = sorted(
            r - 1
            for h in self._local_hosts(view)
            for r in h.loader_ranks
        )
        return LoaderPool(members=tuple(members), generation=view.epoch)

    def _attach_worker_sources(self) -> None:
        """One liveness source per LOCAL host in the view (hosts whose
        loader ranks exist in this process's worker set)."""
        n_local = self.workers.connection.n_producers
        for h in self._local_hosts(self.supervisor.view):
            local = [r for r in h.loader_ranks if 1 <= r <= n_local]
            if local:
                self.supervisor.attach_source(
                    h.host_id, worker_alive_source(self.workers, local)
                )

    def attach_loader(self, loader: Any) -> None:
        """Register the consumer: it immediately adopts the CURRENT
        view's LOCAL pool slice (a loader attached after a loss must
        not rotate onto dead rings) and follows every later view
        change."""
        self.loader = loader
        loader.apply_pool(self._local_pool(self.supervisor.view))

    def rebind_supervisor(self, supervisor: ClusterSupervisor) -> None:
        """Re-point the ladder at a freshly promoted supervisor
        (``cluster.supervision.SupervisorHA.promote``): the new leader
        gets this ladder's listeners and liveness sources, and the
        consumer immediately adopts the replayed view's pool slice (a
        view change the dead leader half-delivered is re-applied here —
        epoch fences make the re-application idempotent)."""
        supervisor.local_host_ids = self.supervisor.local_host_ids
        self.supervisor = supervisor
        supervisor.add_listener(self._on_view_change)
        supervisor.add_rank_listener(self._on_rank_respawned)
        if self.workers is not None:
            self._attach_worker_sources()
        if self.loader is not None:
            self.loader.apply_pool(self._local_pool(supervisor.view))
        self.metrics.incr("cluster.supervisor_rebinds")

    # -- the rung-2 ladder -------------------------------------------------

    def _on_view_change(
        self, old: ClusterView, new: ClusterView, dead: FrozenSet[int]
    ) -> None:
        if self.loader is not None:
            self.loader.apply_pool(self._local_pool(new))
        if dead and self.adopt_cache:
            self._adopt_dead_caches(old, dead)
        # Loss degrades the exchange until rejoin; a rejoin (empty dead
        # set) is the resume edge.  The flag rides the SAME epoch-fenced
        # message as the ranges so suspend/resume can never reorder
        # against the shard assignment they protect.
        self._send_adoptions(new, suspend_exchange=bool(dead))

    def _adopt_dead_caches(
        self, old: ClusterView, dead: FrozenSet[int]
    ) -> None:
        """Warm-start adoption of each dead host's shard-cache disk tier
        (shared-filesystem spill dirs only; unreachable paths fail the
        adoption quietly — resuming cold was always legal)."""
        from ddl_tpu import cache as cache_mod

        for h in old.hosts:
            if h.host_id not in dead or not h.cache_spill_dir:
                continue
            try:
                adopted = cache_mod.adopt_manifest(
                    h.cache_spill_dir, cache_mod.KEY_SCHEMA_VERSION
                )
            except (ShutdownRequested, KeyboardInterrupt):
                raise
            except Exception:
                logger.exception(
                    "cluster: cache adoption from host %d failed", h.host_id
                )
                continue
            if adopted:
                self.metrics.incr("cluster.cache_adoptions")
                logger.warning(
                    "cluster: adopted host %d's cache tier (%s) for "
                    "warm-start recovery", h.host_id, h.cache_spill_dir,
                )

    def _send_adoptions(
        self, view: ClusterView, suspend_exchange: Optional[bool]
    ) -> None:
        """Ship each surviving LOCAL producer its host's post-change
        shard ranges (epoch-fenced; producers ignore stale epochs)."""
        if self.workers is None:
            return
        conn = self.workers.connection
        sent = 0
        for h in self._local_hosts(view):
            local = sorted(
                r for r in h.loader_ranks if 1 <= r <= conn.n_producers
            )
            for peer_idx, rank in enumerate(local):
                msg = ShardAdoption(
                    ranges=view.ranges_of(h.host_id),
                    view_epoch=view.epoch,
                    peer_idx=peer_idx,
                    n_peers=len(local),
                    suspend_exchange=suspend_exchange,
                )
                try:
                    # Rides the acked envelope seam (under the
                    # connection's rejoin lock): a dropped or duplicated
                    # wire attempt becomes a dedup'd backoff retry
                    # instead of a silently stranded adoption, and the
                    # supervisor's fencing term rides the envelope so a
                    # zombie ex-leader's late adoption dies at the
                    # producer.
                    conn.send_control_acked(rank - 1, msg)
                    sent += 1
                except (OSError, ValueError):
                    # A dying channel mid-change: the watchdog/next view
                    # change owns that producer; adoption is re-sent on
                    # the NEXT view change or the post-respawn re-send
                    # (epoch fence makes both safe).
                    logger.warning(
                        "cluster: adoption send to producer %d failed",
                        rank,
                    )
        if sent:
            self.metrics.incr("cluster.shard_adoptions", sent)

    def _on_rank_respawned(self, rank: int) -> None:
        """Re-ship the CURRENT view's adoption to a respawned producer.

        A view change that raced the respawn's channel swap lost its
        adoption send (the old channel was closing), and the fresh
        incarnation starts from its on_init base assignment — without
        this it would serve pre-change ranges and silently drop the
        shards the view moved onto its host.  Epoch-fenced like every
        adoption: an incarnation that already applied this epoch drops
        the duplicate."""
        if self.workers is None:
            return
        view = self.supervisor.view
        host = next(
            (
                h
                for h in self._local_hosts(view)
                if rank in h.loader_ranks
            ),
            None,
        )
        if host is None:
            return  # a departed (or remote) host's rank: nothing to ship
        conn = self.workers.connection
        local = sorted(
            r for r in host.loader_ranks if 1 <= r <= conn.n_producers
        )
        if rank not in local:
            return
        msg = ShardAdoption(
            ranges=view.ranges_of(host.host_id),
            view_epoch=view.epoch,
            peer_idx=local.index(rank),
            n_peers=len(local),
            suspend_exchange=None,
        )
        try:
            # The acked seam, like every adoption send: the respawn race
            # this re-send papers over is exactly a lost delivery, so it
            # gets the same dedup'd-retry contract instead of a second
            # fire-and-forget hope.
            conn.send_control_acked(rank - 1, msg)
            self.metrics.incr("cluster.shard_adoptions")
        except (OSError, ValueError):
            logger.warning(
                "cluster: post-respawn adoption send to producer %d "
                "failed", rank,
            )

    # -- chaos / operator hammers -----------------------------------------

    def kill_host(self, host_id: int) -> ClusterView:
        """Hard-kill every LOCAL worker of ``host_id`` and declare the
        loss (the mock-host chaos hammer the cross-host tests swing; an
        operator draining a node uses the same path).  Declaration runs
        FIRST so the pool shrinks before the dead rings' shutdown flags
        can be mistaken for run teardown."""
        host = self.supervisor.view.host(host_id)
        if host is None:
            raise KeyError(f"host {host_id} is not in the view")
        new = self.supervisor.declare_host_loss(host_id)
        if self.workers is not None:
            n_local = self.workers.connection.n_producers
            for r in host.loader_ranks:
                i = r - 1
                if not (0 <= i < n_local):
                    continue
                if self.workers.processes:
                    p = self.workers.processes[i]
                    if p.exitcode is None:
                        p.terminate()
                        p.join(10)
                # THREAD mode cannot kill a thread: flag its ring's
                # shutdown so the producer exits its next wait.  The
                # consumer never observes it — the pool already dropped
                # this ring, and a revoked in-flight acquire is handled
                # by the loader's pool seam.
                try:
                    self.workers.connection.rings[i].shutdown()
                except (IndexError, OSError):
                    pass
        return new

    def drain_host(self, host_id: int) -> HostInfo:
        """Graceful scale-down (the autoscaler's release half,
        ``ddl_tpu.serve``).  Unlike :meth:`kill_host`, the host's
        workers are PARKED, not killed: the epoch-fenced view change
        drops its rings from every consumer pool (in-flight acquires
        revoked at the fence) and re-partitions its shard ranges onto
        survivors, while its producers simply idle against their full
        rings — warm standby, so a later :meth:`rejoin_host` serves
        their already-committed windows immediately.  A park outlasting
        the transport stall budget ends with the producer exiting on
        its fill timeout; the view has already left it to the cluster
        ladder (the watchdog skips lost ranks), and a rejoin then rides
        the normal respawn path.  Returns the departed
        :class:`HostInfo` — the autoscaler's standby-reserve entry.
        Refuses to drain the last loader host (the never-empty floor).
        """
        view = self.supervisor.view
        host = view.host(host_id)
        if host is None:
            raise KeyError(f"host {host_id} is not in the view")
        survivors = [
            h for h in view.hosts
            if h.loader_ranks and h.host_id != host_id
        ]
        if not survivors:
            raise DDLError(
                f"refusing to drain host {host_id}: it carries the last "
                "loader ranks in the view (never-empty floor)"
            )
        # graceful=True: the identical epoch-fenced change, counted as
        # cluster.host_drains (not host_losses — the failure counter
        # alerting keys on) and logged WARNING.  The exchange still
        # suspends until rejoin: the drained host's producers are
        # PARKED, so an exchange schedule naming them would stall every
        # round exactly as a dead host's would.
        self.supervisor.declare_host_loss(host_id, graceful=True)
        return host

    def rejoin_host(self, host: HostInfo) -> ClusterView:
        """Re-admit a recovered host (the ladder's exit).  The listener
        ships the re-partitioned ranges with ``suspend_exchange=False``
        — shuffle degradation lasts exactly until this fence."""
        new = self.supervisor.rejoin(host)
        if self.workers is not None:
            self._attach_worker_sources()
        return new
