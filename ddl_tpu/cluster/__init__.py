"""ddl_tpu.cluster — multi-host elastic control plane.

Four pieces (docs/ROBUSTNESS.md "Host loss and the view-change
protocol", docs/DEPLOY.md "Multi-host bootstrap"):

- **membership** — host leases + heartbeats layered over existing
  liveness signals, and the deterministic epoch-fenced view-change
  protocol (:class:`ClusterSupervisor`, :class:`ClusterView`).
- **topology** — inter-host link costs, declared or probed
  (:class:`LinkCosts`, :func:`probe_link_costs`).
- **placement** — Cloud-Collectives-style producer→consumer rank
  reordering over those costs, with the never-slower fallback
  (:func:`plan_placement`, :func:`placement_report`).
- **elastic** — the recovery ladder binding view changes to the live
  pipeline: loader-pool shrink, shard adoption, cache warm start,
  degraded shuffle until rejoin (:class:`ElasticCluster`).

The loader-pool decoupling seam (:class:`LoaderPool`) is what makes
loader ranks a resizable pool distinct from trainer ranks:
``DistributedDataLoader`` consumes whatever pool the view publishes.
"""

from ddl_tpu.cluster.elastic import ElasticCluster, worker_alive_source
from ddl_tpu.cluster.membership import (
    ClusterSupervisor,
    ClusterView,
    HostInfo,
    LeaseTable,
    partition_shards,
    view_change,
    view_rejoin,
)
from ddl_tpu.cluster.placement import (
    Placement,
    SimulatedFabric,
    measure_assignment,
    modeled_bytes_per_s,
    naive_placement,
    placement_report,
    plan_placement,
)
from ddl_tpu.cluster.pool import LoaderPool
from ddl_tpu.cluster.supervision import (
    JournaledSupervisor,
    ReplayedState,
    SupervisorHA,
    SupervisorJournal,
    replay_journal,
)
from ddl_tpu.cluster.topology import LinkCosts, probe_link_costs

__all__ = [
    "ClusterSupervisor",
    "ClusterView",
    "ElasticCluster",
    "HostInfo",
    "JournaledSupervisor",
    "LeaseTable",
    "LinkCosts",
    "LoaderPool",
    "Placement",
    "ReplayedState",
    "SimulatedFabric",
    "SupervisorHA",
    "SupervisorJournal",
    "measure_assignment",
    "modeled_bytes_per_s",
    "naive_placement",
    "partition_shards",
    "placement_report",
    "plan_placement",
    "probe_link_costs",
    "replay_journal",
    "view_change",
    "view_rejoin",
    "worker_alive_source",
]
