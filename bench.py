"""Benchmark: loader→HBM ingest throughput + flagship train-step MFU.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...extras}.

Two measurements (BASELINE.md north-star + VERDICT r1 items 1-2):

1. **Ingest** — samples/sec of the full pipeline: producer workers filling
   window rings, consumer draining zero-copy and streaming batches into
   device HBM while a jitted consumer computation runs.  ``vs_baseline``
   compares against a faithful re-creation of the *reference's* design
   point on identical hardware: single-buffered strict alternation (its
   one-window-per-producer token protocol, reference
   ``ddl/datapusher.py:147-170``) with synchronous per-batch transfers and
   no overlap.  The reference itself publishes no numbers (BASELINE.md).
2. **Train MFU** — tokens/sec and model-FLOPs-utilization of the jitted
   Llama fwd+bwd+update step (``parallel/train.make_train_step``), flash
   and dense attention.

Robustness (the round-1 failure mode was an unhandled TPU-backend init
error, BENCH_r01.json rc=1): the backend is probed in a *subprocess* with
a timeout, so a hung/unavailable TPU tunnel degrades to CPU instead of
killing the bench, and the JSON line is emitted even on partial failure
with an ``errors`` field.

Trustworthy-headline contract (ROADMAP item 5): every JSON line stamps
``git_head``; non-TPU runs embed ``last_tpu_artifact`` (the newest
committed chip measurement) so CPU fallbacks can never quietly become
the official trajectory; the ingest headline COMPETES across
prefetch / no-prefetch / prefetch-inline / PROCESS and records
``headline_config`` (never a config the same run measured slower —
bench_smoke enforces); ``vs_baseline`` is measured INTERLEAVED with
winner re-runs; and ``ingest.process_vs_thread`` ships with a per-leg
``core_attach`` record so starved-box ratios are distinguishable from
transport regressions.

Env knobs: DDL_BENCH_PLATFORM=tpu|cpu (skip probing), DDL_BENCH_MODE=
ingest|train|all|big|stream|decode|cache|ici (default all; "big" runs
ONLY the HBM-filling train config, "stream" ONLY the window-stream
configs — the chip-checklist window-size sweep — "decode" ONLY the
serving-phase prefill+decode config, "cache" the shard-cache cold/warm
A/B, "ici" the device-side distribution A/B: Pallas fan-out +
redistribution vs the XLA scatter, DDL_BENCH_ICI_MIB /
DDL_BENCH_ICI_REPS geometry, and "tenancy" the multi-tenant
ingest-service A/B: K concurrent tenants over the shared fair-share
scheduler, autoscaled vs static pool, DDL_BENCH_TENANCY_TENANTS /
_BASE / _FILL_MS / _ROWS / _REPS geometry), DDL_BENCH_PROBE_TIMEOUT_S
(default 300), DDL_BENCH_STREAM_MIB / DDL_BENCH_LOOKAHEAD /
DDL_BENCH_NSLOTS (stream geometry), DDL_BENCH_DECODE_BATCH (serving
batch for the decode configs; default 8 on TPU).  Pipeline knobs that
shape the measured paths: DDL_TPU_INPLACE (write-once producer fills),
DDL_TPU_SHM_STAGING (slot-aliasing staged transfers), DDL_TPU_STAGED.
"""

from __future__ import annotations

import dataclasses
import functools
import json
import os
import re
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, REPO)

import numpy as np

# -- ingest workload geometry -------------------------------------------------
# Env-overridable so `make bench-smoke` can run the full pipeline with a
# tiny geometry on CPU (defaults are the published bench shape).
N_DATA = int(os.environ.get("DDL_BENCH_NDATA", "8192"))  # samples/window
N_VALUES = int(os.environ.get("DDL_BENCH_NVALUES", "256"))  # f32/sample
BATCH = int(os.environ.get("DDL_BENCH_BATCH", "2048"))
EPOCHS_MEASURED = int(os.environ.get("DDL_BENCH_EPOCHS", "24"))
N_PRODUCERS = 2

# -- backend selection --------------------------------------------------------

# Peak dense bf16 matmul FLOP/s per JAX device, by device_kind substring
# (public spec-sheet numbers; first match wins).
_PEAK_FLOPS = (
    ("v6", 918e12),  # Trillium / v6e
    ("v5p", 459e12),
    ("v5", 197e12),  # v5e / "TPU v5 lite"
    ("v4", 275e12),
    ("v3", 61.5e12),  # per-core device
    ("v2", 22.5e12),  # per-core device
)


def _peak_flops(device_kind: str) -> float | None:
    kind = device_kind.lower()
    for sub, peak in _PEAK_FLOPS:
        if sub in kind:
            return peak
    return None


# Peak HBM bandwidth per chip, bytes/s (public spec-sheet numbers; the
# denominator for decode-phase model-bandwidth utilization, where each
# generated token must stream the full parameter set from HBM).
_PEAK_HBM = (
    ("v6", 1640e9),  # Trillium / v6e
    ("v5p", 2765e9),
    ("v5", 819e9),  # v5e / "TPU v5 lite"
    ("v4", 1228e9),
    ("v3", 900e9),
    ("v2", 700e9),
)


def _peak_hbm(device_kind: str) -> float | None:
    kind = device_kind.lower()
    for sub, peak in _PEAK_HBM:
        if sub in kind:
            return peak
    return None


# Per-LINK ICI bandwidth, bytes/s one direction, by device_kind substring
# (public spec-sheet per-chip totals divided by the link count: v2 496/4,
# v3 656/4, v4 2400/6, v5e 1600/4, v5p 4800/6, v6e 3584/4 Gbps).  The
# ring fan-out drives ONE link per chip per step, so the per-hop spec —
# not the per-chip aggregate — is the honest utilization denominator for
# the DDL_BENCH_MODE=ici leg.
_PEAK_ICI_LINK = (
    ("v6", 112e9, 4),  # Trillium / v6e
    ("v5p", 100e9, 6),
    ("v5", 50e9, 4),  # v5e
    ("v4", 50e9, 6),
    ("v3", 20.5e9, 4),
    ("v2", 15.5e9, 4),
)


def _peak_ici_link(device_kind: str) -> float | None:
    kind = device_kind.lower()
    for sub, peak, _links in _PEAK_ICI_LINK:
        if sub in kind:
            return peak
    return None


def best_of(n: int, fn, key):
    """Run ``fn`` n times and return the result minimising ``key``.

    The one timing estimator for this bench: contention on the shared
    chip/tunnel is strictly one-sided noise (it only ever slows a run —
    observed: a 3x-slow transient on an otherwise stable 117 ms step), so
    the best observation is the honest estimate of real cost.
    """
    results = [fn() for _ in range(n)]
    return min(results, key=key)


def best_valid(n: int, fn, key):
    """``best_of`` over runs that may individually fail a plausibility
    gate (``fn`` raises): artifact runs are discarded and the best VALID
    run wins; only if every run is rejected does the failure propagate.
    A gate-after-selection would let the artifact run win selection and
    throw away its valid companions."""
    results, errs = [], []
    for _ in range(n):
        try:
            results.append(fn())
        except Exception as e:  # noqa: BLE001 - re-raised if all fail
            errs.append(e)
    if not results:
        raise errs[0]
    return min(results, key=key)


#: Achieved/measured-link ratios above this are physically impossible —
#: a transfer-timing artifact (the round-2 failure class), not a result.
_UTIL_GATE = 1.05


def _gate_utilization(ns: dict, label: str) -> dict:
    util = ns.get("bandwidth_utilization", 0.0)
    if util > _UTIL_GATE:
        raise RuntimeError(
            f"implausible {label} utilization {util:.3f} (> 1) — "
            "measurement rejected"
        )
    return ns


def pin_platform(default_timeout_s: float = 300.0) -> str:
    """THE platform bring-up for bench and every probe tool: probe the
    backend in a killable subprocess (:func:`_probe_backend`), and when
    it is not a TPU, pin the CPU fallback BEFORE the caller's first
    in-process device touch — the axon sitecustomize re-exports
    ``JAX_PLATFORMS`` at interpreter start, so only the live config pin
    sticks, and an unpinned touch on a hung tunnel hangs the process.
    ``DDL_BENCH_PROBE_TIMEOUT_S`` overrides the probe deadline.  Returns
    the platform; the CPU fallback is announced on stderr so a
    slow-but-healthy attach that timed out cannot silently publish CPU
    numbers as device measurements.
    """
    platform = _probe_backend(
        float(
            os.environ.get(
                "DDL_BENCH_PROBE_TIMEOUT_S", str(default_timeout_s)
            )
        )
    )
    if platform != "tpu":
        os.environ["JAX_PLATFORMS"] = platform
        import jax

        jax.config.update("jax_platforms", platform)
        print(
            f"bench: TPU backend unavailable; pinned platform={platform}",
            file=sys.stderr,
        )
    return platform


def _git_head() -> "str | None":
    """Short HEAD hash of the repo the bench ran from (stamped into every
    JSON line so artifact trails — ``last_tpu_artifact`` — can tie a
    number to the code that produced it)."""
    try:
        out = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"],
            capture_output=True, text=True, timeout=10, cwd=REPO,
        )
        return out.stdout.strip() or None if out.returncode == 0 else None
    except (OSError, subprocess.TimeoutExpired):
        return None


def _core_attach(n_workers: int = None) -> dict:
    """The measurement box's core attach, recorded per ingest leg.

    ``starved`` is the structural verdict: the PROCESS-vs-THREAD stream
    comparison needs every producer process AND the consumer on its own
    core (``n_workers`` defaults to the bench's producers + 1); with
    fewer attached cores a <1x ratio is preemption, not ring overhead
    (docs/PERF_NOTES.md "PROCESS-mode ingest vs THREAD mode"), and the
    bench_smoke ratio gate accepts the starvation proof instead.
    """
    need = (N_PRODUCERS + 1) if n_workers is None else n_workers
    try:
        affinity = len(os.sched_getaffinity(0))
    except (AttributeError, OSError):  # pragma: no cover - non-linux
        affinity = os.cpu_count()
    try:
        load_1m = round(os.getloadavg()[0], 2)
    except (AttributeError, OSError):  # pragma: no cover - non-unix
        load_1m = None
    return {
        "cpu_count": os.cpu_count(),
        "affinity": affinity,
        "load_avg_1m": load_1m,
        "cores_needed": need,
        "starved": bool(affinity is not None and affinity < need),
    }


#: Committed TPU artifacts live here (plus repo-root BENCH_TPU_*.json).
ARTIFACT_DIRS = ("bench_artifacts", ".")


def _artifact_timestamp(path: str, data: dict) -> float:
    """When this artifact was MEASURED, as an epoch for newest-wins
    ranking.  File mtime alone cannot order committed artifacts — git
    does not preserve mtimes, so after a fresh clone every artifact
    carries its checkout time and the max-mtime winner is arbitrary.
    Preference order: the ``recorded`` stamp inside the JSON (every run
    from this round on), a YYYYMMDD date in the filename (the committed
    artifact convention), then mtime as the last resort."""
    rec = data.get("recorded")
    if isinstance(rec, str):
        try:
            return time.mktime(time.strptime(rec[:19], "%Y-%m-%dT%H:%M:%S"))
        except ValueError:
            pass
    m = re.search(r"(20\d{6})", os.path.basename(path))
    if m:
        try:
            return time.mktime(time.strptime(m.group(1), "%Y%m%d"))
        except ValueError:
            pass
    return os.path.getmtime(path)


def _last_tpu_artifact() -> "dict | None":
    """Newest committed TPU bench artifact, summarized.

    A CPU-fallback run embeds this block so its JSON line can never be
    mistaken for (or silently replace) the official chip headline: the
    fallback reports its own numbers AND points at the most recent real
    TPU measurement — path, headline metric/value, and the producing
    commit when the artifact recorded one (``git_head`` is stamped into
    every run from this round on).
    """
    import glob

    best: "tuple | None" = None
    for d in ARTIFACT_DIRS:
        pat = (
            os.path.join(REPO, d, "*.json")
            if d != "." else os.path.join(REPO, "BENCH_TPU_*.json")
        )
        for path in glob.glob(pat):
            try:
                with open(path) as f:
                    data = json.load(f)
            except (OSError, json.JSONDecodeError, UnicodeDecodeError):
                continue
            if not isinstance(data, dict):
                continue
            if data.get("platform") != "tpu" or data.get("value") is None:
                continue
            if "QUARANTINED" in os.path.basename(path):
                continue  # explicitly disowned measurement
            stamp = _artifact_timestamp(path, data)
            if best is None or stamp > best[0]:
                best = (stamp, path, data)
    if best is None:
        return None
    _stamp, path, data = best
    mtime = os.path.getmtime(path)
    return {
        "path": os.path.relpath(path, REPO),
        "metric": data.get("metric"),
        "value": data.get("value"),
        "unit": data.get("unit"),
        "headline_config": data.get("headline_config"),
        "git_head": data.get("git_head"),
        "recorded": data.get("recorded"),
        "mtime": time.strftime(
            "%Y-%m-%dT%H:%M:%S", time.localtime(mtime)
        ),
    }


def _probe_backend(timeout_s: float) -> str:
    """Decide the JAX platform WITHOUT importing jax in this process.

    A broken or unreachable TPU backend can hang ``jax.devices()`` for
    minutes or raise RuntimeError (round 1 died on exactly this, VERDICT
    Missing #1) — so the first touch happens in a killable subprocess.
    """
    forced = os.environ.get("DDL_BENCH_PLATFORM")
    if forced:
        return forced
    try:
        out = subprocess.run(
            [sys.executable, "-c",
             "import jax; print(jax.local_devices()[0].platform)"],
            capture_output=True, text=True, timeout=timeout_s,
        )
        if out.returncode == 0 and out.stdout.strip():
            return out.stdout.strip().splitlines()[-1]
    except subprocess.TimeoutExpired:
        pass
    return "cpu"


# -- ingest bench -------------------------------------------------------------


try:  # import lazily-guarded so `import bench` works before deps resolve
    from ddl_tpu import DataProducerOnInitReturn, ProducerFunctionSkeleton

    class BenchProducer(ProducerFunctionSkeleton):
        """Module-level (picklable): PROCESS mode ships it to spawned
        producer processes, exactly like user producer functions."""

        def on_init(self, producer_idx=0, **kw):
            self._rng = np.random.default_rng(producer_idx)
            self._data = self._rng.random((N_DATA, N_VALUES), np.float32)
            return DataProducerOnInitReturn(
                nData=N_DATA, nValues=N_VALUES, shape=(N_DATA, N_VALUES),
                splits=(N_VALUES - 1, 1),
            )

        def post_init(self, my_ary, **kw):
            np.copyto(my_ary, self._data)

        def execute_function(self, my_ary, **kw):
            # Representative per-window producer work: local in-place
            # shuffle (what the reference example does per refill,
            # reference tests/run_ddl.py:163-167).
            self._rng.shuffle(my_ary)

    # Stream-config geometry: big windows amortize per-transfer cost (the
    # link saturates only at >=8 MiB per put — tools/probe_ingest.py).
    # DDL_BENCH_STREAM_MIB sweeps the window size (utilization-gap
    # diagnosis, VERDICT r4 item 2); DDL_BENCH_LOOKAHEAD deepens the
    # stream pipeline (pair with DDL_BENCH_NSLOTS >= lookahead+1).
    # Defaults are the chip-sweep winner (64 MiB, 3-deep lookahead):
    # this geometry measured 0.915 of the link in a stable window —
    # the BASELINE.md >=0.9 north star (bench-stream-northstar-*.json);
    # 32 MiB / lookahead 1 left ~10% on the table.
    STREAM_MIB = int(os.environ.get("DDL_BENCH_STREAM_MIB", "64"))
    # Rounded to a whole number of batches (serving truncates ragged tails).
    N_DATA_STREAM = max(
        BATCH, STREAM_MIB * (1 << 20) // (N_VALUES * 4) // BATCH * BATCH
    )
    EPOCHS_STREAM = 16
    STREAM_LOOKAHEAD = int(os.environ.get("DDL_BENCH_LOOKAHEAD", "3"))
    # Default derives from the lookahead so deepening the pipeline via
    # DDL_BENCH_LOOKAHEAD alone cannot silently under-provision the ring.
    STREAM_NSLOTS = int(
        os.environ.get("DDL_BENCH_NSLOTS", str(STREAM_LOOKAHEAD + 1))
    )

    class StreamBenchProducer(ProducerFunctionSkeleton):
        """Zero-copy fill: writes each window straight into the ring slot
        from a pregenerated bank — shard-reader-style refill where the
        per-window producer work is one sequential copy (serving
        pre-materialized shards from page cache)."""

        inplace_fill = True

        def on_init(self, producer_idx=0, **kw):
            rng = np.random.default_rng(100 + producer_idx)
            self._bank = rng.random(
                (2 * N_DATA_STREAM, N_VALUES), np.float32
            )
            self._off = 0
            return DataProducerOnInitReturn(
                nData=N_DATA_STREAM, nValues=N_VALUES,
                shape=(N_DATA_STREAM, N_VALUES), splits=(N_VALUES - 1, 1),
            )

        def post_init(self, my_ary, **kw):
            np.copyto(my_ary, self._bank[:N_DATA_STREAM])

        def execute_function(self, my_ary, **kw):
            self._off = (self._off + N_DATA_STREAM // 4) % N_DATA_STREAM
            np.copyto(
                my_ary, self._bank[self._off : self._off + N_DATA_STREAM]
            )

except Exception as _e:  # pragma: no cover - only hit on broken installs
    BenchProducer = None  # type: ignore[assignment]
    StreamBenchProducer = None  # type: ignore[assignment]
    _producer_import_error: Exception = _e


def _make_producer():
    if BenchProducer is None:
        raise RuntimeError(
            "ddl_tpu failed to import at bench startup"
        ) from _producer_import_error
    return BenchProducer()


def _consumer_compute():
    """A small jitted reduction standing in for the training step's
    consumption of the batch (keeps the device busy so overlap matters)."""
    import jax

    @jax.jit
    def f(x, y):
        return (x @ x.T).sum() + y.sum()

    return f


def _run_ingest(
    nslots: int,
    n_producers: int,
    sync_every_batch: bool,
    mode: str = "thread",
    use_prefetch: bool = False,
    link_bytes_per_sec: float = 0.0,
    staged: bool | None = None,
):
    """Returns (samples/sec, north-star metric dict) for one config.

    ``mode="process"`` runs the producers as spawned OS processes over the
    native C++ shm ring — the §2.4 native component's perf number (VERDICT
    r2 Weak #3: it previously had none).  On a 1-core host PROCESS trails
    THREAD by construction (preemptive cache thrash, not ring overhead —
    measured analysis in docs/PERF_NOTES.md); compare the two only where
    ``nproc > n_producers``.  ``use_prefetch`` drains each window via
    ``loader.prefetch()`` (depth-2 lookahead) instead of plain
    ``__getitem__`` iteration.  ``staged`` pins the ingest discipline per
    run (None = the DDL_TPU_STAGED env default) — the bench publishes
    staged vs inline side by side.
    """
    import jax

    from ddl_tpu import DistributedDataLoader, Marker, distributed_dataloader
    from ddl_tpu.ingest import north_star_report
    from ddl_tpu.observability import Metrics

    compute = _consumer_compute()
    metrics = Metrics()
    n_epochs = EPOCHS_MEASURED + 2  # first two epochs are warmup

    @distributed_dataloader(n_producers=n_producers, mode=mode, nslots=nslots)
    def main(env):
        loader = DistributedDataLoader(
            _make_producer(), batch_size=BATCH, connection=env.connection,
            n_epochs=n_epochs, output="jax", metrics=metrics,
            staged=staged,
        )
        t0 = None
        samples = 0
        out = None
        for epoch in range(n_epochs):
            if epoch == 2:  # warmup done (compile + first fills)
                if out is not None:
                    jax.block_until_ready(out)
                metrics.reset()  # steady-state north-star window
                t0 = time.perf_counter()
                samples = 0
            it = loader.prefetch(2) if use_prefetch else loader
            for x, y in it:
                out = compute(x, y)
                if sync_every_batch:
                    jax.block_until_ready(out)
                if t0 is not None:
                    samples += BATCH
                loader.mark(Marker.END_OF_BATCH)
            loader.mark(Marker.END_OF_EPOCH)
        jax.block_until_ready(out)
        # Snapshot the north-star report at the SAME instant the wall
        # clock stops — still inside the consumer role, BEFORE the
        # decorator's producer teardown.  Computing it after main()
        # returned let Metrics.elapsed_s() run through worker joins,
        # deflating bytes/s by the teardown time (seconds in PROCESS
        # mode), so process runs could report more samples/s yet fewer
        # bytes/s than thread runs (VERDICT r4 Weak #3).
        rate = samples / (time.perf_counter() - t0)
        return rate, north_star_report(
            metrics, link_bytes_per_sec=link_bytes_per_sec
        )

    return main()


def _run_ingest_stream(link_bytes_per_sec: float = 0.0, mode: str = "thread"):
    """The zero-copy streaming path: ``loader.windows()`` transfers whole
    windows straight out of ring slots (no host memcpy between producer
    fill and HBM), producers fill slots in place.  This is the config that
    evaluates BASELINE.md's ">=90% bandwidth utilization" target — per-
    batch per-column puts can never reach it on a link with fixed
    per-transfer cost (measured: tools/probe_ingest.py).

    ``mode="process"`` is the production shape on a real TPU host:
    producer processes fill native shm ring slots on their own cores
    while the consumer streams slots into HBM (on the 1-core bench box
    it trails THREAD for the docs/PERF_NOTES.md reasons).
    """
    import jax
    import jax.numpy as jnp

    from ddl_tpu import DistributedDataLoader, Marker, distributed_dataloader
    from ddl_tpu.ingest import north_star_report
    from ddl_tpu.observability import Metrics

    metrics = Metrics()
    # First two windows are warmup/compile; the last STREAM_LOOKAHEAD
    # are the pipeline drain, excluded from the measured span (below).
    n_epochs = EPOCHS_STREAM + 2 + STREAM_LOOKAHEAD

    @jax.jit
    def consume(w):
        return jnp.sum(w[..., -1])

    @distributed_dataloader(
        n_producers=N_PRODUCERS, mode=mode, nslots=STREAM_NSLOTS
    )
    def main(env):
        loader = DistributedDataLoader(
            StreamBenchProducer(), batch_size=BATCH,
            connection=env.connection, n_epochs=n_epochs, output="jax",
            metrics=metrics,
        )
        t0 = None
        samples = 0
        out = None
        seen = 0
        rate = None
        report = None
        for win in loader.windows(lookahead=STREAM_LOOKAHEAD):
            if seen == 2:
                if out is not None:
                    jax.block_until_ready(out)
                metrics.reset()
                t0 = time.perf_counter()
            elif t0 is not None and report is None:
                # The window yielded at the clock start was already on
                # device when the clock started — only count later ones.
                samples += N_DATA_STREAM
            out = consume(win)
            seen += 1
            # Stop BOTH clocks while dispatches still continue — i.e.
            # with the lookahead pipeline as full at the stop as it was
            # at the start.  Ending the span in the drain (the old
            # accounting) counted the start cohort's pre-clock transfer
            # work with nothing offsetting it at the tail, inflating
            # the rate by up to lookahead/EPOCHS_STREAM; with matched
            # in-flight depth at both edges, completions-per-second
            # over the span IS the steady-state throughput.
            if report is None and seen == n_epochs - STREAM_LOOKAHEAD:
                jax.block_until_ready(out)
                rate = samples / (time.perf_counter() - t0)
                # Same-span report (see _run_ingest): registry rates
                # snapshot at the same instant, inside the consumer
                # role, so neither drain nor teardown leaks in.  With
                # completion-time byte accounting (put_window
                # defer_metrics), registry bytes and wall-clock samples
                # cover identical windows: bytes/s == samples/s *
                # bytes_per_sample by construction.
                report = north_star_report(
                    metrics, link_bytes_per_sec=link_bytes_per_sec
                )
            loader.mark(Marker.END_OF_EPOCH)
        jax.block_until_ready(out)  # drain windows run uncounted
        return rate, report

    return main()


# -- train/MFU bench ----------------------------------------------------------


def _train_config(platform: str, size: str = "small"):
    """MXU-saturating single-chip config on TPU; tiny on CPU.

    ``size="big"`` (TPU only) is the HBM-filling credibility config
    (VERDICT r3 item 7): ~1.4B params in bf16 storage (params + adamw
    moments ≈ 8.4 GiB of v5e's 16 GiB), per-layer remat, seq 2048 — MFU
    at a geometry representative of the BASELINE.md 8B-class north-star
    workloads, not a 4-layer toy.
    """
    from ddl_tpu.models.llama import LlamaConfig

    if platform == "tpu" and size == "big":
        import jax.numpy as jnp

        from ddl_tpu.config import TrainConfig

        # Selective remat by default (DDL_TPU_TRAIN_REMAT sweeps the
        # policy): full-layer remat paid the whole-layer recompute —
        # MFU 0.5574 at 1.39B vs 0.6255 at 285M (VERDICT r5 weak #3);
        # "selective" keeps the attention outputs saved so the backward
        # never re-runs the flash kernel.
        tc = TrainConfig(
            remat=os.environ.get("DDL_TPU_TRAIN_REMAT", "selective")
        )
        return (
            tc.model_config(LlamaConfig(
                vocab=32768, d_model=2048, n_layers=20, n_heads=16,
                n_kv_heads=8, d_ff=8192, max_seq=2048,
                param_dtype=jnp.bfloat16,
            )),
            4,  # batch
            2048,  # seq
            6,  # measured steps (~0.5-1s each: big model, remat refwd)
        )
    if platform == "tpu":
        return (
            LlamaConfig(
                vocab=8192, d_model=2048, n_layers=4, n_heads=16,
                n_kv_heads=8, d_ff=8192, max_seq=2048,
            ),
            4,  # batch
            2048,  # seq
            20,  # measured steps (~140ms each; dispatch overhead < 3%)
        )
    return (
        LlamaConfig(
            vocab=512, d_model=128, n_layers=2, n_heads=4, n_kv_heads=2,
            d_ff=352, max_seq=256,
        ),
        4, 128, 4,
    )


def _attn_lm_head_flops_per_token(cfg, seq: int) -> float:
    """Forward matmul FLOPs per token for the parts every decoder family
    shares — attention (qkv/out projections + causal-half scores and
    attn@v, the standard MFU convention: masked positions are not model
    FLOPs) across all layers, plus the lm_head.  Family probes add
    their own per-layer MLP term (dense SwiGLU here; router + top-k
    experts in tools/probe_moe.py) so the accounting cannot drift
    between the published MFU numbers."""
    d, hd = cfg.d_model, cfg.head_dim
    per_layer = (
        2 * d * (cfg.n_heads + 2 * cfg.n_kv_heads) * hd  # qkv proj
        + 2 * cfg.n_heads * hd * d  # out proj
        + 2 * 2 * seq * cfg.n_heads * hd / 2  # scores + attn@v, causal half
    )
    return cfg.n_layers * per_layer + 2 * d * cfg.vocab


def _model_flops_per_token(cfg, seq: int) -> float:
    """Analytic matmul model-FLOPs per token, fwd+bwd (bwd = 2x fwd)."""
    mlp = cfg.n_layers * 3 * 2 * cfg.d_model * cfg.d_ff  # gate/up/down
    return 3.0 * (_attn_lm_head_flops_per_token(cfg, seq) + mlp)


def _run_train(platform: str, attn_impl: str, size: str = "small"):
    """Returns dict with tokens/sec, step time, MFU for one attention impl.

    Timing is ``make_multistep``: all measured steps run chained inside ONE
    jitted program (``lax.scan``), serialized by the params data
    dependence, and the clock stops only after a *host read-back* of the
    final loss.  Async dispatch cannot fake any part of that — the round-2
    bench trusted ``block_until_ready`` after a python loop and published a
    0.55 ms "step" that really took ~200 ms (VERDICT r2 Missing #1).

    Every measurement passes plausibility gates before being reported:
    the step time cannot beat the analytic FLOPs floor (flops/peak, i.e.
    MFU must be < 1), MFU must be positive, and the loss must be finite.
    Gate violations raise, so the caller records an error instead of a
    number.
    """
    import jax
    import optax

    from ddl_tpu.models import llama
    from ddl_tpu.parallel.mesh import make_mesh
    from ddl_tpu.parallel.train import make_multistep

    cfg, batch, seq, steps = _train_config(platform, size)
    cfg = type(cfg)(**{**cfg.__dict__, "attn_impl": attn_impl})
    # Distributed-optimizer knobs ride the standard TrainConfig env
    # (DDL_TPU_TRAIN_OPTIMIZER_SHARDING=zero1 / _GRAD_COMM=int8 — the
    # chip_checklist step-7 train_big re-measure).  zero1 needs a dp
    # axis: with it requested AND a multi-device attach, the mesh spans
    # every local device (the batch dp-shards with it); the default
    # stays the single-chip dp=1 geometry of every prior BENCH_* line.
    import math

    from ddl_tpu.config import TrainConfig

    tc = TrainConfig.load()
    # The dp extent must divide the batch (P(("dp",)) shards its leading
    # axis) — clamp to the gcd so a batch-4 config on a v5e-8 attach
    # runs dp=4 over 4 chips instead of crashing in _reshard.
    n_dp = (
        math.gcd(len(jax.local_devices()), batch)
        if tc.optimizer_sharding == "zero1"
        else 1
    )
    mesh = make_mesh({"dp": n_dp}, devices=jax.local_devices()[:n_dp])
    # mesh=None for the loss: single-chip attention needs no shard_map (and
    # a dp=1 mesh would only trigger the replicated-attention warning path).
    init_fn, multi_fn = make_multistep(
        lambda p, b: llama.next_token_loss(p, b[0], cfg, mesh=None),
        optax.adamw(3e-4), mesh, llama.param_specs(cfg), n_steps=steps,
        **tc.optimizer_kwargs(),
    )
    rng = np.random.default_rng(0)
    batch_tokens = (rng.integers(0, cfg.vocab, (batch, seq)).astype(np.int32),)

    state_box = [init_fn(llama.init_params(cfg, jax.random.key(0)))]
    state_box[0], losses = multi_fn(state_box[0], batch_tokens)  # compile
    first_loss = float(losses[0])  # step-1 loss, before numeric drift

    def _timed_window():
        t0 = time.perf_counter()
        state_box[0], losses = multi_fn(state_box[0], batch_tokens)
        fl = float(losses[-1])  # host sync INSIDE the timed window
        return (time.perf_counter() - t0) / steps, fl

    dt, final_loss = best_of(2, _timed_window, key=lambda r: r[0])

    tokens_per_step = batch * seq
    flops_per_step = _model_flops_per_token(cfg, seq) * tokens_per_step
    kind = jax.local_devices()[0].device_kind
    peak = _peak_flops(kind)
    mfu = flops_per_step / dt / peak if peak else None
    # -- plausibility gates (fail loudly, never publish nonsense) ---------
    if not np.isfinite(final_loss):
        raise RuntimeError(f"non-finite loss {final_loss}")
    if mfu is not None and not (0.0 < mfu < 1.0):
        raise RuntimeError(
            f"implausible MFU {mfu:.3f} (step {dt * 1e3:.2f} ms vs "
            f"FLOPs floor {flops_per_step / peak * 1e3:.2f} ms) — "
            "timing artifact, measurement rejected"
        )
    n_params = sum(
        int(np.prod(np.shape(x)))
        for x in jax.tree.leaves(state_box[0].params)
    )
    from ddl_tpu.models.remat import resolve as _resolve_remat

    return {
        "attn_impl": attn_impl,
        "size": size,
        "remat": _resolve_remat(cfg.remat),
        "optimizer_sharding": tc.optimizer_sharding,
        "grad_comm": tc.grad_comm,
        "dp": n_dp,
        "params_billions": round(n_params / 1e9, 3),
        "tokens_per_sec": round(tokens_per_step / dt, 1),
        "step_time_ms": round(dt * 1e3, 2),
        "model_tflops_per_sec": round(flops_per_step / dt / 1e12, 2),
        "mfu": round(mfu, 4) if mfu is not None else None,
        "device_kind": kind,
        "first_loss": round(first_loss, 4),
        "final_loss": round(final_loss, 4),
    }


def decode_trial(
    gen_call, gen_short_call, batch: int, prompt_len: int,
    new_tokens: int, short_tokens: int, vocab: int,
):
    """One timed serving trial, shared by the bench and tools/
    probe_moe.py so the decode method cannot drift between published
    numbers.

    Decode is timed DIRECTLY as the delta of two generate calls that
    differ only in ``max_new_tokens`` (``new_tokens`` vs
    ``short_tokens``): both programs run the identical prefill, so the
    difference is purely ``new_tokens - short_tokens`` decode steps.
    The previous method — subtracting a SEPARATELY-JITTED prefill from
    the total — understated decode (and inflated MBU): the standalone
    prefill program carries its own dispatch/readback overhead and XLA
    fuses it differently than the in-program prefill it was standing in
    for (advisor r5).  ``prefill_s`` is now the derived remainder
    (total minus the per-step cost times the full step count).

    Validates the generated tokens of BOTH calls and the spans; returns
    ``(decode_s, prefill_s)`` where ``decode_s`` covers the full
    program's ``new_tokens - 1`` scanned steps.  Raises on invalid
    tokens or an implausible span — run it under :func:`best_valid` so
    an artifact trial can never win selection.  Both calls are
    host-synchronized HERE (``np.asarray``) so a caller passing bare
    async jitted functions cannot accidentally time dispatch only."""
    if not 0 < short_tokens < new_tokens:
        raise RuntimeError(
            f"short_tokens {short_tokens} must lie in (0, {new_tokens})"
        )
    t0 = time.perf_counter()
    out = np.asarray(gen_call())
    total_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    out_short = np.asarray(gen_short_call())
    short_s = time.perf_counter() - t0

    for toks, n in ((out, new_tokens), (out_short, short_tokens)):
        gen_tok = toks[:, prompt_len:]
        if gen_tok.shape != (batch, n) or not (
            (gen_tok >= 0) & (gen_tok < vocab)
        ).all():
            raise RuntimeError("decode produced invalid tokens")
    delta_s = total_s - short_s
    if delta_s <= 0:
        # The implausibility guard, on the new quantity: the longer
        # program measuring faster than the shorter one is a timing
        # artifact, never physics.
        raise RuntimeError(
            f"implausible decode delta {delta_s * 1e3:.2f} ms (full "
            f"{total_s * 1e3:.2f}, short {short_s * 1e3:.2f}) — "
            "timing artifact, rejected"
        )
    step_s = delta_s / (new_tokens - short_tokens)
    decode_s = step_s * (new_tokens - 1)
    prefill_s = total_s - decode_s
    if prefill_s <= 0:
        raise RuntimeError(
            f"implausible derived prefill {prefill_s * 1e3:.2f} ms "
            f"(total {total_s * 1e3:.2f}, decode {decode_s * 1e3:.2f}) "
            "— timing artifact, rejected"
        )
    return decode_s, prefill_s


def _run_decode(platform: str, size: str = "small"):
    """Serving-phase benchmark: KV-cache prefill + autoregressive decode.

    Measures the inference path (``models.llama.generate``: one cached
    prefill forward, then ``lax.scan`` decode steps) the way a server
    runs it — bf16 weight storage, greedy decode, the whole
    prefill+decode program under one ``jax.jit`` so the clock spans a
    single device program and stops only after a host read-back of the
    generated tokens.  Decode-only time comes from the delta of two
    generate programs differing only in ``max_new_tokens`` (see
    :func:`decode_trial`) — the in-program prefill cancels exactly,
    unlike the old separately-jitted prefill subtraction.

    Decode steps are memory-bound (every token streams the full bf16
    parameter set from HBM), so the quality metric is model-bandwidth
    utilization: ``mbu_params = param_bytes * steps_per_sec /
    peak_hbm`` — a lower bound, ignoring the KV-cache read.  The same
    plausibility gating as training applies: MBU must land in (0, 1)
    or the measurement is rejected, and generated tokens must be valid
    vocab ids.
    """
    import jax
    import jax.numpy as jnp

    from ddl_tpu.models import llama

    base, _, _, _ = _train_config(platform, size)
    cfg = dataclasses.replace(base, param_dtype=jnp.bfloat16)
    if platform == "tpu":
        batch, prompt_len, new_tokens, trials = 8, 512, 256, 2
    else:
        # Two trials even on CPU: the delta method rejects a trial on
        # either span's noise, so one spare keeps the gate stable.
        batch, prompt_len, new_tokens, trials = 2, 32, 16, 2
    # Serving batch is the MBU lever (weight reads amortize over the
    # batch); sweepable for the batch-scaling record.
    batch = int(os.environ.get("DDL_BENCH_DECODE_BATCH", batch))

    params = llama.init_params(cfg, jax.random.key(0))
    rng = np.random.default_rng(0)
    prompt = jnp.asarray(
        rng.integers(0, cfg.vocab, (batch, prompt_len)).astype(np.int32)
    )

    # Half the steps for the short program: enough step-count contrast
    # for a stable delta, same prefill, same cache geometry class.
    short_tokens = max(1, new_tokens // 2)

    @jax.jit
    def gen(p, toks):
        return llama.generate(p, toks, cfg, max_new_tokens=new_tokens)

    @jax.jit
    def gen_short(p, toks):
        return llama.generate(p, toks, cfg, max_new_tokens=short_tokens)

    np.asarray(gen(params, prompt))  # compile + warm
    np.asarray(gen_short(params, prompt))

    n_params = sum(
        int(np.prod(np.shape(x))) for x in jax.tree.leaves(params)
    )
    # MBU byte count EXCLUDES the embedding table: decode gathers one
    # row per generated token (B rows of d_model), not the (vocab, d)
    # table — counting it overstated MBU by ~5-6% at the bench configs
    # (advisor r5).  Every other weight streams fully per step.
    mbu_params = n_params - cfg.vocab * cfg.d_model
    kind = jax.local_devices()[0].device_kind
    peak_hbm = _peak_hbm(kind) if platform == "tpu" else None
    steps = new_tokens - 1

    def _one_trial():
        """One gated measurement: both generate programs timed so the
        plausibility gate runs per trial INSIDE ``best_valid`` — a
        gate-after-selection would let an artifact run win selection
        and discard its valid companions (see ``best_valid``)."""
        # Decode-only span via the two-program delta (the in-program
        # prefill cancels); max_new_tokens - 1 scanned forward steps
        # produce the remaining tokens (the last needs no forward of
        # its own).
        decode_s, prefill_s = decode_trial(
            lambda: gen(params, prompt),
            lambda: gen_short(params, prompt),
            batch, prompt_len, new_tokens, short_tokens, cfg.vocab,
        )
        mbu = (
            mbu_params * 2 * (steps / decode_s) / peak_hbm
            if peak_hbm else None
        )
        if mbu is not None and not (0.0 < mbu < 1.0):
            raise RuntimeError(
                f"implausible decode MBU {mbu:.3f} (per-step "
                f"{decode_s / steps * 1e3:.3f} ms vs param-read floor "
                f"{mbu_params * 2 / peak_hbm * 1e3:.3f} ms) — timing "
                "artifact, measurement rejected"
            )
        return decode_s, prefill_s, mbu

    decode_s, prefill_s, mbu = best_valid(
        trials, _one_trial, key=lambda r: r[0]
    )
    return {
        "size": size,
        "params_billions": round(n_params / 1e9, 3),
        "batch": batch,
        "prompt_len": prompt_len,
        "new_tokens": new_tokens,
        "prefill_ms": round(prefill_s * 1e3, 2),
        "prefill_tokens_per_sec": round(batch * prompt_len / prefill_s, 1),
        "decode_tokens_per_sec": round(batch * steps / decode_s, 1),
        "decode_step_ms": round(decode_s / steps * 1e3, 3),
        # mbu_params: non-embedding param bytes per step over peak HBM
        # (the embedding is a per-token row gather, not a full read).
        "mbu_params": round(mbu, 4) if mbu is not None else None,
        "mbu_param_bytes": int(mbu_params * 2),
        "device_kind": kind,
    }


def _run_fit(platform: str, attn_impl: str = "flash"):
    """End-to-end training throughput THROUGH the framework: producer
    workers → window rings → zero-copy window stream → one scanned
    multistep per window (``Trainer.fit(window_stream=True)``).  The
    delta against ``train_*``'s pipeline-less multistep ceiling IS the
    input-pipeline overhead.

    Timing: one warm fit compiles the scan (the Trainer caches it per
    window geometry), then a SHORT and a LONG fit on the same Trainer
    are both timed wall-to-wall and differenced — the fixed per-fit cost
    (worker spawn, handshake, first fills) cancels out, leaving the
    steady-state per-window cost: transfer + scan + loss read-back.

    ISSUE 12 — the FUSED vs UNFUSED A/B: the same geometry is measured
    under both dispatch disciplines, interleaved within each rep.
    Fused (``DDL_TPU_FUSED`` default) is the fused compute/ingest step
    — the data plane dispatched under the train step, slot release
    gated on the consuming step's done-future, loss read-back deferred
    one window; unfused (``fused=False``) is the synchronous
    discipline — the window lands (``block_until_ready``), then the
    scan runs to a blocking loss read-back — so measured fused step
    time ≈ max(compute, ingest) while unfused ≈ compute + ingest.
    Both stream the same deterministic windows; a separate untimed
    pass CRCs every window through the ``window_hook`` seam to assert
    ``byte_identical``.  The published ``tokens_per_sec`` is the
    winner's (never-slower invariant; ``winner`` names it), while
    ``pipeline_overhead`` stays the FUSED leg's gated number.
    """
    import optax

    from ddl_tpu import DataProducerOnInitReturn, ProducerFunctionSkeleton
    from ddl_tpu.models import llama
    from ddl_tpu.parallel.mesh import make_mesh
    from ddl_tpu.trainer import Trainer

    import jax

    cfg, batch, seq, _steps = _train_config(platform)
    cfg = type(cfg)(**{**cfg.__dict__, "attn_impl": attn_impl})
    # Steps per window: 8 on TPU; 4 on CPU — deep enough that the scan
    # dominates the window (the production shape), small enough for the
    # smoke-geometry runtime.
    bpw = 8 if platform == "tpu" else 4
    rows = bpw * batch
    short_windows, long_windows = 2, 10

    class TokenWindows(ProducerFunctionSkeleton):
        def on_init(self, producer_idx=0, **kw):
            self._rng = np.random.default_rng(producer_idx)
            return DataProducerOnInitReturn(
                nData=rows, nValues=seq, shape=(rows, seq), splits=(seq,),
                dtype=np.int32,
            )

        def post_init(self, my_ary, **kw):
            my_ary[:] = self._rng.integers(0, cfg.vocab, my_ary.shape)

        def execute_function(self, my_ary, **kw):
            # Representative refill: fresh tokens each window.
            my_ary[:] = self._rng.integers(0, cfg.vocab, my_ary.shape)

    from ddl_tpu.ingest import north_star_report
    from ddl_tpu.observability import Metrics

    mesh = make_mesh({"dp": 1}, devices=jax.local_devices()[:1])
    # Private registries: window-wait / overlap spans must cover ONLY
    # this measurement, and the fused leg's overlap-health counters
    # must not be polluted by the unfused leg's DELIBERATE blocking
    # waits — one trainer (and registry) per discipline, same init,
    # same compiled-scan geometry.
    fit_metrics = Metrics()
    unfused_metrics = Metrics()

    def make_trainer(metrics):
        return Trainer(
            loss_fn=lambda p, b: llama.next_token_loss(
                p, b[0], cfg, mesh=None
            ),
            optimizer=optax.adamw(3e-4),
            mesh=mesh,
            param_specs=llama.param_specs(cfg),
            init_params=llama.init_params(cfg, jax.random.key(0)),
            watchdog=False,
            metrics=metrics,
        )

    trainer = make_trainer(fit_metrics)
    trainer_u = make_trainer(unfused_metrics)

    # Simulated DMA landing wait (CPU A/B only; 0 on real chips, where
    # the H2D + ICI fan-out latency is the genuine article).  A 1-core
    # CPU host cannot overlap CPU-bound ingest with CPU-bound compute
    # no matter the dispatch discipline, so the A/B prices the landing
    # latency as an off-CPU timer at the step's entry — the
    # ThrottledBackend / SimulatedFabric wire-sleep pattern.  This
    # makes the leg a PROTOCOL contract test: the fused discipline
    # must hide a given landing latency under the still-running
    # previous scan; the unfused discipline exposes it serially.  The
    # latency rides the window_hook seam (applied before each window's
    # scan) and is recorded in the JSON as simulated_dma_ms.
    dma_ms = float(os.environ.get(
        "DDL_BENCH_FUSED_DMA_MS", "0" if platform == "tpu" else "30"
    ))

    def dma_hook(win):
        if dma_ms:
            time.sleep(dma_ms / 1e3)
        return win

    def one_fit(n, fused=True, hook=dma_hook):
        t = trainer if fused else trainer_u
        return t.fit(
            TokenWindows(), batch_size=batch, n_epochs=n, n_producers=2,
            mode="thread", output="jax", window_stream=True,
            fused=fused, window_hook=hook,
        )

    one_fit(short_windows, fused=True)  # compile + cache the scan
    one_fit(short_windows, fused=False)

    def timed(n, fused=True):
        t0 = time.perf_counter()
        res = one_fit(n, fused=fused)
        dt = time.perf_counter() - t0
        if not all(np.isfinite(v) for v in res.losses):
            raise RuntimeError(f"non-finite fit losses {res.losses}")
        return dt, res

    # Byte-identity A/B (untimed): the same deterministic producers
    # through both disciplines, every window CRC'd at the window_hook
    # seam — the fused protocol may change dispatch timing, never
    # bytes.  Hashing host-syncs per window, so it never shares a run
    # with the timed legs.
    import zlib

    def hashed_windows(fused):
        hashes = []

        def hook(w):  # untimed pass: no simulated landing wait
            hashes.append(zlib.crc32(np.asarray(w).tobytes()))
            return w

        one_fit(short_windows + 1, fused=fused, hook=hook)
        return hashes

    h_fused = hashed_windows(True)
    h_unfused = hashed_windows(False)
    byte_identical = bool(h_fused) and h_fused == h_unfused

    # MATCHED ceiling: the same per-window scan geometry (n_steps=bpw,
    # per_step=True, sharded device input, deferred loss read-back)
    # driven from ONE pre-staged in-memory window — no producers, no
    # rings, no stream.  pipeline_overhead against THIS is the input
    # pipeline's true cost; the old comparison against the train_*
    # multistep (different scan length, host-numpy input) bundled in
    # call-amortization differences bigger than the thing measured
    # (r5: the "overhead" swung -0.04..+0.10 on identical code).
    from jax.sharding import PartitionSpec as P

    from ddl_tpu.parallel.train import _named, make_multistep

    _, ceil_fn = make_multistep(
        trainer._loss_fn, optax.adamw(3e-4), mesh,
        llama.param_specs(cfg), n_steps=bpw,
        # Matched to the stream loops: window-stream scans run
        # undonated on the CPU client (donated calls execute
        # synchronously there — see Trainer._fit_windows), and the
        # ceiling must price the same compiled program shape.
        donate=platform == "tpu",
    )
    rng = np.random.default_rng(1)
    fixed_win = jax.device_put(
        rng.integers(0, cfg.vocab, (bpw, batch, seq)).astype(np.int32),
        _named(mesh, P(None, ("dp",))),
    )
    ceil_state = trainer._init_fn(
        llama.init_params(cfg, jax.random.key(1))
    )

    def ceiling_run(n):
        nonlocal ceil_state
        pending = None
        t0 = time.perf_counter()
        for _ in range(n):
            ceil_state, losses = ceil_fn(
                ceil_state, (fixed_win,), per_step=True
            )
            # Reduction dispatched right behind its scan — the fused
            # loop's discipline (an in-dispatch-order backend would
            # queue a read-time mean behind the NEXT scan); the ceiling
            # must match the thing it is a ceiling FOR.
            loss_mean = losses.mean()
            if pending is not None:
                float(pending)
            pending = loss_mean
        float(pending)
        return time.perf_counter() - t0

    ceiling_run(short_windows)  # compile + warm
    n_ceil = long_windows - short_windows

    # INTERLEAVED paired sampling: the shared-box noise is one-sided
    # AND drifts minute to minute (measured: identical pure loops swing
    # 320-500 ms/window on an idle 2-core box), so BOTH fit disciplines
    # and the ceiling are sampled back-to-back within each rep — fused
    # short/long, ceiling loop, unfused short/long, all inside a few
    # seconds of each other — and each leg's published overhead is the
    # MEDIAN of its per-rep paired estimates.  Cross-rep
    # min-of-each-side (the naive best_of composition) let the sides
    # pick different noise regimes and swung the ratio by more than the
    # thing measured.
    fit_metrics.reset()  # wait spans cover the measured fits only
    unfused_metrics.reset()
    reps = []  # (fused window_s, unfused window_s, ceiling window_s)
    res = None
    for _ in range(3):
        # Ceiling BETWEEN the fused and unfused pairs: the slow
        # within-rep drift then brackets every leg from both sides.
        dt_short_f = timed(short_windows, fused=True)[0]
        dt_long_f, res = timed(long_windows, fused=True)
        ceil_s = ceiling_run(n_ceil)
        dt_short_u = timed(short_windows, fused=False)[0]
        dt_long_u, _ = timed(long_windows, fused=False)
        df = dt_long_f - dt_short_f
        du = dt_long_u - dt_short_u
        if df <= 0 or du <= 0:
            continue  # a noise spike swallowed a short run; drop rep
        n_timed = long_windows - short_windows
        reps.append((df / n_timed, du / n_timed, ceil_s / n_ceil))
    if not reps:
        raise RuntimeError(
            "implausible fit timings: every interleaved rep had "
            f"{long_windows}-window wall <= {short_windows}-window wall"
        )

    # ONE rep publishes everything: the rep whose FUSED overhead (the
    # gated leg) is the median.  Selecting each leg's median rep
    # independently would compare fused and unfused samples from
    # different noise regimes — exactly the cross-rep composition the
    # interleaving above exists to prevent — and could flip the winner
    # label on a drifting box (the fused/unfused delta is smaller than
    # the documented drift).
    overs = sorted(1.0 - r[2] / r[0] for r in reps)
    med = overs[len(overs) // 2]
    rep = [r for r in reps if 1.0 - r[2] / r[0] == med][0]
    window_s, window_u, ceiling_window_s = rep
    ceiling_u = ceiling_window_s
    tokens_per_window = bpw * batch * seq
    tps_fused = tokens_per_window / window_s
    tps_unfused = tokens_per_window / window_u
    winner = "fused" if tps_fused >= tps_unfused else "unfused"
    fused_report = north_star_report(fit_metrics)
    return {
        "attn_impl": attn_impl,
        # Never-slower invariant: the published rate is the measured
        # winner's; ``winner`` names it.  Every other top-level key
        # stays the FUSED leg's (the default dispatch discipline).
        "tokens_per_sec": round(max(tps_fused, tps_unfused), 1),
        "winner": winner,
        "windows_timed": long_windows - short_windows,
        "steps_per_window": bpw,
        "window_time_ms": round(window_s * 1e3, 2),
        "ceiling_tokens_per_sec": round(
            tokens_per_window / ceiling_window_s, 1
        ),
        "ceiling_window_ms": round(ceiling_window_s * 1e3, 2),
        # Input-pipeline cost vs the MATCHED no-loader ceiling above
        # (>= 0 means the pipeline costs throughput; the FUSED leg is
        # gated <= 0.02 on CPU by tools/bench_smoke.py, at a geometry
        # where the unfused leg must show >= 0.10 — the A/B proves the
        # overlap, not just the absence of overhead).
        "pipeline_overhead": round(
            1.0 - ceiling_window_s / window_s, 4
        ),
        "fused": {
            "tokens_per_sec": round(tps_fused, 1),
            "window_time_ms": round(window_s * 1e3, 2),
            "pipeline_overhead": round(
                1.0 - ceiling_window_s / window_s, 4
            ),
        },
        "unfused": {
            "tokens_per_sec": round(tps_unfused, 1),
            "window_time_ms": round(window_u * 1e3, 2),
            "pipeline_overhead": round(1.0 - ceiling_u / window_u, 4),
            # The unfused window_wait is the EXPOSED ingest (the
            # block_until_ready on each window lands in it).
            "window_wait_s": round(
                unfused_metrics.timer("trainer.window_wait").total_s, 4
            ),
        },
        "fused_vs_unfused": round(tps_fused / tps_unfused, 3),
        "byte_identical": byte_identical,
        "simulated_dma_ms": dma_ms,
        "final_loss": round(res.losses[-1], 4),
        # Overlap health (ISSUE 5 + 12): trainer time spent waiting for
        # the next window + loader time in forced transfer-completion
        # waits — near zero when the data plane hides behind the
        # scanned steps — the measured ingest-overlap lower bound, the
        # fused-window count, the landing-slot high-water (0 on this
        # single-device CPU geometry; the ICI two-slot occupancy is a
        # chip/virtual-mesh measurement — see DDL_BENCH_MODE=ici), and
        # the pipeline-schedule gauges (zero: no pp axis here).
        "window_wait_s": round(
            fit_metrics.timer("trainer.window_wait").total_s, 4
        ),
        "release_wait_s": round(
            fit_metrics.timer("ingest.release_wait").total_s, 4
        ),
        "ingest_overlap_s": round(fused_report["ingest_overlap_s"], 4),
        "fused_windows": fused_report["fused_windows"],
        "slots_in_flight": fused_report["slots_in_flight"],
        "schedule": "none",
        # Process-level gauge (last compiled pipeline schedule; zero
        # here — this bench geometry has no pp axis).
        "pp_bubble": fused_report["pp_bubble"],
    }


# -- attention seq-length sweep ----------------------------------------------

# One harness shared with tools/probe_attn.py (which imports these), so the
# committed audit probe and the published bench numbers cannot diverge.
ATTN_H, ATTN_HKV, ATTN_D = 16, 8, 128  # bench model geometry
# In-jit chained iterations per dispatch.  The axon tunnel costs ~66 ms
# per CALL (measured; iterations inside the scan are free), so per-iter
# numbers carry ~66/chain ms of overhead — 64 keeps that under ~1 ms
# (pessimistic, never flattering).
ATTN_CHAIN = 64


def sweep_batch(T: int) -> int:
    """Batch size at each sweep length (memory-capped above 4k)."""
    return 4 if T <= 4096 else max(1, 4 * 4096 // T)


def attn_measure(impl, B, T, block_q=None, block_k=None, steps=2,
                 chain=ATTN_CHAIN):
    """Seconds per attention fwd+bwd at one geometry, artifact-hostile:
    ``chain`` data-dependent iterations inside ONE jitted scan, clock
    stopped only after a host read-back of the result.  Best of ``steps``
    timed calls — contention on the shared chip is one-sided noise."""
    import jax
    import jax.numpy as jnp

    from ddl_tpu.ops import flash_attention
    from ddl_tpu.parallel.ring_attention import attention_reference

    kv_repeat = ATTN_H // ATTN_HKV
    kq, kk, kv = jax.random.split(jax.random.key(0), 3)
    q = jax.random.normal(kq, (B, T, ATTN_H, ATTN_D), jnp.bfloat16)
    k = jax.random.normal(kk, (B, T, ATTN_HKV, ATTN_D), jnp.bfloat16)
    v = jax.random.normal(kv, (B, T, ATTN_HKV, ATTN_D), jnp.bfloat16)
    if impl == "flash":
        kw = {}
        if block_q:
            kw["block_q"] = block_q
        if block_k:
            kw["block_k"] = block_k
        f = functools.partial(
            flash_attention, causal=True, kv_repeat=kv_repeat, **kw
        )
    else:
        f = functools.partial(
            attention_reference, causal=True, kv_repeat=kv_repeat
        )

    @jax.jit
    def chained(q, k, v):
        def body(carry, _):
            qq = q * (1.0 + carry * 1e-12).astype(q.dtype)
            l, grads = jax.value_and_grad(
                lambda a, b, c: jnp.sum(
                    f(a, b, c).astype(jnp.float32) ** 2
                ),
                argnums=(0, 1, 2),
            )(qq, k, v)
            return l + sum(
                jnp.sum(g.astype(jnp.float32)) for g in grads
            ), None

        tot, _ = jax.lax.scan(body, jnp.float32(0), None, length=chain)
        return tot

    _ = float(chained(q, k, v))  # compile + warmup (host sync)
    times = []
    for _i in range(steps):
        t0 = time.perf_counter()
        out = float(chained(q, k, v))
        times.append(time.perf_counter() - t0)
        if not np.isfinite(out):
            raise RuntimeError(f"non-finite output {out}")
    return float(np.min(times)) / chain


def _attn_sweep(seqs=(2048, 4096, 8192)):
    """Flash vs dense attention fwd+bwd across sequence lengths — shows
    where the Pallas kernel's linear memory beats XLA dense's T²
    (VERDICT r2 item 2)."""
    rows = []
    for T in seqs:
        B = sweep_batch(T)
        row: dict = {"T": T, "B": B}
        for impl in ("flash", "dense"):
            try:
                row[f"{impl}_ms"] = round(attn_measure(impl, B, T) * 1e3, 2)
            except Exception as e:  # noqa: BLE001 - dense may OOM at 8k+
                row[f"{impl}_err"] = f"{type(e).__name__}: {e}"[:120]
        if "flash_ms" in row and "dense_ms" in row:
            row["flash_speedup"] = round(
                row["dense_ms"] / row["flash_ms"], 3
            )
        rows.append(row)
    return rows


# -- shard-cache cold/warm A/B ------------------------------------------------


class _ThrottledRendezvous:
    """The ThrottledBackend pattern applied to the exchange wire: a
    Rendezvous wrapper whose ``put`` pays ``nbytes / link_bytes_per_sec``
    of simulated link time — so the wire-format A/B measures what a
    CONSTRAINED link (DCN between hosts, a shared NIC) actually sees:
    fewer bytes = faster rounds.  Take/discard/retire delegate."""

    span = "thread"

    def __init__(self, inner, link_bytes_per_sec: float):
        self.inner = inner
        self.link = float(link_bytes_per_sec)

    def put(self, key, rows):
        if self.link > 0:
            time.sleep(rows.nbytes / self.link)
        self.inner.put(key, rows)

    def take(self, *a, **kw):
        return self.inner.take(*a, **kw)

    def discard(self, key):
        self.inner.discard(key)

    def retire(self, key):
        self.inner.retire(key)


def _run_obs_ab() -> dict:
    """The tracing layer priced (ISSUE 15: ddl_tpu.obs) — three legs.

    1. **Armed-vs-disarmed overhead A/B** (measured, interleaved): the
       same deterministic THREAD window stream with span tracing + the
       flight recorder armed vs fully disarmed, per-window
       block_until_ready (the synchronous discipline — dispatch-timing
       noise cannot hide a per-window emission cost), best-of per side
       inside each rep.  Gated <= MAX_OBS_OVERHEAD by bench_smoke.
    2. **Byte identity** (untimed): armed and disarmed streams CRC'd
       per window — arming observability must never change data.
    3. **Chaos flight-record leg**: a seeded RING_CORRUPTION with the
       recorder armed — quarantine+replay keeps the stream
       byte-correct AND the corruption leaves a parseable post-mortem
       artifact naming the faulted window's (producer_idx, seq).

    The armed leg's north-star report must carry the histogram keys
    (window_latency_p50/p99, stage_breakdown) with a nonzero span
    count — documented percentiles that nothing emits would rot.
    """
    import tempfile
    import zlib

    from ddl_tpu import DistributedDataLoader, Marker, distributed_dataloader
    from ddl_tpu import faults
    from ddl_tpu.faults import FaultKind, FaultPlan, FaultSpec
    from ddl_tpu.ingest import north_star_report
    from ddl_tpu.obs import recorder as obs_recorder
    from ddl_tpu.obs import spans as obs_spans
    from ddl_tpu.observability import Metrics

    import jax

    n_windows = EPOCHS_STREAM
    n_epochs = n_windows + 2  # first two windows are warmup

    def run_stream(m, crcs=None, n=n_epochs):
        """One THREAD window stream; returns steady-state samples/s
        (None when ``crcs`` is given — identity legs are untimed)."""

        @distributed_dataloader(
            n_producers=2, mode="thread", nslots=STREAM_NSLOTS
        )
        def main(env):
            loader = DistributedDataLoader(
                StreamBenchProducer(), batch_size=BATCH,
                connection=env.connection, n_epochs=n, output="jax",
                metrics=m,
            )
            t0 = None
            seen = 0
            samples = 0
            for win in loader.windows(lookahead=STREAM_LOOKAHEAD):
                # Synchronous discipline: the window lands before the
                # next acquire, so the A/B prices the emission sites
                # themselves, not dispatch-queue timing.
                jax.block_until_ready(win)
                if crcs is not None:
                    crcs.append(
                        zlib.crc32(np.asarray(win).tobytes())
                    )
                seen += 1
                if seen == 2:
                    t0 = time.perf_counter()
                elif t0 is not None:
                    samples += N_DATA_STREAM
                loader.mark(Marker.END_OF_EPOCH)
            return (
                samples / (time.perf_counter() - t0)
                if t0 is not None and samples
                else None
            )

        return main()

    flight_dir = tempfile.mkdtemp(prefix="ddl-obs-bench-")

    def timed_leg(armed):
        m = Metrics()
        if armed:
            with obs_spans.tracing() as slog, obs_recorder.armed(
                directory=flight_dir
            ):
                rate = run_stream(m)
                report = north_star_report(m)
                return rate, report, slog.appended
        return run_stream(m), None, 0

    # -- leg 1: interleaved armed/disarmed overhead -----------------------
    # PAIRED estimates: each rep runs armed and disarmed back-to-back
    # and contributes ONE ratio; the published overhead is the median
    # rep's.  Cross-rep best-of-each-side (the naive composition) lets
    # the two sides pick different regimes of the box's one-sided
    # drift and swings the ratio by more than the thing measured —
    # the same pathology the fit bench's interleaving fixed (PR 12).
    pairs = []  # (armed rate, disarmed rate) per rep
    armed_report = None
    span_events = 0
    for _ in range(5):
        r_a, rep, n_spans = timed_leg(True)
        if rep is not None:
            armed_report = rep
            span_events = max(span_events, n_spans)
        r_d = timed_leg(False)[0]
        pairs.append((r_a, r_d))
    ratios = sorted(a / d for a, d in pairs)
    med_ratio = ratios[len(ratios) // 2]
    armed_rate, disarmed_rate = [
        p for p in pairs if p[0] / p[1] == med_ratio
    ][0]
    overhead = 1.0 - med_ratio

    # -- leg 2: byte identity (untimed) -----------------------------------
    crcs_armed: "list[int]" = []
    crcs_plain: "list[int]" = []
    with obs_spans.tracing(), obs_recorder.armed(directory=flight_dir):
        run_stream(Metrics(), crcs=crcs_armed, n=4)
    run_stream(Metrics(), crcs=crcs_plain, n=4)
    byte_identical = bool(crcs_armed) and crcs_armed == crcs_plain

    # -- leg 3: seeded corruption leaves a flight record ------------------
    chaos_m = Metrics()
    chaos_crcs: "list[int]" = []
    plan = FaultPlan(
        [FaultSpec(
            "producer.commit", FaultKind.RING_CORRUPTION, at=3, param=16,
        )],
        seed=7,
    )
    with obs_spans.tracing(), obs_recorder.armed(
        directory=flight_dir
    ) as rec, faults.armed(plan):
        run_stream(chaos_m, crcs=chaos_crcs, n=6)
    if not plan.fired:
        raise RuntimeError("obs chaos leg: corruption spec never fired")
    flight = {"written": False}
    for path in rec.dumped_paths:
        # Prefer the artifact that names the faulted window's full
        # (producer_idx, seq) identity — the consumer-side corruption
        # dump; the fault-trip dump (producer side) has no seq yet.
        with open(path) as f:
            record = json.load(f)
        win = record.get("window", {})
        flight = {
            "written": True,
            "path": path,
            "reason": record.get("reason"),
            "producer_idx": win.get("producer_idx"),
            "seq": win.get("seq"),
            "ring_events": len(record.get("events", [])),
        }
        if win.get("seq") is not None:
            break

    stage_breakdown = (
        armed_report.get("stage_breakdown", {}) if armed_report else {}
    )
    return {
        "windows_timed": n_windows,
        "window_mib": round(N_DATA_STREAM * N_VALUES * 4 / (1 << 20), 2),
        "disarmed_samples_per_sec": round(disarmed_rate, 1),
        "armed_samples_per_sec": round(armed_rate, 1),
        "overhead": round(overhead, 4),
        "byte_identical": byte_identical,
        "span_events": int(span_events),
        "window_latency_p50": (
            round(armed_report["window_latency_p50"], 6)
            if armed_report else None
        ),
        "window_latency_p99": (
            round(armed_report["window_latency_p99"], 6)
            if armed_report else None
        ),
        "stage_breakdown_keys": sorted(stage_breakdown),
        "chaos": {
            "corrupt_windows": chaos_m.counter(
                "integrity.corrupt_windows"
            ),
            "replays": chaos_m.counter("integrity.replays"),
            "stream_completed": len(chaos_crcs) == 6,
            "flight_dumps": chaos_m.counter("obs.flight_dumps"),
        },
        "flight_record": flight,
    }


def _run_preempt_ab() -> dict:
    """Preemption tolerance priced end to end (ISSUE 14).

    Three legs over one small deterministic window-stream geometry
    (pointnet, 4 steps/window — checkpoint cost, not model cost, is
    the thing measured):

    1. **Checkpoint-stall A/B** (measured, interleaved): the same fit
       checkpointing EVERY window through the synchronous Orbax path
       (``checkpoint_async=False`` — the fit stalls for serialize +
       fsync + rename) vs the async tier (the stall is the D2H
       snapshot alone; the write hides under training).  Published
       per-checkpoint stalls are each rep-median; the headline is the
       sync/async stall reduction.
    2. **Notice → resumed recovery** (deterministic): a seeded
       ``PREEMPT_NOTICE`` lands mid-run through the real
       ``resilience.notice`` chaos site, the guard drains (forced
       final checkpoint), and a fresh trainer resumes —
       ``recovery_wall_s`` = measured drain + restore-to-first-window
       time, with the resumed window stream BYTE-IDENTICAL and the
       loss curve bit-exact vs the uninterrupted reference.
    3. **Hard-kill lost-work bound** (deterministic): a run that dies
       with NO drain (its newest durable checkpoint one interval old)
       resumes losing exactly the windows since that checkpoint —
       ``lost_steps <= ckpt_interval * steps_per_window`` asserted in
       the block, with the replayed tail byte-identical too.
    """
    import tempfile
    import zlib as _zlib

    import optax
    from jax.sharding import PartitionSpec as P

    from ddl_tpu import faults
    from ddl_tpu.faults import FaultKind, FaultPlan, FaultSpec
    from ddl_tpu.models import pointnet
    from ddl_tpu.observability import Metrics
    from ddl_tpu.parallel.mesh import make_mesh
    from ddl_tpu.readers import ArrayProducer
    from ddl_tpu.resilience import PreemptionGuard
    from ddl_tpu.trainer import Trainer

    import jax

    cfg = pointnet.PointNetConfig(n_inputs=3, n_outputs=2)
    mesh = make_mesh({"dp": 1}, devices=jax.local_devices()[:1])
    seed, batch, window = 1234, 16, 64
    n_windows, interval, notice_at = 6, 2, 5
    bpw = window // batch  # steps per window

    def producer():
        data = np.random.default_rng(seed).random((256, 6)).astype(
            np.float32
        )
        return ArrayProducer(data, window_size=window, splits=(3, 2, 1))

    def make_trainer(ckpt_dir, metrics, every=1, **kw):
        return Trainer(
            loss_fn=lambda p, b: pointnet.weighted_mse_loss(p, b, cfg),
            optimizer=optax.adam(1e-2),
            mesh=mesh,
            param_specs=pointnet.param_specs(cfg),
            init_params=pointnet.init_params(cfg, jax.random.key(0)),
            batch_spec=P(("dp",)),
            checkpoint_dir=ckpt_dir,
            checkpoint_every_epochs=every,
            watchdog=False,
            metrics=metrics,
            **kw,
        )

    def run(trainer, n, crcs=None):
        def hook(win):
            if crcs is not None:
                crcs.append(_zlib.crc32(np.asarray(win).tobytes()))
            return win

        return trainer.fit(
            producer(), batch_size=batch, n_epochs=n, n_producers=2,
            mode="thread", output="jax", window_stream=True,
            window_hook=hook,
        )

    base = tempfile.mkdtemp(prefix="ddl-preempt-")

    # -- leg 1: per-checkpoint stall, sync vs async, interleaved -------
    def stall_rep(i):
        m_async, m_sync = Metrics(), Metrics()
        run(make_trainer(
            os.path.join(base, f"a{i}"), m_async, checkpoint_async=True,
        ), n_windows)
        run(make_trainer(
            os.path.join(base, f"s{i}"), m_sync, checkpoint_async=False,
        ), n_windows)
        ta = m_async.timer("resilience.ckpt_submit")
        ts = m_sync.timer("resilience.ckpt_sync")
        if not ta.count or not ts.count:
            raise RuntimeError("checkpoint timers never ticked")
        # The per-rep mean stalls ALSO land in the shared bounded
        # histograms (ddl_tpu.obs): the published medians below read
        # the histogram back — the stall distribution is a first-class
        # Metrics statistic now, not bench-local list sorting.
        stall_hist.observe("bench.ckpt_stall_async", ta.total_s / ta.count)
        stall_hist.observe("bench.ckpt_stall_sync", ts.total_s / ts.count)
        return ta.total_s / ta.count, ts.total_s / ts.count, ta.count

    from ddl_tpu.observability import Metrics as _Metrics

    stall_hist = _Metrics()
    reps = [stall_rep(i) for i in range(3)]
    async_stall = stall_hist.quantile("bench.ckpt_stall_async", 0.5)
    sync_stall = stall_hist.quantile("bench.ckpt_stall_sync", 0.5)

    # -- leg 2: notice → drain → byte-identical resume -----------------
    m_ref = Metrics()
    crcs_ref: list = []
    ref = run(
        make_trainer(os.path.join(base, "ref"), m_ref, every=interval),
        n_windows, crcs=crcs_ref,
    )
    m_b = Metrics()
    guard = PreemptionGuard(deadline_s=30.0, metrics=m_b)
    plan = FaultPlan([
        FaultSpec("resilience.notice", FaultKind.PREEMPT_NOTICE,
                  at=notice_at),
    ])
    crcs_b: list = []
    drain_dir = os.path.join(base, "drain")
    with faults.armed(plan):
        res_b = run(
            make_trainer(drain_dir, m_b, every=interval,
                         preemption_guard=guard),
            n_windows, crcs=crcs_b,
        )
    if not res_b.preempted:
        raise RuntimeError("injected preemption notice never drained")
    drain_s = m_b.timer("resilience.drain").total_s
    m_c = Metrics()
    crcs_c: list = []
    first_window_t: list = []
    t0 = time.perf_counter()

    def resume_hook(win):
        if not first_window_t:
            first_window_t.append(time.perf_counter() - t0)
        crcs_c.append(_zlib.crc32(np.asarray(win).tobytes()))
        return win

    t_resume = make_trainer(drain_dir, m_c, every=interval)
    res_c = t_resume.fit(
        producer(), batch_size=batch, n_epochs=n_windows, n_producers=2,
        mode="thread", output="jax", window_stream=True,
        window_hook=resume_hook,
    )
    recovery_wall_s = drain_s + (
        first_window_t[0] if first_window_t else float("nan")
    )
    drained_identical = (
        crcs_b + crcs_c == crcs_ref
        and res_b.losses + res_c.losses == ref.losses
        and res_c.state.step == ref.state.step
    )

    # -- leg 3: hard kill (no drain) — the lost-work bound -------------
    kill_dir = os.path.join(base, "kill")
    m_d = Metrics()
    run(make_trainer(kill_dir, m_d, every=interval), notice_at)
    # The run "died" at window `notice_at` with NO final checkpoint:
    # the newest durable generation is the last interval multiple.
    m_e = Metrics()
    crcs_e: list = []
    res_e = run(
        make_trainer(kill_dir, m_e, every=interval), n_windows,
        crcs=crcs_e,
    )
    resumed_from = res_e.resumed_from_epoch
    lost_windows = notice_at - resumed_from
    kill_identical = (
        crcs_e == crcs_ref[resumed_from:]
        and res_e.losses == ref.losses[resumed_from:]
    )
    if lost_windows * bpw > interval * bpw:
        raise RuntimeError(
            f"lost {lost_windows} windows > checkpoint interval "
            f"{interval} — the durability bound is broken"
        )

    return {
        "sync_ckpt_stall_s": round(sync_stall, 6),
        "async_ckpt_stall_s": round(async_stall, 6),
        "async_vs_sync": round(async_stall / sync_stall, 4),
        "stall_reduction": round(sync_stall / max(async_stall, 1e-9), 2),
        "checkpoints": int(reps[0][2]),
        "ckpt_interval_windows": interval,
        "steps_per_window": bpw,
        "windows": n_windows,
        "notice_window": notice_at,
        "drain_s": round(drain_s, 4),
        "drain_deadline_s": guard.deadline_s,
        "drained_within_deadline": bool(
            m_b.gauge("resilience.drain_within_deadline")
        ),
        "notices": m_b.counter("resilience.notices"),
        "final_ckpts": m_b.counter("resilience.final_ckpts"),
        "recovery_wall_s": round(recovery_wall_s, 4),
        "resumed_from_window": res_c.resumed_from_epoch,
        "hard_kill_resumed_from": resumed_from,
        "lost_steps": lost_windows * bpw,
        "lost_steps_bound": interval * bpw,
        "byte_identical": bool(drained_identical and kill_identical),
        "loss_bitexact": bool(
            res_b.losses + res_c.losses == ref.losses
            and res_e.losses == ref.losses[resumed_from:]
        ),
    }


def _run_failover_ab() -> dict:
    """Control-plane failover priced end to end (ISSUE 18).

    Four legs over the 2-mock-host THREAD cluster geometry (the
    tests/test_cluster.py shard ladder — control-plane cost, not data
    volume, is the thing measured):

    1. **Steady-state reference** (deterministic): journaled supervisor
       + HA stepper, leader never killed — the per-shard CRC window
       stream is the byte-identity baseline.
    2. **Mid-stream supervisor kill** (measured): the HA leader dies at
       a fixed epoch boundary; the standby's lease-expiry promotion
       replays the journal, re-fences the control channel, and re-sends
       adoptions.  ``takeover_s`` (promotion wall time + lease
       overshoot) is the headline; the window stream must complete
       BYTE-IDENTICAL to leg 1 with zero watchdog failures and the
       journal's replayed term at 2.
    3. **Envelope chaos** (deterministic counters): a host-loss
       adoption wired under ``CONTROL_MSG_DROP`` + ``CONTROL_MSG_DUP``
       at ``transport.control_send`` — the drop is absorbed by the
       acked seam's backoff retry, the dup by ``(incarnation, seq)``
       dedup (applied once, re-acked), full-shard coverage still
       byte-identical.
    4. **Scheduler fairness across the handover** (deterministic): the
       fake-clock admission script — export→adopt roundtrips bit-exact
       and the promoted scheduler grants the SAME order the
       uninterrupted one would have.
    """
    import tempfile
    import zlib as _zlib

    from ddl_tpu import (
        DataProducerOnInitReturn,
        DistributedDataLoader,
        Marker,
        ProducerFunctionSkeleton,
        distributed_dataloader,
    )
    from ddl_tpu import faults
    from ddl_tpu.cluster import (
        ClusterView,
        ElasticCluster,
        HostInfo,
        JournaledSupervisor,
        SupervisorHA,
        replay_journal,
    )
    from ddl_tpu.exceptions import StallTimeoutError
    from ddl_tpu.faults import FaultKind, FaultPlan, FaultSpec
    from ddl_tpu.observability import Metrics
    from ddl_tpu.serve import TenantSpec
    from ddl_tpu.serve.tenancy import FairShareScheduler
    from ddl_tpu.watchdog import Watchdog

    n_shards, rows, vals = 4, 8, 4
    n_epochs, kill_after = 8, 2
    lease_s = 0.3

    def shard_pattern(shard):
        return (
            shard * 1000.0
            + np.arange(rows * vals, dtype=np.float32) % 97
        ).reshape(rows, vals)

    class _ShardProducer(ProducerFunctionSkeleton):
        def __init__(self, ranges_by_producer):
            self.ranges_by_producer = dict(ranges_by_producer)
            self.ranges = ()

        def _shards(self):
            return [s for a, b in self.ranges for s in range(a, b)]

        def on_init(self, producer_idx=1, **kw):
            self.it = 0
            self.ranges = tuple(self.ranges_by_producer[producer_idx])
            return DataProducerOnInitReturn(
                nData=rows, nValues=vals, shape=(rows, vals),
                splits=(vals,),
            )

        def post_init(self, my_ary, **kw):
            my_ary[:] = 0.0

        def execute_function(self, my_ary, **kw):
            shards = self._shards()
            my_ary[:] = shard_pattern(shards[self.it % len(shards)])
            self.it += 1

        def adopt_shards(self, ranges, **kw):
            self.ranges = tuple(ranges)

    def two_host_view():
        return ClusterView.bootstrap(
            [
                HostInfo(0, loader_ranks=(1,), trainer_ranks=(0,)),
                HostInfo(1, loader_ranks=(2,)),
            ],
            n_shards=n_shards,
        )

    base = tempfile.mkdtemp(prefix="ddl-failover-")

    def drain(journal_path, m, *, kill=False, plan=None, kill_host=None,
              n=n_epochs, pace_s=0.0):
        """Run the pipeline; returns (crcs-by-shard, seen-by-shard, ha)."""
        producer = _ShardProducer({1: ((0, 2),), 2: ((2, 4),)})
        # Per-shard CRC streams: within one shard the order is the
        # producer's deterministic cycle, immune to cross-producer
        # interleave timing.
        crcs: dict = {}

        @distributed_dataloader(n_producers=2, mode="thread")
        def run(env):
            sup = JournaledSupervisor(
                two_host_view(), journal=journal_path, lease_s=30.0,
                poll_interval_s=0.05, metrics=m,
            )
            elastic = ElasticCluster(sup, workers=env.workers, metrics=m)
            ha = SupervisorHA(
                sup, elastic=elastic, lease_s=lease_s, standbys=1,
                metrics=m,
            ).start()
            loader = DistributedDataLoader(
                producer, batch_size=rows, connection=env.connection,
                n_epochs=n, output="numpy", timeout_s=60.0, metrics=m,
                cluster=elastic,
            )
            wd = Watchdog(
                env.workers, poll_interval_s=0.05, stall_budget_s=60.0,
                respawn=True, metrics=m,
            ).start()
            seen: dict = {}
            try:
                for ep in range(n):
                    for (win,) in loader:
                        shard = int(win[0, 0] // 1000)
                        crcs.setdefault(shard, []).append(
                            _zlib.crc32(
                                np.ascontiguousarray(win).tobytes()
                            )
                        )
                        seen.setdefault(shard, []).append(win.copy())
                        loader.mark(Marker.END_OF_BATCH)
                    loader.mark(Marker.END_OF_EPOCH)
                    if pace_s:
                        time.sleep(pace_s)
                    if kill and ep == kill_after:
                        ha.kill_leader()
                    if kill and ep == kill_after + 1:
                        deadline = time.monotonic() + 10.0
                        while ha.leader is None:
                            if time.monotonic() > deadline:
                                raise RuntimeError(
                                    "standby never promoted"
                                )
                            time.sleep(0.02)
                    if kill_host is not None and ep == kill_host:
                        elastic.kill_host(1)
            finally:
                wd.stop()
                ha.stop()
            return seen, ha

        if plan is not None:
            with faults.armed(plan):
                seen, ha = run()
        else:
            seen, ha = run()
        return crcs, seen, ha

    # -- legs 1+2: steady-state vs mid-stream supervisor kill ----------
    m_ref = Metrics()
    crcs_ref, _, _ = drain(os.path.join(base, "ref.jrn"), m_ref)
    m_b = Metrics()
    crcs_b, _, ha_b = drain(
        os.path.join(base, "kill.jrn"), m_b, kill=True,
    )
    if ha_b.last_takeover_s is None:
        raise RuntimeError("HA leader kill never produced a promotion")
    replayed = replay_journal(os.path.join(base, "kill.jrn"))
    byte_identical = bool(
        crcs_b == crcs_ref
        and sorted(crcs_ref) == list(range(n_shards))
    )

    # -- leg 3: adoption under envelope drop + dup chaos ---------------
    m_c = Metrics()
    plan = FaultPlan([
        FaultSpec("transport.control_send", FaultKind.CONTROL_MSG_DROP,
                  at=1),
        FaultSpec("transport.control_send", FaultKind.CONTROL_MSG_DUP,
                  at=2),
    ])
    _, seen_c, _ = drain(
        os.path.join(base, "chaos.jrn"), m_c, plan=plan, kill_host=1,
        n=14, pace_s=0.02,
    )
    if not plan.fired:
        raise RuntimeError("envelope chaos specs never fired")
    coverage_ok = sorted(seen_c) == list(range(n_shards)) and all(
        np.array_equal(w, shard_pattern(s))
        for s, wins in seen_c.items() for w in wins
    )

    # -- leg 4: scheduler fairness across the handover -----------------
    class _FakeClock:
        def __init__(self, t=100.0):
            self.t = t

        def __call__(self):
            return self.t

    def sched(clock):
        s = FairShareScheduler(
            quantum_bytes=1 << 20, metrics=Metrics(), clock=clock,
        )
        s.register(TenantSpec("heavy", weight=2.0,
                              byte_budget_per_s=float(4 << 20)))
        s.register(TenantSpec("light", weight=1.0,
                              byte_budget_per_s=float(1 << 20)))
        return s

    def script(s, clock, steps):
        trace = []
        for _ in range(steps):
            clock.t += 0.25
            for name in ("heavy", "light"):
                try:
                    s.admit(name, timeout_s=0.0)
                except StallTimeoutError:
                    trace.append((name, "throttled"))
                    continue
                s.note_served(name, 1 << 20)
                trace.append((name, "granted"))
        return trace

    c1, c2 = _FakeClock(), _FakeClock()
    uninterrupted, interrupted = sched(c1), sched(c2)
    script(uninterrupted, c1, 4)
    script(interrupted, c2, 4)
    snap = interrupted.export_state(now=c2())
    standby = FairShareScheduler(metrics=Metrics(), clock=c2)
    standby.adopt_state(snap, now=c2())
    roundtrip_exact = standby.export_state(now=c2()) == snap
    tail_a = script(uninterrupted, c1, 6)
    tail_b = script(standby, c2, 6)
    fairness_preserved = bool(
        tail_a == tail_b
        and any(t == ("light", "throttled") for t in tail_b)
    )

    dedup_evidence = (
        m_c.counter("ctrl.acked_dup") + m_c.counter("ctrl.stale_acks")
    )
    return {
        "takeover_s": round(ha_b.last_takeover_s, 4),
        "lease_s": lease_s,
        "kill_after_epoch": kill_after,
        "epochs": n_epochs,
        "journal_term": replayed.term,
        "journal_records": replayed.records,
        "promotions": int(m_b.counter("cluster.promotions")),
        "supervisor_crashes": int(
            m_b.counter("cluster.supervisor_crashes")
        ),
        "watchdog_failures": int(m_b.counter("watchdog.failures")),
        "byte_identical": byte_identical,
        "windows": sum(len(v) for v in crcs_b.values()),
        "chaos": {
            "wire_drops": int(m_c.counter("ctrl.wire_drops")),
            "wire_dups": int(m_c.counter("ctrl.wire_dups")),
            "retries": int(m_c.counter("ctrl.retries")),
            "acked": int(m_c.counter("ctrl.acked")),
            "dedup_evidence": int(dedup_evidence),
            "watchdog_failures": int(m_c.counter("watchdog.failures")),
            "coverage_byte_identical": bool(coverage_ok),
        },
        "scheduler_roundtrip_bit_exact": bool(roundtrip_exact),
        "fairness_preserved": fairness_preserved,
    }


def _run_fabric_soak() -> dict:
    """Multi-job ingest fabric soak (ISSUE 19): one supervisor-resident
    admission authority serving a simulated 100-host / 50-job fleet.

    Every admission decision in every leg rides the REAL control path —
    :class:`~ddl_tpu.serve.fabric.FabricClient` envelopes into
    :class:`~ddl_tpu.serve.fabric.IngestFabric` — never a direct
    scheduler poke (ddl-lint DDL026 bans those; this file's exemption
    covers the in-process DRR reference legs of the failover bench, not
    this one).  Legs:

    1. **Zipf fairness soak.**  ``DDL_BENCH_FABRIC_JOBS`` jobs (default
       50) with Zipf-distributed weights, byte budgets priced
       proportional to weight, probed lockstep from
       ``DDL_BENCH_FABRIC_HOSTS`` host bindings (default 100, two per
       job) under a simulated clock.  Demand exceeds every job's
       budget, so served bytes must track weights: the headline is the
       max per-job **weighted-share deviation**
       ``|observed - expected| / expected`` (bench_smoke gates it).
    2. **Scale reaction.**  A job registered mid-soak must reach 80% of
       its budgeted rate within the reaction SLO (simulated seconds
       from registration to rate attainment).
    3. **Preemption drain.**  The heaviest jobs take one in-flight
       grant each, the supervisor revokes them under
       ``DDL_TPU_FABRIC_DRAIN_SLO_S``, and the grants complete from
       other hosts while the drain waits — drained-inside-SLO is the
       gate, and a revoked job's probe must raise the typed
       ``WindowsRevoked``.
    4. **Per-job cache accounting.**  All jobs share ONE
       :class:`~ddl_tpu.cache.CacheStore` warmed through a
       :class:`~ddl_tpu.cache.backends.ThrottledBackend`-priced loader;
       the per-job ``job.<id>.cache.*`` counters must account for every
       access the store saw (isolation without partitioning the tier).
    5. **Transport pricing.**  One full window-transport round across
       the 100-host :class:`~ddl_tpu.cluster.placement.SimulatedFabric`
       (islanded link costs), measured bytes/s.
    6. **Supervisor kill.**  The same demand trace runs twice — once
       uninterrupted, once with the authority killed mid-soak and
       rebuilt via :meth:`IngestFabric.from_journal` — and the
       admission ORDER (the grant audit log) must be bit-identical, the
       rebuilt scheduler ledger bit-equal to the uninterrupted one, and
       a re-sent pre-kill envelope answered from the journaled reply
       (exactly-once across the failover boundary).
    """
    import dataclasses as _dc
    import tempfile
    import threading

    from ddl_tpu.cache import CacheKey, CacheStore
    from ddl_tpu.cache.backends import ThrottledBackend
    from ddl_tpu.cluster.placement import SimulatedFabric, measure_assignment
    from ddl_tpu.cluster.topology import LinkCosts
    from ddl_tpu.exceptions import StallTimeoutError, WindowsRevoked
    from ddl_tpu.observability import Metrics
    from ddl_tpu.serve.fabric import FabricClient, FabricJob, IngestFabric
    from ddl_tpu.serve.jobs import JobCacheView, JobSpec

    n_jobs = int(os.environ.get("DDL_BENCH_FABRIC_JOBS", "50"))
    n_hosts = int(os.environ.get("DDL_BENCH_FABRIC_HOSTS", "100"))
    steps = int(os.environ.get("DDL_BENCH_FABRIC_STEPS", "160"))
    window = 16 << 10  # small windows: fine-grained share quantization
    dt = 0.25  # simulated seconds per lockstep step
    zipf_s = 0.6  # Zipf exponent over job ranks (weight spread ~10x)
    base_rate = float(16 << 10)  # bytes/s budget per unit weight

    class _Clock:
        def __init__(self, t=1000.0):
            self.t = t

        def __call__(self):
            return self.t

    raw = [(k + 1) ** -zipf_s for k in range(n_jobs)]
    weights = [r * n_jobs / sum(raw) for r in raw]

    def build_fleet(n_j, n_h, clock, m, fab):
        """Register n_j jobs through the fabric and fan out n_h host
        bindings (hosts round-robin over jobs), every handle speaking
        the envelope protocol through its own client."""
        clients = [
            FabricClient(fab, f"host{h:03d}", metrics=m, clock=clock)
            for h in range(n_h)
        ]
        jobs = []
        for j in range(n_j):
            spec = JobSpec(
                job_id=f"job{j:02d}",
                weight=weights[j],
                byte_budget_per_s=weights[j] * base_rate,
            )
            jobs.append(clients[j % n_h].register_job(spec))
        bindings = []
        for h in range(n_h):
            j = jobs[h % n_j]
            bindings.append(
                FabricJob(clients[h], j.job_id, j.index, j.seq_base)
            )
        return clients, jobs, bindings

    def soak(bindings, clock, n_steps, served, throttled):
        """Lockstep demand: every binding probes non-blockingly each
        step; a grant is charged immediately (the loader's
        acquire→release cycle collapsed to zero simulated time)."""
        for _ in range(n_steps):
            clock.t += dt
            for b in bindings:
                try:
                    b.admit(timeout_s=0.0)
                except (StallTimeoutError, WindowsRevoked):
                    throttled[0] += 1
                    continue
                b.note_served(window)
                served[b.job_id] = served.get(b.job_id, 0) + window

    # -- leg 1: Zipf fairness soak -------------------------------------
    clock = _Clock()
    m = Metrics()
    fab = IngestFabric(journal=None, metrics=m, clock=clock)
    clients, jobs, bindings = build_fleet(n_jobs, n_hosts, clock, m, fab)
    served: dict = {}
    throttled = [0]
    soak(bindings, clock, steps, served, throttled)
    total = float(sum(served.values()))
    wsum = sum(weights)
    deviations = []
    for j in range(n_jobs):
        expected = weights[j] / wsum
        observed = served.get(f"job{j:02d}", 0) / total
        deviations.append(abs(observed - expected) / expected)
    dev_max = max(deviations)
    dev_mean = sum(deviations) / len(deviations)

    # -- leg 2: scale reaction (a job arrives mid-fleet) ----------------
    late = clients[0].register_job(
        JobSpec("late", weight=1.0, byte_budget_per_s=base_rate)
    )
    late_b = FabricJob(clients[1], late.job_id, late.index, late.seq_base)
    t_reg = clock.t
    reaction_s = None
    late_served: dict = {}
    for _ in range(40):
        soak([late, late_b], clock, 1, late_served, throttled)
        elapsed = clock.t - t_reg
        if late_served.get("late", 0) >= 0.8 * base_rate * elapsed:
            reaction_s = elapsed
            break
    if reaction_s is None:
        raise RuntimeError("late job never reached 80% of its fair rate")

    # -- leg 3: preemption drain under the SLO --------------------------
    slo_s = 2.0
    drain_jobs = [f"job{j:02d}" for j in range(3)]  # the heaviest
    clock.t += 30.0  # refill every bucket: the grants must be clean
    for b in bindings[:3]:
        b.admit(timeout_s=5.0)  # in-flight: note_served withheld
    finisher = threading.Thread(
        target=lambda: (
            time.sleep(0.05),
            [b.note_served(window) for b in bindings[:3]],
        ),
        daemon=True,
    )
    t0 = time.perf_counter()
    finisher.start()
    reply = fab.revoke_jobs(slo_s=slo_s, job_ids=drain_jobs)
    drain_s = time.perf_counter() - t0
    finisher.join(timeout=10)
    drained = bool(reply.ok and reply.value["drained"])
    revoked_probes = 0
    try:
        bindings[0].admit(timeout_s=0.0)  # still fenced out post-drain
    except WindowsRevoked:
        revoked_probes += 1
    fab.clear_job_revocations(drain_jobs)
    bindings[0].admit(timeout_s=5.0)  # the rejoin edge readmits
    bindings[0].note_aborted()

    # -- leg 4: per-job accounting on the ONE shared cache --------------
    cache_jobs = [f"job{j:02d}" for j in range(8)]
    store = CacheStore(ram_budget_bytes=32 << 20, metrics=Metrics())
    backend = ThrottledBackend(latency_s=0.001)
    with tempfile.TemporaryDirectory(prefix="ddl_fabric_cache_") as td:
        shard_path = os.path.join(td, "shard.bin")
        with open(shard_path, "wb") as f:
            f.write(np.arange(1024, dtype=np.float32).tobytes())

        def load_shard():
            with backend.open(shard_path) as fh:
                return np.frombuffer(fh.read(), dtype=np.float32).copy()

        views = {
            job_id: JobCacheView(store, job_id, metrics=m)
            for job_id in cache_jobs
        }
        accesses = 0
        for i, job_id in enumerate(cache_jobs):
            rng = np.random.default_rng(1000 + i)
            # Zipf-ish popularity over 32 shared shard keys: the head
            # keys overlap across jobs, so one job's miss is the
            # fleet's warm hit.
            for k in (rng.zipf(1.5, size=40) - 1) % 32:
                key = CacheKey(
                    source=backend.fingerprint(shard_path),
                    shard=f"shard-{k}",
                    reader="fabric-bench",
                )
                views[job_id].get_or_load(key, load_shard)
                accesses += 1
    per_job = {j: views[j].counts() for j in cache_jobs}
    hits = sum(c["hits"] for c in per_job.values())
    misses = sum(c["misses"] for c in per_job.values())
    # The store's fleet-global counters live in ITS registry; the
    # per-job views must account for every access it saw.
    accounted = bool(
        hits + misses == accesses
        and hits == store.metrics.counter("cache.hits")
        and misses == store.metrics.counter("cache.misses")
    )

    # -- leg 5: one transport round over the simulated 100-host fabric --
    bw = {}
    for a in range(n_hosts):
        for b in range(a + 1, n_hosts):
            # Islands of 10 hosts: 4 GB/s inside, 1 GB/s across.
            bw[(a, b)] = 4e9 if a // 10 == b // 10 else 1e9
    costs = LinkCosts(bw, default_bytes_per_s=1e9)
    assignment = tuple((h, (h + 1) % n_hosts) for h in range(n_hosts))
    fabric_bps = measure_assignment(
        assignment, SimulatedFabric(costs), payload_bytes=256 << 10, reps=2,
    )

    # -- leg 6: supervisor kill mid-soak --------------------------------
    kj, kh, ksteps, kill_after = 10, 10, 12, 6
    base = tempfile.mkdtemp(prefix="ddl_fabric_")

    def kill_trace(kill: bool):
        c = _Clock()
        mk = Metrics()
        journal = os.path.join(base, "kill.jrn") if kill else None
        f1 = IngestFabric(
            journal=journal, metrics=mk, clock=c, snapshot_every=1,
        )
        cl, _, binds = build_fleet(kj, kh, c, mk, f1)
        srv: dict = {}
        thr = [0]
        soak(binds, c, kill_after, srv, thr)
        dedup = 0
        if kill:
            # Capture the last applied envelope off client 0's wire,
            # then kill the authority object entirely.
            captured = {}
            orig = cl[0]._channel

            def tap(cid, env):
                captured["env"] = env
                return orig(cid, env)

            cl[0]._channel = tap
            binds[0].admit(timeout_s=5.0)
            binds[0].note_served(window)
            srv[binds[0].job_id] = srv.get(binds[0].job_id, 0) + window
            cl[0]._channel = orig
            del f1  # the leader is dead; only the journal survives
            f2 = IngestFabric.from_journal(journal, metrics=mk, clock=c)
            for one in cl:
                one.rebind(f2)
            # A post-failover retry of the captured (already applied)
            # envelope, re-fenced at the successor's term: answered
            # from the journaled reply, ledger untouched.
            before = mk.counter("fabric.dup_replies")
            retry = _dc.replace(captured["env"], fence=f2.term)
            reply2, ack2 = f2.handle(cl[0].client_id, retry)
            dedup = int(mk.counter("fabric.dup_replies") - before)
            if not (reply2.ok and ack2.seq == retry.seq):
                raise RuntimeError(
                    "post-failover duplicate was not answered from the "
                    f"journaled reply: {reply2}"
                )
            f1 = f2
        else:
            binds[0].admit(timeout_s=5.0)
            binds[0].note_served(window)
            srv[binds[0].job_id] = srv.get(binds[0].job_id, 0) + window
        soak(binds, c, ksteps - kill_after, srv, thr)
        return f1, c, srv, dedup

    ref_fab, ref_clock, ref_served, _ = kill_trace(kill=False)
    k_fab, k_clock, k_served, dedup_replies = kill_trace(kill=True)
    order_identical = bool(
        k_fab.admission_log == ref_fab.admission_log
        and len(ref_fab.admission_log) > 0
    )
    ledger_identical = bool(
        k_fab.scheduler.export_state(now=k_clock())
        == ref_fab.scheduler.export_state(now=ref_clock())
        and k_served == ref_served
    )

    return {
        "jobs": n_jobs,
        "hosts": n_hosts,
        "steps": steps,
        "window_bytes": window,
        "sim_dt_s": dt,
        "zipf_exponent": zipf_s,
        "granted_windows": int(total // window),
        "throttled_probes": int(throttled[0]),
        "decisions": fab._decisions,
        "share_deviation_max": round(dev_max, 4),
        "share_deviation_mean": round(dev_mean, 4),
        "scale_reaction_s": round(reaction_s, 3),
        "drain": {
            "jobs_revoked": len(drain_jobs),
            "drained": drained,
            "drain_s": round(drain_s, 4),
            "slo_s": slo_s,
            "revoked_probe_typed": revoked_probes == 1,
        },
        "cache": {
            "jobs": len(cache_jobs),
            "accesses": accesses,
            "hits": int(hits),
            "misses": int(misses),
            "hit_ratio": round(hits / max(accesses, 1), 4),
            "per_job_accounted": accounted,
        },
        "transport": {
            "hosts": n_hosts,
            "payload_bytes": 256 << 10,
            "measured_bytes_per_s": round(fabric_bps, 1),
        },
        "failover": {
            "jobs": kj,
            "steps": ksteps,
            "kill_after_step": kill_after,
            "admissions": len(ref_fab.admission_log),
            "admission_order_identical": order_identical,
            "scheduler_ledger_identical": ledger_identical,
            "dedup_replies": int(dedup_replies),
            "successor_term": k_fab.term,
        },
    }


def _run_wire_ab() -> dict:
    """Raw vs quantized vs compressed exchange wire over a throttled
    link (ISSUE 13, ROADMAP item 3).

    Two simulated instances run the REAL ``ThreadExchangeShuffler``
    exchange (the DCN shuffle wire) over a :class:`_ThrottledRendezvous`
    whose put pays simulated link time per byte — the ThrottledBackend
    pattern.  Three legs share one schedule: ``raw`` (fp32 lanes),
    ``int8`` (blockwise-quantized envelopes), and the best available
    lossless codec (compressible token-like float data, so compression
    has something to find).  Legs run INTERLEAVED best-of-reps; the
    winner is the headline under the never-slower invariant.

    Honesty gates baked into the block (bench_smoke enforces):
    the lossless leg's exchanged windows are byte-identical to raw's;
    the lossy leg's loss curve (a deterministic linear-probe SGD on the
    exchanged stream) passes the ``loss_parity`` gate with NONZERO
    drift (zero drift would mean the wire silently wasn't engaged);
    and the winner's ``wire_bytes`` is strictly below raw's at equal
    ``payload_bytes``.

    Geometry knobs: ``DDL_BENCH_WIRE_ROWS``/``COLS`` (window shape,
    default 256x512), ``DDL_BENCH_WIRE_ROUNDS`` (exchange rounds per
    rep, default 12), ``DDL_BENCH_WIRE_REPS`` (default 3),
    ``DDL_BENCH_WIRE_LINK_MBPS`` (simulated link, default 96).
    """
    import threading

    from ddl_tpu import wire as wire_mod
    from ddl_tpu.observability import Metrics
    from ddl_tpu.parallel.optimizer import loss_parity
    from ddl_tpu.shuffle import Rendezvous, ThreadExchangeShuffler
    from ddl_tpu.types import Topology

    rows = int(os.environ.get("DDL_BENCH_WIRE_ROWS", "256"))
    cols = int(os.environ.get("DDL_BENCH_WIRE_COLS", "512"))
    rounds = int(os.environ.get("DDL_BENCH_WIRE_ROUNDS", "12"))
    reps = int(os.environ.get("DDL_BENCH_WIRE_REPS", "3"))
    link = float(os.environ.get("DDL_BENCH_WIRE_LINK_MBPS", "96")) * (1 << 20)
    num_exchange = rows  # every row travels each round: worst-case wire
    # Token-like compressible float data (small integer vocabulary):
    # the lossless tier exists for exactly this shape of shard, and a
    # codec leg over pure noise would only measure zlib's overhead.
    base = [
        (np.random.default_rng(100 + i).integers(0, 32, (rows, cols)))
        .astype(np.float32)
        for i in range(2)
    ]

    def probe_losses(streams) -> list:
        """Deterministic linear-probe SGD over an exchanged window
        stream — the loss-parity gate's curve (one per leg)."""
        w = np.zeros(cols, np.float64)
        y = np.sin(np.arange(rows)).astype(np.float64)
        losses = []
        for win in streams:
            x = win.astype(np.float64)
            pred = x @ w
            losses.append(float(np.mean((pred - y) ** 2)))
            grad = 2.0 * x.T @ (pred - y) / rows
            w -= 1e-5 * grad
        return losses

    def run_leg(wire_dtype, codec):
        """One rep of one leg: both instances exchange `rounds` times
        over the throttled fabric; returns (samples/s, instance-0
        stream, metrics)."""
        rdv = _ThrottledRendezvous(Rendezvous(), link)
        streams = [[], []]
        metrics = [Metrics(), Metrics()]
        errors = []

        def worker(i):
            try:
                topo = Topology(
                    n_instances=2, instance_idx=i, n_producers=1
                )
                sh = ThreadExchangeShuffler(
                    topo, 1, num_exchange=num_exchange, rendezvous=rdv,
                    seed=7, wire_dtype=wire_dtype, codec=codec,
                    codec_level=1,  # wire compression wants speed
                    exchange_timeout_s=60.0,
                )
                sh.metrics = metrics[i]
                ary = base[i].copy()
                for _ in range(rounds):
                    sh.global_shuffle(ary)
                    streams[i].append(ary.copy())
            except Exception as e:  # noqa: BLE001 - surfaced below
                errors.append(e)

        t0 = time.perf_counter()
        ts = [
            threading.Thread(target=worker, args=(i,)) for i in range(2)
        ]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=120.0)
        if any(t.is_alive() for t in ts):
            raise RuntimeError("wire bench leg wedged (exchange stall)")
        if errors:
            raise errors[0]
        dt = time.perf_counter() - t0
        rate = 2 * rows * rounds / dt
        return rate, streams[0], metrics[0]

    codec = next(
        (c for c in ("zstd", "lz4", "zlib")
         if c in wire_mod.available_codecs()),
        "zlib",
    )
    legs = {"raw": ("raw", None), "int8": ("int8", None),
            codec: ("raw", codec)}
    best: dict = {k: 0.0 for k in legs}
    streams: dict = {}
    wire_stats: dict = {}
    for _ in range(reps):  # interleaved: box noise hits every leg alike
        for name, (wd, cd) in legs.items():
            rate, stream, m = run_leg(wd, cd)
            if rate > best[name]:
                best[name] = rate
            streams[name] = stream
            wire_stats[name] = m
    # Per-INSTANCE lane bytes per leg (the wire_stats registries are
    # instance 0's): num_exchange rows × cols × 4 bytes × rounds.
    raw_payload = float(num_exchange * cols * 4 * rounds)
    block: dict = {
        "link_bytes_per_sec": link,
        "rows": rows, "cols": cols, "rounds": rounds, "reps": reps,
        "codec": codec, "codec_level": 1,
        "legs": {},
    }
    for name in legs:
        m = wire_stats[name]
        enc = m.counter("wire.encoded_bytes")
        pay = m.counter("wire.payload_bytes")
        leg = {
            "samples_per_sec": round(best[name], 1),
            # The raw leg's fast path skips the envelope (and its
            # accounting): its wire bytes ARE the lane bytes.
            "wire_bytes": enc if enc else raw_payload,
            "payload_bytes": pay if pay else raw_payload,
        }
        block["legs"][name] = leg
    # Honesty gates: lossless byte identity, lossy parity (bounded AND
    # nonzero drift), encoded wire strictly below raw.
    block["byte_identical"] = all(
        np.array_equal(a, b)
        for a, b in zip(streams["raw"], streams[codec])
    )
    parity = loss_parity(
        probe_losses(streams["raw"]), probe_losses(streams["int8"])
    )
    block["parity"] = bool(parity["parity"])
    block["parity_drift"] = parity["max_rel_drift"]
    block["legs"]["int8"]["parity"] = parity
    winner = max(best, key=lambda k: best[k])
    block["winner"] = winner
    block["samples_per_sec"] = round(best[winner], 1)
    # Never-slower is a MEASUREMENT, not an argmax identity: the
    # selected winner must beat raw again in a fresh interleaved
    # confirmation pair (comparing argmax(best) against max(best) would
    # be a tautology that certifies nothing — bench_smoke asserts THIS
    # flag, retried once against box noise).
    if winner == "raw":
        block["never_slower"] = True
    else:
        confirm = {}
        for name in ("raw", winner):
            wd, cd = legs[name]
            rate, _, _ = run_leg(wd, cd)
            confirm[name] = round(rate, 1)
        block["confirm"] = confirm
        block["never_slower"] = bool(confirm[winner] >= confirm["raw"])
    block["wire_vs_raw"] = round(best[winner] / max(best["raw"], 1e-9), 3)
    w_leg = block["legs"][winner]
    block["winner_wire_below_raw"] = bool(
        winner == "raw"
        or (
            w_leg["wire_bytes"] < block["legs"]["raw"]["wire_bytes"]
            and w_leg["payload_bytes"]
            == block["legs"]["raw"]["payload_bytes"]
        )
    )
    return block


class _AsyncLinkTransfer:
    """A device-put stand-in that is genuinely IN FLIGHT: ``put``
    returns immediately and a timer thread 'lands' the batch after
    ``nbytes / link`` of simulated transfer time.  This is what gives
    prefetch depth something to buy — with a synchronous put, depth
    only changes queue length, never overlap."""

    def __init__(self, batch, link_bytes_per_sec: float):
        import threading

        self.batch = batch
        self._done = threading.Event()
        delay = (
            batch.nbytes / link_bytes_per_sec
            if link_bytes_per_sec > 0 else 0.0
        )
        t = threading.Timer(delay, self._done.set)
        t.daemon = True
        t.start()

    def wait(self):
        self._done.wait()
        return self.batch


def _run_autotune() -> dict:
    """Self-tuned vs shipped-defaults from a mis-matched cold start
    (ISSUE 20, ROADMAP item 4: ddl_tpu.tune).

    Both legs run the SAME two-phase workload on a deliberately
    constrained simulated fabric: (A) ``rounds`` real
    ``ThreadExchangeShuffler`` exchange rounds over a
    :class:`_ThrottledRendezvous` link, then (B) ``batches`` prefetched
    device transfers (:class:`_AsyncLinkTransfer` — put returns an
    in-flight handle, so depth buys real overlap) each followed by a
    fixed simulated compute step.

    The SEED config is mis-matched to the fabric on purpose:
    ``wire_dtype="raw"`` on a link slow enough that quantization wins
    the break-even economics, and ``prefetch_depth=1`` (no overlap at
    all).  The **defaults** leg runs the seed as shipped.  The
    **tuned** leg starts cold from the same seed and pays for its own
    tuning inside its timed window: a :class:`~ddl_tpu.tune.Calibrator`
    pass (measured ``probe_link_costs`` over the throttled fabric +
    the wire microbenchmark → int8 wire, depth floored to the shipped
    default), then a :class:`~ddl_tpu.tune.KnobController` stepped
    once per consumed batch, growing prefetch depth under hysteresis
    with the never-worse guard live.  The tuned leg runs with the
    flight recorder armed; the block counts its ``tune`` ring events.

    Honesty gates baked into the block (bench_smoke enforces):
    ``never_slower`` re-measured on a fresh confirmation pair (the
    wire-bench pattern, never an argmax identity); ZERO never-worse
    reverts in the winning leg; every decision carries ``cost_source``
    provenance with at least one ``measured`` decision; the int8 leg's
    loss curve passes ``loss_parity``; and the decisions were actually
    flight-recorded.

    Geometry knobs: ``DDL_BENCH_AUTOTUNE_ROWS``/``COLS`` (exchange
    window AND batch shape, default 256x512),
    ``DDL_BENCH_AUTOTUNE_ROUNDS`` (exchange rounds, default 6),
    ``DDL_BENCH_AUTOTUNE_BATCHES`` (prefetch batches, default 24),
    ``DDL_BENCH_AUTOTUNE_REPS`` (default 2),
    ``DDL_BENCH_AUTOTUNE_LINK_MBPS`` (simulated link, default 16),
    ``DDL_BENCH_AUTOTUNE_COMPUTE_MS`` (per-batch compute, default 6).
    """
    import threading

    from ddl_tpu.config import LoaderConfig
    from ddl_tpu.ingest import DeviceIngestor, PrefetchIterator
    from ddl_tpu.obs import recorder as obs_recorder
    from ddl_tpu.observability import Metrics
    from ddl_tpu.parallel.optimizer import loss_parity
    from ddl_tpu.shuffle import Rendezvous, ThreadExchangeShuffler
    from ddl_tpu.tune import (
        Calibrator,
        ControllerPolicy,
        KnobController,
        prefetch_knob,
    )
    from ddl_tpu.types import Topology

    rows = int(os.environ.get("DDL_BENCH_AUTOTUNE_ROWS", "256"))
    cols = int(os.environ.get("DDL_BENCH_AUTOTUNE_COLS", "512"))
    rounds = int(os.environ.get("DDL_BENCH_AUTOTUNE_ROUNDS", "6"))
    batches = int(os.environ.get("DDL_BENCH_AUTOTUNE_BATCHES", "24"))
    reps = int(os.environ.get("DDL_BENCH_AUTOTUNE_REPS", "2"))
    link = (
        float(os.environ.get("DDL_BENCH_AUTOTUNE_LINK_MBPS", "16"))
        * (1 << 20)
    )
    compute_s = (
        float(os.environ.get("DDL_BENCH_AUTOTUNE_COMPUTE_MS", "6")) / 1e3
    )
    num_exchange = rows
    # Token-like compressible float windows (the wire bench's shape).
    base = [
        (np.random.default_rng(100 + i).integers(0, 32, (rows, cols)))
        .astype(np.float32)
        for i in range(2)
    ]
    seed_cfg = LoaderConfig(wire_dtype="raw", prefetch_depth=1)
    total_samples = float(2 * rows * rounds + batches * rows)

    def probe_losses(streams) -> list:
        """Deterministic linear-probe SGD over the exchanged stream —
        the loss-parity gate's curve (one per leg)."""
        w = np.zeros(cols, np.float64)
        y = np.sin(np.arange(rows)).astype(np.float64)
        losses = []
        for win in streams:
            x = win.astype(np.float64)
            pred = x @ w
            losses.append(float(np.mean((pred - y) ** 2)))
            grad = 2.0 * x.T @ (pred - y) / rows
            w -= 1e-5 * grad
        return losses

    def run_exchange(wire_dtype, m):
        """Phase A: both instances exchange over the throttled link;
        returns instance 0's window stream."""
        rdv = _ThrottledRendezvous(Rendezvous(), link)
        streams = [[], []]
        metrics = [m, Metrics()]
        errors = []

        def worker(i):
            try:
                topo = Topology(
                    n_instances=2, instance_idx=i, n_producers=1
                )
                sh = ThreadExchangeShuffler(
                    topo, 1, num_exchange=num_exchange, rendezvous=rdv,
                    seed=7, wire_dtype=wire_dtype,
                    exchange_timeout_s=60.0,
                )
                sh.metrics = metrics[i]
                ary = base[i].copy()
                for _ in range(rounds):
                    sh.global_shuffle(ary)
                    streams[i].append(ary.copy())
            except Exception as e:  # noqa: BLE001 - surfaced below
                errors.append(e)

        ts = [
            threading.Thread(target=worker, args=(i,)) for i in range(2)
        ]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=120.0)
        if any(t.is_alive() for t in ts):
            raise RuntimeError("autotune leg wedged (exchange stall)")
        if errors:
            raise errors[0]
        return streams[0]

    def run_prefetch(depth, m, with_controller):
        """Phase B: consume `batches` in-flight transfers behind a
        PrefetchIterator at `depth`; the tuned leg steps the controller
        once per batch (the telemetry loop at batch cadence)."""
        host = (
            np.zeros((rows, cols), np.float32) for _ in range(batches)
        )
        it = PrefetchIterator(
            host, DeviceIngestor(), depth=depth,
            put=lambda b: _AsyncLinkTransfer(b, link),
        )
        ctrl = None
        if with_controller:
            # The shipped-second constants rescaled to the bench's
            # batch cadence: each step's window is one full batch
            # cycle, so a single above-band reading is already a
            # sustained observation (sustain_s=0); the cooldown still
            # spaces actions and runs the never-worse window.
            ctrl = KnobController(
                [prefetch_knob(it)],
                policy=ControllerPolicy(
                    up_stall_fraction=0.25, down_stall_fraction=0.0,
                    sustain_s=0.0, cooldown_s=0.12,
                ),
                metrics=m,
            )
        for h in it:
            with m.timed("consumer.wait"):
                h.wait()
            time.sleep(compute_s)
            m.incr("consumer.samples", rows)
            if ctrl is not None:
                ctrl.step()
        return it._depth, ctrl

    def run_defaults():
        """The seed as shipped: raw wire, depth 1, nobody watching."""
        m = Metrics()
        t0 = time.perf_counter()
        stream = run_exchange(seed_cfg.wire_dtype, m)
        run_prefetch(seed_cfg.prefetch_depth, m, False)
        dt = time.perf_counter() - t0
        return total_samples / dt, stream

    def run_tuned():
        """Cold start from the same seed; calibration + control INSIDE
        the timed window (self-tuning must pay for itself)."""
        m = Metrics()
        rec = obs_recorder.FlightRecorder(capacity=8192)
        with obs_recorder.armed(rec):
            t0 = time.perf_counter()
            cal = Calibrator(
                deadline_s=2.0,
                hosts=[0, 1],
                transfer=lambda a, b, p: time.sleep(p.nbytes / link),
                sample=base[0],
                metrics=m,
            )
            tuned_cfg = cal.calibrate(seed_cfg)
            cfg = tuned_cfg.apply(seed_cfg)
            stream = run_exchange(cfg.wire_dtype, m)
            final_depth, ctrl = run_prefetch(cfg.prefetch_depth, m, True)
            dt = time.perf_counter() - t0
        flight = sum(1 for e in rec.events() if e[1] == "tune")
        return {
            "rate": total_samples / dt,
            "stream": stream,
            "calibration": tuned_cfg,
            "controller": ctrl,
            "wire_dtype": cfg.wire_dtype,
            "boot_depth": cfg.prefetch_depth,
            "final_depth": final_depth,
            "reverts": int(m.counter("tune.reverts")),
            "cost_sources": {
                src: int(m.counter(f"tune.cost_source.{src}"))
                for src in ("measured", "declared", "default")
            },
            "flight_recorded": flight,
        }

    best_defaults = 0.0
    best_tuned: dict = {}
    defaults_stream: list = []
    for _ in range(reps):  # interleaved: box noise hits both legs alike
        d_rate, d_stream = run_defaults()
        if d_rate > best_defaults:
            best_defaults = d_rate
        defaults_stream = d_stream
        t = run_tuned()
        if not best_tuned or t["rate"] > best_tuned["rate"]:
            best_tuned = t

    ctrl = best_tuned["controller"]
    decisions = [
        d.as_dict() for d in best_tuned["calibration"].decisions
    ] + ([d.as_dict() for d in ctrl.decisions] if ctrl else [])
    block: dict = {
        "link_bytes_per_sec": link,
        "rows": rows, "cols": cols, "rounds": rounds,
        "batches": batches, "reps": reps,
        "compute_ms": round(compute_s * 1e3, 2),
        "seed": {
            "wire_dtype": seed_cfg.wire_dtype,
            "prefetch_depth": seed_cfg.prefetch_depth,
        },
        "legs": {
            "defaults": {"samples_per_sec": round(best_defaults, 1)},
            "tuned": {"samples_per_sec": round(best_tuned["rate"], 1)},
        },
        "tuned_knobs": {
            "wire_dtype": best_tuned["wire_dtype"],
            "boot_prefetch_depth": best_tuned["boot_depth"],
            "final_prefetch_depth": best_tuned["final_depth"],
        },
        "calibration": best_tuned["calibration"].as_report(),
        "controller": ctrl.report() if ctrl else {},
        "decisions": decisions,
        "cost_sources": best_tuned["cost_sources"],
        "deadline_hit": best_tuned["calibration"].deadline_hit,
        "reverts": best_tuned["reverts"],
        "flight_recorded": best_tuned["flight_recorded"],
        "vs_defaults": round(
            best_tuned["rate"] / max(best_defaults, 1e-9), 3
        ),
    }
    # Lossy-wire honesty: the tuned leg's exchanged stream must pass
    # the loss-parity gate against the raw defaults stream.
    parity = loss_parity(
        probe_losses(defaults_stream),
        probe_losses(best_tuned["stream"]),
    )
    block["parity"] = bool(parity["parity"])
    block["parity_drift"] = parity["max_rel_drift"]
    # Never-slower is a MEASUREMENT, not an argmax identity: a fresh
    # confirmation pair, exactly the wire-bench discipline (bench_smoke
    # asserts THIS flag, retried once against box noise).
    c_rate, _ = run_defaults()
    confirm_tuned = run_tuned()
    block["confirm"] = {
        "defaults": round(c_rate, 1),
        "tuned": round(confirm_tuned["rate"], 1),
    }
    block["never_slower"] = bool(confirm_tuned["rate"] >= c_rate)
    block["samples_per_sec"] = round(best_tuned["rate"], 1)
    return block


def _run_cache_ab() -> dict:
    """Cold-vs-warm epoch A/B for the shard cache over a throttled backend.

    Drives a ``FileShardProducer`` refill loop directly (no loader/ring —
    this measures the *storage* path, which is what the cache changes)
    over a ``ThrottledBackend`` simulating a slow source, for two epochs:
    epoch 1 pays fetch+decode per shard (and fills the cache), epoch 2
    serves decoded shards from the warm tier.  The same two-epoch
    sequence also runs with the cache disabled, and every epoch's served
    bytes are CRC'd: ``byte_identical`` asserts the cached stream equals
    the uncached one — the cache must never change data, only speed.

    Geometry knobs: ``DDL_BENCH_CACHE_SHARDS`` (default 8),
    ``DDL_BENCH_CACHE_ROWS`` (rows/shard, default 256),
    ``DDL_BENCH_CACHE_LATENCY_S`` (per-open simulated round-trip,
    default 0.02).
    """
    import shutil
    import tempfile
    import zlib

    from ddl_tpu.cache import CacheStore, ThrottledBackend
    from ddl_tpu.observability import Metrics
    from ddl_tpu.readers import FileShardProducer

    n_shards = int(os.environ.get("DDL_BENCH_CACHE_SHARDS", "8"))
    rows = int(os.environ.get("DDL_BENCH_CACHE_ROWS", "256"))
    latency = float(os.environ.get("DDL_BENCH_CACHE_LATENCY_S", "0.02"))
    n_cols = 64
    tmp = tempfile.mkdtemp(prefix="ddl_cache_bench_")
    try:
        rng = np.random.default_rng(0)
        for i in range(n_shards):
            np.save(
                os.path.join(tmp, f"shard_{i:03d}.npy"),
                rng.standard_normal((rows, n_cols)).astype(np.float32),
            )
        pattern = os.path.join(tmp, "shard_*.npy")

        def run_epochs(cache):
            # warm=False: the A/B measures the refill path itself; a
            # background warmer racing epoch 1 would blur cold cost.
            # cache=False (not None) in the control arm: None defers to
            # the DDL_TPU_CACHE env gate, which would silently cache the
            # "uncached" baseline on a gate-exported host.
            prod = FileShardProducer(
                pattern, seed=0, cache=cache if cache is not None else False,
                backend=ThrottledBackend(latency_s=latency), warm=False,
            )
            ret = prod.on_init(producer_idx=1)
            ary = np.zeros(ret.shape, ret.dtype)
            out = []
            for _ in range(2):  # epochs
                crc = 0
                t0 = time.perf_counter()
                for _ in range(n_shards):
                    prod.execute_function(my_ary=ary)
                    crc = zlib.crc32(ary.tobytes(), crc)
                dt = time.perf_counter() - t0
                out.append((rows * n_shards / dt, crc))
            return out

        m = Metrics()
        store = CacheStore(ram_budget_bytes=256 << 20, metrics=m)
        cached = run_epochs(store)
        uncached = run_epochs(None)
        (cold_rate, cold_crc), (warm_rate, warm_crc) = cached
        block = {
            "shards": n_shards,
            "rows_per_shard": rows,
            "backend_latency_s": latency,
            "cold_samples_per_sec": round(cold_rate, 1),
            "warm_samples_per_sec": round(warm_rate, 1),
            "warm_vs_cold": round(warm_rate / cold_rate, 3),
            "byte_identical": (
                cold_crc == uncached[0][1] and warm_crc == uncached[1][1]
            ),
        }
        stats = m.prefixed("cache.")
        for key in ("hits", "misses", "evictions", "quarantined"):
            block[key] = stats.get(key, 0.0)
        block["resident_bytes_max"] = stats.get("resident_bytes.max", 0.0)
        return block
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def _run_placement_ab() -> dict:
    """Topology-aware vs naive producer→consumer placement (ISSUE 10,
    Cloud Collectives arXiv:2105.14088 rank reordering).

    Geometry: 8 mock hosts — 4 loader hosts and 4 trainer hosts — in 4
    two-host islands deliberately PAIRED ACROSS ROLES (each island holds
    one loader + one trainer host), so the naive rank-order round-robin
    pairs every producer with a cross-island consumer while the planner
    can ride 4 intra-island links.  Both assignments are MEASURED over
    the simulated fabric (real memcpys, wire time priced by the declared
    cost matrix — the cache bench's ThrottledBackend pattern): the ratio
    is wall-clock, not model output.  The never-slower invariant holds
    by construction (the naive order is always a candidate plan) and
    bench_smoke gates the measured ratio.

    The chaos half of the block: a seeded ``HOST_LOSS`` at
    ``cluster.heartbeat`` drives one supervisor sweep through a real
    epoch-fenced view change, so the ``view_changes``/``host_losses``
    counters in the JSON chart the membership machinery itself.

    Knobs: ``DDL_BENCH_PLACEMENT_PAYLOAD_MIB`` (default 4),
    ``DDL_BENCH_PLACEMENT_REPS`` (default 3),
    ``DDL_BENCH_PLACEMENT_INTRA_GBPS`` / ``_CROSS_GBPS`` (simulated
    link speeds, default 8 / 1).
    """
    from ddl_tpu import faults
    from ddl_tpu.cluster import (
        ClusterSupervisor,
        ClusterView,
        HostInfo,
        LinkCosts,
        SimulatedFabric,
        placement_report,
    )
    from ddl_tpu.faults import FaultKind, FaultPlan, FaultSpec
    from ddl_tpu.observability import Metrics

    payload = int(
        float(os.environ.get("DDL_BENCH_PLACEMENT_PAYLOAD_MIB", "4"))
        * (1 << 20)
    )
    reps = int(os.environ.get("DDL_BENCH_PLACEMENT_REPS", "3"))
    intra = float(os.environ.get("DDL_BENCH_PLACEMENT_INTRA_GBPS", "8")) * 1e9
    cross = float(os.environ.get("DDL_BENCH_PLACEMENT_CROSS_GBPS", "1")) * 1e9

    loaders, trainers = (0, 1, 2, 3), (4, 5, 6, 7)
    hosts = [
        HostInfo(h, loader_ranks=(h + 1,)) for h in loaders
    ] + [
        HostInfo(h, trainer_ranks=(h - len(loaders),)) for h in trainers
    ]
    view = ClusterView.bootstrap(hosts, n_shards=32)
    # Islands pair loader host h with trainer host 5-h style partners:
    # (0,5) (1,4) (2,7) (3,6) — every naive round-robin pair (0→4, 1→5,
    # 2→6, 3→7) crosses islands; the planner's pairs stay inside them.
    costs = LinkCosts.islands(
        [[0, 5], [1, 4], [2, 7], [3, 6]], intra, cross
    )
    block = placement_report(
        view,
        costs,
        transfer=SimulatedFabric(costs),
        payload_bytes=payload,
        reps=reps,
    )

    # Membership chaos mini-run: one injected host loss through a REAL
    # supervisor sweep — the counters prove the view-change machinery,
    # not a hand-incremented dict.
    m = Metrics()
    sup = ClusterSupervisor(view, lease_s=60.0, metrics=m)
    plan = FaultPlan(
        [FaultSpec("cluster.heartbeat", FaultKind.HOST_LOSS,
                   producer_idx=loaders[-1])]
    )
    with faults.armed(plan):
        sup.sweep()
    assert plan.fired, "HOST_LOSS spec never fired"
    block["view_changes"] = m.counter("cluster.view_changes")
    block["host_losses"] = m.counter("cluster.host_losses")
    block["post_loss_epoch"] = sup.view.epoch
    return block


def _tenancy_pattern_producer(rows: int, vals: int, fill_latency_s: float):
    """Deterministic per-producer window content for the tenancy leg:
    window k from producer p is the constant plane ``p * 1000 + k`` —
    byte-correctness is checkable on any served subsequence regardless
    of pool churn.  ``fill_latency_s`` simulates decode cost (the
    ThrottledBackend pattern) so the producer tier is the measured
    bottleneck and pool size is what moves aggregate throughput.
    THREAD-mode only (deep-copied, never pickled), hence the local
    class."""
    from ddl_tpu import DataProducerOnInitReturn, ProducerFunctionSkeleton

    class PatternProducer(ProducerFunctionSkeleton):
        inplace_fill = True

        def on_init(self, producer_idx=1, **kw):
            self.idx = producer_idx
            self.k = 0
            return DataProducerOnInitReturn(
                nData=rows, nValues=vals, shape=(rows, vals),
                splits=(vals,),
            )

        def post_init(self, my_ary, **kw):
            my_ary[:] = 0.0

        def execute_function(self, my_ary, **kw):
            if fill_latency_s:
                time.sleep(fill_latency_s)
            my_ary[:] = float(self.idx * 1000 + self.k)
            self.k += 1

    return PatternProducer()


def _tenancy_shard_producer(rows: int, vals: int, ranges_by_producer: dict):
    """The chaos leg's producer: serves its host's shard ranges in a
    cycle and re-partitions on ``adopt_shards`` (the test_cluster
    pattern) — so full-shard coverage survives a mid-stream host loss."""
    from ddl_tpu import DataProducerOnInitReturn, ProducerFunctionSkeleton

    def shard_pattern(shard: int):
        return (
            shard * 1000.0
            + np.arange(rows * vals, dtype=np.float32) % 97
        ).reshape(rows, vals)

    class ShardProducer(ProducerFunctionSkeleton):
        inplace_fill = True
        pattern = staticmethod(shard_pattern)

        def _shards(self):
            return [s for a, b in self.ranges for s in range(a, b)]

        def on_init(self, producer_idx=1, **kw):
            self.it = 0
            self.ranges = tuple(ranges_by_producer[producer_idx])
            return DataProducerOnInitReturn(
                nData=rows, nValues=vals, shape=(rows, vals),
                splits=(vals,),
            )

        def post_init(self, my_ary, **kw):
            my_ary[:] = 0.0

        def execute_function(self, my_ary, **kw):
            shards = self._shards()
            my_ary[:] = shard_pattern(shards[self.it % len(shards)])
            self.it += 1

        def adopt_shards(self, ranges, **kw):
            self.ranges = tuple(ranges)

    return ShardProducer()


class _TenantFleet:
    """Autoscaler adapter fanning one resize across every tenant's
    elastic ladder: N independent loader jobs share ONE logical host
    set, so a scale decision must land on each tenant's supervisor (the
    epoch fences keep them mutually consistent — every supervisor
    computes the identical successor view from the same HostInfo)."""

    def __init__(self, elastics):
        self.elastics = list(elastics)

    @property
    def supervisor(self):
        return self.elastics[0].supervisor

    def rejoin_host(self, host):
        view = None
        for e in self.elastics:
            view = e.rejoin_host(host)
        return view

    def drain_host(self, host_id):
        info = None
        for e in self.elastics:
            info = e.drain_host(host_id)
        return info


def _tenancy_leg(
    dynamic: bool,
    demand: "list[int]",
    rows: int,
    vals: int,
    fill_s: float,
    n_hosts_floor: int = 2,
    n_hosts_max: int = 4,
) -> dict:
    """One measured tenancy leg: K tenant loaders (own THREAD envs, one
    ring per mock host, hosts ``floor..max-1`` standing by) drain their
    heavy-tailed demand through one shared fair-share scheduler.
    ``dynamic`` additionally runs the autoscaler on the REAL windowed
    stall signal; the static baseline keeps the floor pool for the whole
    run.  Returns aggregate + per-tenant measurements."""
    import threading

    from ddl_tpu import DistributedDataLoader, Marker, distributed_dataloader
    from ddl_tpu.cluster import ClusterSupervisor, ClusterView, ElasticCluster, HostInfo
    from ddl_tpu.observability import Metrics
    from ddl_tpu.serve import (
        AdmissionController,
        Autoscaler,
        AutoscalerPolicy,
        FairShareScheduler,
        TenantSpec,
    )

    K = len(demand)
    window_bytes = rows * vals * 4
    m = Metrics()
    ctl = AdmissionController(
        scheduler=FairShareScheduler(quantum_bytes=window_bytes, metrics=m),
        metrics=m,
    )
    tenants = [ctl.register(TenantSpec(f"t{i}")) for i in range(K)]

    def bootstrap_view():
        return ClusterView.bootstrap(
            [
                HostInfo(h, loader_ranks=(h + 1,))
                for h in range(n_hosts_floor)
            ],
            n_shards=n_hosts_max * 2,
        )

    pairs = []
    for _ in range(K):
        sup = ClusterSupervisor(bootstrap_view(), lease_s=600.0, metrics=m)
        pairs.append(ElasticCluster(sup, metrics=m))

    per_tenant: dict = {}
    errors: "list[str]" = []
    lock = threading.Lock()

    def run_tenant(i: int) -> None:
        tenant, elastic, n_epochs = tenants[i], pairs[i], demand[i]

        @distributed_dataloader(n_producers=n_hosts_max, mode="thread")
        def tmain(env):
            loader = DistributedDataLoader(
                _tenancy_pattern_producer(rows, vals, fill_s),
                batch_size=rows, connection=env.connection,
                n_epochs=n_epochs, output="numpy", timeout_s=120.0,
                metrics=m, cluster=elastic,
            )
            tenant.bind(loader)
            lats, byte_ok = [], True
            for _ in range(n_epochs):
                t0 = time.perf_counter()
                for (win,) in loader:
                    dt = time.perf_counter() - t0
                    lats.append(dt)
                    # First-class percentiles (ddl_tpu.obs): the same
                    # latencies land in the shared registry's bounded
                    # histogram; the published p50/p99 below read THAT
                    # back, with the raw-list percentile kept as the
                    # independent cross-check.
                    m.observe(
                        f"ingest.{tenant.name}.window_latency", dt
                    )
                    v = win.ravel()[0]
                    if not (win == v).all() or v < 1000.0:
                        byte_ok = False
                    loader.mark(Marker.END_OF_BATCH)
                loader.mark(Marker.END_OF_EPOCH)
            return lats, byte_ok

        try:
            lats, byte_ok = tmain()
            with lock:
                # Primary percentiles come from the Metrics histogram
                # (the values north_star_report surfaces); the raw-list
                # np.percentile rides along as the independent check —
                # bench_smoke asserts they agree within one log bucket.
                per_tenant[tenant.name] = {
                    "windows": n_epochs,
                    "bytes": n_epochs * window_bytes,
                    "p50_window_latency_s": round(
                        m.quantile(
                            f"ingest.{tenant.name}.window_latency", 0.5
                        ), 4
                    ),
                    "p99_window_latency_s": round(
                        m.quantile(
                            f"ingest.{tenant.name}.window_latency", 0.99
                        ), 4
                    ),
                    "p99_window_latency_np_s": round(
                        float(np.percentile(lats, 99)), 4
                    ),
                    "byte_identical": bool(byte_ok),
                }
        except Exception as e:  # noqa: BLE001 - surfaced in the block
            with lock:
                errors.append(f"{tenant.name}: {type(e).__name__}: {e}")

    scaler = None
    if dynamic:
        standby = [
            HostInfo(h, loader_ranks=(h + 1,))
            for h in range(n_hosts_floor, n_hosts_max)
        ]
        scaler = Autoscaler(
            _TenantFleet(pairs),
            standby=standby,
            policy=AutoscalerPolicy(
                up_stall_fraction=0.3, down_stall_fraction=0.02,
                sustain_s=0.1, cooldown_s=0.2,
                min_hosts=n_hosts_floor, max_hosts=n_hosts_max,
            ),
            metrics=m, n_consumers=K, poll_interval_s=0.05,
        ).start()

    threads = [
        threading.Thread(target=run_tenant, args=(i,)) for i in range(K)
    ]
    t_start = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join(300.0)
    wall = time.perf_counter() - t_start
    if scaler is not None:
        scaler.stop()
    hung = [t.name for t in threads if t.is_alive()]
    if hung:
        # A silent join expiry would fabricate samples_per_sec from
        # windows never served AND leak a live pipeline into the next
        # interleaved rep — fail the leg loudly instead.
        raise RuntimeError(f"tenancy leg hung tenants: {hung}")
    if errors:
        raise RuntimeError(f"tenancy leg failed: {errors}")
    total_samples = sum(demand) * rows
    reaction = m.timer("serve.scale_up_reaction")
    # The scheduler/admission report refreshes the per-tenant stall
    # gauges north_star_report surfaces.
    serve_report = ctl.report()
    for name, block in serve_report["tenants"].items():
        if name in per_tenant:
            per_tenant[name]["admission_wait_s"] = round(
                block["admission_wait_s"], 4
            )
            per_tenant[name]["admission_wait_p99_s"] = round(
                block["admission_wait_p99_s"], 6
            )
            per_tenant[name]["stall_fraction"] = round(
                block["stall_fraction"], 4
            )
    # The report-level percentile (the north_star_report key) next to
    # the scheduler's own — one histogram, two readers, must agree.
    from ddl_tpu.ingest import north_star_report as _nsr

    ns = _nsr(m)
    return {
        "samples_per_sec": total_samples / wall,
        "admission_wait_p99_s": round(ns["admission_wait_p99"], 6),
        "wall_s": round(wall, 3),
        "windows": int(sum(demand)),
        "per_tenant": per_tenant,
        "scale_ups": m.counter("serve.scale_ups"),
        "scale_downs": m.counter("serve.scale_downs"),
        "scale_up_reaction_s": round(
            reaction.total_s / reaction.count, 4
        ) if reaction.count else None,
        "pool_hosts_final": m.gauge("serve.pool_hosts"),
        "admissions": serve_report["admissions"],
        "admission_wait_s": round(serve_report["admission_wait_s"], 4),
        "rounds": serve_report["rounds"],
    }


def _tenancy_chaos_leg(K: int, rows: int, vals: int) -> dict:
    """The chaos half of the tenancy block: a TENANT_BURST at
    ``serve.admit`` and a HOST_LOSS at ``cluster.heartbeat`` land
    mid-stream on K concurrent tenants (the burst on tenant 0, the loss
    on mock host 1 of every tenant's fleet view).  Every tenant's
    stream must stay byte-correct with FULL shard coverage — the
    survivors adopt the dead host's ranges — and zero watchdog
    failures."""
    import threading

    from ddl_tpu import DistributedDataLoader, Marker, distributed_dataloader
    from ddl_tpu import faults
    from ddl_tpu.cluster import ClusterSupervisor, ClusterView, ElasticCluster, HostInfo
    from ddl_tpu.faults import FaultKind, FaultPlan, FaultSpec
    from ddl_tpu.observability import Metrics
    from ddl_tpu.serve import AdmissionController, FairShareScheduler, TenantSpec
    from ddl_tpu.watchdog import Watchdog

    n_shards, n_epochs = 4, 12
    m = Metrics()
    ctl = AdmissionController(
        scheduler=FairShareScheduler(
            quantum_bytes=rows * vals * 4, metrics=m
        ),
        metrics=m,
    )
    tenants = [ctl.register(TenantSpec(f"c{i}")) for i in range(K)]
    errors: "list[str]" = []
    coverage: dict = {}
    lock = threading.Lock()

    def run_tenant(i: int) -> None:
        tenant = tenants[i]

        @distributed_dataloader(n_producers=2, mode="thread")
        def tmain(env):
            view = ClusterView.bootstrap(
                [HostInfo(0, loader_ranks=(1,), trainer_ranks=(0,)),
                 HostInfo(1, loader_ranks=(2,))],
                n_shards=n_shards,
            )
            sup = ClusterSupervisor(view, lease_s=60.0, metrics=m)
            elastic = ElasticCluster(sup, workers=env.workers, metrics=m)
            producer = _tenancy_shard_producer(
                rows, vals, {1: ((0, 2),), 2: ((2, 4),)}
            )
            loader = DistributedDataLoader(
                producer, batch_size=rows, connection=env.connection,
                n_epochs=n_epochs, output="numpy", timeout_s=60.0,
                metrics=m, cluster=elastic,
            )
            tenant.bind(loader)
            wd = Watchdog(
                env.workers, poll_interval_s=0.05, stall_budget_s=60.0,
                respawn=True, metrics=m, cluster=sup,
            ).start()
            ref = producer.pattern
            seen, ok = set(), True
            try:
                for _ in range(n_epochs):
                    for (win,) in loader:
                        shard = int(win[0, 0] // 1000)
                        seen.add(shard)
                        if not np.array_equal(win, ref(shard)):
                            ok = False
                        loader.mark(Marker.END_OF_BATCH)
                    loader.mark(Marker.END_OF_EPOCH)
                    # Pace the stream so the watchdog-driven sweeps (and
                    # the armed HOST_LOSS) land mid-run, not after it.
                    time.sleep(0.05)
            finally:
                wd.stop()
            return seen, ok

        try:
            seen, ok = tmain()
            with lock:
                coverage[tenant.name] = {
                    "shards_seen": sorted(seen),
                    "byte_correct": bool(
                        ok and sorted(seen) == list(range(n_shards))
                    ),
                }
        except Exception as e:  # noqa: BLE001 - surfaced in the block
            with lock:
                errors.append(f"{tenant.name}: {type(e).__name__}: {e}")

    plan = FaultPlan([
        # The burst lands on tenant 0's 3rd admission...
        FaultSpec("serve.admit", FaultKind.TENANT_BURST,
                  at=3, producer_idx=0, param=float(8 << 20)),
        # ...while EVERY tenant's supervisor declares mock host 1 dead
        # at its next sweep (count covers all K supervisors' sweeps —
        # repeat declarations of an already-departed host are no-ops).
        FaultSpec("cluster.heartbeat", FaultKind.HOST_LOSS,
                  producer_idx=1, count=10_000),
    ])
    threads = [
        threading.Thread(target=run_tenant, args=(i,)) for i in range(K)
    ]
    with faults.armed(plan):
        for t in threads:
            t.start()
        for t in threads:
            t.join(300.0)
    hung = [t.name for t in threads if t.is_alive()]
    if hung:
        raise RuntimeError(f"tenancy chaos leg hung tenants: {hung}")
    if errors:
        raise RuntimeError(f"tenancy chaos leg failed: {errors}")
    fired = {kind for _site, kind, _idx, _n in plan.fired}
    return {
        "tenants": coverage,
        "byte_correct": all(
            c["byte_correct"] for c in coverage.values()
        ),
        "tenant_bursts": m.counter("serve.tenant_bursts"),
        "host_losses": m.counter("cluster.host_losses"),
        "view_changes": m.counter("cluster.view_changes"),
        # The elastic-side SEND counter: producer-side adoption applies
        # land on the worker threads' default registry, not this leg's.
        "shard_adoptions": m.counter("cluster.shard_adoptions"),
        "watchdog_failures": m.counter("watchdog.failures"),
        "fired_kinds": sorted(fired),
    }


def _run_tenancy_ab() -> dict:
    """The multi-tenant ingest-service A/B (ISSUE 11, ROADMAP item 1).

    K concurrent synthetic tenants on a heavy-tailed demand schedule
    (tenant i demands ``base * K / (i + 1)`` windows — Zipf-1) drain
    throttled producers through ONE shared fair-share scheduler, twice:

    - **static** — the pool is pinned at the floor (2 of 4 mock hosts)
      for the whole run: the provision-for-peak baseline.
    - **dynamic** — the autoscaler watches the real windowed stall
      signal and `rejoin_host`s the standby hosts on sustained demand.

    Both legs are MEASURED (wall-clock aggregate samples/s over real
    THREAD pipelines; the producer throttle makes pool size the
    bottleneck by construction), interleaved best-of-``reps``; the
    winner is the headline under the same never-slower invariant every
    other competition rides, and bench_smoke gates ``vs_static >= 1``.
    Per-tenant p50/p99 window latency, byte-identity flags, admission
    waits, and the scale-up reaction time (sustained-signal-to-rejoin,
    the ``serve.scale_up_reaction`` timer) ride in the block, plus the
    chaos leg (:func:`_tenancy_chaos_leg`).

    Knobs: ``DDL_BENCH_TENANCY_TENANTS`` (K, default 3),
    ``DDL_BENCH_TENANCY_BASE`` (demand base, default 12 — long enough
    that the post-scale-up span dominates the measurement),
    ``DDL_BENCH_TENANCY_FILL_MS`` (producer throttle, default 25),
    ``DDL_BENCH_TENANCY_ROWS`` (window rows, default 256),
    ``DDL_BENCH_TENANCY_REPS`` (default 2).
    """
    K = max(3, int(os.environ.get("DDL_BENCH_TENANCY_TENANTS", "3")))
    base = int(os.environ.get("DDL_BENCH_TENANCY_BASE", "12"))
    fill_s = float(os.environ.get("DDL_BENCH_TENANCY_FILL_MS", "25")) / 1e3
    rows = int(os.environ.get("DDL_BENCH_TENANCY_ROWS", "256"))
    reps = int(os.environ.get("DDL_BENCH_TENANCY_REPS", "2"))
    vals = 8
    # Heavy-tailed (Zipf-1) demand: tenant 0 wants K× tenant K-1's load.
    demand = [max(2, base * K // (i + 1)) for i in range(K)]

    best: dict = {}
    for _ in range(max(1, reps)):
        # Interleaved static/dynamic pairs, best-of per side.
        st = _tenancy_leg(False, demand, rows, vals, fill_s)
        dy = _tenancy_leg(True, demand, rows, vals, fill_s)
        if st["samples_per_sec"] > best.get("static", {}).get(
            "samples_per_sec", 0.0
        ):
            best["static"] = st
        if dy["samples_per_sec"] > best.get("dynamic", {}).get(
            "samples_per_sec", 0.0
        ):
            best["dynamic"] = dy
    st, dy = best["static"], best["dynamic"]
    vs_static = (
        dy["samples_per_sec"] / st["samples_per_sec"]
        if st["samples_per_sec"] > 0
        else 1.0
    )
    winner = "dynamic" if dy["samples_per_sec"] >= st["samples_per_sec"] else "static"
    chaos = _tenancy_chaos_leg(K, rows=32, vals=4)
    return {
        "n_tenants": K,
        "demand_windows": demand,
        "fill_latency_ms": fill_s * 1e3,
        "window_bytes": rows * vals * 4,
        "samples_per_sec": round(
            max(dy["samples_per_sec"], st["samples_per_sec"]), 1
        ),
        "dynamic_samples_per_sec": round(dy["samples_per_sec"], 1),
        "static_samples_per_sec": round(st["samples_per_sec"], 1),
        "vs_static": round(vs_static, 3),
        "winner": winner,
        "scale_ups": dy["scale_ups"],
        "scale_downs": dy["scale_downs"],
        "scale_up_reaction_s": dy["scale_up_reaction_s"],
        "pool_hosts_final": dy["pool_hosts_final"],
        "static_wall_s": st["wall_s"],
        "dynamic_wall_s": dy["wall_s"],
        "per_tenant": dy["per_tenant"],
        "byte_identical": all(
            t["byte_identical"] for t in dy["per_tenant"].values()
        ) and all(
            t["byte_identical"] for t in st["per_tenant"].values()
        ),
        "admission_wait_s": dy["admission_wait_s"],
        "rounds": dy["rounds"],
        "chaos": chaos,
    }


def _ensure_virtual_mesh(n: int) -> None:
    """Force an n-device CPU virtual mesh BEFORE the first backend touch
    (the ici A/B needs a ring to fan out over; a plain CPU attach exposes
    one device).  No-op when the flag is already set — and harmless on
    TPU, where this is never called."""
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n}"
        ).strip()


def _run_ici_ab(platform: str) -> dict:
    """The ICI ingest A/B (ROADMAP item 1): one window, H2D onto the
    anchor device, then distributed to a dp-sharded target two ways —
    ``ici`` (Pallas fan-out ring + redistribution legs,
    ddl_tpu/parallel/ici.py) vs ``xla`` (the pre-existing
    ``device_put`` scatter) — measured INTERLEAVED, best-of both sides.

    Two ratios come out: ``vs_xla`` (end-to-end, the ici-vs-xla
    competition under the never-slower headline invariant) and
    ``bandwidth_utilization`` — the fan-out's measured per-hop wire rate
    over the platform's per-LINK ICI spec (``_PEAK_ICI_LINK``), the
    BASELINE.md ≥0.90 target's denominator.  Off-TPU the kernel runs in
    interpret mode on the virtual mesh (byte-identity + contract-shape
    proof; the utilization denominator is null — there is no ICI).

    Geometry knobs: ``DDL_BENCH_ICI_MIB`` (window size, default 64 on
    TPU / 1 interpreted), ``DDL_BENCH_ICI_REPS`` (default 5).
    """
    import jax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from ddl_tpu.observability import Metrics
    from ddl_tpu.parallel.ici import IciDistributor

    devices = jax.devices()
    n_dev = len(devices)
    if n_dev < 2:
        raise RuntimeError(f"ici A/B needs >= 2 devices, found {n_dev}")
    mesh = Mesh(np.array(devices), ("dp",))
    sharding = NamedSharding(mesh, P("dp"))

    interpret = platform != "tpu"
    mib = int(os.environ.get("DDL_BENCH_ICI_MIB", "1" if interpret else "64"))
    cols = N_VALUES
    rows = max(n_dev, mib * (1 << 20) // (cols * 4) // n_dev * n_dev)
    win = np.random.default_rng(0).random((rows, cols)).astype(np.float32)
    reps = int(os.environ.get("DDL_BENCH_ICI_REPS", "5"))

    m = Metrics()
    dist = IciDistributor(sharding, metrics=m)
    plan = dist.plan(win.shape, win.dtype)  # PlanError -> errors block

    # Warmup both paths (compiles) + the byte-identity check.
    out_i = dist.put(win, jax.device_put)
    out_x = jax.device_put(win, sharding)
    jax.block_until_ready((out_i, out_x))
    byte_identical = bool(
        np.array_equal(np.asarray(out_i), np.asarray(out_x))
    )

    def timed(fn):
        t0 = time.perf_counter()
        jax.block_until_ready(fn())
        return time.perf_counter() - t0

    # End-to-end (H2D + distribution), interleaved so neither side owns
    # the quiet minutes (the PR 6 vs_baseline discipline).
    ici_s, xla_s = [], []
    for _ in range(reps):
        ici_s.append(timed(lambda: dist.put(win, jax.device_put)))
        xla_s.append(timed(lambda: jax.device_put(win, sharding)))

    # Distribution-only (anchor-resident source): the ICI hop itself,
    # the wire-rate numerator — H2D excluded from the clock.
    anchor_block = jax.device_put(win, plan.anchor)
    jax.block_until_ready(anchor_block)
    dist_s = min(timed(lambda: dist.distribute(anchor_block))
                 for _ in range(reps))

    if dist.faulted:
        # A latched fallback mid-bench means the "ici" timings silently
        # measured the xla path — that is not a result.
        raise RuntimeError(
            "ici distributor latched the xla fallback during the A/B "
            f"(ici.fallbacks={m.counter('ici.fallbacks')})"
        )

    nbytes = win.nbytes
    ici_rate = nbytes / min(ici_s)
    xla_rate = nbytes / min(xla_s)
    winner = "ici" if ici_rate >= xla_rate else "xla"
    wire_rate = plan.wire_bytes / dist_s
    per_hop = wire_rate / n_dev  # symmetric ring: wire bytes / link
    link_spec = (
        _peak_ici_link(devices[0].device_kind) if platform == "tpu"
        else None
    )
    util = per_hop / link_spec if link_spec else 0.0
    block = {
        "n_devices": n_dev,
        "window_mib": round(nbytes / 2**20, 2),
        "interpret": interpret,
        "plan_mode": plan.mode,
        "plan_legs": [leg.kind for leg in plan.legs],
        "peak_factor": round(plan.peak_factor, 3),
        "peak_bytes": plan.peak_bytes,
        # The ici-vs-xla competition: the block's headline bytes/s is
        # the WINNER's (never a config this run measured slower).
        "bytes_per_s": round(max(ici_rate, xla_rate), 1),
        "winner": winner,
        "ici_bytes_per_s": round(ici_rate, 1),
        "xla_bytes_per_s": round(xla_rate, 1),
        "vs_xla": round(ici_rate / xla_rate, 3),
        "byte_identical": byte_identical,
        # The ICI hop itself: wire bytes the fan-out+legs moved per
        # window over the distribution-only span, per ring link.
        "wire_bytes": plan.wire_bytes,
        "wire_bytes_per_s": round(wire_rate, 1),
        "per_hop_bytes_per_s": round(per_hop, 1),
        "link_spec_bytes_per_s": link_spec,
        "bandwidth_utilization": round(util, 4),
        "fanout_s": round(m.timer("ici.fanout").total_s, 4),
        "redistribute_s": round(m.timer("ici.redistribute").total_s, 4),
        "fallbacks": m.counter("ici.fallbacks"),
    }
    return _gate_utilization(block, "ici per-hop")


# -- device-shuffle A/B --------------------------------------------------------


def _run_shuffle_ab(platform: str) -> dict:
    """The global-shuffle exchange A/B (ROADMAP item 2 / ISSUE 17): the
    same seeded epoch exchange run two ways — ``host``
    (``ThreadExchangeShuffler`` over the in-process rendezvous, the
    2n-mailbox-hop path) vs ``device`` (``DeviceExchangeShuffler``: one
    collective over the ring per round, ``ddl_tpu/ops/device_shuffle``)
    — measured INTERLEAVED, best-of both sides, byte-identity of the
    post-exchange pools asserted per rep.

    The headline is the WINNER's bytes/s (the never-headline-slower
    invariant every competition rides).  Off-TPU the ring kernel runs
    in interpret mode on the virtual mesh, where the Python-level
    emulation usually LOSES to the host memcpy path — the contract
    (identity, plan accounting, zero fallbacks) must stay green anyway,
    the ici-bench precedent; the chip A/B is chip_checklist step 11.

    Per-leg wire-byte accounting comes from ``plan_exchange``: the
    device path's ICI bytes vs what the HOST path would put on the
    boards raw and wire-encoded (the PR-13 int8 wire pricing, composed
    via ``DDL_TPU_WIRE_DTYPE``/``DDL_BENCH_SHUFFLE_WIRE``).

    Geometry knobs: ``DDL_BENCH_SHUFFLE_INSTANCES`` (ring width,
    default min(4, devices)), ``DDL_BENCH_SHUFFLE_ROWS`` (pool rows per
    instance, default 512 interpreted / 8192 on TPU),
    ``DDL_BENCH_SHUFFLE_ROUNDS`` (default 4), ``DDL_BENCH_SHUFFLE_REPS``
    (default 3), ``DDL_BENCH_SHUFFLE_IMPL`` (ring | xla).
    """
    import threading

    import jax

    from ddl_tpu.observability import Metrics
    from ddl_tpu.ops.device_shuffle import exchange_wire_bytes, plan_exchange
    from ddl_tpu.shuffle import (
        DeviceExchangeFabric,
        DeviceExchangeShuffler,
        Rendezvous,
        ThreadExchangeShuffler,
    )
    from ddl_tpu.types import Topology

    devices = jax.devices()
    n_dev = len(devices)
    interpret = platform != "tpu"
    n = int(os.environ.get("DDL_BENCH_SHUFFLE_INSTANCES", min(4, n_dev)))
    if n < 2 or n_dev < n:
        raise RuntimeError(
            f"shuffle A/B needs 2 <= instances <= devices, "
            f"got {n} instances / {n_dev} devices"
        )
    rows = int(os.environ.get(
        "DDL_BENCH_SHUFFLE_ROWS", "512" if interpret else "8192"
    ))
    cols = N_VALUES
    rounds = int(os.environ.get("DDL_BENCH_SHUFFLE_ROUNDS", "4"))
    reps = int(os.environ.get("DDL_BENCH_SHUFFLE_REPS", "3"))
    impl = os.environ.get("DDL_BENCH_SHUFFLE_IMPL", "ring")
    wire = os.environ.get("DDL_BENCH_SHUFFLE_WIRE") or None
    num_exchange = rows  # the whole pool travels: the worst-case round
    half = num_exchange // 2
    seed = 17

    def pools():
        rng = np.random.default_rng(3)
        return [
            rng.random((rows, cols)).astype(np.float32) for _ in range(n)
        ]

    def run_rounds(make_shuffler, arys):
        """All n instances exchanging concurrently (the real shape: the
        k-th producer of every instance), clocked end to end."""
        shufs = [make_shuffler(i) for i in range(n)]
        errs = []

        def worker(i):
            try:
                for _ in range(rounds):
                    shufs[i].global_shuffle(arys[i])
            except Exception as e:  # noqa: BLE001 - joined + re-raised below
                errs.append(e)

        ts = [
            threading.Thread(target=worker, args=(i,)) for i in range(n)
        ]
        t0 = time.perf_counter()
        for t in ts:
            t.start()
        for t in ts:
            t.join(600)
        dt = time.perf_counter() - t0
        if errs or any(t.is_alive() for t in ts):
            raise RuntimeError(f"exchange workers failed: {errs}")
        return dt, shufs

    def host_shuffler(rdv):
        return lambda i: ThreadExchangeShuffler(
            Topology(n_instances=n, instance_idx=i, n_producers=1),
            1, num_exchange, rendezvous=rdv, seed=seed,
        )

    fabric = DeviceExchangeFabric(impl=impl)
    metrics_by_i = {}

    def device_shuffler(rdv):
        def make(i):
            sh = DeviceExchangeShuffler(
                Topology(n_instances=n, instance_idx=i, n_producers=1),
                1, num_exchange, rendezvous=rdv,
                fabric=fabric, seed=seed,
            )
            sh.metrics = metrics_by_i.setdefault(i, Metrics())
            return sh

        return make

    # Warmup (ring-program compiles) + THE byte-identity assertion.
    host_pools, dev_pools = pools(), pools()
    run_rounds(host_shuffler(Rendezvous()), host_pools)
    run_rounds(device_shuffler(Rendezvous()), dev_pools)
    byte_identical = all(
        np.array_equal(host_pools[i], dev_pools[i]) for i in range(n)
    )
    if not byte_identical:
        raise RuntimeError(
            "device exchange diverged from the host path — identical "
            "seeds must produce identical post-exchange pools"
        )

    # Interleaved best-of timing: each rep clocks both sides once on
    # fresh pools, so neither side owns the quiet minutes (the PR 6
    # vs_baseline discipline).
    host_s, dev_s = [], []
    for _ in range(reps):
        host_s.append(run_rounds(host_shuffler(Rendezvous()), pools())[0])
        dev_s.append(run_rounds(device_shuffler(Rendezvous()), pools())[0])

    # A latched fallback mid-bench means the "device" timings silently
    # measured the host path — that is not a result (the ici A/B's
    # dist.faulted precedent).
    fallbacks = sum(
        m.counter("shuffle.device_fallbacks") for m in metrics_by_i.values()
    )
    if fallbacks:
        raise RuntimeError(
            "device shuffler latched the host fallback during the A/B "
            f"(shuffle.device_fallbacks={fallbacks})"
        )

    # Exchanged payload per timed run: both lanes, every instance,
    # every round.
    per_round = exchange_wire_bytes(n, half, cols, np.dtype(np.float32))
    nbytes = per_round * rounds
    host_rate = nbytes / min(host_s)
    dev_rate = nbytes / min(dev_s)
    winner = "device" if dev_rate >= host_rate else "host"
    plan = plan_exchange(
        n, num_exchange, cols, np.dtype(np.float32),
        wire_dtype=wire, n_devices=n_dev,
    )
    return {
        "n_instances": n,
        "n_devices": n_dev,
        "impl": impl,
        "interpret": interpret,
        "pool_rows": rows,
        "exchange_rows": num_exchange,
        "rounds": rounds,
        "exchanged_mib_per_run": round(nbytes / 2**20, 2),
        # The host-vs-device competition: the block's headline bytes/s
        # is the WINNER's (never a config this run measured slower).
        "bytes_per_s": round(max(host_rate, dev_rate), 1),
        "winner": winner,
        "device_bytes_per_s": round(dev_rate, 1),
        "host_bytes_per_s": round(host_rate, 1),
        "vs_host": round(dev_rate / host_rate, 3),
        "byte_identical": byte_identical,
        # Per-leg wire-byte accounting (plan_exchange): what the device
        # path puts on ICI vs what the host path's boards carry raw and
        # wire-encoded (the PR-13 pricing composition).
        "plannable": plan["plannable"],
        "wire_dtype": plan["wire_dtype"],
        "legs": plan["legs"],
        "ici_bytes_per_round": plan["ici_bytes"],
        "host_bytes_raw_per_round": plan["host_bytes_raw"],
        "host_bytes_wire_per_round": plan["host_bytes_wire"],
        "device_rounds": int(sum(
            m.counter("shuffle.device_rounds")
            for m in metrics_by_i.values()
        )),
        "fallbacks": int(fallbacks),
    }


# -- distributed-optimizer A/B ------------------------------------------------


def _opt_mesh_axes(n_dev: int) -> dict:
    """The opt A/B mesh shape for ``n_dev`` devices: dp × fsdp=2 when a
    2-way fsdp axis fits (so zero1 is exercised COMPOSED with fsdp, the
    acceptance shape), else all-dp.  Shared with tools/probe_opt.py so
    the probe's printed numbers describe the same layout the A/B
    artifact gates on."""
    fsdp = 2 if n_dev >= 4 and n_dev % 2 == 0 else 1
    return {"dp": n_dev // fsdp, "fsdp": fsdp}


def _opt_config():
    """The opt A/B model geometry: big enough that the optimizer update
    and its collectives are a visible step fraction, small enough for
    the CPU virtual mesh.  DDL_BENCH_OPT_* knobs shrink/grow it.
    Shared with tools/probe_opt.py (same desync rationale as
    :func:`_opt_mesh_axes`)."""
    from ddl_tpu.models.llama import LlamaConfig

    d = int(os.environ.get("DDL_BENCH_OPT_DMODEL", "256"))
    layers = int(os.environ.get("DDL_BENCH_OPT_LAYERS", "4"))
    return (
        LlamaConfig(
            vocab=2048, d_model=d, n_layers=layers, n_heads=8,
            n_kv_heads=4, d_ff=4 * d, max_seq=256,
        ),
        int(os.environ.get("DDL_BENCH_OPT_BATCH", "8")),
        int(os.environ.get("DDL_BENCH_OPT_SEQ", "256")),
        int(os.environ.get("DDL_BENCH_OPT_STEPS", "8")),
    )


def _run_opt_ab(platform: str) -> dict:
    """The distributed-optimizer A/B (ROADMAP item 2 / ISSUE 8): one
    llama multistep trained three ways on a dp×fsdp mesh — replicated
    optimizer state, ZeRO-1 (``parallel.optimizer.ShardedOptimizer``),
    and ZeRO-1 + int8 grad comm — INTERLEAVED best-of timing, with the
    loss-curve-parity gate asserted in the artifact.

    Contract (bench_smoke enforces): ``tokens_per_sec`` is the WINNER of
    the zero1-vs-replicated pair (never-headline-slower invariant);
    ``loss_parity`` must be true (fp32 zero1 is bit-exact vs replicated
    — any drift is a correctness bug, not noise); ``int8_parity`` holds
    the quantized path inside ``parity_rel_tol``;
    ``state_bytes_per_replica`` must shrink vs ``state_bytes_replicated``
    (~dp×); ``grad_comm_bytes_quantized`` < ``grad_comm_bytes_raw``.
    """
    import jax
    import optax
    from jax.sharding import PartitionSpec as P

    from ddl_tpu.models import llama
    from ddl_tpu.observability import Metrics
    from ddl_tpu.parallel.mesh import make_mesh
    from ddl_tpu.parallel.optimizer import (
        PARITY_REL_TOL,
        ShardedOptimizer,
        loss_parity,
        state_bytes_per_replica,
        _tree_bytes,
    )
    from ddl_tpu.parallel.train import make_multistep

    devices = jax.devices()
    n_dev = len(devices)
    axes = _opt_mesh_axes(n_dev)
    dp, fsdp = axes["dp"], axes["fsdp"]
    if dp < 2:
        raise RuntimeError(
            f"opt A/B needs a dp axis >= 2, found {n_dev} device(s)"
        )
    mesh = make_mesh(axes, devices=devices)
    cfg, batch, seq, steps = _opt_config()
    specs = llama.param_specs(cfg)
    loss_fn = lambda p, b: llama.next_token_loss(p, b[0], cfg)  # noqa: E731
    rng = np.random.default_rng(0)
    tokens = (rng.integers(0, cfg.vocab, (batch, seq)).astype(np.int32),)
    params = llama.init_params(cfg, jax.random.key(0))
    reps = int(os.environ.get("DDL_BENCH_OPT_REPS", "3"))

    m = Metrics()
    base = optax.adamw(3e-4)
    zopt = ShardedOptimizer(base, mesh, specs)
    qopt = ShardedOptimizer(base, mesh, specs, grad_comm="int8")

    from ddl_tpu.observability import metrics as default_metrics

    variants = {}
    for name, opt in (
        ("replicated", base), ("zero1", zopt), ("int8", qopt),
    ):
        init_fn, multi_fn = make_multistep(
            loss_fn, opt, mesh, specs, batch_spec=P(("dp",)),
            n_steps=steps,
        )
        state = init_fn(params)
        state_bytes = state_bytes_per_replica(state.opt_state)
        # First call = compile + THE parity curve (same init, same
        # batch, so the three curves are directly comparable).
        state, losses = multi_fn(state, tokens)
        variants[name] = {
            "multi": multi_fn,
            "state": state,
            "losses": [float(x) for x in losses],
            "state_bytes": state_bytes,
        }
        if name == "zero1":
            raw_bytes = default_metrics().gauge("opt.grad_comm_bytes_raw")
        if name == "int8":
            quant_bytes = default_metrics().gauge(
                "opt.grad_comm_bytes_quantized"
            )

    # Interleaved timing: each rep times every variant once, so no
    # variant owns the quiet minutes (the PR 6 vs_baseline discipline).
    # The host read-back of the last loss closes each timed window
    # (async dispatch cannot fake it — the _run_train discipline).
    for _ in range(reps):
        for v in variants.values():
            t0 = time.perf_counter()
            v["state"], losses = v["multi"](v["state"], tokens)
            float(losses[-1])
            dt = (time.perf_counter() - t0) / steps
            v["dt"] = min(v.get("dt", float("inf")), dt)

    tps = {
        name: batch * seq / v["dt"] for name, v in variants.items()
    }
    parity_fp32 = loss_parity(
        variants["replicated"]["losses"], variants["zero1"]["losses"]
    )
    parity_int8 = loss_parity(
        variants["replicated"]["losses"], variants["int8"]["losses"]
    )
    legs = zopt.measure_legs(variants["zero1"]["state"].params, metrics=m)
    if not np.isfinite(variants["zero1"]["losses"][-1]):
        raise RuntimeError(
            f"non-finite zero1 loss {variants['zero1']['losses'][-1]}"
        )
    pair = {"zero1": tps["zero1"], "replicated": tps["replicated"]}
    winner = max(pair, key=pair.get)
    n_params = sum(
        int(np.prod(np.shape(x))) for x in jax.tree.leaves(params)
    )
    return {
        "n_devices": n_dev,
        "dp": dp,
        "fsdp": fsdp,
        "steps": steps,
        "params_millions": round(n_params / 1e6, 2),
        # The zero1-vs-replicated competition: the block's headline is
        # the WINNER's (never a config this run measured slower).
        "tokens_per_sec": round(max(pair.values()), 1),
        "winner": winner,
        "zero1_tokens_per_sec": round(tps["zero1"], 1),
        "replicated_tokens_per_sec": round(tps["replicated"], 1),
        "int8_tokens_per_sec": round(tps["int8"], 1),
        "vs_replicated": round(tps["zero1"] / tps["replicated"], 3),
        # THE parity gate: fp32 zero1 must be BIT-EXACT vs replicated
        # (elementwise update on shards — drift means a correctness
        # bug); int8 must stay inside the gate's tolerance.
        "loss_parity": parity_fp32["parity"],
        "loss_drift": parity_fp32["max_rel_drift"],
        "int8_parity": parity_int8["parity"],
        "int8_loss_drift": round(parity_int8["max_rel_drift"], 5),
        "parity_rel_tol": PARITY_REL_TOL,
        "first_loss": round(variants["zero1"]["losses"][0], 4),
        "final_loss": round(variants["zero1"]["losses"][-1], 4),
        # Measured state HBM per dp replica (from the PLACED state's
        # shardings — shrinks ~dp× under zero1) and the per-step grad
        # communication payload raw vs quantized.
        "state_bytes_replicated": variants["replicated"]["state_bytes"],
        "state_bytes_per_replica": variants["zero1"]["state_bytes"],
        "state_shrink": round(
            variants["replicated"]["state_bytes"]
            / max(variants["zero1"]["state_bytes"], 1),
            2,
        ),
        "state_bytes_total": _tree_bytes(
            variants["zero1"]["state"].opt_state
        ),
        "grad_comm_bytes_raw": int(raw_bytes),
        "grad_comm_bytes_quantized": int(quant_bytes),
        "gather_s": round(legs["gather_s"], 5),
        "scatter_s": round(legs["scatter_s"], 5),
    }


# -- driver -------------------------------------------------------------------


def main() -> None:
    t_start = time.perf_counter()
    mode = os.environ.get("DDL_BENCH_MODE", "all")
    errors: dict = {}

    platform = pin_platform()

    result: dict = {
        "metric": "ingest_samples_per_sec",
        "value": None,
        "unit": "samples/s",
        "vs_baseline": None,
        "platform": platform,
        "git_head": _git_head(),
        # Measurement wall-clock: the newest-artifact ranking key that
        # survives a fresh clone (file mtimes do not — _artifact_timestamp).
        "recorded": time.strftime("%Y-%m-%dT%H:%M:%S"),
    }
    if platform != "tpu":
        # Trustworthy-headline contract: a fallback run must carry the
        # newest committed chip measurement alongside its own numbers,
        # so three rounds of CPU fallbacks can never quietly become the
        # "official" trajectory (ROADMAP item 5).
        result["last_tpu_artifact"] = _last_tpu_artifact()

    if mode == "cache":
        # `make cache-bench`: ONLY the shard-cache cold/warm A/B, with
        # its speedup ratio as the headline (docs/CACHING.md).
        result["metric"] = "cache_warm_vs_cold"
        result["unit"] = "x"
        try:
            result["cache"] = _run_cache_ab()
            result["value"] = result["cache"]["warm_vs_cold"]
        except Exception as e:  # noqa: BLE001 - must emit JSON regardless
            errors["cache"] = f"{type(e).__name__}: {e}"
            result["errors"] = errors
        result["elapsed_s"] = round(time.perf_counter() - t_start, 1)
        print(json.dumps(result))
        return

    if mode == "wire":
        # `make wire-bench`: raw vs quantized vs compressed exchange
        # wire over a simulated constrained link (ISSUE 13), with the
        # measured winner as the headline under the same never-slower
        # invariant as every other competition; lossless byte identity
        # + lossy loss-parity baked into the block (bench_smoke
        # enforces).
        result["metric"] = "wire_samples_per_sec"
        result["unit"] = "samples/s"
        try:
            result["wire"] = _run_wire_ab()
            result["value"] = result["wire"]["samples_per_sec"]
            result["headline_config"] = result["wire"]["winner"]
        except Exception as e:  # noqa: BLE001 - must emit JSON regardless
            errors["wire"] = f"{type(e).__name__}: {e}"
            result["errors"] = errors
        result["elapsed_s"] = round(time.perf_counter() - t_start, 1)
        print(json.dumps(result))
        return

    if mode == "autotune":
        # `make tune-bench`: self-tuned vs shipped-defaults from a
        # deliberately mis-matched cold start (ISSUE 20) — boot
        # calibration (measured link probe + wire break-even) plus the
        # closed-loop knob controller, both paying for themselves
        # inside the tuned leg's timed window.  Headline is the
        # speedup ratio; bench_smoke gates never-slower (one noise
        # retry), zero never-worse reverts in the winning leg, and
        # measured cost_source provenance on the decisions.
        result["metric"] = "autotune_vs_defaults"
        result["unit"] = "x"
        try:
            result["autotune"] = _run_autotune()
            result["value"] = result["autotune"]["vs_defaults"]
            result["headline_config"] = "self-tuned"
        except Exception as e:  # noqa: BLE001 - must emit JSON regardless
            errors["autotune"] = f"{type(e).__name__}: {e}"
            result["errors"] = errors
        result["elapsed_s"] = round(time.perf_counter() - t_start, 1)
        print(json.dumps(result))
        return

    if mode == "obs":
        # `make obs-bench`: the tracing layer priced end to end
        # (ISSUE 15) — armed-vs-disarmed span/recorder overhead
        # (interleaved A/B; the headline is the DISARMED production
        # rate, with the armed rate gated <= 2% under it by
        # bench_smoke), byte identity across arming, histogram keys in
        # the armed north-star report, and the seeded-corruption leg's
        # flight-recorder artifact.
        result["metric"] = "obs_samples_per_sec"
        result["unit"] = "samples/s"
        try:
            result["obs"] = _run_obs_ab()
            result["value"] = result["obs"]["disarmed_samples_per_sec"]
            result["headline_config"] = "disarmed"
        except Exception as e:  # noqa: BLE001 - must emit JSON regardless
            errors["obs"] = f"{type(e).__name__}: {e}"
            result["errors"] = errors
        result["elapsed_s"] = round(time.perf_counter() - t_start, 1)
        print(json.dumps(result))
        return

    if mode == "preempt":
        # `make preempt-bench`: preemption tolerance priced end to end
        # (ISSUE 14) — async-vs-sync per-checkpoint stall (interleaved
        # A/B; the headline is the stall reduction), notice→resumed
        # recovery wall time through the real chaos site + guard, and
        # the hard-kill lost-work bound (steps lost <= checkpoint
        # interval), with the resumed streams byte-identical and loss
        # curves bit-exact (bench_smoke enforces the block).
        result["metric"] = "ckpt_stall_reduction"
        result["unit"] = "x"
        try:
            result["preempt"] = _run_preempt_ab()
            result["value"] = result["preempt"]["stall_reduction"]
            result["headline_config"] = "async"
        except Exception as e:  # noqa: BLE001 - must emit JSON regardless
            errors["preempt"] = f"{type(e).__name__}: {e}"
            result["errors"] = errors
        result["elapsed_s"] = round(time.perf_counter() - t_start, 1)
        print(json.dumps(result))
        return

    if mode == "failover":
        # `make failover-bench`: control-plane survivability priced end
        # to end (ISSUE 18) — mid-stream supervisor kill with the
        # lease-expiry standby takeover wall time as the headline, the
        # window stream byte-identical to the steady-state reference
        # with zero watchdog failures, adoption sends absorbed under
        # envelope drop/dup chaos (dedup counters in the block), and
        # scheduler fairness carried bit-exact across the handover
        # (bench_smoke enforces every deterministic field).
        result["metric"] = "failover_takeover_s"
        result["unit"] = "s"
        try:
            result["failover"] = _run_failover_ab()
            result["value"] = result["failover"]["takeover_s"]
        except Exception as e:  # noqa: BLE001 - must emit JSON regardless
            errors["failover"] = f"{type(e).__name__}: {e}"
            result["errors"] = errors
        result["elapsed_s"] = round(time.perf_counter() - t_start, 1)
        print(json.dumps(result))
        return

    if mode == "fabric":
        # `make fabric-bench`: the multi-job ingest fabric soaked end
        # to end (ISSUE 19) — 50 Zipf-weighted jobs probing one
        # supervisor-resident admission authority from 100 simulated
        # host bindings over the acked control plane, with the max
        # per-job weighted-share deviation as the headline (lower is
        # fairer), the scale-reaction / preemption-drain SLOs, per-job
        # accounting on the ONE shared cache, and the supervisor-kill
        # leg's bit-identical admission order (bench_smoke enforces
        # every deterministic field).
        result["metric"] = "fabric_share_deviation"
        result["unit"] = "frac"
        try:
            result["fabric"] = _run_fabric_soak()
            result["value"] = result["fabric"]["share_deviation_max"]
        except Exception as e:  # noqa: BLE001 - must emit JSON regardless
            errors["fabric"] = f"{type(e).__name__}: {e}"
            result["errors"] = errors
        result["elapsed_s"] = round(time.perf_counter() - t_start, 1)
        print(json.dumps(result))
        return

    if mode == "ici":
        # `make ici-bench` / chip_checklist step: the device-side
        # distribution A/B (Pallas fan-out + redistribution vs the XLA
        # scatter), with the winner as the headline — the ici-vs-xla
        # competition rides the same never-headline-slower invariant as
        # the ingest configs (bench_smoke enforces).  Off-TPU the leg
        # runs interpret-mode on the 8-device virtual mesh and the
        # last_tpu_artifact trail (stamped above) marks it a fallback.
        result["metric"] = "ici_bytes_per_sec"
        result["unit"] = "bytes/s"
        try:
            if platform != "tpu":
                _ensure_virtual_mesh(8)
            result["ici"] = _run_ici_ab(platform)
            result["value"] = result["ici"]["bytes_per_s"]
            result["headline_config"] = result["ici"]["winner"]
        except Exception as e:  # noqa: BLE001 - must emit JSON regardless
            errors["ici"] = f"{type(e).__name__}: {e}"
            result["errors"] = errors
        result["elapsed_s"] = round(time.perf_counter() - t_start, 1)
        print(json.dumps(result))
        return

    if mode == "shuffle":
        # `make shuffle-bench` / chip_checklist step 11: the global-
        # shuffle exchange A/B (host rendezvous vs the device-tier
        # collective, ISSUE 17) with the winner as the headline — the
        # same never-headline-slower invariant as the ici/opt
        # competitions, byte-identity asserted per rep, per-leg
        # wire-byte accounting in the block (bench_smoke enforces).
        # Off-TPU the ring runs interpret-mode on the 8-device virtual
        # mesh (it usually LOSES there — the contract stays green) and
        # the last_tpu_artifact trail marks the fallback.
        result["metric"] = "shuffle_bytes_per_sec"
        result["unit"] = "bytes/s"
        try:
            if platform != "tpu":
                _ensure_virtual_mesh(8)
            result["shuffle"] = _run_shuffle_ab(platform)
            result["value"] = result["shuffle"]["bytes_per_s"]
            result["headline_config"] = result["shuffle"]["winner"]
        except Exception as e:  # noqa: BLE001 - must emit JSON regardless
            errors["shuffle"] = f"{type(e).__name__}: {e}"
            result["errors"] = errors
        result["elapsed_s"] = round(time.perf_counter() - t_start, 1)
        print(json.dumps(result))
        return

    if mode == "placement":
        # `make placement-bench`: topology-aware vs naive producer→
        # consumer placement over the simulated fabric (ISSUE 10), with
        # the measured winner as the headline under the same never-
        # headline-slower invariant as every other competition, plus
        # the membership chaos counters (bench_smoke enforces).
        result["metric"] = "placement_bytes_per_sec"
        result["unit"] = "bytes/s"
        try:
            result["placement"] = _run_placement_ab()
            result["value"] = result["placement"]["bytes_per_s"]
            result["headline_config"] = result["placement"]["winner"]
        except Exception as e:  # noqa: BLE001 - must emit JSON regardless
            errors["placement"] = f"{type(e).__name__}: {e}"
            result["errors"] = errors
        result["elapsed_s"] = round(time.perf_counter() - t_start, 1)
        print(json.dumps(result))
        return

    if mode == "tenancy":
        # `make tenancy-bench`: the multi-tenant ingest-service A/B
        # (ISSUE 11) — K concurrent tenants on a heavy-tailed demand
        # schedule, autoscaled pool vs the static floor, with the
        # measured winner as the headline under the same never-slower
        # invariant as every other competition, plus per-tenant p99
        # latency/byte-identity and the burst+host-loss chaos leg
        # (bench_smoke enforces the block).
        result["metric"] = "tenancy_samples_per_sec"
        result["unit"] = "samples/s"
        try:
            result["tenancy"] = _run_tenancy_ab()
            result["value"] = result["tenancy"]["samples_per_sec"]
            result["headline_config"] = result["tenancy"]["winner"]
        except Exception as e:  # noqa: BLE001 - must emit JSON regardless
            errors["tenancy"] = f"{type(e).__name__}: {e}"
            result["errors"] = errors
        result["elapsed_s"] = round(time.perf_counter() - t_start, 1)
        print(json.dumps(result))
        return

    if mode == "opt":
        # `make opt-bench` / chip_checklist step: the distributed-
        # optimizer A/B (zero1 vs replicated state, fp32 vs int8 grad
        # comm) with loss parity asserted in the artifact and the
        # winner as the headline — the same never-headline-slower
        # invariant as the ingest/ici competitions (bench_smoke
        # enforces).  Off-TPU it runs on the 8-device virtual mesh and
        # the last_tpu_artifact trail (stamped above) marks a fallback.
        result["metric"] = "opt_tokens_per_sec"
        result["unit"] = "tokens/s"
        try:
            if platform != "tpu":
                _ensure_virtual_mesh(8)
            result["opt"] = _run_opt_ab(platform)
            result["value"] = result["opt"]["tokens_per_sec"]
            result["headline_config"] = result["opt"]["winner"]
        except Exception as e:  # noqa: BLE001 - must emit JSON regardless
            errors["opt"] = f"{type(e).__name__}: {e}"
            result["errors"] = errors
        result["elapsed_s"] = round(time.perf_counter() - t_start, 1)
        print(json.dumps(result))
        return

    if mode in ("ingest", "all", "stream"):
        # "stream" (chip_checklist step 5's window-size sweep): ONLY the
        # two window-stream configs + the link measure — the batch-path
        # configs don't depend on DDL_BENCH_STREAM_MIB.
        try:
            # One link-capability measurement shared by every ingest config
            # (the denominator for BASELINE.md's utilization target).
            from ddl_tpu.ingest import measure_h2d_bandwidth

            link_bw = measure_h2d_bandwidth()
        except Exception as e:  # noqa: BLE001
            link_bw = 0.0
            errors["h2d_bandwidth"] = f"{type(e).__name__}: {e}"
        def _ingest_best(**kw):
            # Every ingest config uses the same min-under-noise estimator
            # (see best_of) so ablation deltas are not biased by a
            # transient hitting only one side; per-run utilization gates
            # discard artifact runs before selection.
            def run():
                rate, ns = _run_ingest(**kw)
                if kw.get("link_bytes_per_sec"):
                    _gate_utilization(ns, "ingest")
                return rate, ns

            return best_valid(2, run, key=lambda r: -r[0])

        # One kwargs table for every headline contender, shared by the
        # competition below AND the interleaved vs_baseline re-runs — so
        # the ratio's two sides are guaranteed to measure the exact
        # config the headline named.
        headline_kw = {
            # The two staged legs FORCE the engine (staged=True): with the
            # env default, batch_staged routes CPU drains inline, so on
            # the fallback box "prefetch" would silently measure the
            # identical code path as "prefetch_inline" and the
            # staged_vs_inline ablation would compare inline to inline.
            # Forcing keeps each ablation axis one-variable: prefetch vs
            # no_prefetch isolates the lookahead, prefetch vs
            # prefetch_inline isolates the staging engine.  (On
            # accelerators None already stages — forcing changes nothing.)
            "prefetch": dict(
                nslots=2, n_producers=N_PRODUCERS, sync_every_batch=False,
                use_prefetch=True, staged=True, link_bytes_per_sec=link_bw,
            ),
            "no_prefetch": dict(
                nslots=2, n_producers=N_PRODUCERS, sync_every_batch=False,
                use_prefetch=False, staged=True, link_bytes_per_sec=link_bw,
            ),
            "prefetch_inline": dict(
                nslots=2, n_producers=N_PRODUCERS, sync_every_batch=False,
                use_prefetch=True, staged=False, link_bytes_per_sec=link_bw,
            ),
            "process": dict(
                nslots=2, n_producers=N_PRODUCERS, sync_every_batch=False,
                mode="process", use_prefetch=True,
                link_bytes_per_sec=link_bw,
            ),
        }

        if mode != "stream":
            # The headline COMPETES across every batch-path drain the
            # run measures — prefetch/no-prefetch (THREAD, staged),
            # the inline-staging escape hatch, and PROCESS mode — a run
            # must never headline a config it itself measured as slower
            # (VERDICT r5 weak #1; trustworthy-headline refactor).  The
            # winner is recorded as ``headline_config`` and bench_smoke
            # enforces the never-slower invariant against every sibling
            # block in the same JSON line.
            headline_runs: dict = {}
            try:
                headline_runs["prefetch"] = _ingest_best(
                    **headline_kw["prefetch"]
                )
            except Exception as e:  # noqa: BLE001 - must emit JSON regardless
                errors["ingest"] = f"{type(e).__name__}: {e}"
            try:
                # Same pipeline without the prefetch lookahead: the delta
                # IS the prefetch win/loss (VERDICT r2 item 5 asked for
                # before/after).
                headline_runs["no_prefetch"] = _ingest_best(
                    **headline_kw["no_prefetch"]
                )
                no_pf, ns_no_pf = headline_runs["no_prefetch"]
                result["ingest_no_prefetch"] = {
                    "samples_per_sec": round(no_pf, 1),
                    "stall_fraction": round(ns_no_pf["stall_fraction"], 4),
                }
            except Exception as e:  # noqa: BLE001
                errors["ingest_no_prefetch"] = f"{type(e).__name__}: {e}"
            try:
                # The prefetch config over the inline path (DDL_TPU_STAGED=0
                # equivalent): the staged-vs-inline ablation — the delta
                # is the engine's win (pooled buffers + off-thread
                # copy/dispatch + early slot release) — and a headline
                # contender in its own right.
                headline_runs["prefetch_inline"] = _ingest_best(
                    **headline_kw["prefetch_inline"]
                )
                inline, ns_inline = headline_runs["prefetch_inline"]
                result["ingest_inline"] = {
                    "samples_per_sec": round(inline, 1),
                    "stall_fraction": round(ns_inline["stall_fraction"], 4),
                }
                if "prefetch" in headline_runs:
                    result["staged_vs_inline"] = round(
                        headline_runs["prefetch"][0] / inline, 3
                    )
            except Exception as e:  # noqa: BLE001
                errors["ingest_inline"] = f"{type(e).__name__}: {e}"
            try:
                # PROCESS mode: spawned producer processes over the native
                # C++ shm ring — the native transport's throughput number,
                # and the production shape on a multi-core TPU host.
                headline_runs["process"] = _ingest_best(
                    **headline_kw["process"]
                )
                proc, ns_proc = headline_runs["process"]
                result["ingest_process_mode"] = {
                    "samples_per_sec": round(proc, 1),
                    "stall_fraction": round(ns_proc["stall_fraction"], 4),
                    "ingest_bytes_per_sec": round(
                        ns_proc["ingest_bytes_per_sec"], 1
                    ),
                }
            except Exception as e:  # noqa: BLE001
                errors["ingest_process_mode"] = f"{type(e).__name__}: {e}"
            if headline_runs:
                label = max(headline_runs, key=lambda k: headline_runs[k][0])
                best_rate, north_star = headline_runs[label]
                result["value"] = round(best_rate, 1)
                result["headline_config"] = label
                result.update(
                    samples_per_sec=round(north_star["samples_per_sec"], 1),
                    stall_fraction=round(north_star["stall_fraction"], 4),
                    ingest_bytes_per_sec=round(
                        north_star["ingest_bytes_per_sec"], 1
                    ),
                    link_bytes_per_sec=round(
                        north_star.get("link_bytes_per_sec", 0.0), 1
                    ),
                    bandwidth_utilization=round(
                        north_star.get("bandwidth_utilization", 0.0), 4
                    ),
                )
                # Staged-engine observability for the headline run: where
                # the engine spent time and whether the pool recycled
                # (ddl_tpu.staging; zeros when DDL_TPU_STAGED=0).
                result["staging"] = {
                    "stage_copy_s": round(north_star["stage_copy_s"], 4),
                    "transfer_s": round(north_star["transfer_s"], 4),
                    "stall_s": round(north_star["stall_s"], 4),
                    "alias_windows": north_star["alias_windows"],
                    "alias_fallbacks": north_star["alias_fallbacks"],
                    "pool_hits": north_star["pool_hits"],
                    "pool_misses": north_star["pool_misses"],
                    "queue_depth_max": north_star["queue_depth_max"],
                }
                # Robustness observability (docs/ROBUSTNESS.md): all
                # zeros on a healthy run — a nonzero here in a BENCH_*
                # trajectory means the run only "passed" by recovering
                # (replays, respawns, degraded shuffle) and deserves a
                # look even when throughput held.
                result["robustness"] = {
                    "respawns": north_star["respawns"],
                    "watchdog_failures": north_star["watchdog_failures"],
                    "corrupt_windows": north_star["corrupt_windows"],
                    "replays": north_star["replays"],
                    "shuffle_degraded": north_star["shuffle_degraded"],
                    "staging_retries": north_star["staging_retries"],
                    "inline_fallbacks": north_star["inline_fallbacks"],
                }
            try:
                # Shard-cache cold/warm A/B over a throttled backend
                # (ddl_tpu/cache, docs/CACHING.md): the warm tier's win
                # on a slow source, with byte-identity asserted.
                result["cache"] = _run_cache_ab()
            except Exception as e:  # noqa: BLE001
                errors["cache"] = f"{type(e).__name__}: {e}"
        def _stream_result(stream_mode: str) -> dict:
            """One gated best-of stream measurement for ``stream_mode``
            (shared by the thread and process configs so the utilization
            gate cannot be dropped from one of them)."""

            def run():
                rate, ns = _run_ingest_stream(link_bw, mode=stream_mode)
                if link_bw:
                    _gate_utilization(ns, f"stream-{stream_mode}")
                return rate, ns

            rate, ns = best_valid(2, run, key=lambda r: -r[0])
            return {
                "samples_per_sec": round(rate, 1),
                "window_mib": round(N_DATA_STREAM * N_VALUES * 4 / 2**20, 1),
                "bytes_per_sec": round(ns["ingest_bytes_per_sec"], 1),
                "stall_fraction": round(ns["stall_fraction"], 4),
                "bandwidth_utilization": round(
                    ns.get("bandwidth_utilization", 0.0), 4
                ),
                # Captured at leg end: load_avg then reflects THIS leg's
                # contention, so a starved process leg is diagnosable
                # from the committed JSON alone.
                "core_attach": _core_attach(),
            }

        def _headline_util(key: str, label: str) -> None:
            """Let every stream config compete for the headline
            utilization figure, labelled with the winning config."""
            util = result.get(key, {}).get("bandwidth_utilization", 0.0)
            if util > (result.get("bandwidth_utilization") or 0.0):
                result["bandwidth_utilization"] = util
                result["bandwidth_utilization_config"] = label

        try:
            # Zero-copy window streaming (loader.windows + inplace fill):
            # the bandwidth-utilization headline config.
            result["ingest_stream"] = _stream_result("thread")
            _headline_util("ingest_stream", "stream-thread")
        except Exception as e:  # noqa: BLE001
            errors["ingest_stream"] = f"{type(e).__name__}: {e}"
        try:
            # Stream over PROCESS-mode producers: the production shape on
            # a multi-core TPU host (fills on producer cores, consumer
            # core streams slots to HBM).
            result["ingest_stream_process"] = _stream_result("process")
            _headline_util("ingest_stream_process", "stream-process")
        except Exception as e:  # noqa: BLE001
            errors["ingest_stream_process"] = f"{type(e).__name__}: {e}"
        # The PROCESS-vs-THREAD stream ratio + this run's core attach:
        # the write-once producer refactor's north-star number.  A ratio
        # below 0.9 on a starved attach (fewer cores than producers +
        # consumer) is preemption, not transport overhead — the
        # core_attach record makes the two cases distinguishable in the
        # committed JSON, and bench_smoke gates on exactly that.
        ingest_block: dict = {"core_attach": _core_attach()}
        thread_rate = result.get("ingest_stream", {}).get("samples_per_sec")
        proc_rate = result.get("ingest_stream_process", {}).get(
            "samples_per_sec"
        )
        if thread_rate and proc_rate:
            ingest_block["process_vs_thread"] = round(
                proc_rate / thread_rate, 3
            )
        result["ingest"] = ingest_block
        if mode != "stream":
            try:
                # Reference design point: strict alternation, synchronous
                # transfers (its one-window token protocol).  Measured
                # INTERLEAVED with re-runs of the headline winner: the
                # box noise is one-sided and drifts minute-to-minute
                # (measured: identical configs swing 50k-78k samples/s),
                # so a ratio of two distant-in-time measurements is an
                # artifact generator — r05 shipped vs_baseline 0.865
                # from exactly that, while an interleaved best-of pair
                # on the same box reads >1.  Best-of on BOTH sides (the
                # noise only ever slows a run), alternating samples so
                # neither side owns the quiet minutes.
                winner_kw = headline_kw.get(result.get("headline_config"))
                rates_w = (
                    [result["value"]] if result.get("value") else []
                )
                rates_b = []
                for _ in range(2):
                    b_rate, _ns = _run_ingest(
                        nslots=1, n_producers=N_PRODUCERS,
                        sync_every_batch=True,
                    )
                    rates_b.append(b_rate)
                    if winner_kw is not None:
                        w_rate, w_ns = _run_ingest(**winner_kw)
                        if winner_kw.get("link_bytes_per_sec"):
                            # Same artifact filter the original headline
                            # selection ran under (_ingest_best): a re-run
                            # whose utilization reads implausible is the
                            # timing-artifact class the gate exists to
                            # discard — it must not become the published
                            # headline via max(rates_w) either.
                            try:
                                _gate_utilization(w_ns, "ingest-rerun")
                            except RuntimeError:
                                continue  # sample discarded
                        rates_w.append(w_rate)
                baseline = max(rates_b)
                result["baseline_samples_per_sec"] = round(baseline, 1)
                if rates_w:
                    # The re-runs are further samples of the SAME config
                    # under the same estimator: the headline keeps the
                    # best observation (never publishes a number the run
                    # measured slower for its own config).
                    result["value"] = round(max(rates_w), 1)
                    result["vs_baseline"] = round(
                        max(rates_w) / baseline, 3
                    )
            except Exception as e:  # noqa: BLE001
                errors["ingest_baseline"] = f"{type(e).__name__}: {e}"

    if mode in ("train", "all", "big"):
        train: dict = {}
        impls = ("flash", "dense") if platform == "tpu" else ("dense",)
        if mode == "big":
            impls = ()
        for impl in impls:
            try:
                train[impl] = _run_train(platform, impl)
            except Exception as e:  # noqa: BLE001
                errors[f"train_{impl}"] = f"{type(e).__name__}: {e}"
        if platform == "tpu":
            # HBM-filling credibility config (VERDICT r3 item 7): the MFU
            # number README quotes, at a geometry representative of the
            # 8B-class north-star workload.
            try:
                result["train_big"] = _run_train(
                    platform, "flash", size="big"
                )
            except Exception as e:  # noqa: BLE001
                errors["train_big"] = f"{type(e).__name__}: {e}"
        # BOTH impls are reported verbatim (round 2 published only the
        # "best", which was the broken measurement — VERDICT r2 item 1a).
        for impl, r in train.items():
            result[f"train_{impl}"] = r
        if "flash" in train and "dense" in train:
            # Compare STEP-1 losses: same init, same batch, one step — any
            # material gap means one impl computed a different function.
            # (Final losses drift legitimately: bf16 flash vs fp32-softmax
            # dense amplify over the chained optimizer steps.)
            lf, ld = train["flash"]["first_loss"], train["dense"]["first_loss"]
            if abs(lf - ld) > 0.01 * max(abs(ld), 1e-6):
                errors["train_loss_mismatch"] = (
                    f"flash {lf} vs dense {ld} at step 1 from identical init"
                )
            result["flash_speedup_vs_dense"] = round(
                train["flash"]["tokens_per_sec"]
                / train["dense"]["tokens_per_sec"], 3,
            )
        if train:
            best = max(train.values(), key=lambda r: r["tokens_per_sec"])
            result.update(
                train_tokens_per_sec=best["tokens_per_sec"],
                train_step_time_ms=best["step_time_ms"],
                train_mfu=best["mfu"],
                train_model_tflops_per_sec=best["model_tflops_per_sec"],
                train_attn_impl=best["attn_impl"],
                device_kind=best["device_kind"],
            )
        if mode != "big":
            try:
                impl = "flash" if platform == "tpu" else "dense"
                fit = _run_fit(platform, impl)
                if impl in train:
                    # Cross-config reference (the r1-r5 trajectory
                    # metric): end-to-end vs the train_* multistep —
                    # NOT the gated overhead (fit["pipeline_overhead"]
                    # uses the matched in-function ceiling; this one
                    # bundles in scan-length/input-form amortization).
                    fit["overhead_vs_train"] = round(
                        1.0
                        - fit["tokens_per_sec"]
                        / train[impl]["tokens_per_sec"],
                        4,
                    )
                result["fit_stream"] = fit
            except Exception as e:  # noqa: BLE001
                errors["fit_stream"] = f"{type(e).__name__}: {e}"
        if platform == "tpu" and mode != "big":
            try:
                result["attn_sweep"] = _attn_sweep()
            except Exception as e:  # noqa: BLE001
                errors["attn_sweep"] = f"{type(e).__name__}: {e}"

    if mode in ("decode", "all"):
        # Serving-phase numbers (KV-cache prefill + scanned decode):
        # training MFU says nothing about the inference path, and the
        # decode regime is HBM-bound, graded by MBU instead.
        try:
            result["decode"] = _run_decode(platform)
        except Exception as e:  # noqa: BLE001
            errors["decode"] = f"{type(e).__name__}: {e}"
        if platform == "tpu":
            # Serving the HBM-filling 1.4B config: the representative
            # memory-bound decode point (2.8 GB of bf16 weights/step).
            try:
                result["decode_big"] = _run_decode(platform, size="big")
            except Exception as e:  # noqa: BLE001
                errors["decode_big"] = f"{type(e).__name__}: {e}"

    if errors:
        result["errors"] = errors
    if result["value"] is None:
        # Stream-only mode: a stream config IS the run's headline
        # (either mode may have been gate-rejected; take the survivor).
        for key in ("ingest_stream", "ingest_stream_process"):
            if result.get(key):
                result["metric"] = f"{key}_samples_per_sec"
                result["value"] = result[key]["samples_per_sec"]
                break
    if result["value"] is None and result.get("train_tokens_per_sec"):
        # Ingest failed but training measured: still report a headline.
        result["metric"] = "train_tokens_per_sec"
        result["value"] = result["train_tokens_per_sec"]
        result["unit"] = "tokens/s"
    if result["value"] is None and result.get("train_big"):
        # Big-only mode: the big config IS the run's headline.
        result["metric"] = "train_big_tokens_per_sec"
        result["value"] = result["train_big"]["tokens_per_sec"]
        result["unit"] = "tokens/s"
    if result["value"] is None:
        # Decode-only mode: serving throughput is the headline (either
        # size may have been gate-rejected; take the survivor).
        for key in ("decode", "decode_big"):
            if result.get(key):
                result["metric"] = "decode_tokens_per_sec"
                result["value"] = result[key]["decode_tokens_per_sec"]
                result["unit"] = "tokens/s"
                break
    result["bench_wall_s"] = round(time.perf_counter() - t_start, 1)
    print(json.dumps(result))


if __name__ == "__main__":
    main()
