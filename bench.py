"""Benchmark: loader→HBM ingest throughput on the real chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Measures the north-star metric (BASELINE.md): samples/sec of the full
pipeline — producer workers filling window rings, consumer draining
zero-copy and streaming batches into device HBM while a jitted consumer
computation runs.  ``vs_baseline`` compares against a faithful
re-creation of the *reference's* design point on identical hardware:
single-buffered strict alternation (its one-window-per-producer token
protocol, reference ``ddl/datapusher.py:147-170``) with synchronous
per-batch transfers and no overlap.  The reference itself publishes no
numbers to compare against (BASELINE.md).
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np

N_DATA = 8192  # samples per window
N_VALUES = 256  # f32 features per sample -> 8 MiB windows
BATCH = 2048
EPOCHS_MEASURED = 24
N_PRODUCERS = 2


def _make_producer():
    from ddl_tpu import DataProducerOnInitReturn, ProducerFunctionSkeleton

    class BenchProducer(ProducerFunctionSkeleton):
        def on_init(self, producer_idx=0, **kw):
            self._rng = np.random.default_rng(producer_idx)
            self._data = self._rng.random((N_DATA, N_VALUES), np.float32)
            return DataProducerOnInitReturn(
                nData=N_DATA, nValues=N_VALUES, shape=(N_DATA, N_VALUES),
                splits=(N_VALUES - 1, 1),
            )

        def post_init(self, my_ary, **kw):
            np.copyto(my_ary, self._data)

        def execute_function(self, my_ary, **kw):
            # Representative per-window producer work: local in-place
            # shuffle (what the reference example does per refill,
            # reference tests/run_ddl.py:163-167).
            self._rng.shuffle(my_ary)

    return BenchProducer()


def _consumer_compute():
    """A small jitted reduction standing in for the training step's
    consumption of the batch (keeps the device busy so overlap matters)."""
    import jax

    @jax.jit
    def f(x, y):
        return (x @ x.T).sum() + y.sum()

    return f


def _run(nslots: int, n_producers: int, sync_every_batch: bool) -> float:
    """Returns steady-state samples/sec of one pipeline configuration."""
    import jax

    from ddl_tpu import DistributedDataLoader, Marker, distributed_dataloader
    from ddl_tpu.observability import Metrics

    compute = _consumer_compute()
    metrics = Metrics()
    n_epochs = EPOCHS_MEASURED + 2  # first two epochs are warmup

    @distributed_dataloader(n_producers=n_producers, mode="thread", nslots=nslots)
    def main(env):
        loader = DistributedDataLoader(
            _make_producer(), batch_size=BATCH, connection=env.connection,
            n_epochs=n_epochs, output="jax", metrics=metrics,
        )
        t0 = None
        samples = 0
        out = None
        for epoch in range(n_epochs):
            if epoch == 2:  # warmup done (compile + first fills)
                if out is not None:
                    jax.block_until_ready(out)
                t0 = time.perf_counter()
                samples = 0
            for x, y in loader:
                out = compute(x, y)
                if sync_every_batch:
                    jax.block_until_ready(out)
                if t0 is not None:
                    samples += BATCH
                loader.mark(Marker.END_OF_BATCH)
            loader.mark(Marker.END_OF_EPOCH)
        jax.block_until_ready(out)
        return samples / (time.perf_counter() - t0)

    return main()


def main() -> None:
    # Overlapped ddl_tpu pipeline: double-buffered rings, async ingest.
    ours = _run(nslots=2, n_producers=N_PRODUCERS, sync_every_batch=False)
    # Reference design point: strict alternation, synchronous transfers.
    baseline = _run(nslots=1, n_producers=N_PRODUCERS, sync_every_batch=True)
    print(
        json.dumps(
            {
                "metric": "ingest_samples_per_sec",
                "value": round(ours, 1),
                "unit": "samples/s",
                "vs_baseline": round(ours / baseline, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
