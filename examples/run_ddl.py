"""End-to-end example: synthetic pointwise dataset through the full pipeline.

The TPU-native analog of reference ``tests/run_ddl.py`` — its only
executable spec (SURVEY §4): a synthetic CFD-flavoured pointwise dataset
(``run_ddl.py:80-104``), min-max normalised (``:57-77``), loaded by a
``ProducerFunctionSkeleton`` subclass (``:107-167``) and drained by a
decorated main with the explicit ``mark()`` contract (``:228-238``).

Runs in any mode:

    python examples/run_ddl.py                # THREAD mode (single process)
    python examples/run_ddl.py process        # spawned producer processes
    DDL_TPU_N_PRODUCERS=3 python examples/run_ddl.py process

Exit code 0 after a deadlock-free drain of every epoch is the pass
criterion, mirroring the reference's CI gate (``tests/test_ddl.py:14-22``).
"""

from __future__ import annotations

import dataclasses
import logging
import os
import sys
from typing import Any

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from _common import pin_platform_from_env  # noqa: E402

pin_platform_from_env()

from ddl_tpu import (
    DataProducerOnInitReturn,
    DistributedDataLoader,
    Marker,
    ProducerFunctionSkeleton,
    distributed_dataloader,
)


@dataclasses.dataclass
class Params:
    """Workload knobs (reference ``tests/run_ddl.py:243-316``)."""

    nepoch: int = 4
    batch_size: int = 32
    n_data: int = 1024  # samples per producer window
    n_features: int = 10  # columns: 3 pos + 6 field + 1 weight


def make_pointwise_data(n: int, n_features: int, seed: int) -> np.ndarray:
    """Synthetic CFD-style pointwise samples, min-max normalised per column
    (reference ``tests/run_ddl.py:57-104``)."""
    rng = np.random.default_rng(seed)
    raw = rng.random((n, n_features), dtype=np.float32)
    lo, hi = raw.min(axis=0), raw.max(axis=0)
    return (raw - lo) / np.maximum(hi - lo, 1e-12)


class DataProducer(ProducerFunctionSkeleton):
    """Example producer (reference ``tests/run_ddl.py:107-167``): loads its
    shard lazily in the worker, refreshes by in-place shuffle."""

    def __init__(self, params: Params):
        self.params = params
        self._data: np.ndarray | None = None
        self._rng: np.random.Generator | None = None

    def on_init(self, producer_idx: int = 0, n_producers: int = 1,
                instance_idx: int = 0, n_instances: int = 1,
                **kwargs: Any) -> DataProducerOnInitReturn:
        p = self.params
        seed = instance_idx * 1000 + producer_idx
        self._data = make_pointwise_data(p.n_data, p.n_features, seed)
        self._rng = np.random.default_rng(seed + 1)
        return DataProducerOnInitReturn(
            nData=p.n_data,
            nValues=p.n_features,
            shape=(p.n_data, p.n_features),
            splits=(3, p.n_features - 4, 1),  # (pos, target, weight)
            dtype=np.float32,
        )

    def post_init(self, my_ary: np.ndarray, **kwargs: Any) -> None:
        np.copyto(my_ary, self._data)

    def execute_function(self, my_ary: np.ndarray, **kwargs: Any) -> None:
        assert self._rng is not None
        self._rng.shuffle(my_ary)  # in-place local shuffle per window


@distributed_dataloader
def main(params: Params, ddl_env: Any) -> int:
    """Consumer main (reference ``tests/run_ddl.py:171-238``): drain every
    epoch, verifying batch geometry and data integrity."""
    loader = DistributedDataLoader(
        data_producer_function=DataProducer(params),
        batch_size=params.batch_size,
        connection=ddl_env.connection,
        n_epochs=params.nepoch,
        output="numpy",
    )
    total_batches = 0
    for epoch in range(params.nepoch):
        for i, (pos, target, weight) in enumerate(loader):
            assert pos.shape == (params.batch_size, 3)
            assert target.shape == (params.batch_size, params.n_features - 4)
            assert weight.shape == (params.batch_size, 1)
            assert 0.0 <= float(pos[0, 0]) <= 1.0  # normalised
            total_batches += 1
            loader.mark(Marker.END_OF_BATCH)
        loader.mark(Marker.END_OF_EPOCH)
    expected = params.nepoch * (params.n_data // params.batch_size)
    assert total_batches == expected, (total_batches, expected)
    print(f"drained {total_batches} batches over {params.nepoch} epochs: OK")
    return total_batches


if __name__ == "__main__":
    logging.basicConfig(level=logging.INFO)
    if len(sys.argv) > 1:
        os.environ["DDL_TPU_MODE"] = sys.argv[1]
    main(Params())
