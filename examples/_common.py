"""Shared example-runner plumbing.

``DDL_EXAMPLE_PLATFORM=cpu`` pins the JAX backend for an example run.
The env var alone is not enough: the axon PJRT plugin's sitecustomize
re-exports ``JAX_PLATFORMS`` at interpreter start, so the live config
must be updated before any device touch (same trick as
tests/conftest.py).  The test suite sets the knob so examples never
depend on accelerator/tunnel health.
"""

import os


def pin_platform_from_env() -> None:
    plat = os.environ.get("DDL_EXAMPLE_PLATFORM")
    if plat:
        import jax

        jax.config.update("jax_platforms", plat)
