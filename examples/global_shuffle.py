"""Global shuffle example: cross-instance sample exchange, actually running.

The reference's flagship feature — pairwise exchange between same-index
pushers of different instances (reference ``ddl/shuffle.py:92-108``) —
never executed in its shipped code path (its callback dispatcher
short-circuited, SURVEY Q1).  This example runs the fixed machinery for
real: two instances in one process (each one producer + one consumer,
like two hosts of a pod), a shared rendezvous standing in for the
interconnect, and an exchange of half of every window per refill.

Every served window mixes rows from both instances: the round-0
exchange runs before the first window commit (producer loop order:
exchange → local shuffle → commit), and the local in-place shuffle
spreads received rows through the window so later exchange rounds move
fresh samples rather than ping-ponging the same lanes back.

Run: python examples/global_shuffle.py
"""

from __future__ import annotations

import os
import sys
import threading
from typing import Any

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from _common import pin_platform_from_env  # noqa: E402

pin_platform_from_env()

from ddl_tpu import (  # noqa: E402
    DataProducerOnInitReturn,
    DistributedDataLoader,
    Marker,
    ProducerFunctionSkeleton,
)
from ddl_tpu.datapusher import DataPusher  # noqa: E402
from ddl_tpu.shuffle import ThreadExchangeShuffler, Rendezvous  # noqa: E402
from ddl_tpu.transport.connection import (  # noqa: E402
    ConsumerConnection,
    ProducerConnection,
    ThreadChannel,
)
from ddl_tpu.types import RunMode, Topology  # noqa: E402

N_DATA, N_VALUES = 32, 4
BATCH = 8
N_EPOCHS = 3
EXCHANGE_FRACTION = 0.5  # half of every window swaps each refill


class InstanceTagged(ProducerFunctionSkeleton):
    """Rows tagged <instance*1000 + row> so provenance is visible."""

    def __init__(self, instance_idx: int):
        self.instance_idx = instance_idx

    def on_init(self, producer_idx=0, **kw):
        self._rng = np.random.default_rng(self.instance_idx)
        return DataProducerOnInitReturn(
            nData=N_DATA, nValues=N_VALUES, shape=(N_DATA, N_VALUES),
            splits=(N_VALUES - 1, 1),
        )

    def post_init(self, my_ary, **kw):
        tags = self.instance_idx * 1000 + np.arange(N_DATA)
        my_ary[:] = tags[:, None].astype(np.float32)

    def execute_function(self, my_ary, **kw):
        # Local in-place shuffle per refill, exactly what the reference's
        # example producer did (reference tests/run_ddl.py:163-167).  It
        # permutes rows WITHOUT rewriting them, so rows received from the
        # other instance survive and spread through the window — without
        # it, the fixed n=2 swap permutation would ping-pong the same
        # lane rows straight back each round.
        self._rng.shuffle(my_ary)


def run_instance(
    instance_idx: int, rendezvous: Rendezvous, results: dict
) -> None:
    """One 'host': a producer thread + the consumer drain, THREAD mode."""
    topo = Topology(
        n_instances=2, instance_idx=instance_idx, n_producers=1,
        mode=RunMode.THREAD,
    )
    consumer_end, producer_end = ThreadChannel.pair()
    pconn = ProducerConnection(producer_end, 1, cross_process=False)

    def producer() -> None:
        DataPusher(
            pconn, topo, 1,
            shuffler_factory=ThreadExchangeShuffler.factory(rendezvous),
        ).push_data()

    threading.Thread(target=producer, daemon=True).start()

    loader = DistributedDataLoader(
        InstanceTagged(instance_idx),
        batch_size=BATCH,
        connection=ConsumerConnection([consumer_end]),
        n_epochs=N_EPOCHS,
        output="numpy",
        global_shuffle_fraction_exchange=EXCHANGE_FRACTION,
    )
    per_epoch: list = []
    for _epoch in range(N_EPOCHS):
        seen: set = set()
        for x, _y in loader:
            seen.update(int(t) // 1000 for t in x[:, 0])
            loader.mark(Marker.END_OF_BATCH)
        loader.mark(Marker.END_OF_EPOCH)
        per_epoch.append(seen)
    results[instance_idx] = per_epoch


def main() -> int:
    rendezvous = Rendezvous()
    results: dict[int, Any] = {}
    threads = [
        threading.Thread(
            target=run_instance, args=(i, rendezvous, results), daemon=True
        )
        for i in range(2)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(120)
    ok = len(results) == 2
    for i, epochs in sorted(results.items()):
        print(f"instance {i}: origins per epoch = {[sorted(e) for e in epochs]}")
        # EVERY epoch mixes both instances' rows (see module docstring);
        # the reference never got here (Q1).
        ok = ok and all(e == {0, 1} for e in epochs)
    print("PASS" if ok else "FAIL")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
