"""Pipeline-parallel LM pretraining: token stream → Trainer → staged Llama.

The scale-out shape for models too big for one chip's HBM: the decoder
blocks regroup into ``pp`` pipeline stages (GPipe microbatch schedule
riding ``ppermute`` over ICI), each stage holding only its own layers —
and when the mesh also carries a ``tp`` axis, stages run TENSOR-PARALLEL
RESIDENT (local Megatron weight shards, two psums per layer), cutting
per-device weight working memory to params/(S·tp).  The data pipeline is
unchanged: the same token-stream producers, window rings, and
zero-copy window streaming feed the pipelined step.

Run:

    python examples/train_llama_pp.py            # pp=2 × dp over the rest
    python examples/train_llama_pp.py pp_tp      # pp=2 × tp=2 × dp (8 devices)
    python examples/train_llama_pp.py pp_1f1b    # interleaved 1F1B schedule
                                                 # (2 chunks/device: bubble
                                                 # 0.111 vs gpipe's 0.2 at
                                                 # pp=2, M=4)

Exit 0 with finite, decreasing loss is the pass criterion.
"""

from __future__ import annotations

import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from _common import pin_platform_from_env  # noqa: E402

# Pipeline stages need multiple devices; default the CPU sim to 8.
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
pin_platform_from_env()

from train_llama import (  # noqa: E402 - shared synthetic corpus
    SEQ_LEN,
    VOCAB,
    WINDOW_ROWS,
    _token_file_valid,
    make_token_file,
)


def main(layout: str = "pp") -> int:
    import tempfile

    import jax
    import optax
    from jax.sharding import PartitionSpec as P

    from ddl_tpu.config import LoaderConfig, TrainConfig
    from ddl_tpu.models import llama
    from ddl_tpu.parallel import bubble_fraction
    from ddl_tpu.parallel.mesh import make_mesh
    from ddl_tpu.readers import TokenStreamProducer
    from ddl_tpu.trainer import Trainer

    token_file = os.path.join(tempfile.gettempdir(), "ddl_tpu_tokens.bin")
    if not _token_file_valid(token_file):
        make_token_file(token_file)

    n_dev = len(jax.devices())
    n_micro = 4
    # The training hot-path knobs ride TrainConfig (env-overridable as
    # DDL_TPU_TRAIN_*): the pp_1f1b layout selects the interleaved
    # schedule, everything else stays gpipe.
    tc = TrainConfig(
        schedule="1f1b" if layout == "pp_1f1b" else "gpipe",
        pp_chunks=2 if layout == "pp_1f1b" else 0,
        n_microbatches=n_micro,
    )
    if layout == "pp_tp":
        if n_dev % 4:
            raise SystemExit(f"pp_tp needs a multiple of 4 devices, have {n_dev}")
        axes = {"pp": 2, "tp": 2, "dp": n_dev // 4}
    else:
        if n_dev % 2:
            raise SystemExit(f"pp needs an even device count, have {n_dev}")
        axes = {"pp": 2, "dp": n_dev // 2}
    mesh = make_mesh(axes)
    n_chunks = tc.pp_chunks or 1
    print(f"mesh {axes}, {n_micro} microbatches, schedule={tc.schedule}, "
          f"bubble={bubble_fraction(axes['pp'], n_micro, schedule=tc.schedule, n_chunks=tc.pp_chunks or None):.3f}")

    model = llama.LlamaConfig(
        vocab=VOCAB, d_model=128, n_layers=4, n_heads=4, n_kv_heads=2,
        d_ff=256, max_seq=SEQ_LEN,
    )
    cfg = LoaderConfig(
        batch_size=8,
        n_epochs=6,
        n_producers=2,
        mode="thread",
        nslots=2,
        output="jax",
        window_stream=True,
    )
    trainer = Trainer(
        loss_fn=lambda p, b: llama.next_token_loss_pp(
            p, b[0], model, mesh, n_microbatches=n_micro,
            **tc.pipeline_kwargs(),
        ),
        optimizer=optax.adamw(3e-3),
        mesh=mesh,
        param_specs=llama.pp_param_specs(model, n_chunks=n_chunks),
        init_params=llama.stage_params(
            llama.init_params(model, jax.random.key(0)), axes["pp"],
            n_chunks=n_chunks,
        ),
        batch_spec=P(("dp",)),
        train_config=tc,
    )
    result = trainer.fit(
        TokenStreamProducer(token_file, SEQ_LEN, WINDOW_ROWS),
        config=cfg,
    )
    print("epoch losses:", [round(l, 4) for l in result.losses])

    ok = (
        all(np.isfinite(l) for l in result.losses)
        and result.losses[-1] < result.losses[0]
    )
    print("OK" if ok else "FAILED")
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1] if len(sys.argv) > 1 else "pp"))
