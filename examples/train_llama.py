"""End-to-end LM pretraining example: token stream → Trainer → flash Llama.

The BASELINE configs[3-4] shape ("C4-style token feed", "Llama pretrain
loop fed solely by the ddl TPU backend") at laptop scale: a synthetic flat
token file is served by :class:`TokenStreamProducer` workers, batches
stream into HBM with prefetch, and the GSPMD train step runs the
Llama-style decoder with the Pallas flash-attention kernel on TPU (dense
XLA attention elsewhere).  Everything — topology, batch geometry, output
mode — comes from one :class:`LoaderConfig`.

Run:

    python examples/train_llama.py             # THREAD mode
    python examples/train_llama.py process     # spawned producer processes
    DDL_TPU_N_PRODUCERS=4 python examples/train_llama.py process

    # ZeRO-1 optimizer-state sharding over dp (and int8 grad comm) ride
    # the standard TrainConfig env — identical losses, ~dp× less
    # optimizer HBM per replica (ddl_tpu/parallel/optimizer.py):
    DDL_TPU_TRAIN_OPTIMIZER_SHARDING=zero1 python examples/train_llama.py
    DDL_TPU_TRAIN_OPTIMIZER_SHARDING=zero1 DDL_TPU_TRAIN_GRAD_COMM=int8 \
        python examples/train_llama.py

Exit 0 with finite, decreasing loss is the pass criterion.
"""

from __future__ import annotations

import os
import sys
import tempfile

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from _common import pin_platform_from_env  # noqa: E402

pin_platform_from_env()

SEQ_LEN = 128
WINDOW_ROWS = 32
VOCAB = 512


N_TOKENS = 200_000


def make_token_file(path: str) -> None:
    """A synthetic 'corpus': structured token stream (learnable bigrams).

    Written atomically (temp + rename) so an interrupted run never leaves
    a truncated file that a later run would silently train on.
    """
    rng = np.random.default_rng(0)
    # Each token mostly determines its successor — a model that learns
    # anything drives the loss well below log(VOCAB).
    succ = rng.integers(0, VOCAB, VOCAB)
    toks = np.empty(N_TOKENS, np.int32)
    toks[0] = 1
    noise = rng.random(N_TOKENS) < 0.1
    randoms = rng.integers(0, VOCAB, N_TOKENS)
    for i in range(1, N_TOKENS):
        toks[i] = randoms[i] if noise[i] else succ[toks[i - 1]]
    tmp = f"{path}.tmp.{os.getpid()}"
    toks.tofile(tmp)
    os.replace(tmp, path)


def _token_file_valid(path: str) -> bool:
    return (
        os.path.exists(path)
        and os.path.getsize(path) == N_TOKENS * 4
        and int(np.memmap(path, np.int32, mode="r").max()) < VOCAB
    )


def main(mode: str = "thread") -> int:
    import jax
    import optax
    from jax.sharding import PartitionSpec as P

    from ddl_tpu.config import LoaderConfig, TrainConfig
    from ddl_tpu.models import llama
    from ddl_tpu.parallel.mesh import make_mesh
    from ddl_tpu.readers import TokenStreamProducer
    from ddl_tpu.trainer import Trainer

    token_file = os.path.join(tempfile.gettempdir(), "ddl_tpu_tokens.bin")
    if not _token_file_valid(token_file):
        make_token_file(token_file)

    cfg = LoaderConfig(
        batch_size=8,
        n_epochs=6,
        n_producers=int(os.environ.get("DDL_TPU_N_PRODUCERS", "2")),
        mode=mode,
        nslots=2,
        output="jax",
        # The recommended TPU path: one zero-copy transfer per window, one
        # jitted scan of optimizer steps per window (numerically identical
        # to per-batch fit — tests/test_trainer.py proves equivalence).
        window_stream=True,
    )
    model = llama.LlamaConfig(
        vocab=VOCAB, d_model=128, n_layers=2, n_heads=4, n_kv_heads=2,
        d_ff=256, max_seq=SEQ_LEN,
    )
    mesh = make_mesh({"dp": len(jax.local_devices())})
    # TrainConfig.load() picks up DDL_TPU_TRAIN_* from the env —
    # optimizer_sharding=zero1 shards adamw's moments over dp (inert at
    # dp=1; the loss trajectory is bit-identical either way).
    train_config = TrainConfig.load()
    trainer = Trainer(
        loss_fn=lambda p, b: llama.next_token_loss(p, b[0], model),
        optimizer=optax.adamw(3e-3),
        mesh=mesh,
        param_specs=llama.param_specs(model),
        init_params=llama.init_params(model, jax.random.key(0)),
        batch_spec=P(("dp",)),
        train_config=train_config,
    )
    result = trainer.fit(
        TokenStreamProducer(token_file, SEQ_LEN, WINDOW_ROWS),
        config=cfg,
    )
    print("epoch losses:", [round(l, 4) for l in result.losses])

    # Inference on the trained weights: greedy continuation via the exact
    # KV-cache decode path (one-forward prefill + scanned decode steps).
    prompt = jax.numpy.asarray(
        np.memmap(token_file, np.int32, mode="r")[:16][None]
    )
    continued = llama.generate(
        result.state.params, prompt, model, max_new_tokens=16
    )
    print("generated continuation:", np.asarray(continued[0, 16:]).tolist())

    ok = (
        all(np.isfinite(l) for l in result.losses)
        and result.losses[-1] < result.losses[0]
        and continued.shape == (1, 32)
        and int(continued.max()) < VOCAB
    )
    print("PASS" if ok else "FAIL", "- final loss", result.losses[-1])
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main(sys.argv[1] if len(sys.argv) > 1 else "thread"))
