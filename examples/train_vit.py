"""End-to-end image classification: WebDataset tar shards → Trainer → ViT.

The ImageNet-config story (BASELINE configs[1-2]) at laptop scale:
synthetic tar shards in the WebDataset layout (``<key>.png`` +
``<key>.cls``) are streamed by :class:`WebDatasetProducer` workers and a
vision transformer trains on the loader's ``(pixels, label)`` columns
through the GSPMD step — flash attention on TPU, dense elsewhere.

Run:

    python examples/train_vit.py             # THREAD mode
    python examples/train_vit.py process     # spawned producer processes

Exit 0 with finite, decreasing loss is the pass criterion.
"""

from __future__ import annotations

import io
import os
import sys
import tarfile
import tempfile

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from _common import pin_platform_from_env  # noqa: E402

pin_platform_from_env()

IMAGE_SIZE = 16
N_CLASSES = 4
SHARDS = 2
SAMPLES_PER_SHARD = 32


def make_shards(dirpath: str) -> str:
    """Synthetic labeled shards: class k images are brightness-banded, so
    the task is learnable."""
    try:
        from PIL import Image
    except ImportError as e:
        raise SystemExit(
            "this example needs Pillow (pip install 'ddl-tpu[image]')"
        ) from e

    rng = np.random.default_rng(0)
    os.makedirs(dirpath, exist_ok=True)
    for s in range(SHARDS):
        path = os.path.join(dirpath, f"train-{s:04d}.tar")
        tmp = f"{path}.tmp.{os.getpid()}"
        with tarfile.open(tmp, "w") as tf:
            for i in range(SAMPLES_PER_SHARD):
                label = (s * SAMPLES_PER_SHARD + i) % N_CLASSES
                base = 40 + label * 50
                arr = np.clip(
                    rng.normal(base, 12, (IMAGE_SIZE, IMAGE_SIZE, 3)),
                    0, 255,
                ).astype(np.uint8)
                buf = io.BytesIO()
                Image.fromarray(arr).save(buf, format="PNG")
                for name, data in (
                    (f"{s}-{i}.png", buf.getvalue()),
                    (f"{s}-{i}.cls", str(label).encode()),
                ):
                    info = tarfile.TarInfo(name)
                    info.size = len(data)
                    tf.addfile(info, io.BytesIO(data))
        os.replace(tmp, path)
    return os.path.join(dirpath, "train-*.tar")


def main(mode: str = "thread") -> int:
    import jax
    import optax
    from jax.sharding import PartitionSpec as P

    from ddl_tpu.config import LoaderConfig
    from ddl_tpu.models import vit
    from ddl_tpu.parallel.mesh import make_mesh
    from ddl_tpu.readers import WebDatasetProducer
    from ddl_tpu.trainer import Trainer

    pattern = make_shards(
        os.path.join(tempfile.gettempdir(), "ddl_tpu_wds")
    )
    cfg = LoaderConfig(
        batch_size=8,
        n_epochs=6,
        n_producers=2,
        mode=mode,
        nslots=2,
        output="jax",
    )
    model = vit.ViTConfig(
        image_size=IMAGE_SIZE, patch_size=4, d_model=64, n_layers=2,
        n_heads=4, d_ff=128, n_classes=N_CLASSES,
    )
    mesh = make_mesh({"dp": len(jax.local_devices())})
    trainer = Trainer(
        loss_fn=lambda p, b: vit.classification_loss(p, b, model),
        optimizer=optax.adamw(1e-3),
        mesh=mesh,
        param_specs=vit.param_specs(model),
        init_params=vit.init_params(model, jax.random.key(0)),
        batch_spec=P(("dp",)),
    )
    result = trainer.fit(
        WebDatasetProducer(pattern, image_size=IMAGE_SIZE, window_rows=16),
        config=cfg,
    )
    print("epoch losses:", [round(l, 4) for l in result.losses])
    ok = (
        all(np.isfinite(l) for l in result.losses)
        and result.losses[-1] < result.losses[0]
    )
    print("PASS" if ok else "FAIL", "- final loss", result.losses[-1])
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main(sys.argv[1] if len(sys.argv) > 1 else "thread"))
