"""Serving example: train briefly, then batched KV-cache generation.

The reference stops at data loading (no model code at all — SURVEY §0);
this example shows the inference side of the rebuilt stack: a tiny
llama is fitted on a repeating token pattern, then ``generate`` serves
batched completions three ways — greedy, temperature sampling, and
nucleus (top-p) sampling with a top-k cap — all through the in-place
stacked KV cache (prefill in one cached forward, scanned decode steps;
chip-measured 0.85 model-bandwidth utilization at B=8, bench.py
``DDL_BENCH_MODE=decode``).

Run:

    python examples/generate.py

Exit 0 with a learned continuation (greedy decode reproduces the
training pattern) is the pass criterion.
"""

from __future__ import annotations

import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from _common import pin_platform_from_env  # noqa: E402

pin_platform_from_env()

VOCAB = 64
PERIOD = 7
SEQ = 32


def main() -> int:
    import jax
    import jax.numpy as jnp
    import optax

    from ddl_tpu.models import llama
    from ddl_tpu.parallel.mesh import make_mesh
    from ddl_tpu.parallel.train import make_train_step

    cfg = llama.LlamaConfig(
        vocab=VOCAB, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2,
        d_ff=128, max_seq=64, dtype=jnp.float32,
    )
    mesh = make_mesh({"dp": 1}, devices=jax.local_devices()[:1])
    init_fn, step_fn = make_train_step(
        lambda p, b: llama.next_token_loss(p, b, cfg),
        optax.adamw(1e-2), mesh, llama.param_specs(cfg),
    )
    state = init_fn(llama.init_params(cfg, jax.random.key(0)))

    # A deterministic repeating pattern the model can memorise fast.
    tokens = np.tile(np.arange(SEQ, dtype=np.int32) % PERIOD, (8, 1))
    loss = None
    for _ in range(60):
        state, loss = step_fn(state, tokens)
    print(f"train loss after 60 steps: {float(loss):.4f}")

    prompt = jnp.asarray(tokens[:4, :10])

    greedy = llama.generate(state.params, prompt, cfg, max_new_tokens=12)
    continuation = np.asarray(greedy)[:, 10:]
    expected = np.tile(np.arange(10, 22, dtype=np.int32) % PERIOD, (4, 1))
    ok = (continuation == expected).mean()
    print(f"greedy continuation matches pattern: {ok:.0%}")

    sampled = llama.generate(
        state.params, prompt, cfg, max_new_tokens=12,
        temperature=0.8, key=jax.random.key(42),
    )
    nucleus = llama.generate(
        state.params, prompt, cfg, max_new_tokens=12,
        temperature=0.8, key=jax.random.key(43), top_p=0.9, top_k=8,
    )
    print("sampled   :", np.asarray(sampled)[0, 10:].tolist())
    print("nucleus   :", np.asarray(nucleus)[0, 10:].tolist())
    for out in (sampled, nucleus):
        arr = np.asarray(out)
        assert arr.shape == (4, 22) and ((arr >= 0) & (arr < VOCAB)).all()

    if ok < 0.9:
        print("FAIL: model did not learn the pattern")
        return 1
    print("OK")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
