# Developer entry points. The same commands CI runs; no magic.

PY ?= python

.PHONY: lint verify test test-fast bench-smoke cache-bench ici-bench ici-dryrun opt-bench opt-dryrun opt-test placement-bench tenancy-bench serve-test multihost cluster-test check chaos wire-bench wire-dryrun wire-test preempt-test preempt-bench obs-bench obs-test shuffle-bench shuffle-dryrun shuffle-test failover-test failover-bench fabric-test fabric-bench tune-test tune-bench

# Framework-invariant static analysis (tools/ddl_lint, docs/LINT.md).
# Exit 0 = clean; findings print as file:line:col: DDL0xx message.
lint:
	$(PY) -m tools.ddl_lint ddl_tpu/ tests/

# Whole-program verifier (tools/ddl_verify, docs/VERIFY.md): lock-order
# graph + deadlock cycles (VP001), blocking-under-lock (VP002), the
# env-knob contract (VP003), control-protocol exhaustiveness (VP004).
verify:
	$(PY) -m tools.ddl_verify ddl_tpu/

# Full tier-1 suite (CPU-simulated 8-device mesh).
test:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/ -q -m 'not slow'

# Transport + lint gate only: the quick pre-push loop.
test-fast:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_transport.py \
	    tests/test_py_ring.py tests/test_lint.py -q

# Ingest bench at tiny CPU geometry: asserts the JSON line parses and
# carries the staged-ingest extras (tools/bench_smoke.py).
bench-smoke:
	$(PY) tools/bench_smoke.py

# Shard-cache cold/warm A/B over the throttled backend, full geometry
# (docs/CACHING.md; headline = warm/cold speedup).
cache-bench:
	DDL_BENCH_MODE=cache JAX_PLATFORMS=cpu $(PY) bench.py

# ICI distribution A/B (Pallas fan-out + redistribution vs the XLA
# scatter; docs/PERF_NOTES.md "ICI ingest").  On a TPU pod this is the
# real-DMA measurement; elsewhere it runs interpret-mode on the
# virtual mesh and the JSON carries the last_tpu_artifact trail.
ici-bench:
	DDL_BENCH_MODE=ici $(PY) bench.py

# Fan-out kernel dry run on whatever devices exist (interpret mode on
# CPU: per-hop bytes/s for both modes + one full redistribution) —
# the mirror of tools/probe_ingest.py for the post-H2D hop.
ici-dryrun:
	$(PY) tools/probe_ici.py

# Distributed-optimizer A/B (zero1 vs replicated state, fp32 vs int8
# grad comm; docs/PERF_NOTES.md "Distributed optimizer").  Loss parity
# asserted in the artifact; winner is the headline.
opt-bench:
	DDL_BENCH_MODE=opt $(PY) bench.py

# Optimizer-state/grad-comm sweep on whatever devices exist (the CPU
# virtual mesh elsewhere): measured bytes/replica + leg times at small
# scale, analytic v5e-32 pricing for the 8B/4B configs — the mirror of
# tools/probe_ici.py for the optimizer tier.
opt-dryrun:
	$(PY) tools/probe_opt.py

# Topology-aware vs naive producer→consumer placement A/B over the
# simulated fabric (ddl_tpu/cluster/placement.py; Cloud Collectives
# rank reordering) + the membership chaos counters.
placement-bench:
	DDL_BENCH_MODE=placement JAX_PLATFORMS=cpu $(PY) bench.py

# Multi-tenant ingest-service A/B (K concurrent tenants over the shared
# fair-share scheduler, autoscaled vs static pool; docs/SERVING.md) +
# the tenant-burst/host-loss chaos leg.
tenancy-bench:
	DDL_BENCH_MODE=tenancy JAX_PLATFORMS=cpu $(PY) bench.py

# Serve control-plane suite alone (admission/fair-share/autoscaler units,
# concurrent-consumer fairness, the serve fault-site chaos rows).
serve-test:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_serve.py -q

# The full multi-process jax.distributed matrix: virtual-mesh legs
# (dp, dp×sp, pp×dp, dp×ep), checkpoint resume, packed-stream fit, and
# the cross-host elastic chaos leg (slow legs included).
multihost:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_multihost.py -q

# Cluster control-plane suite alone (membership/view-change/placement
# units + the in-process host-loss recovery ladder).
cluster-test:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_cluster.py -q

# The one-shot local gate: static analysis (per-module lint +
# whole-program verify) + bench JSON contract (the bench-smoke contract
# includes the cache block's byte-identity and >=2x warm-vs-cold
# assertions).
check: lint verify bench-smoke

# Chaos suite: deterministic fault matrix + randomized multi-fault soak
# (includes slow PROCESS-mode spawns; docs/ROBUSTNESS.md) + the cache
# corruption/backend-failure ladder (tests/test_cache.py) + the ICI
# DMA-failure → xla-fallback rung (tests/test_ici.py) + the preemption
# notice/checkpoint-corruption rows (tests/test_resilience.py).
chaos:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_faults.py tests/test_cache.py tests/test_ici.py tests/test_cluster.py tests/test_serve.py tests/test_resilience.py tests/test_obs.py tests/test_supervision.py -q

# Distributed-optimizer suite alone (parity matrix, collective units,
# the 4B fits-only-with-zero1 accounting test).
opt-test:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_optimizer.py -q

# Data-plane wire-format A/B (raw vs int8 vs codec exchange wire over a
# simulated constrained link; docs/PERF_NOTES.md "Wire format").
# Lossless byte identity + int8 loss parity asserted in the artifact;
# winner is the headline.
wire-bench:
	DDL_BENCH_MODE=wire JAX_PLATFORMS=cpu $(PY) bench.py

# Per-dtype/per-codec encode/decode bytes/s + compression ratios on
# real shard data, break-even link speeds, and the analytic ICI wire
# pricing — the mirror of probe_ici/probe_opt for the wire tier.
wire-dryrun:
	JAX_PLATFORMS=cpu $(PY) tools/probe_wire.py

# Wire-format suite alone (codec/quantizer units, trailer roundtrip,
# slot/exchange/ICI wire paths, the wire chaos rows).
wire-test:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_wire.py -q

# Preemption-tolerance suite alone (async checkpointer units, the
# restore quarantine/fallback ladder, revocation, SIGTERM/notice drain
# e2e in THREAD and forced-py-ring PROCESS mode; docs/ROBUSTNESS.md).
preempt-test:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_resilience.py -q

# Preemption tolerance priced end to end: async-vs-sync checkpoint
# stall A/B, notice→resumed recovery wall time, hard-kill lost-work
# bound — byte-identical resume asserted in the artifact.
preempt-bench:
	DDL_BENCH_MODE=preempt JAX_PLATFORMS=cpu $(PY) bench.py

# Survivable-control-plane suite alone (supervisor journal replay,
# the acked/fenced envelope seam, lease-expiry HA promotion incl. the
# split-brain row, scheduler-fairness continuity, the mid-stream
# supervisor-kill e2e; docs/ROBUSTNESS.md "Control-plane failover").
failover-test:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_supervision.py -q

# Control-plane failover priced end to end: mid-stream supervisor kill
# with standby takeover wall time as the headline — byte-identical
# stream, zero watchdog failures, envelope drop/dup dedup counters and
# scheduler-fairness continuity asserted in the artifact.
failover-bench:
	DDL_BENCH_MODE=failover JAX_PLATFORMS=cpu $(PY) bench.py

# Multi-job ingest fabric unit + property tests (tests/test_fabric.py:
# supervisor-resident admission, journal-replay failover, per-job
# isolation seams, chaos-matrix rows for the fabric fault kinds).
fabric-test:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_fabric.py -q

# The fleet soak end to end: 50 Zipf-weighted jobs / 100 simulated host
# bindings against ONE supervisor-resident scheduler over the acked
# control plane — weighted-share deviation headline, scale-reaction and
# preemption-drain SLOs, per-job cache accounting, and the supervisor-
# kill leg's bit-identical admission order in the artifact.
fabric-bench:
	DDL_BENCH_MODE=fabric JAX_PLATFORMS=cpu $(PY) bench.py

# Self-tuning unit/e2e matrix (ddl_tpu/tune; docs/TUNING.md):
# hysteresis, cooldown, never-worse revert, deadline-bounded
# calibration, parity flip, drift replan, knob seams.
tune-test:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_tune.py -q

# Self-tuned vs shipped-defaults from a mis-matched cold start (raw
# wire on a throttled link, starved prefetch seed): Calibrator at boot
# + KnobController live, interleaved A/B, never-slower gated by
# bench_smoke.
tune-bench:
	DDL_BENCH_MODE=autotune JAX_PLATFORMS=cpu $(PY) bench.py

# Host-vs-device global-shuffle exchange A/B (ThreadExchangeShuffler
# over the rendezvous boards vs the on-mesh DeviceExchangeShuffler;
# docs/PERF_NOTES.md "Device-side global shuffle").  Byte identity of
# the post-exchange pools asserted per rep; winner is the headline.
# On a TPU pod the ring kernel runs real DMAs; elsewhere interpret
# mode on the virtual mesh (the host path usually wins there — the
# contract, not the speedup, is what CI gates on).
shuffle-bench:
	DDL_BENCH_MODE=shuffle JAX_PLATFORMS=cpu $(PY) bench.py

# Analytic exchange pricing (device ICI bytes vs host boards raw/wire
# per plan_exchange) across ring widths + a live byte-identity parity
# run for both impls on the virtual mesh — the mirror of
# probe_ici/probe_wire for the shuffle tier.
shuffle-dryrun:
	JAX_PLATFORMS=cpu $(PY) tools/probe_shuffle.py

# Device-exchange suite alone (seed parity across geometries, the DMA
# -failure/peer-loss chaos rungs, resolution surface, end-to-end
# stream identity in THREAD and PROCESS modes).
shuffle-test:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_device_shuffle.py -q

# Tracing-layer suite alone (Metrics histograms, SpanLog/Chrome export,
# cross-process aggregation, flight recorder, the doc-reflection test;
# docs/OBSERVABILITY.md).
obs-test:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_obs.py -q

# The tracing layer priced end to end: armed-vs-disarmed span/recorder
# overhead A/B (ceiling <= 2%, byte-identical), histogram percentiles
# in the armed report, and the seeded-corruption flight-record leg.
obs-bench:
	DDL_BENCH_MODE=obs JAX_PLATFORMS=cpu $(PY) bench.py
